"""Deterministic fault injection: the epoch-table fault layer.

The network model is otherwise failure-free except for the *static*
all-pairs reliability matrix (topology/graph.py, core/netmodel.py).
This module adds scheduled, deterministic faults:

* ``link_down`` / ``link_up`` — a topology edge goes away / comes back
  at a fixed sim time. With shortest paths enabled traffic re-routes
  over the surviving edges; pairs left unreachable get reliability 0
  (every packet between them drops) while keeping the healthy base
  latency so lookahead windows and the i32 device matrices never
  change shape.
* ``degrade`` — for a window ``[time, time+duration)`` an edge's
  latency is multiplied and/or extra packet loss is composed in
  (rel' = rel * (1 - extra_packet_loss)).
* ``host_crash`` / ``host_restart`` — manager-side events
  (core/manager.py): the host's processes are killed, its pending
  events quarantined, and at restart the configured processes respawn
  with a fresh network stack.

The **epoch table** is the whole trick: link faults change the network
only at a finite set of times, so the schedule compiles — at load
time, exactly like the base all-pairs matrices — into ``[T]`` epoch
start times plus stacked ``[T, V, V]`` latency/reliability overrides.
Every backend then agrees by construction:

* the CPU twin (core/netmodel.py) picks the epoch by binary search on
  the packet's send time;
* the hybrid judge (device/judge.py) and the device engine
  (device/engine.py) carry the stacked arrays on device and select
  the active epoch with a searchsorted-style comparison inside the
  jitted program, so per-packet lookups stay batched gathers.

Drop rolls keep their (seed, src, pkt_seq) keys — the fault layer only
changes the *reliability the roll is compared against* — so traces are
bit-identical across serial / thread / hybrid / tpu whenever they were
before. During the bootstrap phase packets are never dropped (the
reference's bootstrap rule), so a fault window that overlaps
``general.bootstrap_end_time`` delays losses until bootstrap ends;
latency changes apply immediately.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass
from typing import Optional

import numpy as np

from shadow_tpu.topology import hierarchy
from shadow_tpu.topology.graph import (
    _MIN_PATH_LATENCY_NS,
    _all_pairs_shortest,
    Topology,
    compute_path_matrices,
    dense_adjacency,
    sparse_min_adjacency,
)

LINK_KINDS = ("link_down", "link_up", "degrade")
HOST_KINDS = ("host_crash", "host_restart")
FAULT_KINDS = LINK_KINDS + HOST_KINDS


@dataclass(frozen=True)
class FaultEvent:
    """One validated ``network.faults`` entry (config/schema.py)."""

    kind: str
    time: int                      # sim ns (degrade: window start)
    source: int = -1               # topology GML vertex ids (link kinds)
    target: int = -1
    duration: int = 0              # degrade window length, ns
    latency_multiplier: float = 1.0
    extra_packet_loss: float = 0.0
    host: str = ""                 # host kinds: configured host name


class FaultTable:
    """The compiled link-fault schedule: epoch start times plus one
    [V,V] latency/reliability override pair per epoch. ``times[0]`` is
    always 0 (the healthy base matrices), so every send time maps to
    exactly one epoch.

    Epochs are held as a LIST of per-epoch [V,V] views; unchanged
    epochs (including the epoch-0 healthy base) are *references to the
    topology's own matrices*, never copies, so a schedule with k
    changed epochs allocates k extra [V,V] pairs instead of T. The
    stacked ``latency_ns`` / ``reliability`` [T,V,V] arrays the device
    backends upload materialize lazily on first access; the CPU twin
    never pays for them."""

    is_hierarchical = False

    def __init__(self, times, latency_ns=None, reliability=None,
                 events=None, lat_epochs=None, rel_epochs=None):
        self.times = np.asarray(times, np.int64)
        self.events = list(events) if events else []
        self._lat_stack = None
        self._rel_stack = None
        if lat_epochs is None:
            # back-compat constructor from pre-stacked [T,V,V] arrays
            self._lat_stack = np.asarray(latency_ns, np.int64)
            self._rel_stack = np.asarray(reliability, np.float32)
            lat_epochs = list(self._lat_stack)
            rel_epochs = list(self._rel_stack)
        self._lat_epochs = [np.asarray(a, np.int64) for a in lat_epochs]
        self._rel_epochs = [np.asarray(a, np.float32)
                            for a in rel_epochs]

    @property
    def n_epochs(self) -> int:
        return len(self.times)

    @property
    def latency_ns(self) -> np.ndarray:
        """Stacked [T,V,V] int64 (lazy; device upload path only)."""
        if self._lat_stack is None:
            self._lat_stack = np.stack(self._lat_epochs)
        return self._lat_stack

    @property
    def reliability(self) -> np.ndarray:
        """Stacked [T,V,V] float32 (lazy; device upload path only)."""
        if self._rel_stack is None:
            self._rel_stack = np.stack(self._rel_epochs)
        return self._rel_stack

    @property
    def min_latency_ns(self) -> int:
        """Conservative lookahead floor across every epoch — a degrade
        can only keep or raise the window, never shrink it under a
        backend's feet (all backends consume the same value)."""
        return min(int(a.min()) for a in self._lat_epochs)

    def epoch_of(self, now: int) -> int:
        """Active epoch at send time `now`: the largest i with
        times[i] <= now (binary search; the device engines compute the
        identical index with a vectorized comparison count)."""
        return int(np.searchsorted(self.times, now, side="right") - 1)

    def lookup(self, now: int, src_vertex: int,
               dst_vertex: int) -> tuple[int, float]:
        e = self.epoch_of(now)
        return (int(self._lat_epochs[e][src_vertex, dst_vertex]),
                float(self._rel_epochs[e][src_vertex, dst_vertex]))

    def fingerprint(self) -> str:
        """Stable digest of the compiled schedule, for tools and logs.
        Byte-identical to hashing the stacked arrays (an epoch list is
        a representation detail, not a schedule difference).
        (Checkpoint resume-safety does not go through this method:
        device/checkpoint.py folds the engine's epoch_times and the
        stacked matrices into its world hash directly, so a saved
        state already refuses an edited fault schedule.)"""
        h = hashlib.sha256()
        t = np.ascontiguousarray(self.times)
        h.update(str(t.shape).encode())
        h.update(t.tobytes())
        for eps in (self._lat_epochs, self._rel_epochs):
            h.update(str((len(eps),) + eps[0].shape).encode())
            for a in eps:
                h.update(np.ascontiguousarray(a).tobytes())
        return h.hexdigest()[:12]


class HierFaultTable:
    """The hierarchical twin of FaultTable: one factored table set
    (hierarchy.HierTables) per epoch instead of [V,V] matrices, built
    by _compile_hier in O(affected links + C^2 + V) per changed epoch.
    Unchanged epochs share the topology's base table LEAVES by
    reference; within a changed epoch, only the leaves a fault
    actually touches are new arrays. The device backends consume
    lat_parts_stacked()/rel_parts_stacked() — each factored leaf with
    a leading [T] epoch axis — resolved through
    hierarchy.world_tables."""

    is_hierarchical = True

    def __init__(self, times, epochs, events=None):
        self.times = np.asarray(times, np.int64)
        self.epochs = list(epochs)      # [T] of hierarchy.HierTables
        self.events = list(events) if events else []
        self._lat_stacked = None
        self._rel_stacked = None

    @property
    def n_epochs(self) -> int:
        return len(self.times)

    @property
    def min_latency_ns(self) -> int:
        return min(ht.min_latency_ns() for ht in self.epochs)

    def epoch_of(self, now: int) -> int:
        return int(np.searchsorted(self.times, now, side="right") - 1)

    def lookup(self, now: int, src_vertex: int,
               dst_vertex: int) -> tuple[int, float]:
        return self.epochs[self.epoch_of(now)].lookup(src_vertex,
                                                      dst_vertex)

    def lat_parts_stacked(self) -> tuple:
        """(cluster_lat [T,C,C], cl [T,V], acc_lat [T,V],
        self_lat [T,V]) — the device world leaves (lazy, cached)."""
        if self._lat_stacked is None:
            T = self.n_epochs
            self._lat_stacked = (
                np.stack([h.cluster_lat for h in self.epochs]),
                np.repeat(self.epochs[0].cl[None], T, axis=0),
                np.stack([h.acc_lat for h in self.epochs]),
                np.stack([h.self_lat for h in self.epochs]))
        return self._lat_stacked

    def rel_parts_stacked(self) -> tuple:
        if self._rel_stacked is None:
            T = self.n_epochs
            self._rel_stacked = (
                np.stack([h.cluster_rel for h in self.epochs]),
                np.repeat(self.epochs[0].cl[None], T, axis=0),
                np.stack([h.acc_rel for h in self.epochs]),
                np.stack([h.self_rel for h in self.epochs]))
        return self._rel_stacked

    def fingerprint(self) -> str:
        """Stable digest over the stacked factored leaves (the
        factored schedule is a different representation, so this is
        intentionally NOT comparable to FaultTable.fingerprint())."""
        h = hashlib.sha256()
        t = np.ascontiguousarray(self.times)
        h.update(str(t.shape).encode())
        h.update(t.tobytes())
        for leaf in self.lat_parts_stacked() + self.rel_parts_stacked():
            a = np.ascontiguousarray(leaf)
            h.update(str(a.shape).encode())
            h.update(a.tobytes())
        return h.hexdigest()[:12]


def split_events(events) -> tuple[list, list]:
    """(link_events, host_events), each in schedule order."""
    link = [e for e in events or () if e.kind in LINK_KINDS]
    host = [e for e in events or () if e.kind in HOST_KINDS]
    return link, host


def _edge_indices(top: Topology, ev: FaultEvent) -> list[int]:
    """Indices of every (parallel) edge between the event's endpoints.
    GML ids resolve through the topology; a fault on a nonexistent
    edge is a config error, caught at load time."""
    try:
        s = top.vertex_index_for_id(ev.source)
        d = top.vertex_index_for_id(ev.target)
    except Exception as e:
        raise ValueError(
            f"network.faults: {ev.kind} at {ev.time} ns references "
            f"unknown vertex id(s) {ev.source}->{ev.target}") from e
    hit = [k for k in range(len(top.edge_src))
           if (top.edge_src[k] == s and top.edge_dst[k] == d)
           or (not top.directed
               and top.edge_src[k] == d and top.edge_dst[k] == s)]
    if not hit:
        raise ValueError(
            f"network.faults: {ev.kind} at {ev.time} ns names edge "
            f"{ev.source}->{ev.target}, but the graph has no such "
            "edge")
    return hit


def _epoch_edge_state(events: list, ordered: list,
                      keyed: list, t: int) -> tuple[set, list]:
    """(down_edges, active_degrades) at epoch start time `t` — the
    edge state both the dense and hierarchical compilers replay."""
    down_edges: set[int] = set()
    for i in ordered:
        ev = events[i]
        if ev.time > t:
            break
        _, eids = keyed[i]
        if ev.kind == "link_down":
            down_edges.update(eids)
        elif ev.kind == "link_up":
            down_edges.difference_update(eids)
    degrades = [(events[i], keyed[i][1]) for i in ordered
                if events[i].kind == "degrade"
                and events[i].time <= t
                < events[i].time + events[i].duration]
    return down_edges, degrades


def compile_link_faults(top: Topology,
                        events: list) -> Optional[FaultTable]:
    """Compile the link-fault schedule into a FaultTable (None when no
    link events are configured — the fault-free fast paths stay
    byte-identical to before). Validates pairing (link_up must undo an
    earlier link_down; no double-down), then rebuilds the all-pairs
    matrices per epoch from the modified edge set using the same
    dense_adjacency + compute_path_matrices pipeline as the base
    topology."""
    if not events:
        return None

    for ev in events:
        if ev.time < 0:
            raise ValueError(
                f"network.faults: {ev.kind} has negative time")
        if ev.kind == "degrade":
            if ev.duration <= 0:
                raise ValueError(
                    f"network.faults: degrade at {ev.time} ns needs "
                    "duration > 0")
            if ev.latency_multiplier <= 0:
                raise ValueError(
                    f"network.faults: degrade at {ev.time} ns needs "
                    "latency_multiplier > 0")
            if not (0.0 <= ev.extra_packet_loss <= 1.0):
                raise ValueError(
                    f"network.faults: degrade at {ev.time} ns "
                    "extra_packet_loss must be in [0,1]")
            if ev.latency_multiplier == 1.0 and \
                    ev.extra_packet_loss == 0.0:
                raise ValueError(
                    f"network.faults: degrade at {ev.time} ns changes "
                    "nothing (latency_multiplier 1 and "
                    "extra_packet_loss 0)")

    # resolve endpoints once; pair-key = frozenset-ish sorted vertex
    # tuple for undirected graphs so down/up pairing matches an event
    # written in either direction
    def pair_key(ev):
        ids = _edge_indices(top, ev)
        s = top.vertex_index_for_id(ev.source)
        d = top.vertex_index_for_id(ev.target)
        key = (s, d) if top.directed else tuple(sorted((s, d)))
        return key, ids

    # sweep in (time, config order) to validate down/up pairing
    down_at: dict = {}
    ordered = sorted(range(len(events)), key=lambda i: (events[i].time, i))
    keyed = [pair_key(e) for e in events]
    for i in ordered:
        ev = events[i]
        key, _ = keyed[i]
        if ev.kind == "link_down":
            if key in down_at:
                raise ValueError(
                    f"network.faults: link_down at {ev.time} ns on "
                    f"edge {ev.source}->{ev.target}, but the link is "
                    f"already down (since {down_at[key]} ns)")
            down_at[key] = ev.time
        elif ev.kind == "link_up":
            if key not in down_at:
                raise ValueError(
                    f"network.faults: link_up at {ev.time} ns on edge "
                    f"{ev.source}->{ev.target} without a preceding "
                    "link_down")
            if down_at[key] == ev.time:
                raise ValueError(
                    f"network.faults: link_down and link_up on edge "
                    f"{ev.source}->{ev.target} at the same instant "
                    f"({ev.time} ns) is ambiguous")
            del down_at[key]

    # epoch boundaries: 0 plus every instant the edge state changes
    bounds = {0}
    for ev in events:
        bounds.add(ev.time)
        if ev.kind == "degrade":
            bounds.add(ev.time + ev.duration)
    times = np.array(sorted(bounds), dtype=np.int64)

    if top.hier is not None:
        return _compile_hier(top, events, times, ordered, keyed)

    V = top.n_vertices
    base_lat, base_rel = top.latency_ns, top.reliability
    lat_epochs, rel_epochs = [], []
    for t in times:
        down_edges, degrades = _epoch_edge_state(events, ordered,
                                                 keyed, t)
        if not down_edges and not degrades:
            # share the healthy base matrices by reference — the
            # stacked arrays only materialize lazily for the device
            # backends, so unchanged epochs never copy a [V,V] pair
            lat_epochs.append(base_lat)
            rel_epochs.append(base_rel)
            continue
        elat = top.edge_latency_ns.copy()
        erel = top.edge_reliability.astype(np.float64)
        alive = np.ones(len(elat), dtype=bool)
        for k in down_edges:
            alive[k] = False
        for ev, eids in degrades:
            for k in eids:
                elat[k] = max(1, int(round(
                    int(elat[k]) * ev.latency_multiplier)))
                erel[k] = erel[k] * (1.0 - ev.extra_packet_loss)
        direct_lat, direct_rel = dense_adjacency(
            V, top.directed, top.edge_src, top.edge_dst, elat,
            erel.astype(np.float32), edge_alive=alive)
        lat, rel = compute_path_matrices(
            direct_lat, direct_rel, top.use_shortest_path,
            unreachable_lat=base_lat)
        lat_epochs.append(lat)
        rel_epochs.append(rel)

    return FaultTable(times=times, events=list(events),
                      lat_epochs=lat_epochs, rel_epochs=rel_epochs)


def _hub_connected(n_clusters: int, rv: np.ndarray,
                   ru: np.ndarray) -> bool:
    """Is the (alive) hub subgraph connected? Plain BFS over the
    reduced adjacency entries — C is small by construction."""
    if n_clusters <= 1:
        return True
    nbrs: dict[int, list[int]] = {}
    for a, b in zip(rv.tolist(), ru.tolist()):
        if a != b:
            nbrs.setdefault(a, []).append(b)
            nbrs.setdefault(b, []).append(a)
    seen = {0}
    stack = [0]
    while stack:
        for b in nbrs.get(stack.pop(), ()):
            if b not in seen:
                seen.add(b)
                stack.append(b)
    return len(seen) == n_clusters


def _compile_hier(top: Topology, events: list, times: np.ndarray,
                  ordered: list, keyed: list) -> HierFaultTable:
    """Hierarchical epoch compilation: instead of re-running the
    all-pairs pipeline over [V,V], rebuild only the factored pieces a
    fault touches — the [C,C] cluster pair when a hub-hub link
    changes, the access/self entries of the vertices incident to an
    affected edge otherwise. O(affected links + C^2 + V) per changed
    epoch; unchanged epochs share the base table leaves by reference.

    Exactness vs the dense oracle follows the same composition
    contract as the base builder (topology/hierarchy.py), with one
    extra corner: the dense pipeline gives an *unreachable* pair its
    healthy base latency, which the factored form can only reproduce
    while the latency factors it would compose still equal the base.
    An epoch that combines unreachability with latency-factor changes
    is therefore rejected loudly (the dense representation handles
    it). Every epoch is additionally verified elementwise against the
    dense pipeline when V <= HIER_VERIFY_MAX_V."""
    ht = top.hier
    V = top.n_vertices
    C = ht.n_clusters
    is_hub = np.zeros(V, dtype=bool)
    is_hub[ht.hub_vertex] = True
    hub_rank = np.full(V, -1, dtype=np.int64)
    hub_rank[ht.hub_vertex] = np.arange(C, dtype=np.int64)
    esrc = np.asarray(top.edge_src, np.int64)
    edst = np.asarray(top.edge_dst, np.int64)

    # vertices any event's edge touches, and the slice of edges
    # incident to them: a touched vertex's FULL candidate edge set
    # rides in the slice, so its access/self entries re-reduce with
    # dense_adjacency's exact tie rule (slice order preserves
    # original edge order)
    ev_edges = sorted({k for _, eids in keyed for k in eids})
    touched = np.zeros(V, dtype=bool)
    touched[esrc[ev_edges]] = True
    touched[edst[ev_edges]] = True
    inc = np.nonzero(touched[esrc] | touched[edst])[0]
    hub_pair = is_hub[esrc] & is_hub[edst] & (esrc != edst)
    hub_sel = np.nonzero(is_hub[esrc] & is_hub[edst])[0]
    aff_spokes = np.nonzero(touched & ~is_hub)[0]
    aff_vs = np.nonzero(touched)[0]

    base_dense = ht.dense() if V <= hierarchy.HIER_VERIFY_MAX_V \
        else None

    epochs = []
    for t in times:
        down_edges, degrades = _epoch_edge_state(events, ordered,
                                                 keyed, t)
        if not down_edges and not degrades:
            epochs.append(ht)
            continue
        elat = top.edge_latency_ns.copy()
        erel = top.edge_reliability.astype(np.float64)
        alive = np.ones(len(elat), dtype=bool)
        changed = set(down_edges)
        for k in down_edges:
            alive[k] = False
        for ev, eids in degrades:
            for k in eids:
                elat[k] = max(1, int(round(
                    int(elat[k]) * ev.latency_multiplier)))
                erel[k] = erel[k] * (1.0 - ev.extra_packet_loss)
                changed.add(k)
        changed_idx = np.fromiter(changed, dtype=np.int64)

        # [C,C] rebuild — only when a hub-hub link changed; the hub
        # subgraph re-reduces and re-runs shortest paths exactly like
        # the base builder, with unreachable hub pairs taking the
        # healthy base cluster latency (the dense unreachable rule)
        hub_unreach = False
        if changed_idx.size and hub_pair[changed_idx].any():
            rv, ru, rl, rr = sparse_min_adjacency(
                C, False, hub_rank[esrc[hub_sel]],
                hub_rank[edst[hub_sel]], elat[hub_sel],
                erel[hub_sel].astype(np.float32),
                edge_alive=alive[hub_sel])
            dlat = np.zeros((C, C), dtype=np.int64)
            drel = np.zeros((C, C), dtype=np.float32)
            dlat[rv, ru] = rl
            drel[rv, ru] = rr
            hub_unreach = not _hub_connected(C, rv, ru)
            cc_lat, cc_rel = _all_pairs_shortest(dlat, drel,
                                                 ht.cluster_lat)
            np.fill_diagonal(cc_lat, 0)
            np.fill_diagonal(cc_rel, 1.0)
            cc_lat = cc_lat.astype(np.int64)
            cc_rel = cc_rel.astype(np.float32)
        else:
            cc_lat, cc_rel = ht.cluster_lat, ht.cluster_rel

        # re-reduce the incident slice once; update access entries of
        # touched spokes and self entries of every touched vertex
        rv2, ru2, rl2, rr2 = sparse_min_adjacency(
            V, False, esrc[inc], edst[inc], elat[inc],
            erel[inc].astype(np.float32), edge_alive=alive[inc])
        acc_lat, acc_rel = ht.acc_lat, ht.acc_rel
        downed_spokes = []
        acc_lat_changed = False
        if aff_spokes.size:
            acc_lat = acc_lat.copy()
            acc_rel = acc_rel.copy()
            off2 = rv2 != ru2
            for v in aff_spokes.tolist():
                sel = np.nonzero(off2 & (rv2 == v))[0]
                if not sel.size:
                    # the spoke's only link is down: the pair is
                    # undeliverable (rel 0) at the healthy latency,
                    # exactly the dense unreachable rule
                    downed_spokes.append(v)
                    acc_rel[v] = 0.0
                else:
                    j = sel[0]   # a spoke has exactly one neighbor
                    if int(rl2[j]) != int(ht.acc_lat[v]):
                        acc_lat_changed = True
                    acc_lat[v] = rl2[j]
                    acc_rel[v] = rr2[j]

        self_lat = ht.self_lat.copy()
        self_rel = ht.self_rel.copy()
        cand_lat = np.where(rv2 == ru2, rl2, 2 * rl2)
        cand_rel = np.where(rv2 == ru2, rr2,
                            (rr2 * rr2).astype(np.float32))
        order2 = np.lexsort((cand_rel.astype(np.float64), cand_lat,
                             rv2))
        sv_ = rv2[order2]
        sl_, sr_ = cand_lat[order2], cand_rel[order2]
        firstv = np.ones(len(sv_), dtype=bool)
        firstv[1:] = sv_[1:] != sv_[:-1]
        got = set()
        for j in np.nonzero(firstv)[0]:
            v = int(sv_[j])
            # only touched vertices carry their full candidate set in
            # the slice; everyone else keeps the base self entry
            if touched[v]:
                self_lat[v] = sl_[j]
                self_rel[v] = sr_[j]
                got.add(v)
        for v in aff_vs.tolist():
            if v not in got:      # no alive incident edge: the dense
                self_lat[v] = _MIN_PATH_LATENCY_NS  # zero-lat clamp
                self_rel[v] = 1.0

        cc_lat_changed = cc_lat is not ht.cluster_lat and \
            not np.array_equal(cc_lat, ht.cluster_lat)
        if downed_spokes and (acc_lat_changed or cc_lat_changed):
            raise ValueError(
                f"network.faults: epoch at {int(t)} ns combines an "
                "unreachable pair (downed access link) with latency "
                "changes elsewhere; the dense pipeline pins "
                "unreachable pairs to their HEALTHY base latency, "
                "which the factored tables cannot reproduce while "
                "their latency factors change — use "
                "network.topology.representation: dense for this "
                "schedule")
        if hub_unreach and acc_lat_changed:
            raise ValueError(
                f"network.faults: epoch at {int(t)} ns combines an "
                "unreachable hub pair with access-latency changes; "
                "the dense pipeline pins unreachable pairs to their "
                "HEALTHY base latency, which the factored tables "
                "cannot reproduce while their latency factors change "
                "— use network.topology.representation: dense for "
                "this schedule")

        eht = hierarchy.HierTables(
            cluster_lat=cc_lat, cluster_rel=cc_rel,
            cl=ht.cl, hub_vertex=ht.hub_vertex,
            acc_lat=acc_lat, acc_rel=acc_rel,
            self_lat=self_lat, self_rel=self_rel)

        if base_dense is not None:
            direct_lat, direct_rel = dense_adjacency(
                V, top.directed, top.edge_src, top.edge_dst, elat,
                erel.astype(np.float32), edge_alive=alive)
            want_lat, want_rel = compute_path_matrices(
                direct_lat, direct_rel, top.use_shortest_path,
                unreachable_lat=base_dense[0])
            have_lat, have_rel = eht.dense()
            if not (np.array_equal(want_lat, have_lat)
                    and np.array_equal(want_rel, have_rel)):
                raise ValueError(
                    f"network.faults: epoch at {int(t)} ns is not "
                    "bit-exact against the dense fault pipeline "
                    "under the hierarchical representation — use "
                    "network.topology.representation: dense for "
                    "this schedule")
        epochs.append(eht)

    return HierFaultTable(times=times, epochs=epochs,
                          events=list(events))


def resolve_host_faults(events: list,
                        name_to_id: dict) -> list[tuple[int, int, str]]:
    """Validate host_crash/host_restart events against the built host
    list: names must resolve (group-expanded names like ``client0``),
    and each host's schedule must alternate crash -> restart. Returns
    [(time, host_id, kind)] sorted by time.

    ``name_to_id`` is any mapping-like with ``.get`` — a plain dict
    from the object build, or the columnar build's
    ``host.plane.PlaneNameMap``, which parses generated names back to
    ids WITHOUT materializing a million Host objects first."""
    out: list[tuple[int, int, str]] = []
    state: dict[int, str] = {}
    for ev in sorted(events, key=lambda e: e.time):
        if ev.time < 0:
            raise ValueError(
                f"network.faults: {ev.kind} has negative time")
        hid = name_to_id.get(ev.host)
        if hid is None:
            raise ValueError(
                f"network.faults: {ev.kind} at {ev.time} ns names "
                f"unknown host {ev.host!r}")
        prev = state.get(hid, "up")
        if ev.kind == "host_crash" and prev == "down":
            raise ValueError(
                f"network.faults: host_crash at {ev.time} ns, but "
                f"{ev.host!r} is already crashed")
        if ev.kind == "host_restart" and prev == "up":
            raise ValueError(
                f"network.faults: host_restart at {ev.time} ns "
                f"without a preceding host_crash of {ev.host!r}")
        state[hid] = "down" if ev.kind == "host_crash" else "up"
        out.append((ev.time, hid, ev.kind))
    return out
