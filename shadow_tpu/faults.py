"""Deterministic fault injection: the epoch-table fault layer.

The network model is otherwise failure-free except for the *static*
all-pairs reliability matrix (topology/graph.py, core/netmodel.py).
This module adds scheduled, deterministic faults:

* ``link_down`` / ``link_up`` — a topology edge goes away / comes back
  at a fixed sim time. With shortest paths enabled traffic re-routes
  over the surviving edges; pairs left unreachable get reliability 0
  (every packet between them drops) while keeping the healthy base
  latency so lookahead windows and the i32 device matrices never
  change shape.
* ``degrade`` — for a window ``[time, time+duration)`` an edge's
  latency is multiplied and/or extra packet loss is composed in
  (rel' = rel * (1 - extra_packet_loss)).
* ``host_crash`` / ``host_restart`` — manager-side events
  (core/manager.py): the host's processes are killed, its pending
  events quarantined, and at restart the configured processes respawn
  with a fresh network stack.

The **epoch table** is the whole trick: link faults change the network
only at a finite set of times, so the schedule compiles — at load
time, exactly like the base all-pairs matrices — into ``[T]`` epoch
start times plus stacked ``[T, V, V]`` latency/reliability overrides.
Every backend then agrees by construction:

* the CPU twin (core/netmodel.py) picks the epoch by binary search on
  the packet's send time;
* the hybrid judge (device/judge.py) and the device engine
  (device/engine.py) carry the stacked arrays on device and select
  the active epoch with a searchsorted-style comparison inside the
  jitted program, so per-packet lookups stay batched gathers.

Drop rolls keep their (seed, src, pkt_seq) keys — the fault layer only
changes the *reliability the roll is compared against* — so traces are
bit-identical across serial / thread / hybrid / tpu whenever they were
before. During the bootstrap phase packets are never dropped (the
reference's bootstrap rule), so a fault window that overlaps
``general.bootstrap_end_time`` delays losses until bootstrap ends;
latency changes apply immediately.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from shadow_tpu.topology.graph import (
    Topology,
    compute_path_matrices,
    dense_adjacency,
)

LINK_KINDS = ("link_down", "link_up", "degrade")
HOST_KINDS = ("host_crash", "host_restart")
FAULT_KINDS = LINK_KINDS + HOST_KINDS


@dataclass(frozen=True)
class FaultEvent:
    """One validated ``network.faults`` entry (config/schema.py)."""

    kind: str
    time: int                      # sim ns (degrade: window start)
    source: int = -1               # topology GML vertex ids (link kinds)
    target: int = -1
    duration: int = 0              # degrade window length, ns
    latency_multiplier: float = 1.0
    extra_packet_loss: float = 0.0
    host: str = ""                 # host kinds: configured host name


@dataclass
class FaultTable:
    """The compiled link-fault schedule: epoch start times plus one
    [V,V] latency/reliability override pair per epoch. ``times[0]`` is
    always 0 (the healthy base matrices), so every send time maps to
    exactly one epoch."""

    times: np.ndarray              # [T] int64, ascending, times[0]==0
    latency_ns: np.ndarray         # [T,V,V] int64
    reliability: np.ndarray        # [T,V,V] float32
    events: list = field(default_factory=list)

    @property
    def n_epochs(self) -> int:
        return len(self.times)

    @property
    def min_latency_ns(self) -> int:
        """Conservative lookahead floor across every epoch — a degrade
        can only keep or raise the window, never shrink it under a
        backend's feet (all backends consume the same value)."""
        return int(self.latency_ns.min())

    def epoch_of(self, now: int) -> int:
        """Active epoch at send time `now`: the largest i with
        times[i] <= now (binary search; the device engines compute the
        identical index with a vectorized comparison count)."""
        return int(np.searchsorted(self.times, now, side="right") - 1)

    def lookup(self, now: int, src_vertex: int,
               dst_vertex: int) -> tuple[int, float]:
        e = self.epoch_of(now)
        return (int(self.latency_ns[e, src_vertex, dst_vertex]),
                float(self.reliability[e, src_vertex, dst_vertex]))

    def fingerprint(self) -> str:
        """Stable digest of the compiled schedule, for tools and logs.
        (Checkpoint resume-safety does not go through this method:
        device/checkpoint.py folds the engine's epoch_times and the
        stacked matrices into its world hash directly, so a saved
        state already refuses an edited fault schedule.)"""
        h = hashlib.sha256()
        for a in (self.times, self.latency_ns, self.reliability):
            a = np.ascontiguousarray(a)
            h.update(str(a.shape).encode())
            h.update(a.tobytes())
        return h.hexdigest()[:12]


def split_events(events) -> tuple[list, list]:
    """(link_events, host_events), each in schedule order."""
    link = [e for e in events or () if e.kind in LINK_KINDS]
    host = [e for e in events or () if e.kind in HOST_KINDS]
    return link, host


def _edge_indices(top: Topology, ev: FaultEvent) -> list[int]:
    """Indices of every (parallel) edge between the event's endpoints.
    GML ids resolve through the topology; a fault on a nonexistent
    edge is a config error, caught at load time."""
    try:
        s = top.vertex_index_for_id(ev.source)
        d = top.vertex_index_for_id(ev.target)
    except Exception as e:
        raise ValueError(
            f"network.faults: {ev.kind} at {ev.time} ns references "
            f"unknown vertex id(s) {ev.source}->{ev.target}") from e
    hit = [k for k in range(len(top.edge_src))
           if (top.edge_src[k] == s and top.edge_dst[k] == d)
           or (not top.directed
               and top.edge_src[k] == d and top.edge_dst[k] == s)]
    if not hit:
        raise ValueError(
            f"network.faults: {ev.kind} at {ev.time} ns names edge "
            f"{ev.source}->{ev.target}, but the graph has no such "
            "edge")
    return hit


def compile_link_faults(top: Topology,
                        events: list) -> Optional[FaultTable]:
    """Compile the link-fault schedule into a FaultTable (None when no
    link events are configured — the fault-free fast paths stay
    byte-identical to before). Validates pairing (link_up must undo an
    earlier link_down; no double-down), then rebuilds the all-pairs
    matrices per epoch from the modified edge set using the same
    dense_adjacency + compute_path_matrices pipeline as the base
    topology."""
    if not events:
        return None

    for ev in events:
        if ev.time < 0:
            raise ValueError(
                f"network.faults: {ev.kind} has negative time")
        if ev.kind == "degrade":
            if ev.duration <= 0:
                raise ValueError(
                    f"network.faults: degrade at {ev.time} ns needs "
                    "duration > 0")
            if ev.latency_multiplier <= 0:
                raise ValueError(
                    f"network.faults: degrade at {ev.time} ns needs "
                    "latency_multiplier > 0")
            if not (0.0 <= ev.extra_packet_loss <= 1.0):
                raise ValueError(
                    f"network.faults: degrade at {ev.time} ns "
                    "extra_packet_loss must be in [0,1]")
            if ev.latency_multiplier == 1.0 and \
                    ev.extra_packet_loss == 0.0:
                raise ValueError(
                    f"network.faults: degrade at {ev.time} ns changes "
                    "nothing (latency_multiplier 1 and "
                    "extra_packet_loss 0)")

    # resolve endpoints once; pair-key = frozenset-ish sorted vertex
    # tuple for undirected graphs so down/up pairing matches an event
    # written in either direction
    def pair_key(ev):
        ids = _edge_indices(top, ev)
        s = top.vertex_index_for_id(ev.source)
        d = top.vertex_index_for_id(ev.target)
        key = (s, d) if top.directed else tuple(sorted((s, d)))
        return key, ids

    # sweep in (time, config order) to validate down/up pairing
    down_at: dict = {}
    ordered = sorted(range(len(events)), key=lambda i: (events[i].time, i))
    keyed = [pair_key(e) for e in events]
    for i in ordered:
        ev = events[i]
        key, _ = keyed[i]
        if ev.kind == "link_down":
            if key in down_at:
                raise ValueError(
                    f"network.faults: link_down at {ev.time} ns on "
                    f"edge {ev.source}->{ev.target}, but the link is "
                    f"already down (since {down_at[key]} ns)")
            down_at[key] = ev.time
        elif ev.kind == "link_up":
            if key not in down_at:
                raise ValueError(
                    f"network.faults: link_up at {ev.time} ns on edge "
                    f"{ev.source}->{ev.target} without a preceding "
                    "link_down")
            if down_at[key] == ev.time:
                raise ValueError(
                    f"network.faults: link_down and link_up on edge "
                    f"{ev.source}->{ev.target} at the same instant "
                    f"({ev.time} ns) is ambiguous")
            del down_at[key]

    # epoch boundaries: 0 plus every instant the edge state changes
    bounds = {0}
    for ev in events:
        bounds.add(ev.time)
        if ev.kind == "degrade":
            bounds.add(ev.time + ev.duration)
    times = np.array(sorted(bounds), dtype=np.int64)

    V = top.n_vertices
    base_lat, base_rel = top.latency_ns, top.reliability
    lat_epochs, rel_epochs = [], []
    for t in times:
        # edge state active at time t
        down_edges: set[int] = set()
        for i in ordered:
            ev = events[i]
            if ev.time > t:
                break
            _, eids = keyed[i]
            if ev.kind == "link_down":
                down_edges.update(eids)
            elif ev.kind == "link_up":
                down_edges.difference_update(eids)
        degrades = [(events[i], keyed[i][1]) for i in ordered
                    if events[i].kind == "degrade"
                    and events[i].time <= t
                    < events[i].time + events[i].duration]
        if not down_edges and not degrades:
            lat_epochs.append(base_lat)
            rel_epochs.append(base_rel)
            continue
        elat = top.edge_latency_ns.copy()
        erel = top.edge_reliability.astype(np.float64)
        alive = np.ones(len(elat), dtype=bool)
        for k in down_edges:
            alive[k] = False
        for ev, eids in degrades:
            for k in eids:
                elat[k] = max(1, int(round(
                    int(elat[k]) * ev.latency_multiplier)))
                erel[k] = erel[k] * (1.0 - ev.extra_packet_loss)
        direct_lat, direct_rel = dense_adjacency(
            V, top.directed, top.edge_src, top.edge_dst, elat,
            erel.astype(np.float32), edge_alive=alive)
        lat, rel = compute_path_matrices(
            direct_lat, direct_rel, top.use_shortest_path,
            unreachable_lat=base_lat)
        lat_epochs.append(lat)
        rel_epochs.append(rel)

    return FaultTable(times=times,
                      latency_ns=np.stack(lat_epochs).astype(np.int64),
                      reliability=np.stack(rel_epochs)
                      .astype(np.float32),
                      events=list(events))


def resolve_host_faults(events: list,
                        name_to_id: dict) -> list[tuple[int, int, str]]:
    """Validate host_crash/host_restart events against the built host
    list: names must resolve (group-expanded names like ``client0``),
    and each host's schedule must alternate crash -> restart. Returns
    [(time, host_id, kind)] sorted by time."""
    out: list[tuple[int, int, str]] = []
    state: dict[int, str] = {}
    for ev in sorted(events, key=lambda e: e.time):
        if ev.time < 0:
            raise ValueError(
                f"network.faults: {ev.kind} has negative time")
        hid = name_to_id.get(ev.host)
        if hid is None:
            raise ValueError(
                f"network.faults: {ev.kind} at {ev.time} ns names "
                f"unknown host {ev.host!r}")
        prev = state.get(hid, "up")
        if ev.kind == "host_crash" and prev == "down":
            raise ValueError(
                f"network.faults: host_crash at {ev.time} ns, but "
                f"{ev.host!r} is already crashed")
        if ev.kind == "host_restart" and prev == "up":
            raise ValueError(
                f"network.faults: host_restart at {ev.time} ns "
                f"without a preceding host_crash of {ev.host!r}")
        state[hid] = "down" if ev.kind == "host_crash" else "up"
        out.append((ev.time, hid, ev.kind))
    return out
