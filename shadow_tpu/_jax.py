"""Central jax import point.

Every module that uses jax imports it via ``from shadow_tpu._jax import
jax, jnp`` so that x64 mode (int64 sim times) is enabled exactly once,
before any tracing, while jax-free paths (CLI --show-config, config
parsing, the pure-Python engine) never pay the jax import cost.
"""

import os

import jax
import jax.numpy as jnp

jax.config.update("jax_enable_x64", True)

# honor an explicit JAX_PLATFORMS=cpu request: this environment's TPU
# PJRT plugin force-writes jax_platforms to "axon,cpu" at import,
# overriding the env var, so the request must be re-applied via config
# (the tunneled TPU admits one client at a time — accidental dials from
# tests or CPU-mesh runs would block on the claim)
if os.environ.get("JAX_PLATFORMS", "").strip() == "cpu":
    jax.config.update("jax_platforms", "cpu")

__all__ = ["jax", "jnp"]
