"""Central jax import point.

Every module that uses jax imports it via ``from shadow_tpu._jax import
jax, jnp`` so that x64 mode (int64 sim times) is enabled exactly once,
before any tracing, while jax-free paths (CLI --show-config, config
parsing, the pure-Python engine) never pay the jax import cost.
"""

import jax
import jax.numpy as jnp

jax.config.update("jax_enable_x64", True)

__all__ = ["jax", "jnp"]
