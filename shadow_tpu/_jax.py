"""Central jax import point.

Every module that uses jax imports it via ``from shadow_tpu._jax import
jax, jnp`` so that x64 mode (int64 sim times) is enabled exactly once,
before any tracing, while jax-free paths (CLI --show-config, config
parsing, the pure-Python engine) never pay the jax import cost.
"""

import os

import jax
import jax.numpy as jnp

jax.config.update("jax_enable_x64", True)

# honor an explicit JAX_PLATFORMS=cpu request: this environment's TPU
# PJRT plugin force-writes jax_platforms to "axon,cpu" at import,
# overriding the env var, so the request must be re-applied via config
# (the tunneled TPU admits one client at a time — accidental dials from
# tests or CPU-mesh runs would block on the claim)
if os.environ.get("JAX_PLATFORMS", "").strip() == "cpu":
    jax.config.update("jax_platforms", "cpu")

# persistent compilation cache: device-program compiles run ~50 s on
# the tunneled TPU, and the tuning sweep + bench + profiler compile
# the same few programs across separate processes — the disk cache
# turns every repeat into a hit. Opt-out via SHADOW_TPU_NO_CACHE.
# This is JAX's built-in TRACING-level cache; it also serves as the
# fallback for the engine's AOT executable cache
# (shadow_tpu/device/aotcache.py) on backends whose PJRT client
# cannot serialize executables. An explicit JAX_COMPILATION_CACHE_DIR
# (the standard jax env var — CI's warm-start rung sets it) wins over
# both repo defaults.
if not os.environ.get("SHADOW_TPU_NO_CACHE"):
    try:
        jax.config.update(
            "jax_compilation_cache_dir",
            os.environ.get("JAX_COMPILATION_CACHE_DIR")
            or os.environ.get("SHADOW_TPU_CACHE_DIR")
            or os.path.expanduser("~/.cache/shadow_tpu_xla"))
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          2.0)
    except Exception:                       # noqa: BLE001
        pass        # older jax without the knobs: compile as before

# shard_map moved from jax.experimental to the jax namespace (with
# check_rep renamed check_vma) across jax releases; export one callable
# with the NEW calling convention so engine code is version-agnostic
if hasattr(jax, "shard_map"):
    shard_map = jax.shard_map
else:
    from jax.experimental.shard_map import shard_map as _shard_map

    def shard_map(f, mesh, in_specs, out_specs, check_vma=True):
        return _shard_map(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, check_rep=check_vma)

__all__ = ["jax", "jnp", "shard_map"]
