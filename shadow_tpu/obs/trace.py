"""Flight recorder: span-traced runs with per-phase wall attribution.

The ROADMAP's two biggest open levers — pipelined dispatch and
telemetry-driven auto-tuning — both need ONE missing input: where a
round's wall time goes. The signals exist (SimStats counters,
heartbeat log lines, OCC records, compile-cache attribution, watchdog
dumps) but on no common timeline. This module is that timeline: a
:class:`Tracer` records a span for each unit of work the run already
segments on — supervise.py segment advance, device round dispatch,
judge batching, exchange flush, capacity warm-up/re-plan, checkpoint
save/load, AOT cache lower/compile/serialize/load, retry/backoff
waits, SIGTERM drain — each tagged with its sim-time window,
wall-clock interval, and counters.

Three output surfaces (docs/observability.md):

* a streamed JSONL span log (``TRACE_<label>.jsonl``, one JSON object
  per completed span) written through the streamed-atomic path in
  utils/artifacts — `tail -f`-able mid-run, atomically placed at
  close, and the partial file survives a hang as the post-mortem;
* a Chrome-trace-event / Perfetto-loadable export
  (``TRACE_<label>.trace.json``, obs/perfetto.py);
* a ``METRICS_<label>.json`` summary with per-phase wall attribution
  (host_s / judge_s / dispatch.issue_s / dispatch.sync_s /
  exchange_s / checkpoint_s / retry_s, plus compile_s / plan_s) that
  bench.py and
  scripts/trace_report.py consume. ``host_s`` is the RESIDUAL — total
  tracer-lifetime wall minus every non-host measured bucket — i.e.
  exactly the host-side Python time no span claims, so the buckets
  always sum to the total by construction.

Modes (``experimental.telemetry``): ``off`` is a :class:`NullTracer`
(every call a no-op — zero per-round work of any kind); ``summary``
(the default) accumulates per-phase walls and a small recent-span
ring (for watchdog stall dumps) but stores no span list and writes no
files unless ``telemetry_path`` is set; ``trace`` additionally keeps
the span list (bounded; drops counted loudly) and writes all three
artifacts.

Hard contract: tracing never perturbs the simulation. Spans only READ
values the run already fetched (segment round counts, overflow dims,
``engine.effective``) — no tracer mode adds device work beyond what
the untraced run performs, and traces are bit-identical across
off/summary/trace (pinned by determinism_gate --telemetry).
"""

from __future__ import annotations

import json
import os
import time
from collections import deque
from typing import Optional

from shadow_tpu.utils.slog import get_logger

log = get_logger("obs")

FORMAT = 1
MODES = ("off", "summary", "trace")

# phase buckets for the METRICS wall attribution. "host" is the
# residual bucket (never directly attributed); spans may also carry
# free-form categories, which fold into "host" residual time.
# "dispatch.issue" (asynchronous enqueue cost) and "dispatch.sync"
# (blocking waits for device results) split the old conflated
# "dispatch" bucket so device-bound and sync-bound wall are finally
# distinguishable; "dispatch" itself remains for engine.profile()'s
# fenced phase splits. "reshard" is the mesh-shrink failover's
# degradation cost (liveness probe + re-shard + re-place; the
# rebuild's compile wall lands in "compile" as ever), "chaos" marks
# scripted fault injections (instants — the faults themselves cost
# nothing), "failover" is the hybrid-rerun rung's own overhead
# (the rerun's inner spans keep their phases), and "degrade" marks
# the OOM degradation ladder's rung engagements (admission refusals
# and runtime rungs both land here).
PHASES = ("host", "judge", "dispatch", "dispatch.issue",
          "dispatch.sync", "exchange", "checkpoint",
          "retry", "compile", "plan", "reshard", "chaos",
          "failover", "degrade", "serve")

# recent-span ring size: what a watchdog stall dump embeds so a hang
# report shows what the run WAS doing, not just where it stopped
RECENT_SPANS = 64

# trace-mode span list cap: a runaway CPU run (one judge flush per
# round for hours) must not exhaust memory — past the cap spans still
# stream to the JSONL log and accumulate walls, only the in-memory
# list (the Perfetto export) stops growing, counted in `dropped`
MAX_SPANS = 200_000


class _NullSpan:
    """Reusable no-op span context (the off path allocates nothing)."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def add(self, **kw):
        pass


_NULL_SPAN = _NullSpan()


class NullTracer:
    """telemetry: off — every call a no-op, zero allocations on the
    span path, no files, no recent ring."""

    mode = "off"

    def span(self, name, phase="host", sim_t0=-1, sim_t1=-1, **args):
        return _NULL_SPAN

    def instant(self, name, phase="host", sim_t0=-1, **args):
        pass

    def record(self, name, phase, dur_s, **args):
        pass

    def recent(self, n: int = RECENT_SPANS) -> list:
        return []

    def format_recent(self, n: int = RECENT_SPANS) -> str:
        return ""

    def phase_walls(self) -> dict:
        return {}

    def finalize(self, run_info=None, counters=None):
        return None


class _Span:
    """One in-flight span (context manager). ``add(**kw)`` attaches
    counters mid-flight; an exception inside the span is recorded as
    an ``error`` arg, never swallowed.

    Wall ATTRIBUTION is self-time: a span's bucket receives its gross
    duration minus every span/record completed inside it (the first
    dispatch segment contains the 40s XLA compile — double-counting
    both would make the phase walls sum past the total). The JSONL /
    Perfetto records keep the GROSS duration (that is what a timeline
    renders), with ``self_s`` added when nested time was carved out.
    """

    __slots__ = ("_tr", "name", "phase", "sim_t0", "sim_t1", "args",
                 "_start", "_child_s")

    def __init__(self, tr, name, phase, sim_t0, sim_t1, args):
        self._tr = tr
        self.name = name
        self.phase = phase
        self.sim_t0 = sim_t0
        self.sim_t1 = sim_t1
        self.args = args
        self._child_s = 0.0

    def add(self, **kw):
        self.args.update(kw)

    def __enter__(self):
        self._tr._stack_of().append(self)
        self._start = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        end = time.perf_counter()
        stack = self._tr._stack_of()
        if stack and stack[-1] is self:
            stack.pop()
        if exc_type is not None:
            self.args["error"] = exc_type.__name__
        self._tr._record(self.name, self.phase, self._start, end,
                         self.sim_t0, self.sim_t1, self.args,
                         child_s=self._child_s)
        return False


class Tracer:
    """One run-wide flight recorder (modes ``summary`` / ``trace``).

    The Controller creates ONE instance per run and attaches it to the
    runner and the Manager; module-global :func:`current` serves the
    call sites with no plumbing path (aotcache, capacity,
    engine.profile). Wall stamps are offsets from construction
    (``perf_counter``), so the tracer's lifetime — not just the run()
    window — is the attribution total: pre-run work (bench's
    plan+warm, the engine's first compile) lands inside it.
    """

    def __init__(self, mode: str = "summary", directory: str = "",
                 label: str = "run"):
        if mode not in ("summary", "trace"):
            raise ValueError(f"tracer mode {mode!r} is not "
                             "'summary' or 'trace'")
        self.mode = mode
        self.directory = directory
        self.label = label
        self.files: dict = {}
        self._t0 = time.perf_counter()
        self._walls: dict = {}
        self._span_counts: dict = {}
        self._spans: list = []
        self._recent: deque = deque(maxlen=RECENT_SPANS)
        self._dropped = 0
        self._stream = None
        self._closed = False
        self._summary: Optional[dict] = None
        # per-thread open-span stack for self-time attribution (spans
        # are recorded from the main advance loop; worker threads get
        # their own stack so interleavings cannot misattribute)
        import threading
        self._local = threading.local()

    def _stack_of(self) -> list:
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        return stack

    # -- recording ----------------------------------------------------
    def span(self, name: str, phase: str = "host",
             sim_t0: int = -1, sim_t1: int = -1, **args) -> _Span:
        """Open a span: ``with tracer.span("dispatch", "dispatch",
        sim_t0=t, sim_t1=nxt) as sp: ... sp.add(rounds=r)``."""
        return _Span(self, name, phase, int(sim_t0), int(sim_t1), args)

    def instant(self, name: str, phase: str = "host",
                sim_t0: int = -1, **args) -> None:
        """Zero-duration marker (preemption request, overflow, ...)."""
        now = time.perf_counter()
        self._record(name, phase, now, now, int(sim_t0), -1, args)

    def record(self, name: str, phase: str, dur_s: float,
               ago_s: float = 0.0, **args) -> None:
        """Retro-record an externally timed interval (the AOT cache's
        lower/compile/load walls are measured by the cache itself;
        the tracer only needs them on the timeline). ``ago_s`` shifts
        the interval's END back from now — a caller recording two
        consecutive stages after the fact places the earlier one
        before the later, so the exported timeline shows them in
        sequence instead of overlapping on one track."""
        end = time.perf_counter() - float(ago_s)
        self._record(name, phase, end - float(dur_s), end, -1, -1,
                     args)

    def _record(self, name, phase, start, end, sim_t0, sim_t1, args,
                child_s: float = 0.0):
        dur = end - start
        # the bucket receives SELF time; the enclosing open span (if
        # any) has this span's gross duration carved out of its own
        self_s = max(0.0, dur - child_s)
        self._walls[phase] = self._walls.get(phase, 0.0) + self_s
        self._span_counts[phase] = self._span_counts.get(phase, 0) + 1
        stack = self._stack_of()
        if stack:
            stack[-1]._child_s += dur
        rec = {"name": name, "phase": phase,
               "t0_s": round(start - self._t0, 6),
               "dur_s": round(dur, 6)}
        if child_s > 0:
            rec["self_s"] = round(self_s, 6)
        if sim_t0 >= 0:
            rec["sim_t0"] = int(sim_t0)
        if sim_t1 >= 0:
            rec["sim_t1"] = int(sim_t1)
        if args:
            rec["args"] = args
        self._recent.append(rec)
        if self.mode != "trace":
            return
        if len(self._spans) < MAX_SPANS:
            self._spans.append(rec)
        else:
            self._dropped += 1
        if self._stream is None:
            from shadow_tpu.utils.artifacts import StreamedLines

            try:
                self._stream = StreamedLines(
                    self._path("TRACE", ".jsonl"))
            except OSError as e:
                log.warning("telemetry: could not open the JSONL "
                            "stream (%s) — spans stay in memory only",
                            e)
                self._stream = False      # do not retry per span
        if self._stream:
            try:
                # default=str: span args are free-form kwargs from a
                # dozen call sites — a stray numpy scalar must
                # degrade to its string form, never to a TypeError
                # that aborts the simulation (the recorder's
                # never-break-the-run contract)
                self._stream.write_line(
                    json.dumps(rec, separators=(",", ":"),
                               default=str))
            except Exception as e:      # noqa: BLE001 — degrade, never crash
                # e.g. ValueError: write on a closed stream — a stray
                # span recorded after finalize must never crash
                log.warning("telemetry: JSONL stream failed (%s); "
                            "disabling it for this run", e)
                self._stream.abandon()
                self._stream = False

    # -- read surfaces ------------------------------------------------
    def recent(self, n: int = RECENT_SPANS) -> list:
        """Last completed spans, oldest first (watchdog stall dumps)."""
        out = list(self._recent)
        return out[-n:]

    def format_recent(self, n: int = RECENT_SPANS) -> str:
        """Human-readable recent-span block for a stall dump."""
        spans = self.recent(n)
        if not spans:
            return ""
        lines = [f"  last {len(spans)} completed span(s) "
                 "(flight recorder, oldest first):"]
        for r in spans:
            window = ""
            if "sim_t0" in r:
                window = (f" sim=({r['sim_t0']}"
                          f", {r.get('sim_t1', '?')}] ns")
            lines.append(
                f"    +{r['t0_s']:10.3f}s {r['dur_s']:8.3f}s "
                f"{r['phase']:10s} {r['name']}{window}")
        return "\n".join(lines)

    def phase_walls(self, total_wall_s: Optional[float] = None) -> dict:
        """Per-phase wall attribution: the six contract buckets plus
        compile_s/plan_s, with host_s the residual of the total (the
        tracer's lifetime unless given)."""
        total = (time.perf_counter() - self._t0
                 if total_wall_s is None else float(total_wall_s))
        out = {f"{p}_s": round(self._walls.get(p, 0.0), 3)
               for p in PHASES if p != "host"}
        # any free-form category's wall belongs to the residual too —
        # it was host-side work, just named
        attributed = sum(v for k, v in self._walls.items()
                         if k in PHASES and k != "host")
        out["host_s"] = round(max(0.0, total - attributed), 3)
        return out

    # -- output -------------------------------------------------------
    def _path(self, prefix: str, suffix: str) -> str:
        directory = (self.directory
                     or os.environ.get("SHADOW_TPU_OCC_DIR",
                                       "artifacts"))
        return os.path.join(directory, f"{prefix}_{self.label}{suffix}")

    def finalize(self, run_info: Optional[dict] = None,
                 counters: Optional[dict] = None) -> dict:
        """Close the recorder: land the JSONL stream, export the
        Perfetto trace, write the METRICS record, and return the
        summary dict (SimStats.telemetry). Idempotent — a second call
        returns the first's summary without rewriting files."""
        if self._closed:
            return self._summary
        self._closed = True
        total = time.perf_counter() - self._t0
        phases = self.phase_walls(total)
        dominant = max(phases, key=phases.get)
        summary = {
            "format": FORMAT,
            "mode": self.mode,
            "total_wall_s": round(total, 3),
            "phases": phases,
            "dominant_phase": dominant[:-2],
            "spans": sum(self._span_counts.values()),
            "span_counts": dict(sorted(self._span_counts.items())),
            "dropped_spans": self._dropped,
        }
        if run_info:
            summary["run"] = dict(run_info)
        if counters:
            summary["counters"] = dict(counters)
        # publish BEFORE the file writes: a failure below must leave
        # the idempotence path (and SimStats.telemetry) the summary,
        # not an AttributeError
        self._summary = summary
        if self._stream:
            try:
                self.files["jsonl"] = self._stream.close()
            except OSError as e:
                log.warning("telemetry: could not finalize the JSONL "
                            "log (%s); partial file kept at %s", e,
                            self._stream.partial)
            # spans recorded after finalize (a re-used runner, tests
            # driving the engine directly) still accumulate walls but
            # must not write to the landed file
            self._stream = False
        if self.mode == "trace":
            from shadow_tpu.obs import perfetto

            path = self._path("TRACE", ".trace.json")
            try:
                perfetto.export(self._spans, path, summary)
                self.files["perfetto"] = path
            except Exception as e:      # noqa: BLE001 — degrade, never crash
                log.warning("telemetry: could not write the Perfetto "
                            "trace %s: %s", path, e)
        # summary mode writes the METRICS record only when the config
        # names a destination — the default-on summary must not litter
        # artifacts/ on every test run; trace mode opted in explicitly
        if self.mode == "trace" or self.directory:
            from shadow_tpu.utils.artifacts import atomic_write_json

            path = self._path("METRICS", ".json")
            try:
                atomic_write_json({**summary, "files": self.files},
                                  path, default=str)
                self.files["metrics"] = path
            except Exception as e:      # noqa: BLE001 — degrade, never crash
                log.warning("telemetry: could not write the metrics "
                            "record %s: %s", path, e)
        summary["files"] = dict(self.files)
        if self._dropped:
            log.warning("telemetry: span list hit its %d-span cap — "
                        "%d span(s) streamed to the JSONL log only "
                        "(absent from the Perfetto export)",
                        MAX_SPANS, self._dropped)
        log.info("telemetry (%s): total %.2fs — %s; dominant phase: "
                 "%s%s", self.mode, total,
                 ", ".join(f"{k[:-2]} {v:.2f}s"
                           for k, v in sorted(
                               phases.items(), key=lambda kv: -kv[1])
                           if v > 0) or "no attributed walls",
                 summary["dominant_phase"],
                 f" -> {self.files}" if self.files else "")
        return summary


# -- module-global current tracer -------------------------------------
# set by the Controller for the run's lifetime; call sites without a
# plumbing path (aotcache.ensure, capacity record I/O, engine.profile)
# read it here. A fresh Controller overwrites it — the newest run owns
# the recorder, which is the right owner for every in-process caller.
_CURRENT: object = NullTracer()


def current():
    return _CURRENT


def set_current(tracer) -> None:
    global _CURRENT
    _CURRENT = tracer if tracer is not None else NullTracer()


def resolve_tracer(cfg, n_hosts: int = 0):
    """The Controller's tracer factory from the validated
    ``experimental.telemetry`` / ``telemetry_path`` knobs. The label
    (file stem) is ``<policy>_<n_hosts>`` — successive runs of one
    workload overwrite one record, like OCC records."""
    xp = cfg.experimental
    if xp.telemetry == "off":
        return NullTracer()
    label = f"{xp.scheduler_policy}_{n_hosts}"
    # artifacts_dir is the per-tenant namespacing seam (the campaign
    # server points it at <spool>/campaigns/<cid>/artifacts): an
    # explicit telemetry_path still wins, but a namespaced run lands
    # its METRICS/TRACE records inside its own directory instead of
    # racing other tenants on the shared label-keyed filenames
    directory = xp.telemetry_path or getattr(xp, "artifacts_dir", "")
    return Tracer(mode=xp.telemetry, directory=directory,
                  label=label)
