"""Chrome trace-event export: the flight recorder's span list as a
Perfetto-loadable ``.trace.json``.

The JSON object format of the Trace Event spec (the subset Perfetto's
legacy importer and chrome://tracing both load): one ``"X"`` complete
event per span with microsecond ``ts``/``dur``, grouped onto one
named thread track per phase bucket so the timeline reads as
swimlanes — dispatch / judge / exchange / checkpoint / retry /
compile / plan / host — with sim-time windows and counters in each
event's ``args``. Written atomically (utils/artifacts), so a kill
mid-export never leaves a truncated trace the viewer chokes on.
"""

from __future__ import annotations

from shadow_tpu.obs.trace import PHASES


def to_trace_events(spans: list, meta: dict = None) -> dict:
    """Span records (obs/trace.py ``_record`` dicts) -> the Trace
    Event JSON object. Pure, so tests can pin the format without
    touching disk."""
    pid = 1
    tids = {p: i + 1 for i, p in enumerate(PHASES)}
    events = [{
        "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
        "args": {"name": "shadow-tpu flight recorder"},
    }]
    for phase, tid in tids.items():
        events.append({"name": "thread_name", "ph": "M", "pid": pid,
                       "tid": tid, "args": {"name": phase}})
        # sort_index pins the swimlane order to the PHASES order
        # instead of Perfetto's name sort
        events.append({"name": "thread_sort_index", "ph": "M",
                       "pid": pid, "tid": tid,
                       "args": {"sort_index": tid}})
    for rec in spans:
        tid = tids.get(rec["phase"])
        if tid is None:
            # free-form category: a lane of its own past the fixed set
            tid = tids[rec["phase"]] = len(tids) + 1
            events.append({"name": "thread_name", "ph": "M",
                           "pid": pid, "tid": tid,
                           "args": {"name": rec["phase"]}})
        args = dict(rec.get("args") or {})
        if "sim_t0" in rec:
            args["sim_t0_ns"] = rec["sim_t0"]
        if "sim_t1" in rec:
            args["sim_t1_ns"] = rec["sim_t1"]
        ts_us = round(rec["t0_s"] * 1e6, 3)
        dur_us = round(rec["dur_s"] * 1e6, 3)
        if dur_us <= 0:
            # zero-duration record -> instant event (a vertical tick;
            # an "X" with dur 0 renders as nothing)
            events.append({"name": rec["name"], "ph": "i", "s": "t",
                           "pid": pid, "tid": tid, "ts": ts_us,
                           "args": args})
        else:
            events.append({"name": rec["name"], "ph": "X", "pid": pid,
                           "tid": tid, "ts": ts_us, "dur": dur_us,
                           "args": args})
    out = {"traceEvents": events, "displayTimeUnit": "ms"}
    if meta:
        out["metadata"] = {k: v for k, v in meta.items()
                           if k in ("mode", "total_wall_s", "phases",
                                    "dominant_phase", "run")}
    return out


def export(spans: list, path: str, meta: dict = None) -> str:
    from shadow_tpu.utils.artifacts import atomic_write_json

    # default=str: free-form span args must degrade to strings, not
    # fail the export (the recorder's never-break-the-run contract)
    atomic_write_json(to_trace_events(spans, meta), path, indent=None,
                      default=str)
    return path
