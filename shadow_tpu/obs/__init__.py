"""Run-wide observability: the flight recorder (obs/trace.py) and its
Perfetto export (obs/perfetto.py). See docs/observability.md."""

from shadow_tpu.obs.trace import (       # noqa: F401
    MODES,
    NullTracer,
    PHASES,
    Tracer,
    current,
    resolve_tracer,
    set_current,
)
