"""Events and their deterministic total order.

The reference orders events by (time, dstHostID, srcHostID, per-src-host
sequence number) — event_compare, src/main/core/work/event.c:109-152 —
which makes the simulation schedule a pure function of the config seed.
We keep exactly that key. It is a lexicographic sort key, so it
vectorizes directly on device (device/heap.py uses the same tuple).

CPU-side events carry an arbitrary task closure (the reference's
refcounted Task, core/work/task.c); device-side events are rows of a
struct-of-arrays with an integer `kind` dispatched by the model app.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, NamedTuple


# Event kinds shared by the CPU and device engines. The device engine
# dispatches on these integers with lax.switch; the CPU engine calls the
# matching ModelApp hook.
KIND_BOOT = 0     # host/process start (worker_bootHosts analogue)
KIND_TIMER = 1    # self-scheduled timer/task
KIND_PACKET = 2   # packet delivery from the network model
KIND_STOP = 3     # process/host stop
KIND_TASK = 4     # CPU-only: run the attached task closure
# network-stack kinds (CPU fidelity path; the device transport model
# mirrors their semantics in vectorized form)
KIND_ROUTER_ARRIVAL = 5   # packet arrived at dst's upstream router
KIND_NIC_WAKE = 6         # token-bucket refill wakeup (data: (side,))
KIND_TCP_TIMER = 7        # TCP timer (data: (conn_id, generation))
# model-NIC path (experimental.model_bandwidth): a raw-send packet
# event first passes the destination's RX bandwidth/CoDel stage
# (KIND_PACKET), then re-fires as KIND_PACKET_READY at its post-
# serialization delivery time — on both engines (host/model_nic.py,
# device/engine.py)
KIND_PACKET_READY = 8
# fault injection (shadow_tpu/faults.py, manager-side): kill a host's
# processes and quarantine its pending events / respawn the configured
# processes with a fresh network stack. CPU policies only — under the
# tpu policy host-fault configs fall back to hybrid.
KIND_HOST_CRASH = 9
KIND_HOST_RESTART = 10


class EventKey(NamedTuple):
    time: int          # sim ns
    dst_host: int
    src_host: int
    seq: int           # unique per (src_host); ties therefore impossible


@dataclass(order=False)
class Event:
    time: int
    dst_host: int
    src_host: int
    seq: int
    # CPU path: a closure to run. Device path encodes (kind, data) instead.
    task: Callable[..., Any] | None = None
    kind: int = 0
    data: tuple = field(default_factory=tuple)
    # packets carried by this delivery event (a packet TRAIN's
    # surviving count; 1 for ordinary packets) — stats only, never
    # part of the ordering key
    npkts: int = 1

    @property
    def key(self) -> EventKey:
        return EventKey(self.time, self.dst_host, self.src_host, self.seq)

    def execute(self, ctx) -> None:
        if self.task is not None:
            self.task(ctx, self)
