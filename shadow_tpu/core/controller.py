"""Controller: global clock windows and simulation lifecycle.

Mirrors controller_run (src/main/core/controller.c:79-424): load the
topology, register hosts (attachment + per-host RNG + app processes),
compute the conservative lookahead window ("min time jump" = minimum
path latency, controller.c:125-153), then advance the simulation in
rounds [start, start + lookahead) until stop_time, asking the
Manager(s) for the earliest next event between rounds.
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass
from typing import Optional

import numpy as np

from shadow_tpu import simtime
from shadow_tpu.config.schema import ConfigOptions
from shadow_tpu.core.manager import Manager, SimStats
from shadow_tpu.core.netmodel import NetworkModel
from shadow_tpu.core.scheduler import make_policy
from shadow_tpu.host.host import Host
from shadow_tpu.models import is_model_path, make_app
from shadow_tpu.topology.attach import Attacher, HostAttachment
from shadow_tpu.topology.graph import Topology
from shadow_tpu.utils.rng import SeededRandom
from shadow_tpu.utils.slog import get_logger

log = get_logger("controller")


def load_topology(cfg: ConfigOptions) -> Topology:
    net = cfg.network
    rep = net.representation
    if net.graph_type == "1_gbit_switch":
        return Topology.builtin_1_gbit_switch(representation=rep)
    if net.graph_type == "gml":
        if net.graph_inline:
            return Topology.from_gml(net.graph_inline,
                                     net.use_shortest_path,
                                     representation=rep)
        if net.graph_file:
            with open(net.graph_file) as f:
                return Topology.from_gml(f.read(), net.use_shortest_path,
                                         representation=rep)
        raise ValueError("network.graph.type=gml needs file.path or inline")
    if net.graph_type == "star_clusters":
        from shadow_tpu.topology.generate import generate_star_clusters
        return generate_star_clusters(net.graph_params,
                                      net.use_shortest_path,
                                      representation=rep)
    raise ValueError(f"unknown graph type {net.graph_type!r}")


@dataclass
class BuiltSimulation:
    """Everything instantiated from a config, pre-run."""
    cfg: ConfigOptions
    topology: Topology
    hosts: list[Host]
    netmodel: NetworkModel
    starts: list[tuple[int, int, int]]   # (host_id, start, stop|-1)
    lookahead: int
    dns: object = None
    groups: dict = None                  # group name -> [host ids]
    runtime: object = None               # ManagedRuntime if real procs
    # fault injection (shadow_tpu/faults.py): the compiled link-fault
    # epoch table (None without link faults) and the validated
    # [(time, host_id, kind)] host crash/restart schedule
    fault_table: object = None
    host_faults: list = None
    # columnar builds only (host/plane.py): the HostPlane whose columns
    # DeviceRunner consumes directly; `hosts` is then a LazyHostList
    # view over it
    plane: object = None


# log one [build-heartbeat] line per this many hosts (only for builds
# big enough that silence reads as a hang)
_HEARTBEAT_MIN_HOSTS = 50_000


def _heartbeat(t_start: float, done: int, total: int) -> None:
    elapsed = time.monotonic() - t_start
    rate = done / elapsed if elapsed > 0 else 0.0
    eta = (total - done) / rate if rate > 0 else 0.0
    log.info("[build-heartbeat] %d/%d hosts in %.1fs "
             "(%.0f hosts/s, ETA %.1fs)", done, total, elapsed,
             rate, eta)


def build(cfg: ConfigOptions) -> BuiltSimulation:
    """Instantiate a config: columnar fast path (host/plane.py) for
    pure model-app device-policy runs, the per-host object loop for
    everything else. Both paths produce bit-identical simulations —
    the plane is a representation change, not a semantic one."""
    from shadow_tpu import faults as faultmod
    from shadow_tpu.host import plane as planemod
    from shadow_tpu.routing.dns import Dns

    topology = load_topology(cfg)
    # link faults compile into the epoch table HERE, at load time,
    # exactly like the base all-pairs matrices; host faults resolve
    # against the built host names further down
    link_events, host_events = faultmod.split_events(cfg.network.faults)
    fault_table = faultmod.compile_link_faults(topology, link_events)
    dns = Dns()
    reason = planemod.object_build_reason(cfg, topology)
    if reason is None:
        return _build_columnar(cfg, topology, dns, fault_table,
                               host_events)
    if cfg.ensemble is not None or \
            cfg.experimental.scheduler_policy == "tpu":
        # device policies WANT the fast path; a quiet fallback would
        # read as "columnar is slow" instead of "columnar was refused"
        log.warning("[host-plane] falling back to the object build: "
                    "%s", reason)
    return _build_objects(cfg, topology, dns, fault_table, host_events)


def _lookahead(cfg: ConfigOptions, netmodel: NetworkModel) -> int:
    # the lookahead window must be a static floor over every fault
    # epoch (netmodel.min_latency_ns is fault-aware) — all backends
    # consume this one value, so window sequences stay identical
    return (cfg.experimental.runahead
            if cfg.experimental.runahead is not None
            else netmodel.min_latency_ns)


def _build_columnar(cfg: ConfigOptions, topology: Topology, dns,
                    fault_table, host_events) -> BuiltSimulation:
    """O(groups) vectorized build: every per-host quantity is an array
    fill (strided arange attachment, broadcast bandwidths, one DNS
    block per group); Host objects materialize lazily off the plane."""
    from shadow_tpu import faults as faultmod
    from shadow_tpu.host import plane as planemod
    from shadow_tpu.models import make_app

    n_total = cfg.total_hosts()
    t_start = time.monotonic()
    records: list[planemod.PlaneGroup] = []
    groups: dict[str, range] = {}
    v_parts, d_parts, u_parts, ip_parts = [], [], [], []
    t0_parts, t1_parts = [], []
    base = 0
    for group in cfg.hosts:
        q = group.quantity
        if group.network_node_stride > 0:
            stride_base = topology.vertex_index_for_id(
                group.network_node_id)
            last = stride_base + (q - 1) * group.network_node_stride
            if last >= topology.n_vertices:
                raise ValueError(
                    f"hosts.{group.name}: network_node_stride walks "
                    f"past the topology (host {q - 1} "
                    f"would attach at vertex {last}, the graph has "
                    f"{topology.n_vertices})")
            v = stride_base + np.arange(q, dtype=np.int64) * \
                group.network_node_stride
        elif group.network_node_id is not None:
            v = np.full(q, topology.vertex_index_for_id(
                group.network_node_id), dtype=np.int64)
        else:
            # eligibility guarantees a 1-vertex graph here
            v = np.zeros(q, dtype=np.int64)
        d_parts.append(np.full(q, group.bandwidth_down, dtype=np.int64)
                       if group.bandwidth_down is not None
                       else topology.bw_down_bits[v].astype(np.int64))
        u_parts.append(np.full(q, group.bandwidth_up, dtype=np.int64)
                       if group.bandwidth_up is not None
                       else topology.bw_up_bits[v].astype(np.int64))
        v_parts.append(v)
        ip_parts.append(dns.register_block(base, group.name, q))
        proc = group.processes[0]
        stop = proc.stop_time if proc.stop_time is not None else -1
        records.append(planemod.PlaneGroup(
            name=group.name, base_id=base, count=q,
            pcap_directory=group.pcap_directory,
            path=proc.path, args=proc.args,
            start_time=proc.start_time, stop_time=stop,
            model=proc.path[len("model:"):],
            prototype=make_app(proc.path, proc.args, base, n_total)))
        groups[group.name] = range(base, base + q)
        t0_parts.append(np.full(q, proc.start_time, dtype=np.int64))
        t1_parts.append(np.full(q, stop, dtype=np.int64))
        base += q
        if n_total >= _HEARTBEAT_MIN_HOSTS:
            _heartbeat(t_start, base, n_total)

    def _cat(parts):
        return parts[0] if len(parts) == 1 else np.concatenate(parts)

    starts = planemod.StartColumns(_cat(t0_parts), _cat(t1_parts))
    plane = planemod.HostPlane(cfg, records, _cat(v_parts),
                               _cat(d_parts), _cat(u_parts),
                               _cat(ip_parts), starts)
    netmodel = NetworkModel(
        topology=topology,
        host_vertex=plane.vertex,
        seed=cfg.general.seed,
        bootstrap_end=cfg.general.bootstrap_end_time,
        faults=fault_table,
    )
    host_faults = faultmod.resolve_host_faults(host_events, plane.names)
    log.info("[host-plane] columnar build: %d hosts in %d groups, "
             "%.2fs", n_total, len(records),
             time.monotonic() - t_start)
    return BuiltSimulation(cfg=cfg, topology=topology,
                           hosts=planemod.LazyHostList(plane),
                           netmodel=netmodel, starts=starts,
                           lookahead=_lookahead(cfg, netmodel),
                           dns=dns, runtime=None, groups=groups,
                           fault_table=fault_table,
                           host_faults=host_faults, plane=plane)


def _build_objects(cfg: ConfigOptions, topology: Topology, dns,
                   fault_table, host_events) -> BuiltSimulation:
    from shadow_tpu import faults as faultmod
    from shadow_tpu.host.cpu import Cpu
    from shadow_tpu.routing.address import Address

    root_rng = SeededRandom(cfg.general.seed)
    attacher = Attacher(topology, root_rng.child("attach"))

    hosts: list[Host] = []
    starts: list[tuple[int, int, int]] = []
    groups: dict[str, list[int]] = {}
    runtime = None
    n_total = cfg.total_hosts()
    t_start = time.monotonic()
    beat_every = max(10_000, n_total // 20)
    for group in cfg.hosts:
        # network_node_stride: host i of the group attaches at vertex
        # index base + i*stride — resolved ONCE per group (the id
        # lookup is an O(V) scan; a million strided hosts must not
        # pay it a million times)
        stride_base = None
        if group.network_node_stride > 0:
            stride_base = topology.vertex_index_for_id(
                group.network_node_id)
            last = stride_base + \
                (group.quantity - 1) * group.network_node_stride
            if last >= topology.n_vertices:
                raise ValueError(
                    f"hosts.{group.name}: network_node_stride walks "
                    f"past the topology (host {group.quantity - 1} "
                    f"would attach at vertex {last}, the graph has "
                    f"{topology.n_vertices})")
        members = groups.setdefault(group.name, [])
        # bulk DNS for model-only groups: one vectorized block
        # allocation instead of `quantity` Address constructions and
        # 3x that many dict inserts (hint-less groups only — a
        # requested IP needs the scalar path's validity checks)
        block_ips = None
        if group.quantity > 1 and not group.ip_address_hint and \
                all(is_model_path(p.path) for p in group.processes):
            block_ips = dns.register_block(len(hosts), group.name,
                                           group.quantity)
        for i in range(group.quantity):
            name = group.name if group.quantity == 1 else f"{group.name}{i}"
            host_id = len(hosts)
            members.append(host_id)
            if stride_base is not None:
                v = stride_base + i * group.network_node_stride
                att = HostAttachment(
                    vertex=v,
                    bw_down_bits=(group.bandwidth_down
                                  if group.bandwidth_down is not None
                                  else int(topology.bw_down_bits[v])),
                    bw_up_bits=(group.bandwidth_up
                                if group.bandwidth_up is not None
                                else int(topology.bw_up_bits[v])))
            else:
                att = attacher.attach(
                    network_node_id=group.network_node_id,
                    ip_hint=group.ip_address_hint,
                    city_hint=group.city_code_hint,
                    country_hint=group.country_code_hint,
                    bw_down_override=group.bandwidth_down,
                    bw_up_override=group.bandwidth_up,
                )
            host = Host(host_id=host_id, name=name, vertex=att.vertex,
                        bw_down_bits=att.bw_down_bits,
                        bw_up_bits=att.bw_up_bits,
                        rng=root_rng.child(f"host:{name}"),
                        pcap_directory=group.pcap_directory)
            host.cpu = Cpu()
            if cfg.experimental.model_bandwidth:
                from shadow_tpu.host.model_nic import ModelNic
                host.model_nic = ModelNic(att.bw_up_bits,
                                          att.bw_down_bits)
            if block_ips is not None:
                host.address = Address(host_id=host_id, name=name,
                                       ip=int(block_ips[i]))
            else:
                host.address = dns.register(
                    host_id, name, requested_ip=group.ip_address_hint)
            host.ip = host.address.ip_str
            for proc in group.processes:
                for _ in range(proc.quantity):
                    app = None
                    factory = None   # respawn closure (host_restart)
                    if is_model_path(proc.path):
                        # packet/timer events dispatch to the host's
                        # single model app; real processes are driven
                        # by their syscalls instead, so any number of
                        # those can share the host
                        if any(not hasattr(a, "vpid")
                               for a in host.apps):
                            raise ValueError(
                                f"host {name}: at most one model app "
                                "per host (any number of real "
                                "processes)")
                        app = make_app(proc.path, proc.args,
                                       host_id, n_total)
                        factory = (lambda p=proc.path, a=proc.args,
                                   hid=host_id, n=n_total:
                                   make_app(p, a, hid, n))
                    else:
                        # real executable under syscall interposition
                        import shutil

                        from shadow_tpu.host.process import (
                            ManagedProcess,
                            ManagedRuntime,
                        )
                        if runtime is None:
                            runtime = ManagedRuntime(
                                dns, cfg.general.data_directory,
                                cfg.general.seed,
                                spin_max=cfg.experimental
                                .preload_spin_max)
                        path = proc.path
                        if "/" not in path:
                            path = shutil.which(path) or path
                        path = os.path.abspath(path)
                        if not os.path.exists(path):
                            raise ValueError(
                                f"process executable not found: "
                                f"{proc.path!r}")
                        from shadow_tpu.host.process import \
                            elf_is_static
                        use_ptrace = \
                            cfg.experimental.interpose_method == \
                            "ptrace"
                        if not use_ptrace and elf_is_static(path):
                            # LD_PRELOAD cannot enter a static binary;
                            # the ptrace backend interposes it fully
                            # (every syscall traps, vDSO patched)
                            log.info("%s is statically linked: using "
                                     "the ptrace backend (the preload "
                                     "shim cannot load)", path)
                            use_ptrace = True
                        if use_ptrace:
                            from shadow_tpu.host.ptrace import (
                                PtraceProcess,
                            )
                            app = PtraceProcess(
                                runtime, path, proc.args,
                                proc.environment)
                            factory = (lambda cls=PtraceProcess,
                                       rt=runtime, p=path,
                                       a=proc.args,
                                       e=proc.environment:
                                       cls(rt, p, a, e))
                        else:
                            app = ManagedProcess(
                                runtime, path, proc.args,
                                proc.environment)
                            factory = (lambda cls=ManagedProcess,
                                       rt=runtime, p=path,
                                       a=proc.args,
                                       e=proc.environment:
                                       cls(rt, p, a, e))
                    proc_idx = len(host.apps)
                    host.apps.append(app)
                    if host.respawn is None:
                        host.respawn = []
                    host.respawn.append(
                        (factory, proc.start_time,
                         proc.stop_time if proc.stop_time is not None
                         else -1,
                         is_model_path(proc.path)))
                    # the model app (at most one) is ALWAYS the
                    # packet/timer dispatch target, regardless of its
                    # position in the process list; otherwise the
                    # first process stands in
                    if is_model_path(proc.path) or host.app is None:
                        host.app = app
                    starts.append((host_id, proc.start_time,
                                   proc.stop_time
                                   if proc.stop_time is not None else -1,
                                   proc_idx))
            hosts.append(host)
            if n_total >= _HEARTBEAT_MIN_HOSTS and \
                    len(hosts) % beat_every == 0:
                _heartbeat(t_start, len(hosts), n_total)

    netmodel = NetworkModel(
        topology=topology,
        host_vertex=np.array([h.vertex for h in hosts], dtype=np.int64),
        seed=cfg.general.seed,
        bootstrap_end=cfg.general.bootstrap_end_time,
        faults=fault_table,
    )
    host_faults = faultmod.resolve_host_faults(
        host_events, {h.name: h.host_id for h in hosts})
    lookahead = _lookahead(cfg, netmodel)
    if runtime is not None:
        # managed processes resolve names against this file
        # (dns.c's /etc/hosts-style emission)
        os.makedirs(cfg.general.data_directory, exist_ok=True)
        dns.write_hosts_file(os.path.join(cfg.general.data_directory,
                                          "etc_hosts"))
    return BuiltSimulation(cfg=cfg, topology=topology, hosts=hosts,
                           netmodel=netmodel, starts=starts,
                           lookahead=lookahead, dns=dns, runtime=runtime,
                           groups=groups, fault_table=fault_table,
                           host_faults=host_faults)


class Controller:
    def __init__(self, cfg: ConfigOptions, trace: Optional[list] = None,
                 tracer=None):
        self.cfg = cfg
        # flight recorder (shadow_tpu/obs): ONE per run, attached to
        # whichever executor this config resolves to and published as
        # the module-global current() for call sites with no plumbing
        # path (aotcache.ensure, capacity record I/O, engine.profile).
        # A nested run (the hybrid failover rerun) receives its
        # parent's tracer instead, so the rerun's spans land in the
        # SAME trace under the parent's `failover` span — the parent
        # finalizes, the child must not. Resolved BEFORE build so the
        # boot wall lands in the trace's `plan` phase.
        from shadow_tpu.obs import trace as obstrace
        self._owns_tracer = tracer is None
        self.tracer = (tracer if tracer is not None
                       else obstrace.resolve_tracer(cfg,
                                                    cfg.total_hosts()))
        obstrace.set_current(self.tracer)
        with self.tracer.span("build", "plan",
                              n_hosts=cfg.total_hosts()):
            self.sim = build(cfg)
        policy_name = cfg.experimental.scheduler_policy
        self.runner = None
        self.manager = None
        net_judge = None
        if cfg.ensemble is not None:
            # R-replica campaign in one vmapped device program
            # (shadow_tpu/ensemble/). No hybrid fallback: CPU host
            # emulation cannot vmap, so a config whose apps lack a
            # device twin fails loudly rather than silently running
            # one replica.
            from shadow_tpu.device.runner import NoDeviceTwin
            from shadow_tpu.ensemble.campaign import EnsembleRunner
            try:
                self.runner = EnsembleRunner(self.sim, trace=trace)
                self.runner.tracer = self.tracer
                return
            except NoDeviceTwin as e:
                raise ValueError(
                    "ensemble: the config's apps have no fully-"
                    f"vectorized device twin ({e}) — campaigns "
                    "cannot fall back to hybrid CPU emulation; run "
                    "the replicas as separate processes instead"
                ) from e
        if policy_name == "tpu":
            from shadow_tpu.device.runner import DeviceRunner, NoDeviceTwin
            try:
                self.runner = DeviceRunner(self.sim, trace=trace)
                self.runner.tracer = self.tracer
                return
            except NoDeviceTwin as e:
                log.info("tpu policy -> hybrid: %s", e)
                if cfg.experimental.capacity_plan != "static":
                    # the schema rejects capacity_plan on CPU policies
                    # for exactly this silent-ignore hazard; the
                    # fallback must not hide it either
                    log.warning(
                        "capacity_plan: %s ignored — the hybrid "
                        "fallback's CPU host emulation has no static "
                        "capacities to plan",
                        cfg.experimental.capacity_plan)
                if cfg.experimental.chaos:
                    # the schema's fail-fast rule for fault schedules
                    # must survive the fallback too: a chaos drill
                    # that silently injects nothing would read as a
                    # green failover test that drilled nothing
                    log.warning(
                        "experimental.chaos ignored — the hybrid "
                        "fallback has no device dispatch/checkpoint/"
                        "cache seams to inject at; this run drills "
                        "NOTHING")
                if cfg.experimental.mesh_shards:
                    log.warning(
                        "experimental.mesh_shards=%d ignored — the "
                        "hybrid fallback's CPU host emulation has "
                        "no device mesh to pin",
                        cfg.experimental.mesh_shards)
                policy_name = "hybrid"
        self.strategy_plan = None
        if policy_name == "hybrid":
            # strategy-plan adoption for the hybrid path
            # (tune/plan.py): the judge batching knob is the plan
            # space's hybrid member, so hybrid runs need an adoption
            # path too. The plan identity is the device twin's
            # workload fingerprint — a config without one (the
            # NoDeviceTwin fallback's usual cause) has no plan to
            # match and skips with a log line. policy="hybrid" makes
            # the gates see the policy actually running, not the
            # config's pre-fallback `tpu`.
            if cfg.experimental.strategy_plan != "off":
                from shadow_tpu.device.runner import (
                    NoDeviceTwin,
                    device_twin,
                )
                from shadow_tpu.tune import plan as planmod
                try:
                    twin = device_twin(self.sim)
                    self.strategy_plan = planmod.adopt(
                        cfg, twin, len(self.sim.hosts),
                        policy="hybrid")
                except NoDeviceTwin as e:
                    log.info("strategy_plan: no device twin to "
                             "fingerprint this workload (%s) — no "
                             "plan adopted", e)
            # CPU host emulation + batched device network judgment
            # (worker.c:520-579's hot path on the accelerator)
            from shadow_tpu.device.judge import DeviceJudge
            net_judge = DeviceJudge(
                self.sim.topology,
                self.sim.netmodel.host_vertex,
                cfg.general.seed,
                bootstrap_end=cfg.general.bootstrap_end_time,
                min_batch=cfg.experimental.hybrid_judge_min_batch,
                fault_table=self.sim.fault_table)
            policy_name = cfg.experimental.hybrid_cpu_policy
        if self.sim.plane is not None:
            # a CPU-policy backend reached a columnar sim (the
            # NoDeviceTwin hybrid fallback): the Manager touches every
            # host per event, so lazy materialization buys nothing —
            # materialize the whole table once, up front
            log.info("[host-plane] CPU backend %r: materializing all "
                     "%d hosts", policy_name, len(self.sim.hosts))
            self.sim.hosts = list(self.sim.hosts)
        from shadow_tpu.core.manager import NetOptions
        self.manager = Manager(
            tracer=self.tracer,
            hosts=self.sim.hosts,
            policy=make_policy(policy_name,
                               n_workers=(cfg.experimental.workers
                                          or cfg.general.parallelism),
                               parallelism=cfg.general.parallelism,
                               pin_cpus=cfg.experimental
                               .use_cpu_pinning),
            netmodel=self.sim.netmodel,
            seed=cfg.general.seed,
            trace=trace,
            groups=self.sim.groups,
            net_judge=net_judge,
            net_opts=NetOptions(
                qdisc=cfg.experimental.interface_qdisc,
                router_queue=cfg.experimental.router_queue,
                router_static_capacity=cfg.experimental
                .router_static_capacity,
                bootstrap_end=cfg.general.bootstrap_end_time,
                tcp_congestion=cfg.experimental.tcp_congestion,
                tcp_recv_buffer=cfg.experimental.socket_recv_buffer,
                tcp_send_buffer=cfg.experimental.socket_send_buffer,
                tcp_recv_autotune=cfg.experimental
                .socket_recv_autotune,
                tcp_send_autotune=cfg.experimental
                .socket_send_autotune,
            ),
        )

    def _failover_run(self, exc) -> SimStats:
        """The failover ladder's hybrid rung (failover: hybrid, or
        shrink when no shrink was possible) — finish the run on the
        hybrid backend (CPU host emulation + device network judge)
        instead of aborting. CPU host state cannot be rebuilt from
        device arrays, so the hybrid run replays from t=0; the last
        validated device checkpoint stays on disk to pin a
        device-side resume once the accelerator returns. Determinism
        makes the replayed results bit-identical to what the device
        run would have produced. The rerun shares THIS run's flight
        recorder under a `failover` span, so the whole incident —
        device prefix, escalation, hybrid replay — reads off one
        timeline."""
        import copy

        if exc.checkpoint_path is None:
            # the ONE diagnostic for the persist failure: the
            # escalation could save no state at all, so the hybrid
            # rerun has no device-side resume point — previously this
            # path silently dropped the failover and re-raised
            log.error(
                "DEVICE FAILOVER: %s — no device checkpoint could be "
                "persisted (%s); re-running on the hybrid backend "
                "from t=0 with NO device-side resume point.", exc,
                exc.persist_error or "unknown persist error")
        else:
            log.error(
                "DEVICE FAILOVER: %s — re-running on the hybrid "
                "backend from t=0 (device state is not importable "
                "into CPU hosts; the prefix up to t=%d ns is "
                "replayed). The validated device checkpoint %s "
                "remains for a device-side resume.", exc,
                exc.sim_time, exc.checkpoint_path or "<none>")
        cfg2 = copy.deepcopy(self.cfg)
        xp = cfg2.experimental
        xp.scheduler_policy = "hybrid"
        # supervision/planning/chaos knobs are device-only; the schema
        # would reject them on a CPU policy, and the hybrid replay
        # must not try to checkpoint, re-plan, or re-inject
        xp.checkpoint_save = ""
        xp.checkpoint_save_time = 0
        xp.checkpoint_load = ""
        xp.checkpoint_every = 0
        xp.capacity_plan = "static"
        xp.capacity_warmup = 0
        xp.state_audit = False
        xp.dispatch_retries = 0
        xp.failover = "abort"
        xp.chaos = []
        xp.mesh_shards = 0
        with self.tracer.span("failover.hybrid_rerun", "failover",
                              sim_t0=exc.sim_time,
                              checkpoint=exc.checkpoint_path or "",
                              error=str(exc)[:200]):
            inner = Controller(cfg2, tracer=self.tracer)
            stats = inner.run()
        stats.failover_checkpoint = exc.checkpoint_path or ""
        # reflect the replayed per-host results onto THIS sim's hosts:
        # anything reading c.sim.hosts after the run (the determinism
        # gate's signature path, summary tooling) must see the real
        # counters, not the abandoned device run's zeros
        for mine, theirs in zip(self.sim.hosts, inner.sim.hosts):
            mine.events_executed = theirs.events_executed
            mine.packets_sent = theirs.packets_sent
            mine.packets_dropped = theirs.packets_dropped
            mine.packets_delivered = theirs.packets_delivered
            mine.trace_checksum = theirs.trace_checksum
        return stats

    def run(self) -> SimStats:
        """Run to stop_time. The flight recorder finalizes on EVERY
        exit path — success, failover, or a raised error — so a
        failed run still leaves its trace artifacts (the post-mortem
        is most valuable exactly then), and the summary lands on
        SimStats.telemetry for bench/tooling."""
        stats = None
        try:
            stats = self._run_inner()
            return stats
        finally:
            counters = None
            if stats is not None:
                counters = {"events": stats.events_executed,
                            "packets": stats.packets_sent,
                            "rounds": stats.rounds,
                            "retries": stats.retries,
                            "replans": stats.replans}
                if stats.reshards:
                    # the shrink's degradation cost is a first-class
                    # observable: the count rides the METRICS
                    # counters, the wall rides the reshard phase
                    counters["reshards"] = stats.reshards
                if stats.pipeline:
                    # the METRICS record's overlap-efficiency line:
                    # depth, issue/drain counts, sync wall, and the
                    # host wall hidden behind in-flight device work
                    counters["pipeline"] = dict(stats.pipeline)
            # a nested run (the hybrid failover rerun shares its
            # parent's tracer) must NOT finalize: the parent closes
            # the recorder once for the whole incident timeline and
            # publishes the combined summary onto these stats
            if self._owns_tracer:
                summary = self.tracer.finalize(
                    run_info={
                        "policy": self.cfg.experimental
                        .scheduler_policy,
                        "n_hosts": len(self.sim.hosts),
                        "stop_time": int(self.cfg.general.stop_time),
                        "seed": int(self.cfg.general.seed),
                        "representation": self.sim.topology
                        .representation},
                    counters=counters)
                if stats is not None and summary is not None and \
                        stats.telemetry is None:
                    stats.telemetry = summary

    def _run_inner(self) -> SimStats:
        cfg = self.cfg
        stop = cfg.general.stop_time
        if self.runner is not None:
            from shadow_tpu.device.supervise import DeviceFailover
            try:
                stats = self.runner.run(stop)
            except DeviceFailover as e:
                return self._failover_run(e)
            if stats.preempted:
                log.warning(
                    "run preempted at %s: resume checkpoint %s "
                    "(set experimental.checkpoint_load to continue)",
                    simtime.format_time(stats.end_time),
                    stats.resume_path)
            if stats.retries:
                log.warning("run absorbed %d transient device "
                            "dispatch retr%s", stats.retries,
                            "y" if stats.retries == 1 else "ies")
            if stats.reshards:
                log.warning(
                    "run absorbed %d mesh shrink(s): device loss "
                    "survived on-device — the mesh now runs %d "
                    "shard(s), results bit-identical, throughput "
                    "degraded by the lost share", stats.reshards,
                    self.runner.engine.n_shards)
            if stats.ensemble is not None:
                rec = stats.ensemble
                log.info(
                    "ensemble campaign %s: %d replicas, "
                    "packets_sent aggregates %s",
                    rec["campaign"], rec["workload"]["replicas"],
                    {k: round(v, 1) for k, v in
                     rec["aggregates"]["packets_sent"].items()})
            occ = stats.occupancy
            if occ is not None and "planned" in occ:
                # one-line audit of the adaptive plan: what it chose
                # vs the static knobs, and whether it held first try
                log.info(
                    "capacity plan (%s): %s  [static %s, %d replan%s]",
                    cfg.experimental.capacity_plan, occ["planned"],
                    occ["static"], stats.replans,
                    "" if stats.replans == 1 else "s")
            return stats

        m = self.manager
        m.boot_hosts(self.sim.starts)
        if self.sim.host_faults:
            m.schedule_host_faults(self.sim.host_faults)
        if cfg.general.heartbeat_interval:
            m.schedule_heartbeats(cfg.general.heartbeat_interval, stop)
        lookahead = max(1, self.sim.lookahead)
        log.info("starting: %d hosts, stop=%s, lookahead=%s",
                 len(self.sim.hosts), simtime.format_time(stop),
                 simtime.format_time(lookahead))

        watchdog = None
        if cfg.experimental.round_watchdog:
            from shadow_tpu.core.manager import RoundWatchdog
            watchdog = RoundWatchdog(
                m, cfg.experimental.round_watchdog,
                dump_path=cfg.experimental.round_watchdog_dump)
            watchdog.start()
        try:
            next_time = m.policy.next_event_time()
            while next_time < stop:
                window_end = min(next_time + lookahead, stop)
                next_time = m.run_window(next_time, window_end)

            if self.sim.runtime is not None:
                # kill surviving managed processes (forked children
                # die with their parents), release the arena. Inside
                # the watchdog's try: its SIGINT may land just after
                # the loop exits (progress resumed between the sample
                # and the signal), and that window must surface the
                # same diagnostic, not a bare ^C traceback mid-
                # teardown
                ctx = m._ctx
                ctx.now = stop
                for h in m.hosts:
                    for app in (h.apps or [h.app]):
                        if app is not None and \
                                hasattr(app, "on_sim_end"):
                            ctx.host = h
                            app.on_sim_end(ctx)
                self.sim.runtime.close()
        except KeyboardInterrupt:
            if watchdog is None or not watchdog.fired:
                raise
            # the watchdog aborted a stalled round: surface a
            # diagnostic error, not a bare ^C traceback
            raise RuntimeError(
                "simulation aborted by the round watchdog (no "
                "scheduling progress for "
                f"{cfg.experimental.round_watchdog}s wall — see the "
                "per-host state dump in the log)") from None
        finally:
            if watchdog is not None:
                watchdog.stop()
        m.finalize()
        m.stats.end_time = stop
        m.stats.strategy_plan = self.strategy_plan
        if m.net_judge is not None:
            j = m.net_judge
            log.info("hybrid perf: %d packets judged on device in %d "
                     "batches (%.1f pkts/batch); %d packets in %d "
                     "sub-threshold rounds stayed on the CPU "
                     "(min_batch=%d)", j.packets, j.batches,
                     j.packets / j.batches if j.batches else 0.0,
                     j.cpu_packets, j.cpu_batches, j.min_batch)
        return m.stats
