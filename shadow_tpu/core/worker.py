"""Worker execution context.

The analogue of the reference's thread-local Worker
(src/main/core/worker.c / worker.rs): tracks the active host and clock
during event execution and provides the APIs host code uses to push new
work — here the ModelApp-facing SimContext. `send` is the
worker_sendPacket twin (worker.c:520-579) routed through the
NetworkModel; `schedule` is task scheduling on the active host.
"""

from __future__ import annotations

from typing import Optional

from shadow_tpu import simtime
from shadow_tpu.core.event import Event, KIND_PACKET, KIND_TIMER
from shadow_tpu.host.host import Host
from shadow_tpu.utils import nprng
from shadow_tpu.utils.rng import PURPOSE_APP


class SimContext:
    """Passed to ModelApp hooks; valid only during one event execution."""

    def __init__(self, manager, stats):
        self._m = manager
        self._stats = stats
        self.now: int = simtime.SIMTIME_INVALID
        self.host: Optional[Host] = None

    # -- identity ------------------------------------------------------
    @property
    def host_id(self) -> int:
        return self.host.host_id

    @property
    def n_hosts(self) -> int:
        return len(self._m.hosts)

    def resolve(self, name: str) -> int:
        """Hostname or group reference -> host id (DNS-lite; full DNS
        in host/dns.py). Group refs pick a member keyed by the asking
        host (manager.resolve_ref)."""
        return self._m.resolve_ref(name, self.host.host_id)

    # -- randomness ----------------------------------------------------
    def app_bits(self) -> int:
        """32 deterministic random bits keyed by (APP, host, draw#) —
        identical on CPU and device engines."""
        seq = self.host.next_app_seq()
        key = nprng.fold_in(
            nprng.fold_in(
                nprng.fold_in(self._m.rng_key, PURPOSE_APP),
                self.host.host_id),
            seq)
        return int(nprng.random_bits32(key))

    def pure_bits(self, purpose: int, a: int, b: int) -> int:
        """32 deterministic bits from a STATELESS key (purpose, a, b) —
        no per-host draw counter consumed, so any host can recompute
        the same value (e.g. an onion route as a pure function of the
        client id). Identical on CPU and device engines."""
        key = nprng.fold_in(
            nprng.fold_in(
                nprng.fold_in(self._m.rng_key, purpose), a), b)
        return int(nprng.random_bits32(key))

    def app_uniform(self) -> float:
        seq = self.host.next_app_seq()
        key = nprng.fold_in(
            nprng.fold_in(
                nprng.fold_in(self._m.rng_key, PURPOSE_APP),
                self.host.host_id),
            seq)
        return float(nprng.uniform01(key))

    # -- event generation ---------------------------------------------
    def send(self, dst_host: int, size: int, data: tuple = ()) -> bool:
        """Send a packet through the network model. Returns False if the
        drop roll discarded it (the caller — like a real app — cannot
        observe this directly; returned for stats/tests only). In
        hybrid mode cross-host judgments are deferred to the round's
        device batch, so the verdict is not yet known and True is
        returned unconditionally — apps must not branch on it."""
        host = self.host
        pkt_seq = host.next_packet_seq()
        # the event seq is consumed for every send, delivered or not, so
        # the network judgment can be deferred (batched to the device in
        # hybrid mode) without perturbing any later seq allocation
        ev_seq = host.next_event_seq()
        if host.model_nic is not None:
            # bandwidth-modeled raw send: serialize on the TX bucket,
            # drop-gate at the SEND time (device parity), arrive at
            # depart+latency. Judged synchronously even in hybrid mode
            # (the TX state is inherently sequential per host).
            depart = host.model_nic.tx_depart(self.now, size)
            verdict = self._m.netmodel.judge(self.now, host.host_id,
                                             dst_host, pkt_seq)
            host.packets_sent += 1
            if not verdict.delivered:
                host.packets_dropped += 1
                return False
            ev = Event(time=depart + verdict.latency_ns,
                       dst_host=dst_host, src_host=host.host_id,
                       seq=ev_seq, kind=KIND_PACKET,
                       data=(size,) + tuple(data))
            self._m.push_event(ev)
            return True
        if self._m.net_judge is not None:
            self._m.defer_judgment(self.now, host, dst_host, pkt_seq,
                                   ev_seq, KIND_PACKET,
                                   (size,) + tuple(data))
            return True
        verdict = self._m.netmodel.judge(self.now, host.host_id, dst_host,
                                         pkt_seq)
        # per-host counters are the single source of truth for packet
        # totals (Manager.finalize sums them)
        host.packets_sent += 1
        if not verdict.delivered:
            host.packets_dropped += 1
            return False
        ev = Event(time=verdict.deliver_time, dst_host=dst_host,
                   src_host=host.host_id, seq=ev_seq,
                   kind=KIND_PACKET, data=(size,) + tuple(data))
        self._m.push_event(ev)
        return True

    def send_train(self, dst_host: int, size: int, data: tuple = (),
                   count: int = 1, mask: Optional[int] = None) -> int:
        """Send `count` packets as ONE train event (a tgen chunk):
        one event/one delivery, per-packet drop rolls with the same
        keys individual sends would use. The delivered event's data is
        (size, *data, survivor_bitmask). Returns the survivor mask (0
        = whole train lost); like send(), apps must not branch on it.
        Trains are the standard DES optimization for bulk flows: the
        event count per chunk drops from `count` to 1 on both engines
        while loss statistics stay bit-identical.

        `mask`: forwarding a previous hop's survivors — only its set
        bits are real packets (sent/dropped/rolled into the result);
        seq consumption and roll keys still span all `count` lanes so
        the device twin's lane math lines up exactly.

        Trains are judged synchronously even under hybrid mode's
        deferred (device-batched) judgment — the verdict is a pure
        function of stable keys, so results are identical; deferral is
        a batching optimization for per-packet send() traffic."""
        count = max(1, count)
        live = (1 << count) - 1 if mask is None \
            else mask & ((1 << count) - 1)
        host = self.host
        pkt_seq0 = host._packet_seq
        host._packet_seq += count
        ev_seq = host.next_event_seq()
        surv, deliver, lat = self._m.netmodel.judge_train(
            self.now, host.host_id, dst_host, pkt_seq0, count,
            live=live.bit_count())
        surv &= live
        host.packets_sent += live.bit_count()
        host.packets_dropped += live.bit_count() - surv.bit_count()
        if host.model_nic is not None:
            # dropped trains still consume uplink serialization (the
            # network drops them later) — device-engine parity
            depart = host.model_nic.tx_depart(self.now, size)
            deliver = depart + lat
        if surv == 0:
            return 0
        ev = Event(time=deliver, dst_host=dst_host,
                   src_host=host.host_id, seq=ev_seq,
                   kind=KIND_PACKET, data=(size,) + tuple(data)
                   + (surv,), npkts=surv.bit_count())
        self._m.push_event(ev)
        return surv

    def schedule(self, delay_ns: int, data: tuple = ()) -> None:
        """Self timer after delay_ns -> on_timer."""
        host = self.host
        ev = Event(time=self.now + max(0, delay_ns),
                   dst_host=host.host_id, src_host=host.host_id,
                   seq=host.next_event_seq(), kind=KIND_TIMER,
                   data=tuple(data))
        self._m.push_event(ev)

    # -- socket API (CPU fidelity path: NIC token buckets, router
    # queues, in-simulator TCP/UDP — see shadow_tpu/host/netstack.py) --
    def tcp_connect(self, dst_host: int, dst_port: int,
                    on_connected=None, on_data=None, on_closed=None):
        self.host.net.ctx = self
        return self.host.net.tcp_connect(self.now, dst_host, dst_port,
                                         on_connected=on_connected,
                                         on_data=on_data,
                                         on_closed=on_closed)

    def tcp_listen(self, port: int, on_accept=None, on_data=None,
                   on_closed=None):
        self.host.net.ctx = self
        return self.host.net.tcp_listen(port, on_accept=on_accept,
                                        on_data=on_data,
                                        on_closed=on_closed)

    def udp_socket(self, port=None, on_datagram=None):
        self.host.net.ctx = self
        return self.host.net.udp_socket(port, on_datagram=on_datagram)

    def consume_cpu(self, native_ns: int) -> None:
        """Model synthetic CPU load: subsequent events on this host are
        delayed while the virtual CPU works off the backlog
        (cpu.c cpu_addDelay; phold's cpuload knob)."""
        if self.host.cpu is not None:
            self.host.cpu.add_delay(native_ns)
