"""The inter-host network model (CPU reference implementation).

This is the semantic twin of the hot path in the reference's
worker_sendPacket (src/main/core/worker.c:520-579):

    reliability lookup -> random drop roll -> latency lookup ->
    schedule delivery event on the destination host

but expressed as a pure function over precomputed topology matrices and
the counter RNG, so the device engine (shadow_tpu/device/engine.py) can
run the *identical* computation as batched gathers, and traces match
bit-for-bit between the two engines.

Drop rule: a packet from src with per-source sequence number `pkt_seq`
is dropped iff reliability < 1 and
    uniform01(fold(seed, DROP, src_host, pkt_seq)) >= reliability.
During the bootstrap phase packets are never dropped (the reference
skips drops while bootstrapping so initial connections always form).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field

import numpy as np

from shadow_tpu.topology.graph import Topology
from shadow_tpu.utils import nprng
from shadow_tpu.utils.rng import PURPOSE_PACKET_DROP


@dataclass
class PacketVerdict:
    delivered: bool
    deliver_time: int      # sim ns (valid when delivered)
    latency_ns: int


@dataclass
class NetworkModel:
    topology: Topology
    host_vertex: np.ndarray        # [H] vertex index per host
    seed: int
    bootstrap_end: int = 0
    # compiled link-fault schedule (shadow_tpu/faults.py FaultTable);
    # None = the static base matrices. The lookup is keyed by the
    # packet's SEND time — the same key every device backend uses —
    # so traces stay bit-identical across engines under faults.
    faults: object = None
    # per-path packet counters (topology_incrementPathPacketCounter
    # analogue), aggregated per (src_vertex, dst_vertex); judged from
    # multiple worker threads under threaded policies
    path_packets: dict = field(default_factory=dict)
    _lock: threading.Lock = field(default_factory=threading.Lock)

    @property
    def min_latency_ns(self) -> int:
        if self.faults is not None:
            # conservative across every fault epoch (a degrade only
            # raises latency, but the lookahead must be a static floor)
            return min(self.topology.min_latency_ns,
                       self.faults.min_latency_ns)
        return self.topology.min_latency_ns

    def _path(self, now: int, sv: int, dv: int) -> tuple[int, float]:
        """(latency_ns, reliability) of the path sv->dv at send time
        `now` — the single lookup both judge paths share, epoch-aware
        under a fault schedule."""
        if self.faults is not None:
            return self.faults.lookup(now, sv, dv)
        if self.topology.hier is not None:
            # hierarchical representation: two-level factored lookup
            return self.topology.hier.lookup(sv, dv)
        return (int(self.topology.latency_ns[sv, dv]),
                float(self.topology.reliability[sv, dv]))

    def record_paths(self, counts: dict) -> None:
        """Merge a batch of per-(src_vertex, dst_vertex) packet counts
        (one lock take per batch; the hybrid flush path)."""
        with self._lock:
            for key, n in counts.items():
                self.path_packets[key] = self.path_packets.get(key, 0) + n

    def judge_train(self, now: int, src_host: int, dst_host: int,
                    pkt_seq0: int, count: int,
                    live: int = -1) -> tuple[int, int, int]:
        """Judge a packet TRAIN (count packets sharing one path and
        send instant, e.g. a tgen chunk): per-packet drop rolls with
        the same (src, pkt_seq0+j) keys individual sends would use, so
        loss statistics are bit-identical to per-packet sends. Returns
        (survivor_bitmask, deliver_time, latency_ns); bit j set means
        packet pkt_seq0+j survived. `live` (< 0 = count) is the number
        of lanes that actually carry packets (a masked forward) — the
        path histogram counts only those, matching the device twin."""
        # numpy uint64 shifts are undefined past 63 and would corrupt
        # the survivor mask silently — fail loudly instead
        assert count <= 64, \
            f"judge_train count={count} exceeds the 64-bit mask"
        sv = int(self.host_vertex[src_host])
        dv = int(self.host_vertex[dst_host])
        latency, reliability = self._path(now, sv, dv)

        surv = (1 << count) - 1
        if reliability < 1.0 and now >= self.bootstrap_end:
            rolls = nprng.packet_uniform(
                self.seed, PURPOSE_PACKET_DROP, src_host,
                np.arange(pkt_seq0, pkt_seq0 + count))
            bits = (rolls < reliability).astype(np.uint64)
            surv = int((bits << np.arange(count, dtype=np.uint64))
                       .sum())
        key = (sv, dv)
        with self._lock:
            self.path_packets[key] = self.path_packets.get(key, 0) \
                + (count if live < 0 else live)
        return surv, now + latency, latency

    def judge(self, now: int, src_host: int, dst_host: int,
              pkt_seq: int) -> PacketVerdict:
        sv = int(self.host_vertex[src_host])
        dv = int(self.host_vertex[dst_host])
        latency, reliability = self._path(now, sv, dv)

        delivered = True
        if reliability < 1.0 and now >= self.bootstrap_end:
            roll = float(nprng.packet_uniform(
                self.seed, PURPOSE_PACKET_DROP, src_host, pkt_seq))
            delivered = roll < reliability

        key = (sv, dv)
        with self._lock:
            self.path_packets[key] = self.path_packets.get(key, 0) + 1
        return PacketVerdict(delivered=delivered,
                             deliver_time=now + latency,
                             latency_ns=latency)
