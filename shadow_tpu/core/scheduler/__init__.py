from shadow_tpu.core.scheduler.base import SchedulerPolicy
from shadow_tpu.core.scheduler.serial import SerialPolicy

__all__ = ["SchedulerPolicy", "SerialPolicy", "make_policy"]


def make_policy(name: str, n_workers: int = 0, parallelism: int = 0,
                pin_cpus: bool = False) -> SchedulerPolicy:
    """Policy factory (scheduler_policy_type.h analogue). The five CPU
    policies of the reference map onto our thread-pool policies; `serial`
    is the single-threaded oracle and `tpu` is handled by the device
    engine (core/manager.py selects it before reaching here).
    `parallelism` caps concurrently-running workers (the
    LogicalProcessors layer); `pin_cpus` applies the affinity module's
    placement to the LP threads."""
    if name == "serial":
        return SerialPolicy()
    if name in ("host", "steal", "thread", "threadXthread", "threadXhost"):
        from shadow_tpu.core.scheduler.threads import ThreadedPolicy
        return ThreadedPolicy(kind=name, n_workers=n_workers,
                              parallelism=parallelism,
                              pin_cpus=pin_cpus)
    raise ValueError(f"unknown scheduler policy {name!r}")
