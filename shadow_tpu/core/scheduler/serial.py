"""Single-threaded reference policy: one global priority queue.

The correctness oracle for every other policy: the (time, dst, src,
seq) total order makes its execution schedule the canonical one that
threaded and device policies must reproduce observably.
"""

from __future__ import annotations

from typing import Optional

from shadow_tpu import simtime
from shadow_tpu.core.event import Event
from shadow_tpu.core.scheduler.base import SchedulerPolicy
from shadow_tpu.utils.pqueue import PriorityQueue


class SerialPolicy(SchedulerPolicy):
    def __init__(self):
        self._q = PriorityQueue()
        self._hosts: set[int] = set()

    def add_host(self, host_id: int) -> None:
        self._hosts.add(host_id)

    def push(self, event: Event, barrier: int) -> None:
        event = self.apply_barrier(event, barrier)
        self._q.push(event.key, event)

    def pop(self, barrier: int) -> Optional[Event]:
        head = self._q.peek()
        if head is None or head[0].time >= barrier:
            return None
        return self._q.pop()[1]

    def next_event_time(self) -> int:
        key = self._q.peek_key()
        return simtime.SIMTIME_MAX if key is None else key.time
