"""Thread-pool scheduler policies.

The five CPU policies of the reference (scheduler_policy_type.h, chosen
by `experimental.scheduler_policy`):

* ``host``          — hosts partitioned over workers, one locked queue
                      per host, each worker drains its own hosts
                      (scheduler_policy_host_single.c).
* ``steal``         — per-host queues, but workers dynamically claim the
                      next unprocessed host from a shared cursor — whole-
                      host work stealing (scheduler_policy_host_steal.c).
* ``thread``        — one queue per worker; events routed by destination
                      host's owning worker (scheduler_policy_thread_single.c).
* ``threadXthread`` — cross-worker pushes go to unlocked per-(src
                      worker, dst worker) staging queues, merged into
                      the destination worker's main queue when its next
                      round starts; same-worker pushes (which may be
                      runnable in the current window) go direct
                      (scheduler_policy_thread_perthread.c).
* ``threadXhost``   — per-host queues iterated thread-major
                      (scheduler_policy_thread_perhost.c).

Correctness invariants shared with the reference: a host's events
execute serially in (time, dst, src, seq) order on exactly one worker
per round, and cross-host pushes below the round barrier are bumped to
it, so nothing a worker does can create same-window work for a host
another worker already finished.

Python threads share the GIL, so these policies exist for API parity,
correctness testing, and as the structure the native C++ worker pool
slots into — the performance path is the `tpu` device policy.
"""

from __future__ import annotations

import os
import threading
from typing import Optional

from shadow_tpu import simtime
from shadow_tpu.core.event import Event
from shadow_tpu.core.scheduler.base import SchedulerPolicy
from shadow_tpu.utils.latch import CountDownLatch
from shadow_tpu.utils.pqueue import PriorityQueue


class _LockedQueue:
    """async_priority_queue.c analogue."""

    __slots__ = ("_q", "_lock")

    def __init__(self):
        self._q = PriorityQueue()
        self._lock = threading.Lock()

    def push(self, key, item) -> None:
        with self._lock:
            self._q.push(key, item)

    def pop_before(self, barrier: int) -> Optional[Event]:
        with self._lock:
            head = self._q.peek()
            if head is None or head[0].time >= barrier:
                return None
            return self._q.pop()[1]

    def next_time(self) -> int:
        with self._lock:
            key = self._q.peek_key()
            return simtime.SIMTIME_MAX if key is None else key.time


_worker_tls = threading.local()


class ThreadedPolicy(SchedulerPolicy):
    def __init__(self, kind: str, n_workers: int = 0,
                 parallelism: int = 0, pin_cpus: bool = False):
        self.kind = kind
        self.n_workers = n_workers if n_workers > 0 else (os.cpu_count() or 2)
        # LogicalProcessors (logical_processor.rs): worker CONTEXTS may
        # exceed the concurrency cap; `parallelism` OS threads then
        # multiplex them with round-robin assignment + stealing
        self.parallelism = min(parallelism or self.n_workers,
                               self.n_workers)
        self.pin_cpus = pin_cpus
        self._host_queues: dict[int, _LockedQueue] = {}
        self._worker_queues: list[_LockedQueue] = []
        # threadXthread: staging[src_worker][dst_worker]. LOCKED: with
        # LP multiplexing a worker's merge runs whenever an LP reaches
        # it mid-round, concurrent with other workers' pushes — the
        # old "merged at round start" ordering argument no longer
        # holds
        self._staging: list[list[_LockedQueue]] = []
        self._owner: dict[int, int] = {}       # host -> worker
        self._worker_hosts: list[list[int]] = []
        self._pool: Optional[_WorkerPool] = None

    # -- topology of queues -------------------------------------------
    def _per_host(self) -> bool:
        return self.kind in ("host", "steal", "threadXhost")

    def add_host(self, host_id: int) -> None:
        if not self._worker_hosts:
            self._worker_hosts = [[] for _ in range(self.n_workers)]
            self._worker_queues = [_LockedQueue()
                                   for _ in range(self.n_workers)]
            if self.kind == "threadXthread":
                self._staging = [
                    [_LockedQueue() for _ in range(self.n_workers)]
                    for _ in range(self.n_workers)
                ]
        w = host_id % self.n_workers          # round-robin assignment
        self._owner[host_id] = w
        self._worker_hosts[w].append(host_id)
        if self._per_host():
            self._host_queues[host_id] = _LockedQueue()

    # -- SchedulerPolicy interface ------------------------------------
    def push(self, event: Event, barrier: int) -> None:
        event = self.apply_barrier(event, barrier)
        dst_w = self._owner[event.dst_host]
        src_w = getattr(_worker_tls, "wid", None)
        if (self.kind == "threadXthread" and src_w is not None
                and src_w != dst_w):
            # cross-worker: stage without locking (events are barrier-
            # bumped, so they cannot be runnable before the next round)
            self._staging[src_w][dst_w].push(event.key, event)
        elif self._per_host():
            self._host_queues[event.dst_host].push(event.key, event)
        else:
            self._worker_queues[dst_w].push(event.key, event)

    def merge_staging(self, dst_w: int) -> None:
        for src_w in range(self.n_workers):
            q = self._staging[src_w][dst_w]
            while (ev := q.pop_before(simtime.SIMTIME_MAX)) is not None:
                self._worker_queues[dst_w].push(ev.key, ev)

    def pop(self, barrier: int) -> Optional[Event]:
        raise RuntimeError("ThreadedPolicy executes rounds via "
                           "run_parallel, not central pop")

    def next_event_time(self) -> int:
        queues = list(self._host_queues.values() if self._per_host()
                      else self._worker_queues)
        times = [q.next_time() for q in queues]
        for row in self._staging:
            for q in row:
                times.append(q.next_time())
        return min(times, default=simtime.SIMTIME_MAX)

    # -- parallel round execution -------------------------------------
    def run_parallel(self, manager, window_end: int) -> None:
        if self._pool is None:
            self._pool = _WorkerPool(self, manager)
        self._pool.run_round(window_end)

    def shutdown(self) -> None:
        if self._pool is not None:
            self._pool.shutdown()
            self._pool = None


class _WorkerPool:
    """Persistent pthread-pool analogue (core/worker.c:132-185) with a
    LogicalProcessors layer (logical_processor.rs:17-60): `parallelism`
    OS threads multiplex `n_workers` worker contexts. Each round the
    worker ids are dealt round-robin onto per-LP ready queues; an idle
    LP steals worker ids from its neighbors (pop_worker_to_run_on).
    With parallelism == n_workers this degenerates to one worker per
    thread, the reference's common case. Threads optionally pin to the
    affinity module's CPU assignment (worker.c:316-330)."""

    def __init__(self, policy: ThreadedPolicy, manager):
        self.policy = policy
        self.manager = manager
        self.n = policy.n_workers
        self.n_lps = policy.parallelism
        self._error: Optional[BaseException] = None
        self._barrier = simtime.SIMTIME_INVALID
        self._start = [threading.Semaphore(0) for _ in range(self.n_lps)]
        self._done: Optional[CountDownLatch] = None
        self._shutdown = False
        self._steal_lock = threading.Lock()
        self._steal_cursor = 0
        self._lp_lock = threading.Lock()
        self._lp_ready: list[list[int]] = [[] for _ in range(self.n_lps)]
        self._states: dict[int, tuple] = {}     # wid -> (ctx, stats)
        if policy.pin_cpus:
            from shadow_tpu.utils.affinity import good_worker_affinity
            self._affinity = good_worker_affinity(self.n_lps)
        else:
            self._affinity = None
        self._threads = [
            threading.Thread(target=self._run, args=(i,), daemon=True,
                             name=f"shadow-worker-{i}")
            for i in range(self.n_lps)
        ]
        for t in self._threads:
            t.start()

    def run_round(self, window_end: int) -> None:
        self._barrier = window_end
        self._steal_cursor = 0
        self._error: Optional[BaseException] = None
        for lp in self._lp_ready:
            lp.clear()
        for wid in range(self.n):
            self._lp_ready[wid % self.n_lps].append(wid)
        self._done = CountDownLatch(self.n_lps)
        for s in self._start:
            s.release()
        self._done.wait()
        if self._error is not None:
            raise RuntimeError(
                "worker thread failed during simulation round"
            ) from self._error

    def shutdown(self) -> None:
        self._shutdown = True
        for s in self._start:
            s.release()

    # -- worker bodies -------------------------------------------------
    def _next_worker(self, lp: int) -> Optional[int]:
        """Pop a ready worker id: own queue first, then steal round-
        robin from the other LPs (logical_processor.rs:42-55)."""
        with self._lp_lock:
            for j in range(self.n_lps):
                q = self._lp_ready[(lp + j) % self.n_lps]
                if q:
                    return q.pop(0)
        return None

    def _run(self, lp: int) -> None:
        from shadow_tpu.core.scheduler.threads import _worker_tls
        if self._affinity is not None:
            from shadow_tpu.utils.affinity import pin_current_thread
            pin_current_thread(self._affinity[lp])
        while True:
            self._start[lp].acquire()
            if self._shutdown:
                return
            barrier = self._barrier
            try:
                if self.policy.kind == "steal":
                    # host-level stealing is already global: every LP
                    # drains from the shared cursor
                    ctx, stats = self._state_for(lp)
                    _worker_tls.wid = lp
                    self._drain_stealing(ctx, stats, barrier)
                else:
                    while (wid := self._next_worker(lp)) is not None:
                        self._run_worker(wid, barrier)
            except BaseException as e:   # propagate to run_round
                if self._error is None:
                    self._error = e
            finally:
                self._done.count_down()

    def _state_for(self, wid: int) -> tuple:
        st = self._states.get(wid)
        if st is None:
            st = self._states[wid] = self.manager.make_worker_state()
        return st

    def _run_worker(self, wid: int, barrier: int) -> None:
        _worker_tls.wid = wid
        ctx, stats = self._state_for(wid)
        if self.policy.kind == "threadXthread":
            self.policy.merge_staging(wid)
        if self.policy._per_host():
            for hid in self.policy._worker_hosts[wid]:
                self._drain(self.policy._host_queues[hid],
                            ctx, stats, barrier)
        else:
            self._drain(self.policy._worker_queues[wid],
                        ctx, stats, barrier)

    def _drain(self, q: _LockedQueue, ctx, stats, barrier: int) -> None:
        while (ev := q.pop_before(barrier)) is not None:
            self.manager.execute_event(ev, ctx, stats)

    def _drain_stealing(self, ctx, stats, barrier: int) -> None:
        hosts = list(self.policy._host_queues.keys())
        while True:
            with self._steal_lock:
                i = self._steal_cursor
                self._steal_cursor += 1
            if i >= len(hosts):
                return
            self._drain(self.policy._host_queues[hosts[i]],
                        ctx, stats, barrier)
