"""Scheduler policy interface.

Mirrors the reference's vtable (src/main/core/scheduler/
scheduler_policy.h:22-33): addHost / push / pop / getNextTime, plus the
causality rule applied on push — a cross-host event with a time below
the current round barrier is bumped up to the barrier
(scheduler_policy_host_single.c:174-220). Same-host events may land
anywhere in the future (a host's own timeline is sequential anyway).
"""

from __future__ import annotations

from typing import Optional

from shadow_tpu import simtime
from shadow_tpu.core.event import Event


class SchedulerPolicy:
    def add_host(self, host_id: int) -> None:
        raise NotImplementedError

    def push(self, event: Event, barrier: int) -> None:
        """Insert an event. `barrier` is the current round's end time;
        cross-host events earlier than it are delayed to it."""
        raise NotImplementedError

    def pop(self, barrier: int) -> Optional[Event]:
        """Remove and return the next event strictly before `barrier`,
        in (time, dst, src, seq) order, or None if none remain."""
        raise NotImplementedError

    def next_event_time(self) -> int:
        """Earliest pending event time, or SIMTIME_MAX if empty."""
        raise NotImplementedError

    @staticmethod
    def apply_barrier(event: Event, barrier: int) -> Event:
        if (event.src_host != event.dst_host
                and barrier != simtime.SIMTIME_INVALID
                and event.time < barrier):
            event.time = barrier
        return event
