from shadow_tpu.core.event import (
    Event,
    EventKey,
    KIND_BOOT,
    KIND_PACKET,
    KIND_STOP,
    KIND_TIMER,
)
from shadow_tpu.core.manager import Manager, SimStats
from shadow_tpu.core.controller import Controller, build, load_topology

__all__ = [
    "Event", "EventKey",
    "KIND_BOOT", "KIND_PACKET", "KIND_STOP", "KIND_TIMER",
    "Manager", "SimStats", "Controller", "build", "load_topology",
]
