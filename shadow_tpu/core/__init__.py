from shadow_tpu.core.event import Event, EventKey

__all__ = ["Event", "EventKey"]
