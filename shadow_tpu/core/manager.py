"""Manager: drives one machine's share of the simulation.

The round-loop owner, mirroring manager_run (src/main/core/manager.c:
615-649): given a time window [start, end) from the Controller, execute
every pending event below the barrier via the scheduler policy, then
report the earliest next event time for the Controller to open the next
window. Serial policies are drained centrally; threaded policies run
the round on their worker pool (each worker gets its own SimContext and
stats bucket, merged at finalize). Multi-manager distribution (stubbed
in the reference, controller.c:352-354) maps here to one Manager per
device-mesh slice.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Callable, Optional

from shadow_tpu import simtime
from shadow_tpu.core.event import (
    Event,
    KIND_BOOT,
    KIND_NIC_WAKE,
    KIND_PACKET,
    KIND_PACKET_READY,
    KIND_ROUTER_ARRIVAL,
    KIND_STOP,
    KIND_TCP_TIMER,
    KIND_TIMER,
)
from shadow_tpu.core.netmodel import NetworkModel
from shadow_tpu.core.scheduler.base import SchedulerPolicy
from shadow_tpu.core.worker import SimContext
from shadow_tpu.host.host import Host
from shadow_tpu.utils import nprng
from shadow_tpu.utils.checksum import chk_mix
from shadow_tpu.utils.slog import get_logger, set_context, clear_context

log = get_logger("manager")


def resolve_host_ref(name_to_id: dict, groups: dict, name: str,
                     asker_id: int) -> int:
    """Hostname OR host-group reference -> host id. A `quantity: N`
    group named `g` expands to hosts g0..gN-1 (controller.py, which
    also records the explicit member list in BuiltSimulation.groups —
    no name-pattern guessing, so a group `web` never absorbs a
    sibling group `web2`). A bare group reference resolves to one
    member chosen deterministically by the asking host (asker_id
    modulo group size) so client fleets spread over server groups
    identically on the CPU and device engines."""
    hid = name_to_id.get(name)
    if hid is not None:
        return hid
    members = (groups or {}).get(name)
    if members:
        return members[asker_id % len(members)]
    raise KeyError(f"unknown host name {name!r}")


@dataclass
class SimStats:
    ok: bool = True
    end_time: int = 0
    events_executed: int = 0
    packets_sent: int = 0
    packets_delivered: int = 0
    packets_dropped: int = 0
    rounds: int = 0
    # device-engine occupancy telemetry (device/capacity.py record:
    # measured high-water marks + the capacities that held them);
    # None on CPU policies
    occupancy: Optional[dict] = None
    # capacity re-plan/retry cycles the run needed (0 = the plan held)
    replans: int = 0

    def merge(self, other: "SimStats") -> None:
        self.events_executed += other.events_executed
        self.packets_sent += other.packets_sent
        self.packets_delivered += other.packets_delivered
        self.packets_dropped += other.packets_dropped

    def summary(self) -> str:
        return (f"{self.events_executed} events, "
                f"{self.packets_sent} packets sent "
                f"({self.packets_delivered} delivered, "
                f"{self.packets_dropped} dropped), "
                f"{self.rounds} rounds")


@dataclass
class NetOptions:
    """Per-host network-stack knobs plumbed from the config."""
    qdisc: str = "fifo"
    router_queue: str = "codel"
    router_static_capacity: int = 1024
    bootstrap_end: int = 0
    tcp_congestion: str = "reno"
    # defaults live in host/tcp.py (DEFAULT_RECV_WINDOW/SEND_BUFFER)
    tcp_recv_buffer: int = 0
    tcp_send_buffer: int = 0
    tcp_recv_autotune: bool = True
    tcp_send_autotune: bool = True

    def __post_init__(self):
        from shadow_tpu.host.tcp import (
            DEFAULT_RECV_WINDOW,
            DEFAULT_SEND_BUFFER,
        )
        self.tcp_recv_buffer = self.tcp_recv_buffer \
            or DEFAULT_RECV_WINDOW
        self.tcp_send_buffer = self.tcp_send_buffer \
            or DEFAULT_SEND_BUFFER


@dataclass
class Manager:
    hosts: list[Host]
    policy: SchedulerPolicy
    netmodel: NetworkModel
    seed: int
    stats: SimStats = field(default_factory=SimStats)
    trace: Optional[list] = None    # (time, dst, src, kind) if recording
    on_event_hook: Optional[Callable] = None
    net_opts: NetOptions = field(default_factory=NetOptions)
    groups: Optional[dict] = None   # group name -> [host ids]
    # hybrid mode: when set, packet judgments (drop roll + latency) are
    # deferred per round and computed on the device in one batch
    # (device/judge.py); None = judge synchronously on CPU
    net_judge: Optional[object] = None

    def __post_init__(self):
        from shadow_tpu.host.netstack import HostNetStack

        self.rng_key = nprng.seed_key(self.seed)
        self._name_to_id = {h.name: h.host_id for h in self.hosts}
        # out-of-band TCP payload streams for managed processes,
        # keyed (src_host, src_port, dst_host, dst_port)
        self._streams: dict[tuple, object] = {}
        self._barrier = simtime.SIMTIME_INVALID
        self._trace_lock = threading.Lock()
        self._worker_stats: list[SimStats] = []
        # egress packets awaiting the batched device judgment:
        # (now, src_host, dst_host, pkt_seq, ev_seq, kind, data)
        self._pending: list[tuple] = []
        self._pending_lock = threading.Lock()
        self._last_hb_flush = simtime.SIMTIME_INVALID
        self._ctx = SimContext(self, self.stats)
        no = self.net_opts
        for h in self.hosts:
            self.policy.add_host(h.host_id)
            h.net = HostNetStack(
                h, self, qdisc=no.qdisc, router_queue=no.router_queue,
                router_static_capacity=no.router_static_capacity,
                bootstrap_end=no.bootstrap_end,
                tcp_congestion=no.tcp_congestion,
                tcp_recv_buffer=no.tcp_recv_buffer,
                tcp_send_buffer=no.tcp_send_buffer,
                tcp_recv_autotune=no.tcp_recv_autotune,
                tcp_send_autotune=no.tcp_send_autotune)

    def resolve(self, name: str) -> int:
        if name not in self._name_to_id:
            raise KeyError(f"unknown host name {name!r}")
        return self._name_to_id[name]

    def resolve_ref(self, name: str, asker_id: int) -> int:
        return resolve_host_ref(self._name_to_id, self.groups, name,
                                asker_id)

    def stream_channel(self, key: tuple):
        """Byte channel for one TCP direction (host/descriptors.py)."""
        ch = self._streams.get(key)
        if ch is None:
            from shadow_tpu.host.descriptors import StreamChannel
            ch = self._streams[key] = StreamChannel()
        return ch

    def push_event(self, ev: Event) -> None:
        self.policy.push(ev, self._barrier)

    def make_worker_state(self) -> tuple[SimContext, SimStats]:
        """Per-worker execution state for threaded policies."""
        stats = SimStats()
        self._worker_stats.append(stats)
        return SimContext(self, stats), stats

    def boot_hosts(self, start_times: list[tuple]) -> None:
        """start_times: (host_id, start_time, stop_time|-1[, proc_idx])
        per process. Boot/stop events enter the queue before the first
        round (worker_bootHosts analogue, worker.c:581-591); the
        process index rides in the event data so multi-process hosts
        boot each process independently."""
        for entry in start_times:
            host_id, t_start, t_stop = entry[0], entry[1], entry[2]
            idx = entry[3] if len(entry) > 3 else 0
            h = self.hosts[host_id]
            self.push_event(Event(time=t_start, dst_host=host_id,
                                  src_host=host_id,
                                  seq=h.next_event_seq(),
                                  kind=KIND_BOOT, data=(idx,)))
            if t_stop is not None and t_stop >= 0:
                self.push_event(Event(time=t_stop, dst_host=host_id,
                                      src_host=host_id,
                                      seq=h.next_event_seq(),
                                      kind=KIND_STOP, data=(idx,)))

    def _apply_verdict(self, rec: tuple, delivered: bool,
                       deliver_time: int) -> None:
        """Single place where a judged packet becomes stats + an event
        (or a drop) — used by both the synchronous fallback and the
        batched device path, so their bookkeeping cannot diverge."""
        from shadow_tpu.routing.packet import PacketStatus

        _, src_h, dst_h, _, ev_seq, kind, data = rec
        host = self.hosts[src_h]
        host.packets_sent += 1
        pkt = data[0] if kind == KIND_ROUTER_ARRIVAL else None
        if not delivered:
            host.packets_dropped += 1
            if pkt is not None:
                pkt.add_status(PacketStatus.INET_DROPPED)
            return
        if pkt is not None:
            pkt.add_status(PacketStatus.INET_SENT)
        self.push_event(Event(time=int(deliver_time), dst_host=dst_h,
                              src_host=src_h, seq=ev_seq, kind=kind,
                              data=data))

    def defer_judgment(self, now: int, host, dst_host: int, pkt_seq: int,
                       ev_seq: int, kind: int, data: tuple) -> None:
        """Hybrid mode: queue one egress packet for the end-of-round
        device batch. The event seq was already consumed by the caller
        so later seq allocations are unaffected by the deferral.

        Self-destined packets are judged synchronously instead: they
        are exempt from the causality bump (SchedulerPolicy
        .apply_barrier), so one below the barrier must enter the queue
        NOW to run this round in per-host time order (possible when a
        runahead override exceeds the self-path latency). The verdict
        is a pure function of (seed, src, pkt_seq) either way, so sync
        and batched rolls agree bit-for-bit."""
        rec = (now, host.host_id, dst_host, pkt_seq, ev_seq, kind, data)
        if dst_host == host.host_id:
            v = self.netmodel.judge(now, host.host_id, dst_host, pkt_seq)
            self._apply_verdict(rec, v.delivered, v.deliver_time)
            return
        with self._pending_lock:
            self._pending.append(rec)

    def flush_judgments(self) -> None:
        """Judge every pending cross-host packet in one device batch
        and push the delivery events. Verdicts are bit-identical to the
        synchronous CPU path (same threefry chain, same latency
        matrices), so hybrid traces equal pure-CPU traces."""
        from collections import Counter

        import numpy as np

        with self._pending_lock:
            pending, self._pending = self._pending, []
        if not pending:
            return
        j = self.net_judge
        if len(pending) < getattr(j, "min_batch", 0):
            # adaptive: a round this small never amortizes the device
            # dispatch — the synchronous CPU roll is bit-identical
            # (same threefry chain), so only the wall clock changes
            for rec in pending:
                v = self.netmodel.judge(rec[0], rec[1], rec[2], rec[3])
                self._apply_verdict(rec, v.delivered, v.deliver_time)
            j.cpu_batches += 1
            j.cpu_packets += len(pending)
            nm = self.netmodel
            nm.record_paths(Counter(
                (int(nm.host_vertex[r[1]]), int(nm.host_vertex[r[2]]))
                for r in pending))
            return
        now = np.fromiter((p[0] for p in pending), np.int64, len(pending))
        src = np.fromiter((p[1] for p in pending), np.int32, len(pending))
        dst = np.fromiter((p[2] for p in pending), np.int32, len(pending))
        seq = np.fromiter((p[3] for p in pending), np.int32, len(pending))
        delivered, deliver_time = self.net_judge.judge_batch(
            now, src, dst, seq)
        nm = self.netmodel
        nm.record_paths(Counter(
            (int(nm.host_vertex[r[1]]), int(nm.host_vertex[r[2]]))
            for r in pending))
        for i, rec in enumerate(pending):
            self._apply_verdict(rec, bool(delivered[i]), deliver_time[i])

    def run_window(self, window_start: int, window_end: int) -> int:
        """Execute all events in [window_start, window_end); return the
        earliest remaining event time (scheduler_awaitNextRound).

        In hybrid mode the round's cross-host egress packets are judged
        in one device batch after the drain; every verdict lands at or
        after the barrier (cross-host events get the causality bump,
        self-destined ones were judged synchronously), so one flush per
        round suffices."""
        self._barrier = window_end
        if hasattr(self.policy, "run_parallel"):
            self.policy.run_parallel(self, window_end)
        else:
            while (ev := self.policy.pop(window_end)) is not None:
                self.execute_event(ev, self._ctx, self.stats)
        if self.net_judge is not None:
            self.flush_judgments()
        self.stats.rounds += 1
        return self.policy.next_event_time()

    def finalize(self) -> SimStats:
        for ws in self._worker_stats:
            self.stats.merge(ws)
        self._worker_stats.clear()
        # packet totals come from the per-host counters, which both the
        # raw-send path (worker.py) and the socket path (netstack.py)
        # maintain — the single source of truth
        self.stats.packets_sent = sum(h.packets_sent for h in self.hosts)
        self.stats.packets_dropped = sum(h.packets_dropped
                                         for h in self.hosts)
        self.stats.packets_delivered = sum(h.packets_delivered
                                           for h in self.hosts)
        if hasattr(self.policy, "shutdown"):
            self.policy.shutdown()
        for h in self.hosts:
            if h.net is not None and h.net.pcap is not None:
                h.net.pcap.close()
        return self.stats

    def schedule_heartbeats(self, interval: int, stop: int) -> None:
        """Per-host heartbeat chain (tracker_heartbeat, tracker.c:565)."""
        from shadow_tpu.core.event import KIND_TASK
        from shadow_tpu.host.tracker import Tracker

        def make_task(host):
            def task(ctx, ev):
                # hybrid: settle this round's pending drop verdicts so
                # the CSV counters match the pure-CPU oracle's interval
                # attribution (drop rolls are pure functions of
                # (seed, src, pkt_seq) — flushing mid-round is safe).
                # Serial policies only: under threaded policies a flush
                # from a worker would race other workers' counter
                # updates, and threaded heartbeat attribution is
                # unordered in pure-CPU mode anyway. One flush per
                # heartbeat tick, not per host.
                if (self.net_judge is not None
                        and not hasattr(self.policy, "run_parallel")
                        and self._last_hb_flush != ev.time):
                    self._last_hb_flush = ev.time
                    self.flush_judgments()
                host.tracker.heartbeat(ev.time, host)
                nxt = ev.time + interval
                if nxt < stop:
                    self.push_event(Event(
                        time=nxt, dst_host=host.host_id,
                        src_host=host.host_id,
                        seq=host.next_event_seq(), kind=KIND_TASK,
                        task=task))
            return task

        for h in self.hosts:
            h.tracker = Tracker(h.name, interval)
            self.push_event(Event(time=interval, dst_host=h.host_id,
                                  src_host=h.host_id,
                                  seq=h.next_event_seq(),
                                  kind=KIND_TASK, task=make_task(h)))

    @staticmethod
    def _proc_of(host, ev: Event):
        """BOOT/STOP dispatch target: the process the event's index
        names (multi-process hosts), defaulting to the primary app."""
        if ev.data and host.apps:
            idx = ev.data[0]
            if 0 <= idx < len(host.apps):
                return host.apps[idx]
        return host.app

    def execute_event(self, ev: Event, ctx: SimContext,
                      stats: SimStats) -> None:
        """event_execute analogue (core/work/event.c:64): set the clock
        and host context, apply the CPU-delay model, dispatch by kind."""
        host = self.hosts[ev.dst_host]
        if host.cpu is not None:
            host.cpu.update_time(ev.time)
            if host.cpu.is_blocked(ev.time):
                # defer delivery while the virtual CPU is busy
                # (event.c:70-87). Deferral times are forced strictly
                # increasing per host: precision rounding could
                # otherwise re-order two deferred events whose original
                # order the (time,dst,src,seq) key had fixed.
                new_time = ev.time + host.cpu.delay_until_ready(ev.time)
                floor = getattr(host, "_cpu_defer_floor", -1)
                new_time = max(new_time, floor + 1)
                host._cpu_defer_floor = new_time
                ev.time = new_time
                self.policy.push(ev, self._barrier)
                return
        ctx.now = ev.time
        ctx.host = host
        set_context(ev.time, host.name, host.host_id)
        try:
            host.events_executed += 1
            host.trace_checksum = chk_mix(host.trace_checksum, ev.time,
                                          ev.src_host, ev.kind, ev.seq)
            if host.tracker is not None:
                host.tracker.on_event()
            stats.events_executed += 1
            if self.trace is not None:
                with self._trace_lock:
                    self.trace.append((ev.time, ev.dst_host, ev.src_host,
                                       ev.kind))
            if self.on_event_hook is not None:
                self.on_event_hook(ev)
            app = host.app
            if ev.task is not None:
                ev.execute(ctx)
            elif ev.kind in (KIND_ROUTER_ARRIVAL, KIND_NIC_WAKE,
                             KIND_TCP_TIMER):
                host.net.handle_event(ev, ev.time, ctx)
            elif ev.kind == KIND_PACKET:
                nic = host.model_nic
                if nic is not None:
                    # model-NIC RX stage: CoDel may drop; otherwise the
                    # payload re-fires as KIND_PACKET_READY after the
                    # download-bandwidth serialization. Pushed without
                    # the causality bump: it is this host's own future
                    # (the device engine inserts into the local heap
                    # the same way).
                    size = ev.data[0] if ev.data else 0
                    deliver = nic.rx_deliver(ev.time, size)
                    if deliver < 0:
                        host.packets_dropped += 1
                    else:
                        self.policy.push(
                            Event(time=deliver, dst_host=ev.dst_host,
                                  src_host=ev.src_host, seq=ev.seq,
                                  kind=KIND_PACKET_READY, data=ev.data,
                                  npkts=ev.npkts),
                            simtime.SIMTIME_INVALID)
                else:
                    host.packets_delivered += ev.npkts
                    if app is not None:
                        size = ev.data[0] if ev.data else 0
                        app.on_packet(ctx, ev.src_host, size,
                                      ev.data[1:])
            elif ev.kind == KIND_PACKET_READY:
                host.packets_delivered += ev.npkts
                if app is not None:
                    size = ev.data[0] if ev.data else 0
                    app.on_packet(ctx, ev.src_host, size, ev.data[1:])
            elif ev.kind == KIND_TIMER:
                if app is not None:
                    app.on_timer(ctx, ev.data)
            elif ev.kind == KIND_BOOT:
                target = self._proc_of(host, ev)
                if target is not None:
                    target.boot(ctx)
            elif ev.kind == KIND_STOP:
                target = self._proc_of(host, ev)
                if target is not None:
                    target.on_stop(ctx)
        finally:
            clear_context()
