"""Manager: drives one machine's share of the simulation.

The round-loop owner, mirroring manager_run (src/main/core/manager.c:
615-649): given a time window [start, end) from the Controller, execute
every pending event below the barrier via the scheduler policy, then
report the earliest next event time for the Controller to open the next
window. Serial policies are drained centrally; threaded policies run
the round on their worker pool (each worker gets its own SimContext and
stats bucket, merged at finalize). Multi-manager distribution (stubbed
in the reference, controller.c:352-354) maps here to one Manager per
device-mesh slice.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Callable, Optional

from shadow_tpu import simtime
from shadow_tpu.core.event import (
    Event,
    KIND_BOOT,
    KIND_HOST_CRASH,
    KIND_HOST_RESTART,
    KIND_NIC_WAKE,
    KIND_PACKET,
    KIND_PACKET_READY,
    KIND_ROUTER_ARRIVAL,
    KIND_STOP,
    KIND_TCP_TIMER,
    KIND_TIMER,
)
from shadow_tpu.core.netmodel import NetworkModel
from shadow_tpu.core.scheduler.base import SchedulerPolicy
from shadow_tpu.core.worker import SimContext
from shadow_tpu.host.host import Host
from shadow_tpu.obs.trace import NullTracer
from shadow_tpu.utils import nprng
from shadow_tpu.utils.checksum import chk_mix
from shadow_tpu.utils.slog import get_logger, set_context, clear_context

log = get_logger("manager")


def resolve_host_ref(name_to_id: dict, groups: dict, name: str,
                     asker_id: int) -> int:
    """Hostname OR host-group reference -> host id. A `quantity: N`
    group named `g` expands to hosts g0..gN-1 (controller.py, which
    also records the explicit member list in BuiltSimulation.groups —
    no name-pattern guessing, so a group `web` never absorbs a
    sibling group `web2`). A bare group reference resolves to one
    member chosen deterministically by the asking host (asker_id
    modulo group size) so client fleets spread over server groups
    identically on the CPU and device engines."""
    hid = name_to_id.get(name)
    if hid is not None:
        return hid
    members = (groups or {}).get(name)
    if members:
        return members[asker_id % len(members)]
    raise KeyError(f"unknown host name {name!r}")


@dataclass
class SimStats:
    ok: bool = True
    end_time: int = 0
    events_executed: int = 0
    packets_sent: int = 0
    packets_delivered: int = 0
    packets_dropped: int = 0
    rounds: int = 0
    # device-engine occupancy telemetry (device/capacity.py record:
    # measured high-water marks + the capacities that held them);
    # None on CPU policies
    occupancy: Optional[dict] = None
    # capacity re-plan/retry cycles the run needed (0 = the plan held)
    replans: int = 0
    # supervised-run outcomes (device/supervise.py): transient device
    # dispatch retries the run absorbed; whether it was gracefully
    # preempted (SIGTERM/SIGINT drain — the run is INCOMPLETE and
    # resumable from resume_path, and the CLI exits EXIT_PREEMPTED)
    retries: int = 0
    preempted: bool = False
    resume_path: str = ""
    # mesh shrinks absorbed (failover: shrink, device/supervise.py):
    # the run lost device(s) mid-flight and continued on-device on
    # the surviving mesh — throughput degraded by the lost share,
    # results bit-identical
    reshards: int = 0
    # set when the tpu policy failed over to the hybrid backend
    # mid-run (the device checkpoint named here pins a device-side
    # resume; the hybrid results replayed from t=0)
    failover_checkpoint: str = ""
    # ensemble campaign record (shadow_tpu/ensemble/campaign.py):
    # per-replica results + aggregates; None outside ensemble runs.
    # The top-level counters above then hold CAMPAIGN totals (summed
    # over replicas)
    ensemble: Optional[dict] = None
    # AOT compile-cache attribution (device/aotcache.py report():
    # per-program hit/miss events + lower/compile/load walls); None
    # on CPU policies or with experimental.compile_cache: off
    compile_cache: Optional[dict] = None
    # flight-recorder summary (shadow_tpu/obs): per-phase wall
    # attribution (host_s/judge_s/dispatch_s/exchange_s/checkpoint_s/
    # retry_s/...), span counts, and the paths of any TRACE_*/
    # METRICS_* artifacts written. None with telemetry: off. bench.py
    # stamps the phase walls into its records from here.
    telemetry: Optional[dict] = None
    # strategy-plan provenance (shadow_tpu/tune/plan.py adopt()):
    # which PLAN record steered this run's execution knobs, the
    # knobs actually applied, and the ones skipped (hand-set or
    # inapplicable). None when experimental.strategy_plan resolved
    # to nothing. bench.py stamps this into its records — plans
    # change wall time only, so provenance is what keeps tuned and
    # default records honestly comparable.
    strategy_plan: Optional[dict] = None
    # pipelined segment dispatch telemetry (device/supervise.py
    # advance): depth, issued/drained/discarded segment counts, the
    # wall blocked in dispatch.sync, the host wall overlapped with
    # in-flight device work, and the overlap-efficiency share.
    # None on CPU policies (no segment pipeline to report).
    pipeline: Optional[dict] = None
    # OOM degradation-ladder rungs engaged (device/supervise.py): a
    # deterministic RESOURCE_EXHAUSTED walked the ladder (pipeline
    # depth / replica batching / dispatch segment) this many times —
    # each rung shrank the footprint and replayed bit-identically
    degrades: int = 0
    # preflight admission verdict (device/capacity.py
    # admission_verdict): mode, budget + source, modeled footprint,
    # action taken (admit/degrade/over/off/no-budget), and any
    # static overrides applied. None on CPU policies.
    admission: Optional[dict] = None
    # live device allocator stats at the end of the run, when the
    # backend exposes them (TPU/GPU memory_stats); -1 = unavailable
    # (CPU backends) — the heartbeat lines print "n/a" for the same
    # reason
    mem_bytes_in_use: int = -1
    mem_budget: int = -1
    # wall-clock heartbeat gaps that exceeded the configured
    # staleness threshold (experimental.heartbeat_stale_after x the
    # expected cadence; device/supervise.py HeartbeatMonitor). A
    # nonzero count means the run stalled between segment boundaries
    # — the campaign server's watchdog polls the same monitor live
    # to turn a wedged campaign into a supervised kill + requeue
    stale_heartbeats: int = 0

    def merge(self, other: "SimStats") -> None:
        self.events_executed += other.events_executed
        self.packets_sent += other.packets_sent
        self.packets_delivered += other.packets_delivered
        self.packets_dropped += other.packets_dropped

    def summary(self) -> str:
        return (f"{self.events_executed} events, "
                f"{self.packets_sent} packets sent "
                f"({self.packets_delivered} delivered, "
                f"{self.packets_dropped} dropped), "
                f"{self.rounds} rounds")


@dataclass
class NetOptions:
    """Per-host network-stack knobs plumbed from the config."""
    qdisc: str = "fifo"
    router_queue: str = "codel"
    router_static_capacity: int = 1024
    bootstrap_end: int = 0
    tcp_congestion: str = "reno"
    # defaults live in host/tcp.py (DEFAULT_RECV_WINDOW/SEND_BUFFER)
    tcp_recv_buffer: int = 0
    tcp_send_buffer: int = 0
    tcp_recv_autotune: bool = True
    tcp_send_autotune: bool = True

    def __post_init__(self):
        from shadow_tpu.host.tcp import (
            DEFAULT_RECV_WINDOW,
            DEFAULT_SEND_BUFFER,
        )
        self.tcp_recv_buffer = self.tcp_recv_buffer \
            or DEFAULT_RECV_WINDOW
        self.tcp_send_buffer = self.tcp_send_buffer \
            or DEFAULT_SEND_BUFFER


@dataclass
class Manager:
    hosts: list[Host]
    policy: SchedulerPolicy
    netmodel: NetworkModel
    seed: int
    stats: SimStats = field(default_factory=SimStats)
    trace: Optional[list] = None    # (time, dst, src, kind) if recording
    on_event_hook: Optional[Callable] = None
    net_opts: NetOptions = field(default_factory=NetOptions)
    groups: Optional[dict] = None   # group name -> [host ids]
    # hybrid mode: when set, packet judgments (drop roll + latency) are
    # deferred per round and computed on the device in one batch
    # (device/judge.py); None = judge synchronously on CPU
    net_judge: Optional[object] = None
    # flight recorder (shadow_tpu/obs): attached by the Controller;
    # directly-constructed Managers (tests) get the inert NullTracer,
    # so the flush path needs no None guards. Judge flushes record
    # spans here, and the round watchdog embeds the recent-span ring
    # in its stall dump.
    tracer: object = field(default_factory=NullTracer)

    def __post_init__(self):
        from shadow_tpu.host.netstack import HostNetStack

        self.rng_key = nprng.seed_key(self.seed)
        self._name_to_id = {h.name: h.host_id for h in self.hosts}
        # out-of-band TCP payload streams for managed processes,
        # keyed (src_host, src_port, dst_host, dst_port); the lock
        # covers create-vs-teardown races under threaded policies
        # (host-crash teardown runs on the crashed host's worker
        # while peers may be resolving channels concurrently)
        self._streams: dict[tuple, object] = {}
        self._streams_lock = threading.Lock()
        self._barrier = simtime.SIMTIME_INVALID
        self._trace_lock = threading.Lock()
        self._worker_stats: list[SimStats] = []
        # egress packets awaiting the batched device judgment:
        # (now, src_host, dst_host, pkt_seq, ev_seq, kind, data)
        self._pending: list[tuple] = []
        self._pending_lock = threading.Lock()
        self._last_hb_flush = simtime.SIMTIME_INVALID
        self._hb_interval = 0        # set by schedule_heartbeats
        self._hb_stop = 0
        self._ctx = SimContext(self, self.stats)
        no = self.net_opts
        for h in self.hosts:
            self.policy.add_host(h.host_id)
            h.net = HostNetStack(
                h, self, qdisc=no.qdisc, router_queue=no.router_queue,
                router_static_capacity=no.router_static_capacity,
                bootstrap_end=no.bootstrap_end,
                tcp_congestion=no.tcp_congestion,
                tcp_recv_buffer=no.tcp_recv_buffer,
                tcp_send_buffer=no.tcp_send_buffer,
                tcp_recv_autotune=no.tcp_recv_autotune,
                tcp_send_autotune=no.tcp_send_autotune)

    def resolve(self, name: str) -> int:
        if name not in self._name_to_id:
            raise KeyError(f"unknown host name {name!r}")
        return self._name_to_id[name]

    def resolve_ref(self, name: str, asker_id: int) -> int:
        return resolve_host_ref(self._name_to_id, self.groups, name,
                                asker_id)

    def stream_channel(self, key: tuple):
        """Byte channel for one TCP direction (host/descriptors.py)."""
        with self._streams_lock:
            ch = self._streams.get(key)
            if ch is None:
                from shadow_tpu.host.descriptors import StreamChannel
                ch = self._streams[key] = StreamChannel()
            return ch

    def push_event(self, ev: Event) -> None:
        self.policy.push(ev, self._barrier)

    def make_worker_state(self) -> tuple[SimContext, SimStats]:
        """Per-worker execution state for threaded policies."""
        stats = SimStats()
        self._worker_stats.append(stats)
        return SimContext(self, stats), stats

    def schedule_host_faults(self, host_faults: list[tuple]) -> None:
        """host_faults: [(time, host_id, kind)] from
        faults.resolve_host_faults — crash/restart events enter the
        queue before the first round, consuming event seqs exactly
        like boot/stop events (identically under every CPU policy, so
        traces stay policy-invariant)."""
        for t, host_id, kind in host_faults:
            h = self.hosts[host_id]
            self.push_event(Event(
                time=t, dst_host=host_id, src_host=host_id,
                seq=h.next_event_seq(),
                kind=(KIND_HOST_CRASH if kind == "host_crash"
                      else KIND_HOST_RESTART)))

    def _host_crash(self, ctx, host) -> None:
        """KIND_HOST_CRASH: the machine dies mid-run. Managed (real)
        processes are killed for real; model apps simply stop
        executing (their objects are replaced at restart). Pending
        events for the host are quarantined as they surface
        (execute_event), and the shared TCP payload channels the host
        participated in are dropped so surviving peers observe resets/
        timeouts through their own retry logic instead of reading a
        ghost's stream."""
        log.info("host %s crashed (fault injection)", host.name)
        for app in host.apps:
            if hasattr(app, "on_sim_end"):
                # ManagedProcess/PtraceProcess: kill the OS process
                app.on_sim_end(ctx)
        host.crashed = True
        # under threaded policies a peer draining in the same window
        # may interleave with this teardown by wall clock; the lock
        # makes the dict operations safe, and per-connection readers
        # tolerate a vanished channel as a reset (managed-TCP fault
        # scenarios wanting strict cross-run byte-level determinism
        # should run a serial policy, like threaded heartbeat
        # attribution already does)
        with self._streams_lock:
            for key in [k for k in self._streams
                        if k[0] == host.host_id
                        or k[2] == host.host_id]:
                del self._streams[key]
        # the pcap writer deliberately survives the crash: the capture
        # up to the outage is exactly the artifact a fault-injection
        # user inspects, and the restart re-attaches it (a fresh
        # HostNetStack would truncate the file)

    def _host_restart(self, ctx, host) -> None:
        """KIND_HOST_RESTART: respawn the configured processes from
        the factories captured at build time, on a FRESH network
        stack/CPU model — a rebooted machine keeps nothing but its
        disk (the per-host data dir). Boot events are pushed at the
        restart time (self-destined, so no causality bump) and the
        processes' original stop_times still apply when still in the
        future."""
        from shadow_tpu.core.event import KIND_TASK
        from shadow_tpu.host.cpu import Cpu
        from shadow_tpu.host.netstack import HostNetStack

        log.info("host %s restarting (fault injection; %d events "
                 "quarantined while down)", host.name,
                 host.events_quarantined)
        host.crashed = False
        old_pcap = host.net.pcap if host.net is not None else None
        no = self.net_opts
        pcap_dir, host.pcap_directory = host.pcap_directory, None
        try:
            host.net = HostNetStack(
                host, self, qdisc=no.qdisc,
                router_queue=no.router_queue,
                router_static_capacity=no.router_static_capacity,
                bootstrap_end=no.bootstrap_end,
                tcp_congestion=no.tcp_congestion,
                tcp_recv_buffer=no.tcp_recv_buffer,
                tcp_send_buffer=no.tcp_send_buffer,
                tcp_recv_autotune=no.tcp_recv_autotune,
                tcp_send_autotune=no.tcp_send_autotune)
        finally:
            host.pcap_directory = pcap_dir
        # re-attach the surviving capture (see _host_crash): the
        # constructor would have truncated the pre-crash file
        host.net.pcap = old_pcap
        if host.cpu is not None:
            host.cpu = Cpu()
        if host.model_nic is not None:
            host.model_nic = type(host.model_nic)(host.bw_up_bits,
                                                  host.bw_down_bits)
        # the heartbeat chain is self-rescheduling, so a tick that
        # surfaced during the outage was quarantined and the chain is
        # dead — re-seed it at the next interval boundary (the outage
        # shows as a gap, then ticks resume). ONLY dead chains: a
        # short outage whose next tick never surfaced while down
        # still has its live chain queued, and a second seed would
        # double every subsequent tick.
        if self._hb_interval and getattr(host, "_hb_dead", False):
            host._hb_dead = False
            nxt = (ctx.now // self._hb_interval + 1) * \
                self._hb_interval
            if nxt < self._hb_stop:
                self.push_event(Event(
                    time=nxt, dst_host=host.host_id,
                    src_host=host.host_id,
                    seq=host.next_event_seq(), kind=KIND_TASK,
                    task=self._make_hb_task(host)))
        if not host.respawn:
            log.warning("host %s restarted with no respawn factories "
                        "(nothing boots)", host.name)
            return
        host.apps = []
        host.app = None
        for proc_idx, (factory, start_time, stop_time, is_model) in \
                enumerate(host.respawn):
            if stop_time is not None and 0 <= stop_time <= ctx.now:
                # the process's configured life ended while the host
                # was down — it stays dead (a None placeholder keeps
                # later processes' BOOT/STOP indices aligned)
                host.apps.append(None)
                continue
            app = factory()
            host.apps.append(app)
            # mirror build()'s primary-app rule: the model app (at
            # most one) is always the packet/timer dispatch target
            if is_model or host.app is None:
                host.app = app
            # boot NOW only if the original start has passed; a
            # future start_time still has its original KIND_BOOT
            # event queued (it was never quarantined), and the
            # original KIND_STOP likewise fires on this new app —
            # pushing duplicates here would double-boot/-stop
            if start_time <= ctx.now:
                self.push_event(Event(
                    time=ctx.now, dst_host=host.host_id,
                    src_host=host.host_id,
                    seq=host.next_event_seq(),
                    kind=KIND_BOOT, data=(proc_idx,)))

    def boot_hosts(self, start_times: list[tuple]) -> None:
        """start_times: (host_id, start_time, stop_time|-1[, proc_idx])
        per process. Boot/stop events enter the queue before the first
        round (worker_bootHosts analogue, worker.c:581-591); the
        process index rides in the event data so multi-process hosts
        boot each process independently."""
        for entry in start_times:
            host_id, t_start, t_stop = entry[0], entry[1], entry[2]
            idx = entry[3] if len(entry) > 3 else 0
            h = self.hosts[host_id]
            self.push_event(Event(time=t_start, dst_host=host_id,
                                  src_host=host_id,
                                  seq=h.next_event_seq(),
                                  kind=KIND_BOOT, data=(idx,)))
            if t_stop is not None and t_stop >= 0:
                self.push_event(Event(time=t_stop, dst_host=host_id,
                                      src_host=host_id,
                                      seq=h.next_event_seq(),
                                      kind=KIND_STOP, data=(idx,)))

    def _apply_verdict(self, rec: tuple, delivered: bool,
                       deliver_time: int) -> None:
        """Single place where a judged packet becomes stats + an event
        (or a drop) — used by both the synchronous fallback and the
        batched device path, so their bookkeeping cannot diverge."""
        from shadow_tpu.routing.packet import PacketStatus

        _, src_h, dst_h, _, ev_seq, kind, data = rec
        host = self.hosts[src_h]
        host.packets_sent += 1
        pkt = data[0] if kind == KIND_ROUTER_ARRIVAL else None
        if not delivered:
            host.packets_dropped += 1
            if pkt is not None:
                pkt.add_status(PacketStatus.INET_DROPPED)
            return
        if pkt is not None:
            pkt.add_status(PacketStatus.INET_SENT)
        self.push_event(Event(time=int(deliver_time), dst_host=dst_h,
                              src_host=src_h, seq=ev_seq, kind=kind,
                              data=data))

    def defer_judgment(self, now: int, host, dst_host: int, pkt_seq: int,
                       ev_seq: int, kind: int, data: tuple) -> None:
        """Hybrid mode: queue one egress packet for the end-of-round
        device batch. The event seq was already consumed by the caller
        so later seq allocations are unaffected by the deferral.

        Self-destined packets are judged synchronously instead: they
        are exempt from the causality bump (SchedulerPolicy
        .apply_barrier), so one below the barrier must enter the queue
        NOW to run this round in per-host time order (possible when a
        runahead override exceeds the self-path latency). The verdict
        is a pure function of (seed, src, pkt_seq) either way, so sync
        and batched rolls agree bit-for-bit."""
        rec = (now, host.host_id, dst_host, pkt_seq, ev_seq, kind, data)
        if dst_host == host.host_id:
            v = self.netmodel.judge(now, host.host_id, dst_host, pkt_seq)
            self._apply_verdict(rec, v.delivered, v.deliver_time)
            return
        with self._pending_lock:
            self._pending.append(rec)

    def flush_judgments(self) -> None:
        """Judge every pending cross-host packet in one device batch
        and push the delivery events. Verdicts are bit-identical to the
        synchronous CPU path (same threefry chain, same latency
        matrices), so hybrid traces equal pure-CPU traces."""
        from collections import Counter

        import numpy as np

        with self._pending_lock:
            pending, self._pending = self._pending, []
        if not pending:
            return
        j = self.net_judge
        with self.tracer.span("judge.flush", "judge",
                              sim_t0=pending[0][0],
                              sim_t1=self._barrier,
                              pkts=len(pending)) as sp:
            if len(pending) < getattr(j, "min_batch", 0):
                # adaptive: a round this small never amortizes the
                # device dispatch — the synchronous CPU roll is
                # bit-identical (same threefry chain), so only the
                # wall clock changes
                for rec in pending:
                    v = self.netmodel.judge(rec[0], rec[1], rec[2],
                                            rec[3])
                    self._apply_verdict(rec, v.delivered,
                                        v.deliver_time)
                j.cpu_batches += 1
                j.cpu_packets += len(pending)
                nm = self.netmodel
                nm.record_paths(Counter(
                    (int(nm.host_vertex[r[1]]),
                     int(nm.host_vertex[r[2]])) for r in pending))
                sp.add(where="cpu")
                return
            now = np.fromiter((p[0] for p in pending), np.int64,
                              len(pending))
            src = np.fromiter((p[1] for p in pending), np.int32,
                              len(pending))
            dst = np.fromiter((p[2] for p in pending), np.int32,
                              len(pending))
            seq = np.fromiter((p[3] for p in pending), np.int32,
                              len(pending))
            delivered, deliver_time = self.net_judge.judge_batch(
                now, src, dst, seq)
            nm = self.netmodel
            nm.record_paths(Counter(
                (int(nm.host_vertex[r[1]]), int(nm.host_vertex[r[2]]))
                for r in pending))
            for i, rec in enumerate(pending):
                self._apply_verdict(rec, bool(delivered[i]),
                                    deliver_time[i])
            sp.add(where="device")

    def run_window(self, window_start: int, window_end: int) -> int:
        """Execute all events in [window_start, window_end); return the
        earliest remaining event time (scheduler_awaitNextRound).

        In hybrid mode the round's cross-host egress packets are judged
        in one device batch after the drain; every verdict lands at or
        after the barrier (cross-host events get the causality bump,
        self-destined ones were judged synchronously), so one flush per
        round suffices."""
        self._barrier = window_end
        if hasattr(self.policy, "run_parallel"):
            self.policy.run_parallel(self, window_end)
        else:
            while (ev := self.policy.pop(window_end)) is not None:
                self.execute_event(ev, self._ctx, self.stats)
        if self.net_judge is not None:
            self.flush_judgments()
        self.stats.rounds += 1
        return self.policy.next_event_time()

    def finalize(self) -> SimStats:
        for ws in self._worker_stats:
            self.stats.merge(ws)
        self._worker_stats.clear()
        # packet totals come from the per-host counters, which both the
        # raw-send path (worker.py) and the socket path (netstack.py)
        # maintain — the single source of truth
        self.stats.packets_sent = sum(h.packets_sent for h in self.hosts)
        self.stats.packets_dropped = sum(h.packets_dropped
                                         for h in self.hosts)
        self.stats.packets_delivered = sum(h.packets_delivered
                                           for h in self.hosts)
        if hasattr(self.policy, "shutdown"):
            self.policy.shutdown()
        for h in self.hosts:
            if h.net is not None and h.net.pcap is not None:
                h.net.pcap.close()
        return self.stats

    def _make_hb_task(self, host):
        """One host's self-rescheduling heartbeat task (shared by the
        initial seeding and the host_restart re-seed)."""
        from shadow_tpu.core.event import KIND_TASK

        interval, stop = self._hb_interval, self._hb_stop

        def task(ctx, ev):
            # hybrid: settle this round's pending drop verdicts so
            # the CSV counters match the pure-CPU oracle's interval
            # attribution (drop rolls are pure functions of
            # (seed, src, pkt_seq) — flushing mid-round is safe).
            # Serial policies only: under threaded policies a flush
            # from a worker would race other workers' counter
            # updates, and threaded heartbeat attribution is
            # unordered in pure-CPU mode anyway. One flush per
            # heartbeat tick, not per host.
            if (self.net_judge is not None
                    and not hasattr(self.policy, "run_parallel")
                    and self._last_hb_flush != ev.time):
                self._last_hb_flush = ev.time
                self.flush_judgments()
            host.tracker.heartbeat(ev.time, host)
            nxt = ev.time + interval
            if nxt < stop:
                self.push_event(Event(
                    time=nxt, dst_host=host.host_id,
                    src_host=host.host_id,
                    seq=host.next_event_seq(), kind=KIND_TASK,
                    task=task))
        # lets the quarantine path recognize a dead heartbeat chain
        # (the restart re-seed must not duplicate a chain whose next
        # tick survived the outage)
        task._hb_chain = True
        return task

    def schedule_heartbeats(self, interval: int, stop: int) -> None:
        """Per-host heartbeat chain (tracker_heartbeat, tracker.c:565)."""
        from shadow_tpu.core.event import KIND_TASK
        from shadow_tpu.host.tracker import Tracker

        self._hb_interval, self._hb_stop = interval, stop
        for h in self.hosts:
            h.tracker = Tracker(h.name, interval)
            self.push_event(Event(time=interval, dst_host=h.host_id,
                                  src_host=h.host_id,
                                  seq=h.next_event_seq(),
                                  kind=KIND_TASK,
                                  task=self._make_hb_task(h)))

    def dump_state(self) -> str:
        """Per-host / per-process diagnostic snapshot — what the round
        watchdog prints when a round stalls: executed/quarantined
        event counts, crash state, app types, and for managed (real)
        processes each thread's parked (blocked) syscall."""
        lines = []
        for h in self.hosts:
            apps = ",".join(type(a).__name__ for a in h.apps) or "-"
            lines.append(
                f"  host {h.name} (id {h.host_id}): "
                f"events={h.events_executed} "
                f"quarantined={h.events_quarantined} "
                f"crashed={h.crashed} apps=[{apps}]")
            for app in h.apps:
                threads = getattr(app, "threads", None)
                if not isinstance(threads, dict):
                    continue
                for vtid, th in threads.items():
                    parked = getattr(th, "parked", None)
                    if parked is None:
                        continue
                    from shadow_tpu.host.syscalls import NR_NAME
                    nr = parked[0] if parked else -1
                    lines.append(
                        f"    vtid {vtid}: blocked in syscall "
                        f"{NR_NAME.get(nr, nr)}")
        return "\n".join(lines)

    @staticmethod
    def _proc_of(host, ev: Event):
        """BOOT/STOP dispatch target: the process the event's index
        names (multi-process hosts), defaulting to the primary app."""
        if ev.data and host.apps:
            idx = ev.data[0]
            if 0 <= idx < len(host.apps):
                return host.apps[idx]
        return host.app

    def execute_event(self, ev: Event, ctx: SimContext,
                      stats: SimStats) -> None:
        """event_execute analogue (core/work/event.c:64): set the clock
        and host context, apply the CPU-delay model, dispatch by kind."""
        host = self.hosts[ev.dst_host]
        if host.crashed and ev.kind != KIND_HOST_RESTART:
            # quarantine: a crashed host executes nothing — events
            # surfacing for it while down are counted (packet kinds
            # also count as drops: the network lost them at the dead
            # NIC) and discarded. Per-host event order makes this
            # deterministic under every policy: the crash event at an
            # earlier (time, src, seq) key always runs first.
            host.events_quarantined += 1
            if ev.kind in (KIND_PACKET, KIND_PACKET_READY,
                           KIND_ROUTER_ARRIVAL):
                host.packets_dropped += ev.npkts
            if ev.task is not None and \
                    getattr(ev.task, "_hb_chain", False):
                # the self-rescheduling heartbeat tick died here —
                # _host_restart re-seeds exactly the dead chains
                host._hb_dead = True
            return
        if host.cpu is not None:
            host.cpu.update_time(ev.time)
            if host.cpu.is_blocked(ev.time):
                # defer delivery while the virtual CPU is busy
                # (event.c:70-87). Deferral times are forced strictly
                # increasing per host: precision rounding could
                # otherwise re-order two deferred events whose original
                # order the (time,dst,src,seq) key had fixed.
                new_time = ev.time + host.cpu.delay_until_ready(ev.time)
                floor = getattr(host, "_cpu_defer_floor", -1)
                new_time = max(new_time, floor + 1)
                host._cpu_defer_floor = new_time
                ev.time = new_time
                self.policy.push(ev, self._barrier)
                return
        ctx.now = ev.time
        ctx.host = host
        set_context(ev.time, host.name, host.host_id)
        try:
            host.events_executed += 1
            host.trace_checksum = chk_mix(host.trace_checksum, ev.time,
                                          ev.src_host, ev.kind, ev.seq)
            if host.tracker is not None:
                host.tracker.on_event()
            stats.events_executed += 1
            if self.trace is not None:
                with self._trace_lock:
                    self.trace.append((ev.time, ev.dst_host, ev.src_host,
                                       ev.kind))
            if self.on_event_hook is not None:
                self.on_event_hook(ev)
            app = host.app
            if ev.task is not None:
                ev.execute(ctx)
            elif ev.kind in (KIND_ROUTER_ARRIVAL, KIND_NIC_WAKE,
                             KIND_TCP_TIMER):
                host.net.handle_event(ev, ev.time, ctx)
            elif ev.kind == KIND_PACKET:
                nic = host.model_nic
                if nic is not None:
                    # model-NIC RX stage: CoDel may drop; otherwise the
                    # payload re-fires as KIND_PACKET_READY after the
                    # download-bandwidth serialization. Pushed without
                    # the causality bump: it is this host's own future
                    # (the device engine inserts into the local heap
                    # the same way).
                    size = ev.data[0] if ev.data else 0
                    deliver = nic.rx_deliver(ev.time, size)
                    if deliver < 0:
                        host.packets_dropped += 1
                    else:
                        self.policy.push(
                            Event(time=deliver, dst_host=ev.dst_host,
                                  src_host=ev.src_host, seq=ev.seq,
                                  kind=KIND_PACKET_READY, data=ev.data,
                                  npkts=ev.npkts),
                            simtime.SIMTIME_INVALID)
                else:
                    host.packets_delivered += ev.npkts
                    if app is not None:
                        size = ev.data[0] if ev.data else 0
                        app.on_packet(ctx, ev.src_host, size,
                                      ev.data[1:])
            elif ev.kind == KIND_PACKET_READY:
                host.packets_delivered += ev.npkts
                if app is not None:
                    size = ev.data[0] if ev.data else 0
                    app.on_packet(ctx, ev.src_host, size, ev.data[1:])
            elif ev.kind == KIND_TIMER:
                if app is not None:
                    app.on_timer(ctx, ev.data)
            elif ev.kind == KIND_BOOT:
                target = self._proc_of(host, ev)
                if target is not None:
                    target.boot(ctx)
            elif ev.kind == KIND_STOP:
                target = self._proc_of(host, ev)
                if target is not None:
                    target.on_stop(ctx)
            elif ev.kind == KIND_HOST_CRASH:
                self._host_crash(ctx, host)
            elif ev.kind == KIND_HOST_RESTART:
                self._host_restart(ctx, host)
        finally:
            clear_context()


class RoundWatchdog:
    """Wall-clock stall detector for the scheduling round loop
    (experimental.round_watchdog, seconds; 0 = off).

    A wedged host-side call — a blocking open the emulation missed, a
    managed process spinning off-channel — used to hang the whole
    simulator forever with zero diagnostics. The watchdog samples a
    cheap progress signal (rounds + per-host executed-event counters)
    from a daemon thread; when NOTHING moves for `interval` wall
    seconds it dumps per-host/per-process state (Manager.dump_state:
    current blocked syscall, quarantine counts) plus the flight
    recorder's last completed spans (shadow_tpu/obs — what the run
    was DOING when it froze) and aborts the run with a diagnostic
    instead of hanging.

    `on_stall(dump)` is injectable for tests; the default logs the
    dump, marks stats not-ok, and interrupts the main thread.
    `dump_path` (experimental.round_watchdog_dump) additionally
    persists the dump to a file via the atomic tmp+rename helper —
    written BEFORE on_stall runs, so even a custom handler (or a
    truncated log) leaves the post-mortem on disk."""

    def __init__(self, manager: Manager, interval_s: float,
                 on_stall=None, dump_path: str = ""):
        if interval_s <= 0:
            raise ValueError("round_watchdog interval must be > 0")
        self._m = manager
        self.interval = float(interval_s)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.on_stall = on_stall or self._default_stall
        self.dump_path = dump_path
        self.fired = False

    def _progress(self) -> tuple:
        m = self._m
        return (m.stats.rounds,
                sum(h.events_executed for h in m.hosts),
                sum(h.events_quarantined for h in m.hosts))

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._run, name="round-watchdog", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)

    def _run(self) -> None:
        import time as _time

        poll = max(0.05, min(self.interval / 4.0, 1.0))
        last = self._progress()
        last_t = _time.monotonic()
        while not self._stop.wait(poll):
            cur = self._progress()
            if cur != last:
                last, last_t = cur, _time.monotonic()
                continue
            if _time.monotonic() - last_t >= self.interval:
                self.fired = True
                dump = self._m.dump_state()
                # the flight recorder's recent-span ring shows what
                # the run WAS doing (last dispatches, judge flushes,
                # checkpoints), not just where it stopped — embedded
                # in both the log dump and the on-disk post-mortem
                tracer = getattr(self._m, "tracer", None)
                recent = (tracer.format_recent()
                          if tracer is not None else "")
                if recent:
                    dump = f"{dump}\n{recent}"
                if self.dump_path:
                    try:
                        from shadow_tpu.utils.artifacts import \
                            atomic_write_text
                        atomic_write_text(
                            f"round watchdog stall dump (no progress "
                            f"for {self.interval:.0f}s wall)\n"
                            f"{dump}\n", self.dump_path)
                        log.info("watchdog stall dump -> %s",
                                 self.dump_path)
                    except OSError as e:
                        log.warning("could not write watchdog dump "
                                    "%s: %s", self.dump_path, e)
                self.on_stall(dump)
                return

    def _default_stall(self, dump: str) -> None:
        import signal

        log.error(
            "round watchdog: no scheduling progress for %.0fs wall — "
            "aborting with per-host state:\n%s", self.interval, dump)
        self._m.stats.ok = False
        # a REAL signal to the main thread: pthread_kill delivers
        # SIGINT so a main thread wedged inside a blocking C call
        # (the exact class this watchdog exists for) takes EINTR and
        # raises KeyboardInterrupt; interrupt_main() would only set a
        # flag checked between bytecodes, which such a thread never
        # reaches
        try:
            signal.pthread_kill(threading.main_thread().ident,
                                signal.SIGINT)
        except (ValueError, ProcessLookupError, RuntimeError, OSError):
            import _thread
            _thread.interrupt_main()
