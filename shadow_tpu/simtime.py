"""Simulation time: signed 64-bit nanoseconds since simulation start.

Mirrors the reference's SimulationTime/EmulatedTime split (reference
src/main/core/support/definitions.h:40-90): simulation time starts at 0 ns;
emulated (wall-clock visible to applications) time is offset so that sim
start corresponds to a fixed epoch, giving deterministic `gettimeofday`
results inside the simulation.

We use *signed* int64 (not u64 like the reference) because JAX/XLA has no
native uint64 on TPU and signed arithmetic makes "invalid = -1" sentinels
cheap. 2**63 ns is ~292 years of simulated time, far beyond any run.
"""

from __future__ import annotations

import numpy as np

# dtype used for every time value, host- and device-side.
TIME_DTYPE = np.int64

SIMTIME_INVALID: int = -1
SIMTIME_MAX: int = np.iinfo(np.int64).max - 1

SIMTIME_ONE_NANOSECOND: int = 1
SIMTIME_ONE_MICROSECOND: int = 1_000
SIMTIME_ONE_MILLISECOND: int = 1_000_000
SIMTIME_ONE_SECOND: int = 1_000_000_000
SIMTIME_ONE_MINUTE: int = 60 * SIMTIME_ONE_SECOND
SIMTIME_ONE_HOUR: int = 60 * SIMTIME_ONE_MINUTE

# Emulated time offset: simulation time 0 == 2000-01-01 00:00:00 UTC
# (946684800 seconds after the Unix epoch), matching the reference
# (definitions.h:79) so applications observe plausible wall-clock dates.
EMULATED_TIME_OFFSET: int = 946_684_800 * SIMTIME_ONE_SECOND

# Network constants (reference definitions.h:173-195).
CONFIG_MTU: int = 1500
CONFIG_HEADER_SIZE_TCP: int = 20
CONFIG_HEADER_SIZE_IP: int = 20
CONFIG_HEADER_SIZE_UDP: int = 8
CONFIG_HEADER_SIZE_TCPIPETH: int = 54
CONFIG_HEADER_SIZE_UDPIPETH: int = 42
CONFIG_TCP_TIMEWAIT_SECONDS: int = 60
CONFIG_TCP_MAX_SEGMENT_SIZE: int = CONFIG_MTU - CONFIG_HEADER_SIZE_TCP - CONFIG_HEADER_SIZE_IP


def from_seconds(s: float) -> int:
    return int(round(s * SIMTIME_ONE_SECOND))


def from_millis(ms: float) -> int:
    return int(round(ms * SIMTIME_ONE_MILLISECOND))


def from_micros(us: float) -> int:
    return int(round(us * SIMTIME_ONE_MICROSECOND))


def to_seconds(t: int) -> float:
    return t / SIMTIME_ONE_SECOND


def to_millis(t: int) -> float:
    return t / SIMTIME_ONE_MILLISECOND


def to_emulated(t: int) -> int:
    """Sim time -> emulated (application-visible) nanoseconds since Unix epoch."""
    return t + EMULATED_TIME_OFFSET


def from_emulated(t: int) -> int:
    return t - EMULATED_TIME_OFFSET


def format_time(t: int) -> str:
    """Human-readable hh:mm:ss.nnnnnnnnn, for log stamps."""
    if t < 0:
        return "n/a"
    ns = t % SIMTIME_ONE_SECOND
    s = t // SIMTIME_ONE_SECOND
    h, s = divmod(s, 3600)
    m, s = divmod(s, 60)
    return f"{h:02d}:{m:02d}:{s:02d}.{ns:09d}"
