"""Worker CPU affinity (src/main/host/affinity.c analogue).

Parses the machine topology from /proc/cpuinfo (processor, physical
package id, core id) and hands out one CPU per worker, spreading
across physical cores before reusing hyperthread siblings — the same
placement goal as the reference's affinity_getGoodWorkerAffinity
(affinity.c, used core/worker.c:316-330). Pinning is per-thread via
sched_setaffinity(0) from inside the worker thread.

Fails soft everywhere: exotic /proc formats or containers without
affinity rights degrade to "no pinning", never to an error.
"""

from __future__ import annotations

import os

from shadow_tpu.utils.slog import get_logger

log = get_logger("affinity")


def platform_cpus() -> list[int]:
    """CPU ids ordered for worker assignment: one logical CPU per
    physical (package, core) first, then the remaining hyperthread
    siblings, each group in id order."""
    try:
        with open("/proc/cpuinfo") as f:
            text = f.read()
    except OSError:
        return sorted(os.sched_getaffinity(0))
    cpus = []                    # (processor, physical_id, core_id)
    cur: dict = {}
    for line in text.splitlines():
        if not line.strip():
            if "processor" in cur:
                cpus.append((cur["processor"],
                             cur.get("physical id", 0),
                             cur.get("core id", cur["processor"])))
            cur = {}
            continue
        if ":" in line:
            k, _, v = line.partition(":")
            k, v = k.strip(), v.strip()
            if k in ("processor", "physical id", "core id"):
                try:
                    cur[k] = int(v)
                except ValueError:
                    pass
    if "processor" in cur:
        cpus.append((cur["processor"], cur.get("physical id", 0),
                     cur.get("core id", cur["processor"])))
    if not cpus:
        return sorted(os.sched_getaffinity(0))
    allowed = os.sched_getaffinity(0)
    cpus = [c for c in cpus if c[0] in allowed] or \
        [(c, 0, c) for c in sorted(allowed)]
    seen_cores: set = set()
    primary, siblings = [], []
    for proc, phys, core in sorted(cpus, key=lambda c: c[0]):
        if (phys, core) in seen_cores:
            siblings.append(proc)
        else:
            seen_cores.add((phys, core))
            primary.append(proc)
    return primary + siblings


def good_worker_affinity(n_workers: int) -> list[int]:
    """CPU id for each worker index (wraps when workers > CPUs)."""
    cpus = platform_cpus()
    return [cpus[i % len(cpus)] for i in range(n_workers)]


def pin_current_thread(cpu: int) -> bool:
    """Pin the calling thread to one CPU; False if not permitted."""
    try:
        os.sched_setaffinity(0, {cpu})
        return True
    except OSError as e:
        log.debug("cpu pinning unavailable: %s", e)
        return False
