"""Per-host trace checksums.

Both engines fold every executed event's (time, src, kind, seq) into a
63-bit rolling hash per host. Because a host's events execute in the
same order under every policy and engine (the (time, dst, src, seq)
total order), equal checksums certify equal per-host schedules — the
cross-engine equivalence oracle used by tests, and the spiritual
successor of the reference's determinism suite (src/test/determinism/,
which byte-compares host stdout between runs).

Pure integer math, identical in Python and in jax int64 (both sides
mask to 63 bits, which commutes with two's-complement wraparound).
"""

MASK63 = (1 << 63) - 1
CHK_MUL = 1000003
CHK_SRC = 2654435761
CHK_KIND = 1315423911
CHK_SEQ = 2246822519


def chk_mix(chk: int, time: int, src: int, kind: int, seq: int) -> int:
    mix = (time ^ (src * CHK_SRC) ^ (kind * CHK_KIND)
           ^ (seq * CHK_SEQ)) & MASK63
    return (chk * CHK_MUL + mix) & MASK63
