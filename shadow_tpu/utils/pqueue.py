"""Binary-heap priority queue with deterministic total order.

Equivalent of the reference's utility/priority_queue.c (175 LoC binary heap).
Entries are (key, item); ties are impossible by construction because every
event key ends in a unique sequence number (see core/event.py).
"""

from __future__ import annotations

import heapq
from typing import Any, Optional


class PriorityQueue:
    __slots__ = ("_heap",)

    def __init__(self):
        self._heap: list[tuple[Any, Any]] = []

    def push(self, key, item) -> None:
        heapq.heappush(self._heap, (key, item))

    def peek(self) -> Optional[tuple[Any, Any]]:
        return self._heap[0] if self._heap else None

    def peek_key(self):
        return self._heap[0][0] if self._heap else None

    def pop(self) -> Optional[tuple[Any, Any]]:
        return heapq.heappop(self._heap) if self._heap else None

    def __len__(self) -> int:
        return len(self._heap)

    def __bool__(self) -> bool:
        return bool(self._heap)
