"""Pcap capture of simulated packets.

Equivalent of src/main/utility/pcap_writer.c + the interface capture
hook (network_interface.c:341-377): writes classic pcap files (magic
0xa1b2c3d4, LINKTYPE_RAW IPv4) with synthesized IP/TCP/UDP headers so
standard tools (wireshark/tcpdump) can open simulated traces.
"""

from __future__ import annotations

import struct

from shadow_tpu import simtime
from shadow_tpu.routing.packet import Packet, Protocol

LINKTYPE_RAW = 101


class PcapWriter:
    def __init__(self, path: str):
        self._f = open(path, "wb")
        self._f.write(struct.pack("<IHHiIII", 0xA1B2C3D4, 2, 4, 0, 0,
                                  65535, LINKTYPE_RAW))

    def _ip_header(self, packet: Packet, src_ip: int, dst_ip: int,
                   payload_len: int) -> bytes:
        proto = 6 if packet.protocol == Protocol.TCP else 17
        total = 20 + payload_len
        return struct.pack(">BBHHHBBHII", 0x45, 0, total, 0, 0, 64,
                           proto, 0, src_ip, dst_ip)

    def write(self, now: int, packet: Packet, src_ip: int,
              dst_ip: int) -> None:
        if packet.protocol == Protocol.TCP and packet.tcp is not None:
            h = packet.tcp
            l4 = struct.pack(">HHIIBBHHH", h.src_port, h.dst_port,
                             h.seq & 0xFFFFFFFF, h.ack & 0xFFFFFFFF,
                             5 << 4, int(h.flags) & 0x3F,
                             min(h.window, 65535), 0, 0)
        else:
            l4 = struct.pack(">HHHH", packet.src_port, packet.dst_port,
                             8 + packet.size, 0)
        body = l4 + b"\x00" * packet.size
        frame = self._ip_header(packet, src_ip, dst_ip, len(body)) + body
        sec, ns = divmod(now, simtime.SIMTIME_ONE_SECOND)
        self._f.write(struct.pack("<IIII", sec, ns // 1000, len(frame),
                                  len(frame)))
        self._f.write(frame)

    def close(self) -> None:
        self._f.close()
