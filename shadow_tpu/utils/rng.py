"""Deterministic randomness.

Two layers, both fully determined by the global config seed:

* **Host-side hierarchy** (`SeededRandom`): controller -> manager -> host,
  like the reference's seeded GLib Random chain
  (src/main/utility/random.c, seeded controller->manager->host per
  SURVEY §5). Children are derived by hashing (parent_seed, label), so
  host creation order doesn't matter — an improvement over stream-order
  seeding.

* **Device-side counter RNG**: threefry keyed by stable integer ids
  (`jax.random.fold_in`). Every stochastic decision in the network model
  (per-packet drop rolls, jitter) is keyed by (purpose, host_id, seq), so
  results are bit-identical across reruns *and* across device-mesh
  shapes, unlike per-host sequential streams.
"""

from __future__ import annotations

import hashlib
import struct

import numpy as np

from shadow_tpu._jax import jax, jnp

# Stable purpose tags for counter-RNG domains.
PURPOSE_PACKET_DROP = 1
PURPOSE_HOST_BOOT = 2
PURPOSE_APP = 3
PURPOSE_JITTER = 4
PURPOSE_TOR_ROUTE = 5


def _derive(seed: int, label: str) -> int:
    h = hashlib.blake2b(
        struct.pack("<q", seed) + label.encode(), digest_size=8
    ).digest()
    return struct.unpack("<q", h)[0] & 0x7FFF_FFFF_FFFF_FFFF


class SeededRandom:
    """Deterministic RNG node in the controller->manager->host
    hierarchy. The numpy generator is built LAZILY: device-engine
    runs create one node per host but never draw from most of them,
    and the eager PCG64 spin-up was a measurable slice of the
    100k-host build."""

    def __init__(self, seed: int):
        self.seed = int(seed)
        self._rng = None

    @property
    def rng(self) -> np.random.Generator:
        if self._rng is None:
            self._rng = np.random.Generator(np.random.PCG64(self.seed))
        return self._rng

    def child(self, label: str) -> "SeededRandom":
        return SeededRandom(_derive(self.seed, label))

    def random(self) -> float:
        return float(self.rng.random())

    def randint(self, low: int, high: int) -> int:
        """Uniform integer in [low, high)."""
        return int(self.rng.integers(low, high))

    def shuffle(self, items: list) -> None:
        self.rng.shuffle(items)

    def np_rng(self) -> np.random.Generator:
        return self.rng


def base_key(seed: int) -> jax.Array:
    """Root device PRNG key for a simulation.

    The full 64-bit seed feeds the key (x64 mode is always on — _jax.py),
    so device randomness, like the host-side hierarchy, is a pure function
    of the whole config seed.
    """
    return jax.random.PRNGKey(seed)


def packet_key(key: jax.Array, purpose, host_id, seq) -> jax.Array:
    """Counter-based key for one stochastic decision.

    Works under jit/vmap: fold_in accepts traced integers.
    """
    k = jax.random.fold_in(key, purpose)
    k = jax.random.fold_in(k, host_id)
    return jax.random.fold_in(k, seq)


def uniform01(key: jax.Array, purpose, host_id, seq) -> jax.Array:
    """One deterministic uniform in [0,1) keyed by (purpose, host, seq)."""
    return jax.random.uniform(
        packet_key(key, purpose, host_id, seq), (), dtype=jnp.float32
    )
