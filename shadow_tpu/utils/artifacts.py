"""Atomic artifact writes: one tmp-file + os.replace helper.

Every JSON/state artifact the simulator emits (OCC_*.json occupancy
records, ENSEMBLE_*.json campaign records, device checkpoints, the
round-watchdog stall dump) must never be observable half-written: a
mid-write kill (SIGKILL, OOM, a preemption that outruns the drain)
used to leave truncated JSON that later loads choke on with a bare
parse error. POSIX rename is atomic within a filesystem, so every
writer here lands the full payload in a sibling tmp file and
os.replace()s it into place — readers see the old content or the new
content, never a prefix.

The tmp name carries the pid so two concurrent runs racing onto one
canonical path (two bench invocations sharing an OCC record) never
interleave into each other's tmp file; the loser's os.replace simply
lands second.
"""

from __future__ import annotations

import json
import os


def atomic_write(path: str, write_fn, mode: str = "wb") -> None:
    """Write via `write_fn(file_object)` into `path + .<pid>.tmp`,
    fsync, then atomically os.replace into place. On any failure the
    tmp file is removed — no decoy artifacts."""
    directory = os.path.dirname(path)
    if directory:
        os.makedirs(directory, exist_ok=True)
    tmp = f"{path}.{os.getpid()}.tmp"
    try:
        with open(tmp, mode) as f:
            write_fn(f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def atomic_write_json(obj, path: str, **json_kwargs) -> None:
    """Serialize `obj` BEFORE opening the tmp file (a non-serializable
    object must not even leave a tmp behind), then write atomically."""
    json_kwargs.setdefault("indent", 1)
    json_kwargs.setdefault("sort_keys", True)
    text = json.dumps(obj, **json_kwargs)
    atomic_write(path, lambda f: f.write(text), mode="w")


def atomic_write_text(text: str, path: str) -> None:
    atomic_write(path, lambda f: f.write(text), mode="w")


def append_line(path: str, line: str) -> None:
    """Durably append ONE line to a journal file: O_APPEND write of
    the full line + newline in a single syscall, then fsync. The
    append-only twin of atomic_write for logs that must accumulate
    (the campaign server's submission journal): a crash can tear at
    most the final line — POSIX O_APPEND writes are atomic with
    respect to other appenders, and every line before the fsync'd
    one is already on disk — so replay treats exactly one trailing
    partial line as the crash frontier, never silent mid-file loss."""
    if "\n" in line:
        raise ValueError("append_line appends exactly one line; "
                         f"embedded newline in {line!r}")
    directory = os.path.dirname(path)
    if directory:
        os.makedirs(directory, exist_ok=True)
    fd = os.open(path, os.O_WRONLY | os.O_APPEND | os.O_CREAT, 0o644)
    try:
        os.write(fd, (line + "\n").encode("utf-8"))
        os.fsync(fd)
    finally:
        os.close(fd)


class StreamedLines:
    """Line-streamed artifact with atomic final placement — the JSONL
    flight-recorder log's writer (shadow_tpu/obs). A span log must be
    STREAMED (a hung run's partial log is exactly the post-mortem
    artifact) but the canonical path must never hold a half-written
    file, so lines land in ``<path>.<pid>.partial`` as they are
    written (flushed every ``flush_every`` lines, so `tail -f` works)
    and ``close()`` fsyncs and os.replace()s the stream into place —
    the same tmp+rename contract as atomic_write, stretched over the
    artifact's lifetime. ``abandon()`` (error paths) keeps the partial
    file on disk: unlike a failed atomic_write, the prefix written so
    far is evidence, not a decoy."""

    def __init__(self, path: str, flush_every: int = 64):
        self.path = path
        self.partial = f"{path}.{os.getpid()}.partial"
        self.flush_every = max(1, int(flush_every))
        directory = os.path.dirname(path)
        if directory:
            os.makedirs(directory, exist_ok=True)
        self._f = open(self.partial, "w")
        self._pending = 0

    def write_line(self, line: str) -> None:
        self._f.write(line)
        self._f.write("\n")
        self._pending += 1
        if self._pending >= self.flush_every:
            self._f.flush()
            self._pending = 0

    def close(self) -> str:
        """Finalize: flush, fsync, and atomically land the stream at
        the canonical path. Returns the final path."""
        self._f.flush()
        os.fsync(self._f.fileno())
        self._f.close()
        os.replace(self.partial, self.path)
        return self.path

    def abandon(self) -> str:
        """Stop writing but KEEP the partial file (error paths): the
        prefix is the post-mortem. Returns the partial path."""
        try:
            self._f.flush()
            self._f.close()
        except OSError:
            pass
        return self.partial
