"""String-keyed counters, like the reference's utility/counter.rs (531 LoC):
a name -> u64 histogram used for object/syscall/packet accounting, merged
across workers at shutdown (manager.c:663-729)."""

from __future__ import annotations

from collections import defaultdict


class Counter:
    def __init__(self):
        self._c: dict[str, int] = defaultdict(int)

    def add(self, name: str, n: int = 1) -> None:
        self._c[name] += n

    def sub(self, name: str, n: int = 1) -> None:
        self._c[name] -= n

    def get(self, name: str) -> int:
        return self._c.get(name, 0)

    def merge(self, other: "Counter") -> None:
        for k, v in other._c.items():
            self._c[k] += v

    def as_dict(self) -> dict[str, int]:
        return dict(self._c)

    def __str__(self) -> str:
        items = ", ".join(f"{k}:{v}" for k, v in sorted(self._c.items()))
        return "{" + items + "}"
