"""Known-noise XLA stderr filtering for captured log tails.

The driver that runs bench.py / __graft_entry__.py captures the last
few KB of stderr into BENCH_*/MULTICHIP_*.json ``tail`` fields. On
every CPU(-fallback) start, XLA's cpu_aot_loader logs a multi-KB
single-line machine-feature WARNING (see MULTICHIP_r05.json) that
drowns every useful line in that window. ``TF_CPP_MIN_LOG_LEVEL=2``
suppresses most of it, but the AOT loader line is emitted through a
path that ignores the knob on some jaxlib builds — so the entry
points additionally route fd 2 through :func:`install_fd_filter`,
which drops known-noise lines AT THE PIPE, before anything the driver
could capture. Everything else (including real XLA errors) passes
through byte-for-byte.

:func:`filter_tail` is the pure-string twin for consumers that
already hold a captured tail: drop the noise lines and keep the last
~10 meaningful ones.
"""

from __future__ import annotations

import atexit
import os
import threading

# substrings marking a stderr line as known noise. Matched per line —
# the cpu_aot_loader warning is ONE multi-KB line, so a single match
# drops the whole blob.
NOISE_MARKERS = (
    "cpu_aot_loader",
    "Loading XLA:CPU AOT result",
    "machine type for execution",
    "Machine type used for XLA:CPU compilation",
    "This could lead to execution errors such as SIGILL",
    # absl/TF banner noise that survives TF_CPP_MIN_LOG_LEVEL on
    # some builds
    "TensorFlow binary is optimized",
    "computation placer already registered",
)


def is_noise_line(line: str) -> bool:
    return any(m in line for m in NOISE_MARKERS)


def filter_tail(text: str, keep: int = 10) -> str:
    """Drop known-noise lines from a captured stderr tail and keep
    the last `keep` meaningful (non-empty, non-noise) lines."""
    lines = [ln for ln in text.splitlines()
             if ln.strip() and not is_noise_line(ln)]
    return "\n".join(lines[-keep:])


class _FdFilter:
    """Routes an OS-level fd (default 2) through a pipe; a daemon
    thread forwards every line that is not known noise to the
    original fd. Line-based: a line is held until its newline
    arrives, so the multi-KB one-line XLA warning is dropped whole.
    An unterminated trailing chunk is flushed on close/exit AND after
    a short idle window — a hard crash (C++ abort, SIGILL) never runs
    atexit, so holding a partial line indefinitely would lose exactly
    the diagnostic that mattered; the idle flush bounds that loss to
    whatever arrived in the final IDLE_FLUSH_S. (Bytes a crash leaves
    unread in the kernel pipe are inherently unrecoverable from
    inside the process — the filter trades that sliver for clean
    captured tails on every surviving path.)"""

    IDLE_FLUSH_S = 0.2

    def __init__(self, fd: int = 2):
        self.fd = fd
        self.saved = os.dup(fd)
        self._rd, self._wr = os.pipe()
        os.dup2(self._wr, fd)
        os.close(self._wr)
        self._thread = threading.Thread(target=self._pump,
                                        daemon=True)
        self._thread.start()
        atexit.register(self.close)

    def _pump(self) -> None:
        import select

        buf = b""
        try:
            while True:
                ready, _, _ = select.select([self._rd], [], [],
                                            self.IDLE_FLUSH_S)
                if not ready:
                    if buf:
                        # idle: forward the partial line now rather
                        # than risk dying with it (a leaked noise
                        # FRAGMENT beats a lost crash diagnostic)
                        self._emit(buf)
                        buf = b""
                    continue
                chunk = os.read(self._rd, 65536)
                if not chunk:
                    break
                buf += chunk
                while True:
                    nl = buf.find(b"\n")
                    if nl < 0:
                        break
                    line, buf = buf[:nl + 1], buf[nl + 1:]
                    self._emit(line)
        except OSError:
            pass
        if buf:
            self._emit(buf)

    def _emit(self, line: bytes) -> None:
        try:
            text = line.decode("utf-8", "replace")
        except Exception:       # noqa: BLE001 — never lose output
            text = ""
        if text and is_noise_line(text):
            return
        try:
            os.write(self.saved, line)
        except OSError:
            pass

    def close(self) -> None:
        """Restore the original fd and drain the pipe. Idempotent."""
        if self.saved is None:
            return
        try:
            os.dup2(self.saved, self.fd)
        except OSError:
            pass
        # closing the last write end EOFs the reader thread
        self._thread.join(timeout=2.0)
        for f in (self._rd, self.saved):
            try:
                os.close(f)
            except OSError:
                pass
        self.saved = None


_installed: _FdFilter | None = None


def install_fd_filter(fd: int = 2):
    """Install the stderr noise filter once per process (no-op on
    repeat calls, and disabled entirely by
    SHADOW_TPU_STDERR_FILTER=0). Returns the filter handle."""
    global _installed
    if os.environ.get("SHADOW_TPU_STDERR_FILTER", "1") == "0":
        return None
    if _installed is None:
        _installed = _FdFilter(fd)
    return _installed
