"""Numpy replica of jax's threefry counter RNG (bit-for-bit).

The CPU reference engine must make the *same* stochastic decisions
(packet-drop rolls) as the device engine to be a trace-equivalence
oracle, without paying a jax dispatch per packet. Threefry-2x32 is a
pure ARX hash, so we reimplement the exact chain used by
``jax.random.fold_in`` + ``jax.random.uniform`` (jax._src.prng, with
``threefry_partitionable`` on — the default) in vectorized numpy.
tests/test_nprng.py asserts bit-identity against jax on every path.

All functions are vectorized: ``data``/etc. may be numpy arrays.
"""

from __future__ import annotations

import numpy as np

_ROT_A = (13, 15, 26, 6)
_ROT_B = (17, 29, 16, 24)
_PARITY = np.uint32(0x1BD11BDA)


def _rotl(x: np.ndarray, r: int) -> np.ndarray:
    return (x << np.uint32(r)) | (x >> np.uint32(32 - r))


def threefry2x32(k1, k2, x0, x1) -> tuple[np.ndarray, np.ndarray]:
    """The Threefry-2x32 block cipher, 20 rounds (matches XLA's
    threefry2x32 primitive)."""
    with np.errstate(over="ignore"):
        k1 = np.asarray(k1, dtype=np.uint32)
        k2 = np.asarray(k2, dtype=np.uint32)
        x0 = np.asarray(x0, dtype=np.uint32).copy()
        x1 = np.asarray(x1, dtype=np.uint32).copy()
        ks = (k1, k2, k1 ^ k2 ^ _PARITY)

        x0 = x0 + ks[0]
        x1 = x1 + ks[1]
        for block in range(5):
            rots = _ROT_A if block % 2 == 0 else _ROT_B
            for r in rots:
                x0 = x0 + x1
                x1 = _rotl(x1, r) ^ x0
            x0 = x0 + ks[(block + 1) % 3]
            x1 = x1 + ks[(block + 2) % 3] + np.uint32(block + 1)
        return x0, x1


def seed_key(seed) -> tuple[np.ndarray, np.ndarray]:
    """jax.random.PRNGKey(seed) -> raw (k1, k2) uint32 pair."""
    seed = np.asarray(seed, dtype=np.uint64)
    return (seed >> np.uint64(32)).astype(np.uint32), \
        (seed & np.uint64(0xFFFFFFFF)).astype(np.uint32)


def fold_in(key: tuple[np.ndarray, np.ndarray], data
            ) -> tuple[np.ndarray, np.ndarray]:
    """jax.random.fold_in on raw key pairs (data is cast to uint32,
    exactly like threefry_fold_in)."""
    k1, k2 = key
    data = np.asarray(data, dtype=np.uint32)
    zero = np.zeros_like(data)
    return threefry2x32(k1, k2, zero, data)


def random_bits32(key: tuple[np.ndarray, np.ndarray]) -> np.ndarray:
    """32 random bits for a scalar draw per key (partitionable path,
    shape ()): threefry(k1,k2,0,0) -> bits1 ^ bits2."""
    k1, k2 = key
    zero = np.zeros_like(k1)
    b1, b2 = threefry2x32(k1, k2, zero, zero)
    return b1 ^ b2


def uniform01(key: tuple[np.ndarray, np.ndarray]) -> np.ndarray:
    """jax.random.uniform(key, (), dtype=float32): mantissa-fill trick."""
    bits = random_bits32(key)
    float_bits = (bits >> np.uint32(9)) | np.uint32(0x3F800000)
    return float_bits.view(np.float32) - np.float32(1.0)


# ---------------------------------------------------------------------
# The composed chain used for packet decisions, mirroring
# shadow_tpu.utils.rng.uniform01 (purpose -> host -> seq fold-ins).

def packet_uniform(seed: int, purpose, host_id, seq) -> np.ndarray:
    k = seed_key(seed)
    k = fold_in(k, purpose)
    k = fold_in(k, host_id)
    k = fold_in(k, seq)
    return uniform01(k)
