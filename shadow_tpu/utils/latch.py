"""CountDownLatch (utility/count_down_latch.c analogue)."""

from __future__ import annotations

import threading


class CountDownLatch:
    def __init__(self, count: int):
        self._count = count
        self._cond = threading.Condition()

    def count_down(self) -> None:
        with self._cond:
            self._count -= 1
            if self._count <= 0:
                self._cond.notify_all()

    def wait(self, timeout: float | None = None) -> bool:
        with self._cond:
            return self._cond.wait_for(lambda: self._count <= 0, timeout)
