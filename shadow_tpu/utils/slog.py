"""Sim-time-stamped logging.

Equivalent of the reference's ShadowLogger (core/logger/shadow_logger.rs):
records are tagged with both wall time and simulation time plus the active
host context, and buffered per run. We layer on Python's logging with a
context object the worker sets around event execution.
"""

from __future__ import annotations

import logging
import sys
import threading
import time
from dataclasses import dataclass

from shadow_tpu import simtime

_context = threading.local()


@dataclass
class LogContext:
    sim_time: int = simtime.SIMTIME_INVALID
    host_name: str = ""
    host_id: int = -1


def set_context(sim_time: int, host_name: str = "", host_id: int = -1) -> None:
    _context.ctx = LogContext(sim_time, host_name, host_id)


def clear_context() -> None:
    _context.ctx = LogContext()


def get_context() -> LogContext:
    return getattr(_context, "ctx", LogContext())


class SimTimeFormatter(logging.Formatter):
    def __init__(self):
        super().__init__()
        self._start = time.monotonic()

    def format(self, record: logging.LogRecord) -> str:
        ctx = get_context()
        wall = time.monotonic() - self._start
        stamp = simtime.format_time(ctx.sim_time)
        host = f" [{ctx.host_name}]" if ctx.host_name else ""
        return (f"{wall:012.6f} [{stamp}] {record.levelname.lower()}"
                f"{host} [{record.name}] {record.getMessage()}")


def init_logging(level: str = "info", stream=None) -> None:
    lvl = {"error": logging.ERROR, "warning": logging.WARNING,
           "info": logging.INFO, "debug": logging.DEBUG,
           "trace": logging.DEBUG}.get(level, logging.INFO)
    handler = logging.StreamHandler(stream or sys.stderr)
    handler.setFormatter(SimTimeFormatter())
    root = logging.getLogger("shadow_tpu")
    root.handlers[:] = [handler]
    root.setLevel(lvl)


def get_logger(name: str) -> logging.Logger:
    return logging.getLogger(f"shadow_tpu.{name}")
