# Deliberately jax-free: utils.rng pulls in jax, so it is imported
# directly by the modules that need it (see shadow_tpu/_jax.py).
from shadow_tpu.utils.pqueue import PriorityQueue
from shadow_tpu.utils.counters import Counter

__all__ = ["PriorityQueue", "Counter"]
