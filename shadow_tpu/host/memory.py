"""Plugin-memory access: the MemoryManager equivalent.

The reference reaches into managed-process address spaces two ways:
a MemoryCopier over process_vm_readv/writev and a MemoryMapper that
remaps the plugin heap into Shadow (src/main/host/memory_manager/
mod.rs:1-17, memory_copier.rs). This is the copier path.

A zero-copy mapper port was evaluated and DELIBERATELY not built:
measured on a 2 MB managed TCP transfer (tcp_client/tcp_server under
the preload shim), the copier accounts for 1.2% of simulation wall
time (1690 ops / 4 MB / 35 ms of 2.94 s) — the hot path in this
simulator is the IPC ping-pong + dispatch, not the copies the
reference's mapper eliminates. The mapper's machinery (rewriting
plugin mmap/brk to MAP_SHARED shmem files; memory_mapper.rs:22-35)
would buy at most that 1% here while adding an in-plugin remap
protocol to both interposition backends. Revisit only if a profile
shows the copier share growing past ~10% (e.g. a syscall-dense
workload moving large iovecs).

Works on direct children without privileges (Yama ptrace_scope 1
allows parent->child).

Also holds the struct codecs for the kernel ABI types the syscall
handler marshals (sockaddr_in, timespec, epoll_event, pollfd, iovec,
utsname) — the kernel_types.h analogue.
"""

from __future__ import annotations

import ctypes
import ctypes.util
import os
import struct

_libc = ctypes.CDLL(None, use_errno=True)


class _IoVec(ctypes.Structure):
    _fields_ = [("iov_base", ctypes.c_void_p),
                ("iov_len", ctypes.c_size_t)]


def _vm_op(fn, pid: int, local_buf, remote_addr: int, n: int) -> int:
    local = _IoVec(ctypes.cast(local_buf, ctypes.c_void_p), n)
    remote = _IoVec(ctypes.c_void_p(remote_addr), n)
    got = fn(pid, ctypes.byref(local), 1, ctypes.byref(remote), 1, 0)
    if got < 0:
        err = ctypes.get_errno()
        raise OSError(err, os.strerror(err))
    return got


class ProcessMemory:
    """Read/write a live child process's memory by address.

    Primary transport: process_vm_readv/writev (the reference's
    MemoryCopier, memory_copier.rs). Fallback: /proc/[pid]/mem seeks —
    some sandboxes restrict the vm syscalls (Yama, seccomp policies on
    the SIMULATOR) while still exposing /proc; the first EPERM flips
    this process over permanently."""

    def __init__(self, pid: int):
        self.pid = pid
        self._use_proc = False
        self._proc_r = None         # cached /proc/[pid]/mem handles
        self._proc_w = None
        # copier-share telemetry: memory.py's "revisit the zero-copy
        # mapper past ~10% of wall" threshold is MONITORED, not
        # aspirational — the tracker heartbeat diffs these per
        # interval, and SHADOWTPU_COPY_TIMING=1 adds wall-time
        # accumulation (scripts/copier_share.py divides by run wall)
        self.copy_ops = 0
        self.copy_bytes = 0
        self.copy_ns = 0
        self._timed = bool(os.environ.get("SHADOWTPU_COPY_TIMING"))

    def _proc_read(self, addr: int, n: int) -> bytes:
        if self._proc_r is None:
            self._proc_r = open(f"/proc/{self.pid}/mem", "rb",
                                buffering=0)
        self._proc_r.seek(addr)
        return self._proc_r.read(n)

    def _proc_write(self, addr: int, data: bytes) -> int:
        if self._proc_w is None:
            self._proc_w = open(f"/proc/{self.pid}/mem", "wb",
                                buffering=0)
        self._proc_w.seek(addr)
        return self._proc_w.write(data)

    def read(self, addr: int, n: int) -> bytes:
        if n == 0:
            return b""
        self.copy_ops += 1
        self.copy_bytes += n
        if self._timed:
            import time
            t0 = time.perf_counter_ns()
            try:
                return self._read_impl(addr, n)
            finally:
                self.copy_ns += time.perf_counter_ns() - t0
        return self._read_impl(addr, n)

    def _read_impl(self, addr: int, n: int) -> bytes:
        if self._use_proc:
            return self._proc_read(addr, n)
        buf = ctypes.create_string_buffer(n)
        try:
            got = _vm_op(_libc.process_vm_readv, self.pid, buf, addr, n)
        except OSError as e:
            if e.errno == 1:            # EPERM: fall back to /proc
                self._use_proc = True
                return self._proc_read(addr, n)
            raise
        return buf.raw[:got]

    def write(self, addr: int, data: bytes) -> int:
        if not data:
            return 0
        self.copy_ops += 1
        self.copy_bytes += len(data)
        if self._timed:
            import time
            t0 = time.perf_counter_ns()
            try:
                return self._write_impl(addr, data)
            finally:
                self.copy_ns += time.perf_counter_ns() - t0
        return self._write_impl(addr, data)

    def _write_impl(self, addr: int, data: bytes) -> int:
        if self._use_proc:
            return self._proc_write(addr, data)
        buf = ctypes.create_string_buffer(data, len(data))
        try:
            return _vm_op(_libc.process_vm_writev, self.pid, buf, addr,
                          len(data))
        except OSError as e:
            if e.errno == 1:            # EPERM: fall back to /proc
                self._use_proc = True
                return self._proc_write(addr, data)
            raise

    def read_cstr(self, addr: int, max_len: int = 4096) -> bytes:
        """Read a NUL-terminated string (page-sized probes)."""
        out = b""
        while len(out) < max_len:
            chunk = min(256, max_len - len(out))
            data = self.read(addr + len(out), chunk)
            if b"\0" in data:
                return out + data[: data.index(b"\0")]
            out += data
        return out


# ---- kernel ABI codecs (host/syscall/kernel_types.h analogue) -------

AF_INET = 2

SOCKADDR_IN = struct.Struct("<HH4s8x")        # family, port(BE), addr


def pack_sockaddr_in(ip_be: bytes, port: int) -> bytes:
    return SOCKADDR_IN.pack(AF_INET, ((port & 0xFF) << 8) | (port >> 8),
                            ip_be)


def unpack_sockaddr_in(data: bytes) -> tuple[int, int, bytes]:
    """-> (family, host-order port, 4-byte BE ip)."""
    if len(data) < 8:
        raise ValueError("short sockaddr")
    family, port_be = struct.unpack_from("<HH", data)
    ip = data[4:8]
    port = ((port_be & 0xFF) << 8) | (port_be >> 8)
    return family, port, ip


TIMESPEC = struct.Struct("<qq")               # tv_sec, tv_nsec
TIMEVAL = struct.Struct("<qq")                # tv_sec, tv_usec


def pack_timespec(ns: int) -> bytes:
    return TIMESPEC.pack(ns // 1_000_000_000, ns % 1_000_000_000)


def unpack_timespec(data: bytes) -> int:
    sec, nsec = TIMESPEC.unpack_from(data)
    return sec * 1_000_000_000 + nsec


def pack_timeval(ns: int) -> bytes:
    return TIMEVAL.pack(ns // 1_000_000_000, (ns % 1_000_000_000) // 1000)


# epoll_event on x86_64 is packed: u32 events, u64 data
EPOLL_EVENT = struct.Struct("<IQ")
EPOLL_EVENT_SIZE = 12

POLLFD = struct.Struct("<ihh")                # fd, events, revents

IOVEC = struct.Struct("<QQ")                  # base, len


def read_iovec(mem: ProcessMemory, iov_addr: int,
               iovcnt: int) -> list[tuple[int, int]]:
    if iovcnt <= 0 or iovcnt > 1024:
        return []
    raw = mem.read(iov_addr, IOVEC.size * iovcnt)
    return [IOVEC.unpack_from(raw, i * IOVEC.size) for i in range(iovcnt)]


UTSNAME_FIELD = 65


def pack_utsname(nodename: str) -> bytes:
    def f(s: str) -> bytes:
        b = s.encode()[: UTSNAME_FIELD - 1]
        return b + b"\0" * (UTSNAME_FIELD - len(b))

    return (f("Linux") + f(nodename) + f("5.15.0-shadowtpu")
            + f("#1 SMP shadow_tpu simulated") + f("x86_64") + f(""))
