"""Per-host metrics tracker with heartbeat log lines.

Equivalent of src/main/host/tracker.c: accumulates per-interval
processing counts and per-interface byte/packet counters, and emits
`[shadow-heartbeat] [node]` and `[socket]` CSV lines with one-time
header rows (tracker.c:418-560) so existing shadow log-parsing
workflows (docs/parsing_shadow_logs.md) carry over. Socket lines cover
the host's live TCP connections with send/retransmit segment counts;
finer header/payload byte splits land with socket-buffer accounting.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from shadow_tpu import simtime
from shadow_tpu.utils.slog import get_logger

log = get_logger("heartbeat")


@dataclass
class Tracker:
    host_name: str
    interval_ns: int
    _header_logged: bool = False
    # interval accumulators
    events: int = 0
    packets_sent: int = 0
    packets_dropped: int = 0
    bytes_sent: int = 0
    bytes_received: int = 0
    copy_ops: int = 0           # ProcessMemory copier share (managed
    copy_bytes: int = 0         # plugins only; model apps copy nothing)
    _last: dict = field(default_factory=dict)
    _socket_header_logged: bool = False

    _events_total_last: int = 0

    def on_event(self) -> None:
        self.events += 1

    def set_events_total(self, total: int) -> None:
        """Device path: the engine reports a CUMULATIVE per-host event
        count; diff it into this interval's value (the CPU path counts
        per event via on_event instead)."""
        self.events = total - self._events_total_last
        self._events_total_last = total

    def snapshot_host(self, host) -> None:
        """Diff cumulative host/NIC counters into interval values."""
        cur = {
            "packets_sent": host.packets_sent,
            "packets_dropped": host.packets_dropped,
        }
        if host.net is not None:
            cur["bytes_sent"] = host.net.eth.bytes_sent
            cur["bytes_received"] = host.net.eth.bytes_received
        ops = by = 0
        for app in getattr(host, "apps", ()):
            mem = getattr(app, "mem", None)
            if mem is not None:
                ops += mem.copy_ops
                by += mem.copy_bytes
            for child in getattr(app, "children", {}).values():
                cmem = getattr(child, "mem", None)
                if cmem is not None:
                    ops += cmem.copy_ops
                    by += cmem.copy_bytes
        cur["copy_ops"], cur["copy_bytes"] = ops, by
        for k, v in cur.items():
            setattr(self, k, v - self._last.get(k, 0))
        self._last = cur

    def heartbeat(self, now: int, host) -> None:
        self.snapshot_host(host)
        if not self._header_logged:
            self._header_logged = True
            log.info("[shadow-heartbeat] [node-header] "
                     "time,name,events,packets-sent,packets-dropped,"
                     "bytes-sent,bytes-received,copy-ops,copy-bytes")
        log.info("[shadow-heartbeat] [node] %d,%s,%d,%d,%d,%d,%d,%d,%d",
                 now // simtime.SIMTIME_ONE_SECOND, self.host_name,
                 self.events, self.packets_sent, self.packets_dropped,
                 self.bytes_sent, self.bytes_received,
                 self.copy_ops, self.copy_bytes)
        self.events = 0
        self._heartbeat_sockets(now, host)

    def _heartbeat_sockets(self, now: int, host) -> None:
        """[socket] lines for live TCP connections (tracker.c socket
        rows)."""
        if host.net is None or not host.net._conns:
            return
        if not self._socket_header_logged:
            self._socket_header_logged = True
            log.info("[shadow-heartbeat] [socket-header] "
                     "time,name,local-port,peer,peer-port,state,"
                     "segments-sent,segments-retransmitted,"
                     "bytes-received")
        for (lport, peer, pport), sock in sorted(host.net._conns.items()):
            log.info("[shadow-heartbeat] [socket] %d,%s,%d,%d,%d,%s,"
                     "%d,%d,%d",
                     now // simtime.SIMTIME_ONE_SECOND, self.host_name,
                     lport, peer, pport, sock.state.name,
                     sock.segments_sent, sock.segments_retransmitted,
                     sock.bytes_received)
