"""Plugin address-space tracking: the MemoryManager's map side.

The reference's MemoryManager pairs a copier (process_vm_readv — ours
lives in host/memory.py) with a mapping tracker fed by /proc/[pid]/maps
and kept consistent through mmap/brk/munmap/mremap (memory_manager/
mod.rs:1-17, proc_maps.rs, interval_map.rs). This module provides the
tracker: an interval map over the plugin's VM, a /proc parser to
(re)build it, and the update operations the syscall layer applies.

Backend split: under ptrace every syscall stops, so munmap/mprotect/
brk (whose effects are fully determined at entry) update the map
live, while mmap/mremap placements are kernel-chosen and unknowable
at entry — they mark the snapshot stale for a lazy /proc refresh.
Under preload all of these run native (they must: the dynamic loader
issues them before the shim can exist in a post-execve image), so
the map is purely a refreshed snapshot there. Queries self-heal on a
miss with one refresh; callers treat the tracker as a consistent
snapshot for bounds checks and observability, not a lock-step
mirror.
"""

from __future__ import annotations

from bisect import bisect_right, insort
from dataclasses import dataclass
from typing import Optional


@dataclass(frozen=True)
class Mapping:
    start: int
    end: int                     # exclusive
    perms: str                   # e.g. "rw-p"
    offset: int = 0
    path: str = ""

    @property
    def readable(self) -> bool:
        return self.perms[:1] == "r"

    @property
    def writable(self) -> bool:
        return self.perms[1:2] == "w"

    def __len__(self) -> int:
        return self.end - self.start


class IntervalMap:
    """Non-overlapping intervals over the address space
    (interval_map.rs analogue): insertion clips existing overlaps
    (mmap MAP_FIXED semantics), removal punches holes (munmap can
    split a mapping in two)."""

    def __init__(self):
        self._starts: list[int] = []
        self._maps: dict[int, Mapping] = {}

    def __len__(self) -> int:
        return len(self._starts)

    def __iter__(self):
        for s in self._starts:
            yield self._maps[s]

    def _del(self, start: int) -> None:
        self._starts.remove(start)
        del self._maps[start]

    def _put(self, m: Mapping) -> None:
        insort(self._starts, m.start)
        self._maps[m.start] = m

    def clear(self) -> None:
        self._starts.clear()
        self._maps.clear()

    def find(self, addr: int) -> Optional[Mapping]:
        i = bisect_right(self._starts, addr) - 1
        if i < 0:
            return None
        m = self._maps[self._starts[i]]
        return m if addr < m.end else None

    def overlapping(self, start: int, end: int) -> list[Mapping]:
        out = []
        i = max(0, bisect_right(self._starts, start) - 1)
        for s in self._starts[i:]:
            m = self._maps[s]
            if m.start >= end:
                break
            if m.end > start:
                out.append(m)
        return out

    def covered(self, start: int, end: int) -> bool:
        """True iff [start, end) is fully inside tracked mappings
        (they may be adjacent)."""
        at = start
        for m in self.overlapping(start, end):
            if m.start > at:
                return False
            at = m.end
            if at >= end:
                return True
        return at >= end

    def bulk_load(self, rows: list) -> None:
        """Replace the whole map with already-sorted, disjoint rows
        (a /proc snapshot) in O(n)."""
        self._starts = [m.start for m in rows]
        self._maps = {m.start: m for m in rows}

    def add(self, m: Mapping) -> None:
        """Insert, clipping anything it overlaps (MAP_FIXED)."""
        self.remove(m.start, m.end)
        self._put(m)

    def remove(self, start: int, end: int) -> None:
        """Punch [start, end) out of the map (munmap)."""
        for m in self.overlapping(start, end):
            self._del(m.start)
            if m.start < start:
                self._put(Mapping(m.start, start, m.perms, m.offset,
                                  m.path))
            if m.end > end:
                self._put(Mapping(end, m.end, m.perms,
                                  m.offset + (end - m.start), m.path))

    def protect(self, start: int, end: int, perms: str) -> None:
        """Change permissions on [start, end) (mprotect), splitting
        mappings at the boundaries."""
        for m in self.overlapping(start, end):
            self._del(m.start)
            if m.start < start:
                self._put(Mapping(m.start, start, m.perms, m.offset,
                                  m.path))
            lo, hi = max(m.start, start), min(m.end, end)
            self._put(Mapping(lo, hi, perms,
                              m.offset + (lo - m.start), m.path))
            if m.end > end:
                self._put(Mapping(end, m.end, m.perms,
                                  m.offset + (end - m.start), m.path))


def parse_proc_maps(text: str) -> list[Mapping]:
    """Parse /proc/[pid]/maps content (proc_maps.rs analogue)."""
    out = []
    for line in text.splitlines():
        parts = line.split(maxsplit=5)
        if len(parts) < 5:
            continue
        rng, perms, offset = parts[0], parts[1], parts[2]
        path = parts[5] if len(parts) > 5 else ""
        try:
            lo, hi = (int(x, 16) for x in rng.split("-"))
            off = int(offset, 16)
        except ValueError:
            continue
        out.append(Mapping(lo, hi, perms, off, path))
    return out


class ProcessMaps:
    """The per-process tracker: snapshot from /proc, live updates from
    the syscall layer (ptrace backend), convenience queries."""

    def __init__(self, pid: int):
        self.pid = pid
        self.map = IntervalMap()
        self.brk: int = 0            # program break (heap end)
        self._brk_start: int = 0
        # set when a kernel-chosen placement happened (non-FIXED mmap,
        # mremap under the preload backend): queries refresh first
        self.dirty: bool = True

    def refresh(self) -> bool:
        """Rebuild the snapshot from /proc/[pid]/maps."""
        try:
            with open(f"/proc/{self.pid}/maps") as f:
                text = f.read()
        except OSError:
            return False
        # /proc rows are sorted and disjoint: bulk-load in O(n)
        rows = parse_proc_maps(text)
        self.map.bulk_load(rows)
        for m in rows:
            if m.path == "[heap]":
                self._brk_start, self.brk = m.start, m.end
        self.dirty = False
        return True

    # -- live updates from the syscall layer ---------------------------
    PROT_READ, PROT_WRITE, PROT_EXEC = 1, 2, 4

    def _perms(self, prot: int) -> str:
        return (("r" if prot & self.PROT_READ else "-")
                + ("w" if prot & self.PROT_WRITE else "-")
                + ("x" if prot & self.PROT_EXEC else "-") + "p")

    def on_mmap(self, addr: int, length: int, prot: int,
                offset: int = 0, path: str = "") -> None:
        end = addr + ((length + 4095) & ~4095)
        self.map.add(Mapping(addr, end, self._perms(prot), offset,
                             path))

    def on_munmap(self, addr: int, length: int) -> None:
        self.map.remove(addr, addr + ((length + 4095) & ~4095))

    def on_mprotect(self, addr: int, length: int, prot: int) -> None:
        self.map.protect(addr, addr + ((length + 4095) & ~4095),
                         self._perms(prot))

    def on_brk(self, new_brk: int) -> None:
        if self._brk_start == 0:
            self._brk_start = new_brk
        new_brk = max(new_brk, self._brk_start)
        if self.brk and new_brk < self.brk:
            self.map.remove(new_brk, self.brk)     # heap shrank
        if new_brk > self._brk_start:
            self.map.add(Mapping(self._brk_start, new_brk, "rw-p",
                                 0, "[heap]"))
        self.brk = new_brk

    # -- queries -------------------------------------------------------
    def _fresh(self) -> None:
        if self.dirty:
            self.refresh()

    def _check(self, addr: int, n: int, want) -> bool:
        if n <= 0:
            return True
        was_dirty = self.dirty
        self._fresh()

        def walk() -> bool:
            at, end = addr, addr + n
            for m in self.map.overlapping(addr, end):
                if m.start > at or not want(m):
                    return False
                at = m.end
                if at >= end:
                    return True
            return False

        if walk():
            return True
        if was_dirty:
            return False        # the walk already saw a fresh snapshot
        # a miss may just be a stale snapshot (preload backend: mmap
        # runs native and never marks us dirty): refresh and retry
        # once. Stale HITS on an unmapped region remain possible until
        # the next miss — the tracker is a snapshot, not a mirror.
        return self.refresh() and walk()

    def readable(self, addr: int, n: int) -> bool:
        return self._check(addr, n, lambda m: m.readable)

    def writable(self, addr: int, n: int) -> bool:
        return self._check(addr, n, lambda m: m.writable)

    def region_of(self, addr: int) -> Optional[Mapping]:
        was_dirty = self.dirty
        self._fresh()
        m = self.map.find(addr)
        if m is None and not was_dirty and self.refresh():
            m = self.map.find(addr)     # stale-miss retry
        return m
