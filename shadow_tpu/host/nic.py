"""Network interface: token-bucket rate limiting + send qdiscs.

Equivalent of the reference's NetworkInterface (src/main/host/
network_interface.c): each interface polices bandwidth with token
buckets refilled every 1 ms to `bytes_per_ms` with burst capacity
refill+MTU (network_interface.c:33-41, 99-228); the receive side drains
the Router until tokens run out (:448-482); the send side pulls packets
from sockets that registered interest, in FIFO-by-priority or round-
robin qdisc order (:497-631); during bootstrap bandwidth is unlimited
(:459-461).

The interface is event-driven: when tokens run dry it schedules a
wakeup at the next 1 ms refill boundary instead of polling.
"""

from __future__ import annotations

from collections import deque
from typing import Callable, Optional, Protocol as TProtocol

from shadow_tpu import simtime
from shadow_tpu.routing.packet import Packet, PacketStatus
from shadow_tpu.routing.router import Router

REFILL_NS = simtime.SIMTIME_ONE_MILLISECOND


class PacketSource(TProtocol):
    """A socket that can be pulled for outbound packets
    (compat_socket pull model, network_interface.c:497-631)."""

    def has_packet_to_send(self) -> bool: ...

    def peek_packet_size(self) -> Optional[int]:
        """Total on-wire size of the next packet, or None if none."""
        ...

    def pull_packet(self, now: int) -> Optional[Packet]: ...


class TokenBucket:
    """Refill-on-access token bucket with 1 ms granularity
    (network_interface.c:99-228)."""

    def __init__(self, bytes_per_second: int):
        self.refill_bytes = max(1, bytes_per_second // 1000)  # per ms
        self.capacity = self.refill_bytes + simtime.CONFIG_MTU
        self.tokens = self.capacity
        self._last_refill_ms = 0

    def _advance(self, now: int) -> None:
        now_ms = now // REFILL_NS
        if now_ms > self._last_refill_ms:
            self.tokens = min(
                self.capacity,
                self.tokens + (now_ms - self._last_refill_ms)
                * self.refill_bytes)
            self._last_refill_ms = now_ms

    def try_consume(self, now: int, nbytes: int) -> bool:
        self._advance(now)
        if self.tokens >= nbytes:
            self.tokens -= nbytes
            return True
        return False

    def can_consume(self, now: int, nbytes: int) -> bool:
        self._advance(now)
        return self.tokens >= nbytes

    def consume_deficit(self, now: int, nbytes: int) -> None:
        """Charge for a packet that must go through even if it differs
        from the one the caller budgeted for (deficit accounting: the
        balance may dip negative and recovers on refill)."""
        self._advance(now)
        self.tokens -= nbytes

    def next_refill_time(self, now: int) -> int:
        return (now // REFILL_NS + 1) * REFILL_NS


class NetworkInterface:
    def __init__(self, host_id: int, bw_down_bits: int, bw_up_bits: int,
                 qdisc: str = "fifo",
                 router: Optional[Router] = None,
                 bootstrap_end: int = 0):
        self.host_id = host_id
        self.recv_bucket = TokenBucket(bw_down_bits // 8)
        self.send_bucket = TokenBucket(bw_up_bits // 8)
        self.qdisc = qdisc
        self.router = router or Router()
        self.router.on_enqueue = self._on_router_enqueue
        self.bootstrap_end = bootstrap_end

        # send side: sockets wanting to send (fifo keeps registration
        # order = priority order; rr rotates — the reference's
        # FifoSocketQueue / RrSocketQueue, network_queuing_disciplines.c)
        self._send_queue: deque[PacketSource] = deque()
        self._send_pending_wakeup = False
        self._recv_pending_wakeup = False

        # wired by HostNetStack
        self.transmit: Optional[Callable[[Packet, int], None]] = None
        self.deliver: Optional[Callable[[Packet, int], None]] = None
        self.schedule_wakeup: Optional[Callable[[int, int], None]] = None
        self.count_drops: Optional[Callable[[int], None]] = None
        # counters (Tracker feed)
        self.bytes_sent = 0
        self.bytes_received = 0
        self.packets_sent = 0
        self.packets_received = 0
        self.recv_dropped = 0

    # -- helpers -------------------------------------------------------
    def _unlimited(self, now: int) -> bool:
        return now < self.bootstrap_end

    # -- send side -----------------------------------------------------
    def wants_send(self, source: PacketSource, now: int) -> None:
        """A socket has packets ready (networkinterface_wantsSend,
        network_interface.c:633-663)."""
        if source not in self._send_queue:
            self._send_queue.append(source)
        self.send_packets(now)

    def send_packets(self, now: int) -> None:
        """Pull from sockets while tokens allow (:571-631)."""
        while self._send_queue:
            src = self._send_queue[0]
            size = src.peek_packet_size()
            if size is None:
                self._send_queue.popleft()
                continue
            if not self._unlimited(now) and \
                    not self.send_bucket.try_consume(now, size):
                self._schedule_send_wakeup(now)
                return
            packet = src.pull_packet(now)
            if packet is None:
                self._send_queue.popleft()
                continue
            if self.qdisc == "roundrobin":
                self._send_queue.rotate(-1)
            self._transmit(packet, now)

    def _transmit(self, packet: Packet, when: int) -> None:
        packet.add_status(PacketStatus.SND_INTERFACE_SENT)
        self.bytes_sent += packet.total_size
        self.packets_sent += 1
        assert self.transmit is not None
        self.transmit(packet, when)

    def _schedule_send_wakeup(self, now: int) -> None:
        if not self._send_pending_wakeup and self.schedule_wakeup:
            self._send_pending_wakeup = True
            self.schedule_wakeup(self.send_bucket.next_refill_time(now), 0)

    def on_send_wakeup(self, now: int) -> None:
        self._send_pending_wakeup = False
        self.send_packets(now)

    # -- receive side --------------------------------------------------
    def _on_router_enqueue(self, now: int) -> None:
        self.receive_packets(now)

    def receive_packets(self, now: int) -> None:
        """Drain the router while tokens allow
        (networkinterface_receivePackets, :448-482)."""
        while True:
            head = self.router.peek()
            if head is None:
                return
            if not self._unlimited(now) and \
                    not self.recv_bucket.can_consume(now, head.total_size):
                self._schedule_recv_wakeup(now)
                return
            drops_before = self._router_drop_count()
            packet = self.router.dequeue(now)
            dropped = self._router_drop_count() - drops_before
            if dropped and self.count_drops is not None:
                self.recv_dropped += dropped
                self.count_drops(dropped)
            if packet is None:     # CoDel dropped the whole backlog
                if self.router.peek() is not None:
                    continue
                return
            # charge the packet actually delivered (CoDel may have
            # dropped the peeked head and returned a later one)
            if not self._unlimited(now):
                self.recv_bucket.consume_deficit(now, packet.total_size)
            packet.add_status(PacketStatus.RCV_INTERFACE_RECEIVED)
            self.bytes_received += packet.total_size
            self.packets_received += 1
            assert self.deliver is not None
            self.deliver(packet, now)

    def _router_drop_count(self) -> int:
        return getattr(self.router.queue, "total_dropped", 0)

    def _schedule_recv_wakeup(self, now: int) -> None:
        if not self._recv_pending_wakeup and self.schedule_wakeup:
            self._recv_pending_wakeup = True
            self.schedule_wakeup(self.recv_bucket.next_refill_time(now), 1)

    def on_recv_wakeup(self, now: int) -> None:
        self._recv_pending_wakeup = False
        self.receive_packets(now)
