"""A simulated host (node).

Mirror of the reference's Host (src/main/host/host.c:49-213): identity,
topology attachment, bandwidths, deterministic per-host RNG, and the
per-host id counters that make the event order reproducible — the
event-sequence counter (host_getNewEventID) and packet-sequence counter
(packet ids). The interfaces/router/TCP machinery attaches here as the
host emulation layer grows.

Columnar builds (host/plane.py) do not construct these objects up
front: the plane holds the same fields as [H] numpy columns and
``HostPlane.materialize`` builds a Host lazily — field for field
identical to the object build, including the RNG seed — only when
something actually touches it (a CPU backend, a tracker heartbeat,
tooling reading ``sim.hosts``).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional

from shadow_tpu.utils.rng import SeededRandom


@dataclass
class Host:
    host_id: int
    name: str
    vertex: int                 # topology vertex index
    bw_down_bits: int
    bw_up_bits: int
    rng: SeededRandom
    app: Any = None             # primary app (model-dispatch target)
    apps: list = field(default_factory=list)   # all processes, in
                                # config order (process.c's per-host
                                # process list; BOOT/STOP events carry
                                # the index)
    net: Any = None             # HostNetStack (CPU engines)
    cpu: Any = None             # host/cpu.py Cpu delay model
    model_nic: Any = None       # host/model_nic.py ModelNic (raw sends)
    tracker: Any = None         # host/tracker.py Tracker
    address: Any = None         # routing/address.py Address (via DNS)
    pcap_directory: Optional[str] = None
    ip: Optional[str] = None

    # deterministic id streams (reference host.c:85-95)
    _event_seq: int = 0
    _packet_seq: int = 0
    _app_seq: int = 0

    # fault injection (core/manager.py KIND_HOST_CRASH/RESTART): a
    # crashed host executes nothing — its pending events are
    # quarantined (counted, packet kinds also count as drops) until
    # the restart respawns the configured processes via `respawn`
    # [(factory, start_time, stop_time, is_model)] captured at build
    crashed: bool = False
    events_quarantined: int = 0
    respawn: Optional[list] = None

    # per-host stats (Tracker-lite; grows into host/tracker.py)
    events_executed: int = 0
    packets_sent: int = 0
    packets_delivered: int = 0
    packets_dropped: int = 0
    # rolling hash of the executed-event schedule (utils/checksum.py);
    # equal across engines/policies iff per-host schedules match
    trace_checksum: int = 0

    def next_event_seq(self) -> int:
        s = self._event_seq
        self._event_seq += 1
        return s

    def next_packet_seq(self) -> int:
        s = self._packet_seq
        self._packet_seq += 1
        return s

    def next_app_seq(self) -> int:
        s = self._app_seq
        self._app_seq += 1
        return s
