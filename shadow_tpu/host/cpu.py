"""Virtual CPU delay model.

Equivalent of src/main/host/cpu.c: native execution time is scaled by
the ratio of the host's configured frequency to the machine's raw
frequency, and event delivery is deferred while the virtual CPU is
"busy" past a threshold (cpu.c:16-49, applied around event execution in
event.c:70-87). Model apps report synthetic load via
SimContext.consume_cpu().
"""

from __future__ import annotations

from dataclasses import dataclass

from shadow_tpu import simtime


@dataclass
class Cpu:
    freq_khz: int = 3_000_000          # host's configured frequency
    raw_freq_khz: int = 3_000_000      # native machine frequency
    threshold_ns: int = simtime.SIMTIME_ONE_MILLISECOND
    precision_ns: int = 200 * simtime.SIMTIME_ONE_MICROSECOND
    now: int = 0
    _busy_until: int = 0

    def scale(self, native_ns: int) -> int:
        return native_ns * self.raw_freq_khz // max(1, self.freq_khz)

    def update_time(self, now: int) -> None:
        self.now = max(self.now, now)

    def add_delay(self, native_ns: int) -> None:
        """Account virtual execution time (cpu_addDelay)."""
        base = max(self._busy_until, self.now)
        self._busy_until = base + self.scale(native_ns)

    def is_blocked(self, now: int) -> bool:
        """True if event delivery should wait (cpu_isBlocked): the
        backlog exceeds the threshold."""
        if self.threshold_ns <= 0:
            return False
        return (self._busy_until - now) > self.threshold_ns

    def delay_until_ready(self, now: int) -> int:
        """How long to defer an event, rounded up to the model
        precision (cpu_getDelay)."""
        raw = max(0, self._busy_until - now)
        if self.precision_ns > 0:
            steps = (raw + self.precision_ns - 1) // self.precision_ns
            return steps * self.precision_ns
        return raw
