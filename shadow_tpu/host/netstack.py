"""Per-host network stack: interfaces + router + socket demux.

The glue the reference spreads across host.c (interface/router
creation, host.c:184-199), network_interface.c (socket association
:257-339) and the descriptor table: one eth interface fed by an
upstream Router, a socket table keyed (protocol, local port) for
listeners plus (local port, peer host, peer port) for TCP connections,
ephemeral port allocation, and the event plumbing (packet arrivals,
NIC refill wakeups, TCP timers) into the discrete-event engine.
"""

from __future__ import annotations

from typing import Optional

from shadow_tpu.core.event import (
    Event,
    KIND_NIC_WAKE,
    KIND_ROUTER_ARRIVAL,
    KIND_TCP_TIMER,
)
from shadow_tpu.host.nic import NetworkInterface
from shadow_tpu.host.sockets import (
    BaseSocket,
    EPHEMERAL_PORT_START,
    UdpSocket,
)
from shadow_tpu.host.tcp import TcpSocket
from shadow_tpu.routing.packet import Packet, PacketStatus, Protocol
from shadow_tpu.routing.router import Router
from shadow_tpu.routing.queues import make_router_queue


class HostNetStack:
    def __init__(self, host, manager, qdisc: str = "fifo",
                 router_queue: str = "codel",
                 router_static_capacity: int = 1024,
                 bootstrap_end: int = 0,
                 tcp_congestion: str = "reno",
                 tcp_recv_buffer: int = 0,
                 tcp_send_buffer: int = 0,
                 tcp_recv_autotune: bool = True,
                 tcp_send_autotune: bool = True):
        from shadow_tpu.host.tcp import (
            DEFAULT_RECV_WINDOW,
            DEFAULT_SEND_BUFFER,
        )
        tcp_recv_buffer = tcp_recv_buffer or DEFAULT_RECV_WINDOW
        tcp_send_buffer = tcp_send_buffer or DEFAULT_SEND_BUFFER
        self.host = host
        self._m = manager
        # per-socket TCP knobs (TcpSocket reads these off its net)
        self.tcp_congestion = tcp_congestion
        self.tcp_recv_buffer = tcp_recv_buffer
        self.tcp_send_buffer = tcp_send_buffer
        self.tcp_recv_autotune = tcp_recv_autotune
        self.tcp_send_autotune = tcp_send_autotune
        router = Router(make_router_queue(router_queue,
                                          router_static_capacity))
        self.eth = NetworkInterface(
            host.host_id, host.bw_down_bits, host.bw_up_bits,
            qdisc=qdisc, router=router, bootstrap_end=bootstrap_end)
        self.eth.transmit = self._transmit
        self.eth.deliver = self._demux
        self.eth.schedule_wakeup = self._schedule_nic_wake
        self.eth.count_drops = self._count_drops

        self._listeners: dict[tuple[Protocol, int], BaseSocket] = {}
        self._conns: dict[tuple[int, int, int], TcpSocket] = {}
        # cumulative TCP counters, surviving socket teardown (the
        # tracker's retransmit split, tracker.c:12-50)
        self.tcp_segments_sent = 0
        self.tcp_segments_retransmitted = 0
        self._by_conn_id: dict[int, TcpSocket] = {}
        self._next_conn_id = 0
        self._next_ephemeral = EPHEMERAL_PORT_START
        # the SimContext of the event currently being executed on this
        # host — set by handle_event / the app-facing API so socket
        # callbacks can reach scheduling/stats (a host only ever
        # executes on one worker at a time, so this is race-free)
        self.ctx = None

        # pcap capture (network_interface.c:341-377)
        self.pcap = None
        if host.pcap_directory:
            import os

            from shadow_tpu.utils.pcap import PcapWriter
            os.makedirs(host.pcap_directory, exist_ok=True)
            self.pcap = PcapWriter(os.path.join(
                host.pcap_directory, f"{host.name}-eth.pcap"))

    # -- registration --------------------------------------------------
    def new_conn_id(self, sock) -> int:
        cid = self._next_conn_id
        self._next_conn_id += 1
        self._by_conn_id[cid] = sock
        return cid

    def alloc_port(self) -> int:
        p = self._next_ephemeral
        self._next_ephemeral += 1
        return p

    def register(self, sock: BaseSocket) -> None:
        if isinstance(sock, TcpSocket) and sock.peer is not None:
            self._conns[(sock.local_port, *sock.peer)] = sock
        else:
            self._listeners[(sock.proto, sock.local_port)] = sock

    def unregister(self, sock: BaseSocket) -> None:
        if isinstance(sock, TcpSocket) and sock.peer is not None:
            self._conns.pop((sock.local_port, *sock.peer), None)
        # a TCP child shares its listener's port: only remove the
        # listener entry if this socket *is* the registered listener
        key = (sock.proto, sock.local_port)
        if self._listeners.get(key) is sock:
            self._listeners.pop(key)
        if isinstance(sock, TcpSocket):
            self._by_conn_id.pop(sock.conn_id, None)

    def interface_for(self, dst_host: int) -> NetworkInterface:
        return self.eth           # lo short-circuits inside _transmit

    # -- packet creation ----------------------------------------------
    def new_packet(self, dst_host: int, protocol: Protocol, size: int,
                   src_port: int = 0, dst_port: int = 0,
                   payload=None) -> Packet:
        pkt = Packet(src_host=self.host.host_id,
                     packet_id=self.host.next_packet_seq(),
                     dst_host=dst_host, protocol=protocol, size=size,
                     src_port=src_port, dst_port=dst_port,
                     payload=payload)
        pkt.add_status(PacketStatus.SND_CREATED)
        return pkt

    def _ip_of(self, host_id: int) -> int:
        addr = self._m.hosts[host_id].address
        return addr.ip if addr is not None else host_id

    # -- egress: interface -> network model -> dst router --------------
    def _transmit(self, packet: Packet, now: int) -> None:
        host = self.host
        if self.pcap is not None:
            self.pcap.write(now, packet, self._ip_of(host.host_id),
                            self._ip_of(packet.dst_host))
        # seq consumed per send (delivered or not) so the judgment can
        # be deferred to the batched device path without changing any
        # later seq allocation on this host
        ev_seq = host.next_event_seq()
        if self._m.net_judge is not None:
            self._m.defer_judgment(now, host, packet.dst_host,
                                   packet.packet_id, ev_seq,
                                   KIND_ROUTER_ARRIVAL, (packet,))
            return
        verdict = self._m.netmodel.judge(now, host.host_id,
                                         packet.dst_host,
                                         packet.packet_id)
        host.packets_sent += 1
        if not verdict.delivered:
            packet.add_status(PacketStatus.INET_DROPPED)
            host.packets_dropped += 1
            return
        packet.add_status(PacketStatus.INET_SENT)
        ev = Event(time=verdict.deliver_time, dst_host=packet.dst_host,
                   src_host=host.host_id, seq=ev_seq,
                   kind=KIND_ROUTER_ARRIVAL, data=(packet,))
        self._m.push_event(ev)

    # -- ingress: router arrival -> NIC -> socket ----------------------
    def _demux(self, packet: Packet, now: int) -> None:
        sock: Optional[BaseSocket] = None
        if packet.protocol == Protocol.TCP and packet.tcp is not None:
            sock = self._conns.get((packet.dst_port, packet.src_host,
                                    packet.tcp.src_port))
        if sock is None:
            sock = self._listeners.get((packet.protocol, packet.dst_port))
        if self.pcap is not None:
            self.pcap.write(now, packet, self._ip_of(packet.src_host),
                            self._ip_of(self.host.host_id))
        if sock is None:
            packet.add_status(PacketStatus.RCV_INTERFACE_DROPPED)
            self.host.packets_dropped += 1
            return
        self.host.packets_delivered += 1
        sock.handle_packet(packet, now)

    def _count_drops(self, n: int) -> None:
        self.host.packets_dropped += n

    # -- event plumbing ------------------------------------------------
    def _self_event(self, when: int, kind: int, data: tuple) -> None:
        h = self.host
        self._m.push_event(Event(time=when, dst_host=h.host_id,
                                 src_host=h.host_id,
                                 seq=h.next_event_seq(), kind=kind,
                                 data=data))

    def _schedule_nic_wake(self, when: int, side: int) -> None:
        self._self_event(when, KIND_NIC_WAKE, (side,))

    def schedule_tcp_timer(self, conn_id: int, gen: int,
                           when: int) -> None:
        self._self_event(when, KIND_TCP_TIMER, (conn_id, gen))

    def handle_event(self, ev: Event, now: int, ctx=None) -> None:
        if ctx is not None:
            self.ctx = ctx
        if ev.kind == KIND_ROUTER_ARRIVAL:
            packet: Packet = ev.data[0]
            if not self.eth.router.enqueue(packet, now):
                self.host.packets_dropped += 1   # single/static tail drop
        elif ev.kind == KIND_NIC_WAKE:
            if ev.data[0] == 0:
                self.eth.on_send_wakeup(now)
            else:
                self.eth.on_recv_wakeup(now)
        elif ev.kind == KIND_TCP_TIMER:
            conn_id, gen = ev.data
            sock = self._by_conn_id.get(conn_id)
            if sock is not None:
                sock.on_timer(now, gen)

    # -- app-facing API (used via SimContext) --------------------------
    def udp_socket(self, port: Optional[int] = None,
                   on_datagram=None) -> UdpSocket:
        port = port if port is not None else self.alloc_port()
        sock = UdpSocket(self, port, on_datagram=on_datagram)
        self.register(sock)
        return sock

    def tcp_listen(self, port: int, on_accept=None, on_data=None,
                   on_closed=None) -> TcpSocket:
        sock = TcpSocket(self, port)
        sock.on_accept = on_accept
        sock.on_data = on_data
        sock.on_closed = on_closed
        sock.listen()
        return sock

    def tcp_connect(self, now: int, dst_host: int, dst_port: int,
                    on_connected=None, on_data=None,
                    on_closed=None) -> TcpSocket:
        sock = TcpSocket(self, self.alloc_port())
        sock.on_connected = on_connected
        sock.on_data = on_data
        sock.on_closed = on_closed
        sock.connect(now, dst_host, dst_port)
        return sock
