"""In-simulator TCP with Reno congestion control.

Equivalent of the reference's TCP stack (src/main/host/descriptor/
tcp.c — state machine tcp.c:41-51; tcp_cong_reno.c — slow start /
AIMD congestion avoidance / fast recovery; retransmit queue — the C++
tally, tcp_retransmit_tally.cc), rebuilt event-driven over the packet
layer:

* three-way handshake, server child-socket multiplexing off a LISTEN
  socket, FIN teardown with TIME_WAIT (60 s, definitions.h:195)
* byte-sequence send space with MSS segmentation, a retransmit queue,
  cumulative ACKs, duplicate-ACK fast retransmit (3 dupacks) with
  NewReno-style partial-ACK recovery, RFC 6298 RTO estimation from
  RFC 7323-style timestamps, and SACK: the receiver reports up to 4
  out-of-order blocks per ACK and the sender's RetransmitTally skips
  selectively-acked spans when picking retransmission holes
* Reno congestion window: slow start to ssthresh, +MSS*MSS/cwnd per ACK
  in congestion avoidance, halving on loss, cwnd=1 MSS on RTO
* receive-side reordering buffer with cumulative ACK generation and a
  fixed advertised window (buffer autotuning lands with the
  socket-buffer work)

Payload bytes are modeled as counts (apps observe sizes, not content);
`size` rides the packet like the reference's payload length.
"""

from __future__ import annotations

import enum
from typing import Callable, Optional

from shadow_tpu import simtime
from shadow_tpu.routing.packet import (
    Packet,
    PacketStatus,
    Protocol,
    TcpFlags,
    TcpHeader,
)
from shadow_tpu.host.sockets import BaseSocket

MSS = simtime.CONFIG_TCP_MAX_SEGMENT_SIZE
INIT_CWND_SEGMENTS = 10          # modern initial window (RFC 6928)
DEFAULT_RECV_WINDOW = 174760     # reference socket_recv_buffer default
DEFAULT_SEND_BUFFER = 131072     # reference socket_send_buffer default
MAX_AUTOTUNE_BUFFER = 1 << 24    # 16 MiB cap for autotuned buffers
RECV_EPOCH_NS = 200 * simtime.SIMTIME_ONE_MILLISECOND  # DRS epoch
MIN_RTO_NS = 200 * simtime.SIMTIME_ONE_MILLISECOND
MAX_RTO_NS = 60 * simtime.SIMTIME_ONE_SECOND
TIME_WAIT_NS = simtime.CONFIG_TCP_TIMEWAIT_SECONDS \
    * simtime.SIMTIME_ONE_SECOND


class RenoCongestion:
    """NewReno congestion control (tcp_cong_reno.c:13-40): slow start
    to ssthresh, AIMD avoidance, fast recovery with inflation. One
    instance per socket, dispatched through the vtable points below —
    the pluggable-CC seam of the reference's tcp_cong.h."""

    name = "reno"

    def on_ack(self, s, acked: int) -> None:
        """New data cumulatively acked outside recovery."""
        if s.cwnd < s.ssthresh:
            s.cwnd += min(acked, MSS)             # slow start
        else:
            s.cwnd += max(1, MSS * MSS // s.cwnd)  # cong avoidance

    def on_enter_recovery(self, s) -> None:
        """Third duplicate ACK: fast retransmit + fast recovery."""
        s.ssthresh = max(s._flight() // 2, 2 * MSS)
        s.cwnd = s.ssthresh + 3 * MSS

    def on_recovery_ack(self, s) -> None:
        """Further dup ACK while in recovery: window inflation."""
        s.cwnd += MSS

    def on_exit_recovery(self, s) -> None:
        s.cwnd = s.ssthresh

    def on_rto(self, s) -> None:
        """Retransmission timeout: collapse to one segment."""
        s.ssthresh = max(s._flight() // 2, 2 * MSS)
        s.cwnd = MSS


# tcp_cong.h's algorithm registry; additional algorithms (cubic, bbr)
# slot in here and are selected by experimental.tcp_congestion
CONGESTION_ALGORITHMS = {"reno": RenoCongestion}


def make_congestion(name: str):
    try:
        return CONGESTION_ALGORITHMS[name]()
    except KeyError:
        raise ValueError(
            f"unknown tcp congestion algorithm {name!r} "
            f"(have: {sorted(CONGESTION_ALGORITHMS)})") from None


class RetransmitTally:
    """Sender-side record of which byte ranges the peer has selectively
    acknowledged — the role of the reference's C++ retransmit tally
    (tcp_retransmit_tally.cc:10-30, a ranges structure driving which
    blocks get retransmitted). Kept as a sorted list of disjoint
    [start, end) spans above the cumulative ACK point."""

    def __init__(self):
        self.sacked: list[list[int]] = []     # sorted disjoint [s, e)

    def mark_sacked(self, start: int, end: int) -> None:
        if end <= start:
            return
        merged = []
        placed = False
        for s, e in self.sacked:
            if e < start or s > end:          # disjoint
                merged.append([s, e])
            else:                             # overlap/adjacent: fuse
                start, end = min(s, start), max(e, end)
        for i, (s, _) in enumerate(merged):
            if s > start:
                merged.insert(i, [start, end])
                placed = True
                break
        if not placed:
            merged.append([start, end])
        self.sacked = merged

    def clear_below(self, ack: int) -> None:
        self.sacked = [[max(s, ack), e] for s, e in self.sacked
                       if e > ack]

    def is_sacked(self, start: int, end: int) -> bool:
        """True if [start, end) lies fully inside one sacked span."""
        for s, e in self.sacked:
            if s <= start and end <= e:
                return True
            if s > start:
                break
        return False


class TcpState(enum.Enum):
    CLOSED = 0
    LISTEN = 1
    SYN_SENT = 2
    SYN_RCVD = 3
    ESTABLISHED = 4
    FIN_WAIT_1 = 5
    FIN_WAIT_2 = 6
    CLOSING = 7
    TIME_WAIT = 8
    CLOSE_WAIT = 9
    LAST_ACK = 10


class TcpSocket(BaseSocket):
    def __init__(self, net, local_port: int):
        super().__init__(net, Protocol.TCP, local_port)
        self.state = TcpState.CLOSED
        self.conn_id = net.new_conn_id(self)

        # callbacks (status-listener equivalents)
        self.on_connected: Optional[Callable] = None
        self.on_data: Optional[Callable] = None       # (sock, nbytes, now)
        self.on_closed: Optional[Callable] = None
        self.on_accept: Optional[Callable] = None     # listener only
        self.on_writable: Optional[Callable] = None   # send space freed

        # send sequence state (byte space; SYN/FIN consume one each)
        self.iss = 0
        self.snd_una = 0
        self.snd_nxt = 0
        self.send_pending = 0          # app bytes not yet segmented
        self.fin_pending = False
        self.fin_sent_seq: Optional[int] = None
        self.retx: list[list] = []     # [seq, len, n_tx, ts_staged, flags]
        self.peer_window = DEFAULT_RECV_WINDOW
        self.tally = RetransmitTally()  # peer-SACKed spans

        # congestion control: pluggable vtable (tcp_cong.h), selected
        # by experimental.tcp_congestion; Reno implements the
        # reference's tcp_cong_reno.c
        self.cc = make_congestion(getattr(net, "tcp_congestion",
                                          "reno"))
        self.cwnd = INIT_CWND_SEGMENTS * MSS
        self.ssthresh = 1 << 30
        self.dup_acks = 0
        self.in_recovery = False
        self.recover = 0
        # buffer sizing (reference tcp.c autotuning): the send cap
        # tracks 2x cwnd when autotuned; the receive window doubles
        # whenever an epoch fills it (simplified DRS), both bounded
        self.send_buffer = getattr(net, "tcp_send_buffer",
                                   DEFAULT_SEND_BUFFER)
        self._send_autotune = getattr(net, "tcp_send_autotune", True)
        self._recv_autotune = getattr(net, "tcp_recv_autotune", True)
        self._recv_epoch_bytes = 0
        self._recv_epoch_start = 0

        # RTO (RFC 6298)
        self.srtt: Optional[int] = None
        self.rttvar = 0
        self.rto = simtime.SIMTIME_ONE_SECOND
        self._timer_gen = 0
        self._rto_armed = False

        # receive state
        self.irs = 0
        self.rcv_nxt = 0
        self.reorder: dict[int, int] = {}      # seq -> len
        self.recv_window = getattr(net, "tcp_recv_buffer",
                                   DEFAULT_RECV_WINDOW)
        self.bytes_received = 0
        self.bytes_acked = 0
        # stats (tracker feed; retransmit split like tracker.c:12-50)
        self.segments_sent = 0
        self.segments_retransmitted = 0

    # ------------------------------------------------------------------
    # public API (the syscall layer's entry points)
    # ------------------------------------------------------------------
    def listen(self) -> None:
        self.state = TcpState.LISTEN
        self.net.register(self)

    def connect(self, now: int, dst_host: int, dst_port: int) -> None:
        self.peer = (dst_host, dst_port)
        self.net.register(self)
        self.state = TcpState.SYN_SENT
        self._emit(now, TcpFlags.SYN, seq=self.snd_nxt)
        self.snd_nxt += 1
        self._arm_rto(now)

    def send_buffer_limit(self) -> int:
        """App-visible send buffer cap; autotuned to track 2x cwnd so
        the window, not the buffer, limits throughput (tcp.c send-side
        autotuning)."""
        if self._send_autotune:
            return min(MAX_AUTOTUNE_BUFFER,
                       max(self.send_buffer, 2 * self.cwnd))
        return self.send_buffer

    def send(self, now: int, nbytes: int) -> int:
        """App write: queue nbytes for transmission."""
        if self.state not in (TcpState.ESTABLISHED, TcpState.CLOSE_WAIT):
            raise RuntimeError(f"send in state {self.state}")
        self.send_pending += nbytes
        self._try_send(now)
        return nbytes

    def close(self, now: int) -> None:
        if self.state == TcpState.LISTEN or self.state == TcpState.CLOSED:
            self.state = TcpState.CLOSED
            super().close(now)
            return
        self.fin_pending = True
        self._try_send(now)

    # ------------------------------------------------------------------
    # segment emission
    # ------------------------------------------------------------------
    def _flight(self) -> int:
        return self.snd_nxt - self.snd_una

    def _sack_blocks(self) -> tuple:
        """Up to 4 selective-ack blocks from the reorder buffer
        (receiver side of packet.h:20-33's selective ACK list)."""
        if not self.reorder:
            return ()
        spans = []
        for seq in sorted(self.reorder):
            end = seq + self.reorder[seq]
            if spans and seq <= spans[-1][1]:
                spans[-1][1] = max(spans[-1][1], end)
            else:
                spans.append([seq, end])
        return tuple((s, e) for s, e in spans[:4])

    def _emit(self, now: int, flags: TcpFlags, seq: int, size: int = 0,
              track: bool = True) -> None:
        dst_host, dst_port = self.peer
        hdr = TcpHeader(flags=int(flags), seq=seq, ack=self.rcv_nxt,
                        window=self.recv_window,
                        src_port=self.local_port, dst_port=dst_port,
                        sack=self._sack_blocks(),
                        ts_val=now, ts_echo=self._ts_echo)
        pkt = self.net.new_packet(dst_host=dst_host, protocol=Protocol.TCP,
                                  size=size, src_port=self.local_port,
                                  dst_port=dst_port)
        pkt.tcp = hdr
        self.segments_sent += 1
        self.net.tcp_segments_sent += 1
        if track and (size > 0 or flags & (TcpFlags.SYN | TcpFlags.FIN)):
            self.retx.append([seq, size, 1, now, int(flags)])
        self._stage(pkt, now)

    _ts_echo = 0

    def _try_send(self, now: int) -> None:
        window = min(self.cwnd, self.peer_window)
        while self.send_pending > 0 and self._flight() < window:
            seg = min(MSS, self.send_pending, window - self._flight())
            if seg <= 0:
                break
            self._emit(now, TcpFlags.ACK, seq=self.snd_nxt, size=seg)
            self.snd_nxt += seg
            self.send_pending -= seg
            self._arm_rto(now)
        if (self.fin_pending and self.send_pending == 0
                and self.fin_sent_seq is None):
            self.fin_sent_seq = self.snd_nxt
            self._emit(now, TcpFlags.FIN | TcpFlags.ACK, seq=self.snd_nxt)
            self.snd_nxt += 1
            self._arm_rto(now)
            if self.state == TcpState.ESTABLISHED:
                self.state = TcpState.FIN_WAIT_1
            elif self.state == TcpState.CLOSE_WAIT:
                self.state = TcpState.LAST_ACK

    def _send_ack(self, now: int) -> None:
        self._emit(now, TcpFlags.ACK, seq=self.snd_nxt, track=False)

    # ------------------------------------------------------------------
    # timers
    # ------------------------------------------------------------------
    def _arm_rto(self, now: int) -> None:
        if not self._rto_armed:
            self._rto_armed = True
            self._timer_gen += 1
            self.net.schedule_tcp_timer(self.conn_id, self._timer_gen,
                                        now + self.rto)

    def _restart_rto(self, now: int) -> None:
        self._rto_armed = False
        if self.retx:
            self._arm_rto(now)

    def on_timer(self, now: int, gen: int) -> None:
        if self.state == TcpState.TIME_WAIT:
            if gen == self._timer_gen:
                self._finish_close(now)
            return
        if gen != self._timer_gen or not self._rto_armed:
            return                      # stale timer
        self._rto_armed = False
        if not self.retx:
            return
        # RTO fire (tcp retransmit timer): back off, collapse cwnd
        self.cc.on_rto(self)
        self.dup_acks = 0
        self.in_recovery = False
        self.rto = min(self.rto * 2, MAX_RTO_NS)
        # RFC 2018 §8 renege safety: after an RTO the sender must
        # discard SACK state and retransmit from the cumulative ACK
        # point — otherwise a fully-SACKed-but-reneged flight leaves
        # _retransmit_first with no candidate and progress stalls
        # until the peer volunteers a new cumulative ACK.
        self.tally.sacked.clear()
        self._retransmit_first(now)
        self._arm_rto(now)

    def _retransmit_first(self, now: int) -> None:
        """Retransmit the lowest outstanding hole the peer has NOT
        selectively acknowledged (the tally's job in the reference:
        SACKed blocks are never resent)."""
        if not self.retx:
            return
        candidates = [e for e in self.retx
                      if not self.tally.is_sacked(e[0],
                                                  e[0] + max(e[1], 1))]
        if not candidates:
            return
        seq, size, n_tx, _, flags = min(candidates, key=lambda e: e[0])
        for e in self.retx:
            if e[0] == seq:
                e[2] += 1
                e[3] = now
        self.segments_retransmitted += 1
        self.net.tcp_segments_retransmitted += 1
        self._emit(now, TcpFlags(flags), seq=seq, size=size, track=False)

    # ------------------------------------------------------------------
    # inbound segments
    # ------------------------------------------------------------------
    def handle_packet(self, packet: Packet, now: int) -> None:
        hdr = packet.tcp
        if hdr is None:
            return
        flags = TcpFlags(hdr.flags)
        packet.add_status(PacketStatus.RCV_SOCKET_PROCESSED)
        self._ts_echo = hdr.ts_val
        self.peer_window = max(hdr.window, 1)

        if flags & TcpFlags.RST:
            self._abort(now)
            return

        if self.state == TcpState.LISTEN:
            if flags & TcpFlags.SYN:
                self._accept_child(packet, now)
            return

        if self.state == TcpState.SYN_SENT:
            if flags & TcpFlags.SYN and flags & TcpFlags.ACK:
                self.irs = hdr.seq
                self.rcv_nxt = hdr.seq + 1
                self._handle_ack(hdr, now)
                self.state = TcpState.ESTABLISHED
                self._send_ack(now)
                if self.on_connected:
                    self.on_connected(self.net.ctx, self, now)
                self._try_send(now)
            return

        if flags & TcpFlags.SYN:
            # duplicate SYN in SYN_RCVD: re-ack
            self._send_ack(now)
            return

        if flags & TcpFlags.ACK:
            self._handle_ack(hdr, now)
            if self.state == TcpState.SYN_RCVD and \
                    hdr.ack > self.iss:
                self.state = TcpState.ESTABLISHED
                if self.on_accept:
                    self.on_accept(self.net.ctx, self, now)
            elif self.state == TcpState.FIN_WAIT_1 and \
                    self.fin_sent_seq is not None and \
                    hdr.ack > self.fin_sent_seq:
                self.state = TcpState.FIN_WAIT_2
            elif self.state == TcpState.CLOSING and \
                    self.fin_sent_seq is not None and \
                    hdr.ack > self.fin_sent_seq:
                self._enter_time_wait(now)
            elif self.state == TcpState.LAST_ACK and \
                    self.fin_sent_seq is not None and \
                    hdr.ack > self.fin_sent_seq:
                self._finish_close(now)
                return

        if packet.size > 0:
            self._handle_data(hdr.seq, packet.size, now)

        if flags & TcpFlags.FIN:
            self._handle_fin(hdr, now)

    # -- ACK processing + Reno (tcp_cong_reno.c) -----------------------
    def _handle_ack(self, hdr: TcpHeader, now: int) -> None:
        ack = hdr.ack
        if ack > self.snd_nxt:
            return
        for s, e in hdr.sack:
            self.tally.mark_sacked(s, e)
        if ack > self.snd_una:
            acked = ack - self.snd_una
            self.snd_una = ack
            self.bytes_acked += acked
            self.retx = [e for e in self.retx if e[0] + max(e[1], 1) > ack]
            self.tally.clear_below(ack)
            self._sample_rtt(now, hdr.ts_echo)
            if self.in_recovery:
                if ack >= self.recover:
                    self.in_recovery = False
                    self.cc.on_exit_recovery(self)
                    self.dup_acks = 0
                else:
                    # NewReno partial ACK: retransmit next hole
                    self._retransmit_first(now)
            else:
                self.dup_acks = 0
                self.cc.on_ack(self, acked)
            self._restart_rto(now)
            self._try_send(now)
            if self.on_writable:
                self.on_writable(self.net.ctx, self, now)
        elif ack == self.snd_una and self._flight() > 0:
            self.dup_acks += 1
            if self.dup_acks == 3 and not self.in_recovery:
                # fast retransmit + fast recovery
                self.cc.on_enter_recovery(self)
                self.in_recovery = True
                self.recover = self.snd_nxt
                self._retransmit_first(now)
            elif self.in_recovery:
                self.cc.on_recovery_ack(self)
                self._try_send(now)

    def _sample_rtt(self, now: int, ts_echo: int) -> None:
        if ts_echo <= 0:
            return
        r = now - ts_echo
        if r < 0:
            return
        if self.srtt is None:
            self.srtt = r
            self.rttvar = r // 2
        else:
            self.rttvar = (3 * self.rttvar + abs(self.srtt - r)) // 4
            self.srtt = (7 * self.srtt + r) // 8
        self.rto = min(max(self.srtt + max(4 * self.rttvar,
                                           simtime.SIMTIME_ONE_MILLISECOND),
                           MIN_RTO_NS), MAX_RTO_NS)

    # -- inbound data --------------------------------------------------
    def _handle_data(self, seq: int, size: int, now: int) -> None:
        if seq + size <= self.rcv_nxt:
            self._send_ack(now)                 # old retransmission
            return
        if seq > self.rcv_nxt:
            self.reorder[seq] = max(self.reorder.get(seq, 0), size)
            self._send_ack(now)                 # dup ACK
            return
        # in order (possibly overlapping)
        delivered = seq + size - self.rcv_nxt
        self.rcv_nxt = seq + size
        while self.rcv_nxt in self.reorder:
            sz = self.reorder.pop(self.rcv_nxt)
            delivered += sz
            self.rcv_nxt += sz
        self.bytes_received += delivered
        # receive-buffer autotuning (tcp.c's dynamic right-sizing,
        # simplified): a time-bounded epoch that fills the advertised
        # window means the sender is window-limited — double it. The
        # epoch bound keeps slow trickle flows from accumulating their
        # way to the cap over a lifetime.
        if self._recv_autotune:
            if now - self._recv_epoch_start > RECV_EPOCH_NS:
                self._recv_epoch_start = now
                self._recv_epoch_bytes = 0
            self._recv_epoch_bytes += delivered
            if self._recv_epoch_bytes >= self.recv_window:
                self.recv_window = min(MAX_AUTOTUNE_BUFFER,
                                       self.recv_window * 2)
                self._recv_epoch_bytes = 0
                self._recv_epoch_start = now
        self._send_ack(now)
        if self.on_data:
            self.on_data(self.net.ctx, self, delivered, now)

    # -- teardown ------------------------------------------------------
    def _handle_fin(self, hdr: TcpHeader, now: int) -> None:
        # the FIN occupies the seq slot after any data
        if hdr.seq > self.rcv_nxt:
            return               # out of order FIN; wait for data
        self.rcv_nxt = max(self.rcv_nxt, hdr.seq + 1)
        self._send_ack(now)
        if self.state == TcpState.ESTABLISHED:
            self.state = TcpState.CLOSE_WAIT
            if self.on_closed:
                self.on_closed(self.net.ctx, self, now)
        elif self.state == TcpState.FIN_WAIT_1:
            self.state = TcpState.CLOSING
        elif self.state == TcpState.FIN_WAIT_2:
            self._enter_time_wait(now)

    def _enter_time_wait(self, now: int) -> None:
        self.state = TcpState.TIME_WAIT
        self._timer_gen += 1
        self.net.schedule_tcp_timer(self.conn_id, self._timer_gen,
                                    now + TIME_WAIT_NS)

    def _finish_close(self, now: int) -> None:
        was = self.state
        self.state = TcpState.CLOSED
        super().close(now)
        if was != TcpState.TIME_WAIT and self.on_closed:
            self.on_closed(self.net.ctx, self, now)

    def _abort(self, now: int) -> None:
        self.state = TcpState.CLOSED
        super().close(now)
        if self.on_closed:
            self.on_closed(self.net.ctx, self, now)

    # -- server side ---------------------------------------------------
    def _accept_child(self, packet: Packet, now: int) -> None:
        """Spawn a connection socket for an incoming SYN (the
        reference's server child-socket multiplexing in tcp.c)."""
        hdr = packet.tcp
        child = TcpSocket(self.net, self.local_port)
        child.peer = (packet.src_host, hdr.src_port)
        child.state = TcpState.SYN_RCVD
        child.irs = hdr.seq
        child.rcv_nxt = hdr.seq + 1
        child._ts_echo = hdr.ts_val
        child.on_accept = self.on_accept
        child.on_data = self.on_data
        child.on_closed = self.on_closed
        self.net.register(child)
        child._emit(now, TcpFlags.SYN | TcpFlags.ACK, seq=child.snd_nxt)
        child.snd_nxt += 1
        child._arm_rto(now)
