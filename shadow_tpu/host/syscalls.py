"""Syscall emulation for managed (real) processes.

The rebuild of the reference's syscall dispatch (src/main/host/
syscall_handler.c:247-533 and the per-area handlers in host/syscall/:
socket.c, epoll.c, poll.c, time.c, unistd.c, uio.c, fcntl.c, ioctl.c),
re-targeted at the virtual-descriptor layer (host/descriptors.py) and
the in-simulator network stack. Conventions:

* Handlers return the kernel ABI result: >= 0 on success, -errno on
  failure. Returning the NATIVE sentinel tells the shim to execute the
  syscall for real through its raw-syscall escape.
* A handler that must wait raises `Blocked(descs, deadline)`; the
  process parks the syscall on a Condition (syscall_condition.c) and
  the handler is re-entered from scratch when it fires — restart
  semantics, so handlers keep per-invocation progress in
  `process.syscall_state` (cleared when the syscall finally replies).
* Time is simulated: clocks read the host's event clock (+ the
  2000-01-01 EMULATED_TIME_OFFSET for wall clocks, definitions.h:79);
  sleeps and timeouts park on timer events, which is what advances
  the simulation.
"""

from __future__ import annotations

import os
import struct
from typing import Optional

from shadow_tpu import simtime
from shadow_tpu.host import memory as kmem
from shadow_tpu.host.descriptors import (
    EPOLLERR,
    EPOLLIN,
    EPOLLOUT,
    ERR,
    EpollDesc,
    EventfdDesc,
    Futex,
    HostFileDesc,
    PipeDesc,
    R,
    TableFull,
    TcpDesc,
    TcpListenDesc,
    TimerfdDesc,
    UdpDesc,
    UnixPairDesc,
    VFD_BASE,
    VFD_END,
    VirtualFileDesc,
    W,
)
from shadow_tpu.utils.slog import get_logger

log = get_logger("syscalls")

_libc_handle = None


def _libc():
    global _libc_handle
    if _libc_handle is None:
        import ctypes
        _libc_handle = ctypes.CDLL(None, use_errno=True)
    return _libc_handle


# ---- x86_64 syscall numbers ----------------------------------------

NR = dict(
    read=0, write=1, close=3, fstat=5, poll=7, lseek=8, ioctl=16,
    pread64=17, pwrite64=18, readv=19, writev=20, pipe=22, select=23,
    dup=32, dup2=33, nanosleep=35, getitimer=36, alarm=37, setitimer=38,
    getpid=39, socket=41, connect=42, accept=43, sendto=44, recvfrom=45,
    sendmsg=46, recvmsg=47, shutdown=48, bind=49, listen=50,
    getsockname=51, getpeername=52, socketpair=53, setsockopt=54,
    getsockopt=55, clone=56, fork=57, vfork=58, exit=60, uname=63,
    fcntl=72, gettimeofday=96, getppid=110, time=201, epoll_create=213,
    clock_gettime=228, clock_nanosleep=230, exit_group=231,
    epoll_wait=232, epoll_ctl=233, pselect6=270, ppoll=271,
    epoll_pwait=281, timerfd_create=283, eventfd=284,
    timerfd_settime=286, timerfd_gettime=287, accept4=288, eventfd2=290,
    epoll_create1=291, dup3=292, pipe2=293, recvmmsg=299, sendmmsg=307,
    getrandom=318, newfstatat=262, statx=332,
    getrusage=98, times=100, sched_setaffinity=203,
    sched_getaffinity=204, getcpu=309,
    sched_yield=24, gettid=186, sysinfo=99, futex=202,
    set_tid_address=218, sendfile=40, tgkill=234, clone3=435,
    wait4=61, kill=62, waitid=247, rt_sigaction=13, pause=34,
    rt_sigprocmask=14, rt_sigpending=127, rt_sigtimedwait=128,
    rt_sigsuspend=130, tkill=200, execve=59,
    mmap=9, mprotect=10, munmap=11, brk=12, mremap=25,
    open=2, openat=257,
    # fd-mediated file family (ref syscall/file.c + fileat.c)
    flock=73, fsync=74, fdatasync=75, truncate=76, ftruncate=77,
    getdents=78, chdir=80, fchdir=81, rename=82, mkdir=83, rmdir=84,
    creat=85, link=86, unlink=87, symlink=88, readlink=89,
    chmod=90, fchmod=91, chown=92, fchown=93, lchown=94,
    utime=132, getdents64=217, utimes=235,
    mkdirat=258, fchownat=260, futimesat=261, unlinkat=263,
    renameat=264, linkat=265, symlinkat=266, readlinkat=267,
    fchmodat=268, faccessat=269, utimensat=280, fallocate=285,
    renameat2=316, faccessat2=439,
    setxattr=188, lsetxattr=189, fsetxattr=190, getxattr=191,
    lgetxattr=192, fgetxattr=193, listxattr=194, llistxattr=195,
    flistxattr=196, removexattr=197, lremovexattr=198,
    fremovexattr=199,
    prlimit64=302, prctl=157, set_robust_list=273,
    get_robust_list=274, getrlimit=97, setrlimit=160, fstatfs=138,
    preadv=295, pwritev=296, preadv2=327, pwritev2=328,
    mknod=133, mknodat=259, readahead=187, fadvise64=221,
    sync_file_range=277, syncfs=306,
)
NR_NAME = {v: k for k, v in NR.items()}

# errno
EPERM, ENOENT, EINTR, EBADF, EAGAIN, EFAULT, EINVAL = 1, 2, 4, 9, 11, 14, 22
ENXIO = 6
ECHILD = 10
ENOTTY, ESPIPE, EPIPE, ENOSYS, ENOTSOCK, EDESTADDRREQ = 25, 29, 32, 38, 88, 89
EMSGSIZE, ENOPROTOOPT, EPROTONOSUPPORT, EOPNOTSUPP, EAFNOSUPPORT = \
    90, 92, 93, 95, 97
E2BIG, EACCES, EMFILE = 7, 13, 24
EEXIST, EXDEV, ENODEV, ENOTDIR, EISDIR, ENOTEMPTY = 17, 18, 19, 20, 21, 39
ENAMETOOLONG, ELOOP, ERANGE, ENODATA = 36, 40, 34, 61
EADDRINUSE, ENETUNREACH, ECONNRESET, EISCONN, ENOTCONN = 98, 101, 104, 106, 107
ETIMEDOUT, ECONNREFUSED, EINPROGRESS, EALREADY = 110, 111, 115, 114

# socket constants
AF_INET, AF_UNIX = 2, 1
SOCK_STREAM, SOCK_DGRAM = 1, 2
SOCK_NONBLOCK, SOCK_CLOEXEC = 0x800, 0x80000
SOL_SOCKET, SOL_TCP = 1, 6
SO_ERROR, SO_TYPE, SO_SNDBUF, SO_RCVBUF, SO_ACCEPTCONN = 4, 3, 7, 8, 30
MSG_DONTWAIT, MSG_PEEK = 0x40, 0x02

_LIBC = None


def _libc():
    # cached ctypes handle for the few operations the os module
    # cannot express (fallocate modes, renameat2 exchange)
    global _LIBC
    if _LIBC is None:
        import ctypes
        _LIBC = ctypes.CDLL(None, use_errno=True)
    return _LIBC
SHUT_RD, SHUT_WR, SHUT_RDWR = 0, 1, 2
O_NONBLOCK, O_RDWR = 0x800, 0x2
F_DUPFD, F_GETFD, F_SETFD, F_GETFL, F_SETFL, F_DUPFD_CLOEXEC = \
    0, 1, 2, 3, 4, 1030
FIONREAD, FIONBIO = 0x541B, 0x5421
EPOLL_CTL_ADD, EPOLL_CTL_DEL, EPOLL_CTL_MOD = 1, 2, 3
CLOCK_REALTIME, CLOCK_MONOTONIC = 0, 1
TFD_TIMER_ABSTIME = 1
EFD_SEMAPHORE, EFD_NONBLOCK = 1, 0x800

UDP_MAX_PAYLOAD = simtime.CONFIG_MTU - simtime.CONFIG_HEADER_SIZE_UDPIPETH

NATIVE = object()          # sentinel: shim executes the syscall for real
APPLIED = object()         # sentinel: result already poked into %rax
#                            (ptrace clone/fork rewrite it at the exit
#                            stop); the backend resumes with no reply


class CloneGo:
    """sys_clone's approval value: the process layer replies
    IPC_CLONE_GO (child vtid + child channel offset) instead of a
    plain DONE result (clone.c's thread_clone handshake)."""

    __slots__ = ("vtid", "channel_offset")

    def __init__(self, vtid: int, channel_offset: int):
        self.vtid = vtid
        self.channel_offset = channel_offset


class Blocked(Exception):
    """Raised by a handler that must wait (SYSCALL_BLOCK analogue)."""

    def __init__(self, descs=(), deadline: Optional[int] = None):
        super().__init__("blocked")
        self.descs = list(descs)
        self.deadline = deadline


class FatalDivergence(RuntimeError):
    """Kernel/simulator state divergence that must abort the run —
    never degraded to an errno by the dispatch crash guards."""


def _s32(v: int) -> int:
    """Syscall args arrive as u64; recover signed 32-bit values."""
    v &= 0xFFFFFFFF
    return v - (1 << 32) if v >= (1 << 31) else v


def _s64(v: int) -> int:
    return v - (1 << 64) if v >= (1 << 63) else v


class SyscallHandler:
    def __init__(self, process):
        self.p = process

    # -- helpers -------------------------------------------------------
    @property
    def mem(self) -> kmem.ProcessMemory:
        return self.p.mem

    @property
    def table(self):
        return self.p.table

    @property
    def state(self) -> dict:
        return self.p.syscall_state


    def _no_desc(self, fd: int):
        """fd is not one of our virtual descriptors. Under ptrace every
        syscall traps, so stdio / real-file fds legitimately reach the
        handler: hand them back to the kernel (the preload shim's
        fd>=VFD_BASE gate, native/shim/shim.c, does this client-side;
        the reference's equivalent is its native-syscall list,
        syscall_handler.c:225-229)."""
        return NATIVE if 0 <= fd < VFD_BASE else -EBADF

    def _desc(self, fd: int):
        d = self.table.get(fd)
        if d is None or d.closed:
            return None
        return d

    def _self_ip_be(self) -> bytes:
        return struct.pack(">I", self.p.host.address.ip)

    def _write_sockaddr(self, addr_ptr: int, len_ptr: int, ip_be: bytes,
                        port: int) -> None:
        if not addr_ptr or not len_ptr:
            return
        cur = struct.unpack("<I", self.mem.read(len_ptr, 4))[0]
        sa = kmem.pack_sockaddr_in(ip_be, port)
        self.mem.write(addr_ptr, sa[: min(len(sa), cur)])
        self.mem.write(len_ptr, struct.pack("<I", len(sa)))

    def _host_ip_be(self, host_id: int) -> bytes:
        addr = self.p.manager.hosts[host_id].address
        return struct.pack(">I", addr.ip if addr else host_id)

    def _resolve_dst(self, ip_be: bytes) -> Optional[int]:
        ip = struct.unpack(">I", ip_be)[0]
        if ip == 0x7F000001 or ip == 0:          # 127.0.0.1 / INADDR_ANY
            return self.p.host.host_id
        if ip == self.p.host.address.ip:
            return self.p.host.host_id
        return self.p.resolve_ip(ip)

    def _nonblock(self, desc, flags: int = 0) -> bool:
        return desc.nonblock or bool(flags & MSG_DONTWAIT)

    # -- dispatch ------------------------------------------------------
    def dispatch(self, ctx, nr: int, args):
        self.p.host.net.ctx = ctx
        if getattr(self.p, "publish_sim_time", False):
            ch = getattr(self.p.current, "channel", None)
            if ch is not None:
                ch.set_sim_now(ctx.now)   # passive shim clock (logf)
        name = NR_NAME.get(nr)
        if name is None:
            return NATIVE
        fn = getattr(self, "sys_" + name, None)
        if fn is None:
            return -ENOSYS
        try:
            return fn(ctx, args)
        except TableFull:
            # virtual fd window [600, 1024) exhausted: EMFILE, the
            # same answer the kernel gives at RLIMIT_NOFILE
            return -EMFILE

    # ==================================================================
    # time (host/syscall/time.c)
    # ==================================================================
    def _now_wall(self, ctx) -> int:
        return ctx.now + simtime.EMULATED_TIME_OFFSET

    def sys_clock_gettime(self, ctx, a):
        clk, ts_ptr = _s32(a[0]), a[1]
        if not ts_ptr:
            return -EFAULT
        t = self._now_wall(ctx) if clk in (0, 5, 8) else ctx.now
        self.mem.write(ts_ptr, kmem.pack_timespec(t))
        return 0

    def sys_gettimeofday(self, ctx, a):
        tv_ptr = a[0]
        if tv_ptr:
            self.mem.write(tv_ptr, kmem.pack_timeval(self._now_wall(ctx)))
        return 0

    def sys_time(self, ctx, a):
        secs = self._now_wall(ctx) // simtime.SIMTIME_ONE_SECOND
        if a[0]:
            self.mem.write(a[0], struct.pack("<q", secs))
        return secs

    def _sleep_until(self, ctx, deadline: int, rem_ptr: int = 0):
        if ctx.now >= deadline:
            if rem_ptr:
                self.mem.write(rem_ptr, kmem.pack_timespec(0))
            return 0
        raise Blocked(deadline=deadline)

    def sys_nanosleep(self, ctx, a):
        st = self.state
        if "deadline" not in st:
            ns = kmem.unpack_timespec(self.mem.read(a[0], 16))
            if ns < 0:
                return -EINVAL
            st["deadline"] = ctx.now + ns
        return self._sleep_until(ctx, st["deadline"], a[1])

    def sys_clock_nanosleep(self, ctx, a):
        st = self.state
        clk, flags = _s32(a[0]), _s32(a[1])
        if "deadline" not in st:
            ns = kmem.unpack_timespec(self.mem.read(a[2], 16))
            if flags & TFD_TIMER_ABSTIME:
                if clk in (0, 5, 8):
                    ns -= simtime.EMULATED_TIME_OFFSET
                st["deadline"] = max(ns, ctx.now)
            else:
                if ns < 0:
                    return -EINVAL
                st["deadline"] = ctx.now + ns
        return self._sleep_until(ctx, st["deadline"],
                                 a[3] if not flags & TFD_TIMER_ABSTIME
                                 else 0)

    def sys_alarm(self, ctx, a):
        return 0            # accepted, never fires (no signals yet)

    def sys_setitimer(self, ctx, a):
        return 0

    def sys_getitimer(self, ctx, a):
        if a[1]:
            self.mem.write(a[1], b"\0" * 32)
        return 0

    # ==================================================================
    # identity / misc (unistd.c, shadow.c)
    # ==================================================================
    def sys_getpid(self, ctx, a):
        return self.p.vpid

    def sys_getppid(self, ctx, a):
        parent = getattr(self.p, "parent_proc", None)
        return parent.vpid if parent is not None else 1

    def sys_uname(self, ctx, a):
        if not a[0]:
            return -EFAULT
        self.mem.write(a[0], kmem.pack_utsname(self.p.host.name))
        return 0

    def sys_getrandom(self, ctx, a):
        buf, n = a[0], min(int(a[1]), 1 << 20)
        data = self.p.deterministic_bytes(n)
        self.mem.write(buf, data)
        return n

    def sys_exit(self, ctx, a):
        """Thread exit: only the calling thread dies (clone.c model);
        the process exits when its last thread does."""
        code = _s32(a[0])
        cur = getattr(self.p, "current", None)
        if cur is not None and hasattr(self.p, "thread_exit"):
            self.p.thread_exit(ctx, cur, code)
        else:
            self.p.begin_exit(code)
        return NATIVE

    def sys_exit_group(self, ctx, a):
        self.p.begin_exit(_s32(a[0]))
        for th in getattr(self.p, "threads", {}).values():
            th.alive = False        # _continue replies, then stops
        return NATIVE

    # clone flag bits (uapi)
    CLONE_VM, CLONE_FS, CLONE_FILES = 0x100, 0x200, 0x400
    CLONE_SIGHAND, CLONE_THREAD = 0x800, 0x10000
    CLONE_SYSVSEM, CLONE_SETTLS = 0x40000, 0x80000

    def sys_clone(self, ctx, a):
        """Managed thread creation (clone.c:30: CLONE_THREAD-style
        clones; fork-style clones — no CLONE_THREAD, e.g. glibc's
        fork() — route to the fork path under ptrace, where no shim
        pre-normalizes them). The heavy lifting lives in the backend's
        spawn_thread/spawn_fork."""
        flags = int(a[0])
        if not flags & self.CLONE_THREAD:
            # fork-style clone: only reaches us under ptrace (the
            # preload shim rewrites these to SYS_fork client-side);
            # pass the stack/tid words so the tracer can redirect the
            # COW child onto the requested clone stack
            if getattr(self.p, "interpose_style", "") == "ptrace":
                if not getattr(self.p, "supports_fork", False):
                    return -ENOSYS
                return self.p.spawn_fork(ctx, flags=flags,
                                         parsed=(a[2], a[3], a[1]))
            return -EOPNOTSUPP
        required = (self.CLONE_VM | self.CLONE_FS | self.CLONE_FILES |
                    self.CLONE_SIGHAND | self.CLONE_THREAD |
                    self.CLONE_SYSVSEM | self.CLONE_SETTLS)
        if (flags & required) != required:
            return -EOPNOTSUPP
        if not getattr(self.p, "supports_threads", False):
            return -ENOSYS
        return self.p.spawn_thread(ctx, flags, a)

    def sys_clone3(self, ctx, a):
        """clone3 (musl/Go issue it natively): parse struct
        clone_args and route to the thread/fork paths. Supported on
        the ptrace backend (every syscall traps with full memory
        access); the preload shim refuses with ENOSYS, which glibc
        answers by falling back to classic clone."""
        if getattr(self.p, "interpose_style", "") != "ptrace":
            return -ENOSYS
        ptr, size = a[0], int(a[1])
        if not ptr:
            return -EFAULT
        if size < 64:
            return -EINVAL
        if size > 4096:
            # kernel rejects size > PAGE_SIZE outright (ADVICE r4 #4)
            return -E2BIG
        try:
            raw = self.mem.read(ptr, size)
        except OSError:
            return -EFAULT
        if any(raw[64:]):
            # extension fields we don't emulate (set_tid, cgroup):
            # the kernel's rule for unknown nonzero trailing bytes
            return -E2BIG
        (flags, _pidfd, child_tid, parent_tid, _exit_sig, stack,
         stack_size, _tls) = struct.unpack("<8Q", raw[:64])
        stack_top = (stack + stack_size) if stack else 0
        flags = int(flags)
        if flags & self.CLONE_THREAD:
            required = (self.CLONE_VM | self.CLONE_FS |
                        self.CLONE_FILES | self.CLONE_SIGHAND |
                        self.CLONE_THREAD | self.CLONE_SYSVSEM)
            if (flags & required) != required:
                return -EOPNOTSUPP
            if not getattr(self.p, "supports_threads", False):
                return -ENOSYS
            return self.p.spawn_thread(
                ctx, flags, a,
                parsed=(int(parent_tid), int(child_tid),
                        int(stack_top)))
        if not getattr(self.p, "supports_fork", False):
            return -ENOSYS
        return self.p.spawn_fork(
            ctx, flags=flags,
            parsed=(int(parent_tid), int(child_tid), int(stack_top)))

    def sys_fork(self, ctx, a):
        """fork / vfork / fork-style clone: the shim normalizes all
        three to SYS_fork (vfork degrades to COW-fork semantics). The
        process layer allocates the child's vpid + channel; the shim
        performs the real fork and reports the native pid via
        IPC_FORK_RESULT (process.c:457-651's child-process creation,
        reshaped for the preload funnel)."""
        if not getattr(self.p, "supports_fork", False):
            return -ENOSYS      # ptrace backend: fork later
        return self.p.spawn_fork(ctx)

    def sys_vfork(self, ctx, a):
        return self.sys_fork(ctx, a)

    def sys_wait4(self, ctx, a):
        """Virtual child wait (kernel/exit.c semantics over vpids):
        reaps a zombie child, writes the wstatus, blocks without
        WNOHANG. The shim additionally reaps the REAL zombie
        natively after the virtual result."""
        pid, status_ptr, options = _s32(a[0]), a[1], _s32(a[2])
        WNOHANG = 1
        p = self.p
        children = getattr(p, "children", None)
        if children is None:
            return -ECHILD
        matching = [c for c in children.values()
                    if pid in (-1, c.vpid)]
        if not matching:
            return -ECHILD
        for c in matching:
            if c.wstatus is not None:
                if status_ptr:
                    self.mem.write(status_ptr,
                                   struct.pack("<i", c.wstatus))
                del children[c.vpid]
                return c.vpid
        if options & WNOHANG:
            return 0
        raise Blocked()          # child_exited wakes the parked thread

    def sys_waitid(self, ctx, a):
        """waitid over virtual children (modern glibc posix_spawn
        waits this way): P_ALL/P_PID, WEXITED reaping (WNOWAIT keeps
        the zombie), CLD_EXITED/CLD_KILLED siginfo."""
        P_ALL, P_PID = 0, 1
        WNOHANG, WEXITED, WNOWAIT = 1, 4, 0x01000000
        idtype, vid, info_ptr, options = (_s32(a[0]), _s32(a[1]),
                                          a[2], _s32(a[3]))
        if idtype not in (P_ALL, P_PID) or not options & WEXITED:
            return -EINVAL
        children = getattr(self.p, "children", None)
        if children is None:
            return -ECHILD
        matching = [c for c in children.values()
                    if idtype == P_ALL or c.vpid == vid]
        if not matching:
            return -ECHILD
        for c in matching:
            if c.wstatus is not None:
                if info_ptr:
                    CLD_EXITED, CLD_KILLED = 1, 2
                    if c.term_signal is not None:
                        code, status = CLD_KILLED, c.term_signal
                    else:
                        code, status = CLD_EXITED, (c.wstatus >> 8) \
                            & 0xFF
                    # glibc siginfo_t SIGCHLD layout: signo, errno,
                    # code, pad, pid, uid, status, utime, stime
                    SIGCHLD = 17
                    info = struct.pack("<iii4xiii", SIGCHLD, 0, code,
                                       c.vpid, 0, status)
                    info = info + b"\x00" * (128 - len(info))
                    self.mem.write(info_ptr, info)
                if not options & WNOWAIT:
                    del children[c.vpid]
                return 0
        if options & WNOHANG:
            if info_ptr:
                self.mem.write(info_ptr, b"\x00" * 128)
            return 0
        raise Blocked()          # child_exited wakes the parked thread

    def sys_kill(self, ctx, a):
        """Virtual signal delivery by vpid (signal.c's kill path):
        routed to the target process on the same simulated host."""
        pid, sig = _s32(a[0]), _s32(a[1])
        if not getattr(self.p, "supports_signals", False):
            return -ENOSYS      # ptrace backend: signals later
        target = self.p
        if pid > 0 and pid != self.p.vpid:
            target = self._find_process(pid)
            if target is None:
                return -3       # ESRCH
        if sig == 0:
            return 0
        if sig < 1 or sig > 64:
            return -EINVAL
        target.deliver_signal(ctx, sig)
        return 0

    def _find_process(self, vpid: int):
        """vpid -> live ManagedProcess on the same host (parent,
        children, siblings)."""
        seen = set()
        stack = [self.p]
        root = getattr(self.p, "parent_proc", None)
        while root is not None:
            stack.append(root)
            root = getattr(root, "parent_proc", None)
        while stack:
            proc = stack.pop()
            if id(proc) in seen:
                continue
            seen.add(id(proc))
            if proc.vpid == vpid:
                return proc if proc.alive else None
            stack.extend(getattr(proc, "children", {}).values())
        # fall back to any process on this host (configured siblings)
        for app in getattr(self.p.host, "apps", []) or []:
            if getattr(app, "vpid", None) == vpid:
                return app if app.alive else None
        return None

    def sys_rt_sigaction(self, ctx, a):
        """Virtual signal dispositions (signal.c:rt_sigaction): the
        handler address + flags are recorded simulator-side and
        invoked in the plugin via IPC_SIGNAL at syscall boundaries.
        Hardware faults (SEGV/BUS/ILL/FPE) stay native — the shim owns
        SIGSEGV for TSC emulation and chains app handlers itself;
        SIGSYS is load-bearing and silently ignored."""
        if not getattr(self.p, "supports_signals", False):
            return NATIVE       # backend without signal support
        signum, act_ptr, old_ptr = _s32(a[0]), a[1], a[2]
        SIGKILL, SIGSTOP, SIGSYS = 9, 19, 31
        SIGSEGV = 11
        if getattr(self.p, "signal_style", "ipc") == "inject":
            # ptrace backend: record the disposition virtually (it
            # gates delivery decisions) AND install it natively — an
            # injected signal runs the kernel-built handler frame.
            # The tracer consumes TSC SIGSEGVs before delivery, so
            # even SEGV handlers are safe to keep native.
            if signum in (SIGKILL, SIGSTOP) and act_ptr:
                return -EINVAL
            if signum < 1 or signum > 64:
                return -EINVAL
            if act_ptr:
                handler, flags, restorer, mask = struct.unpack(
                    "<QQQQ", self.mem.read(act_ptr, 32))
                self.p.sigactions[signum] = (handler, flags,
                                             restorer, mask)
            return NATIVE       # kernel installs + fills oldact
        HW_NATIVE = (4, 7, 8)   # ILL, BUS, FPE: shim doesn't own these
        if signum in HW_NATIVE:
            return NATIVE
        if signum == SIGSEGV:
            # NEVER native: the shim's SIGSEGV handler is the TSC
            # emulation; libc-level registrations are chained by the
            # shim's sigaction override, and raw-syscall registrations
            # are recorded here but only fire virtually (documented
            # limitation — real faults still chain via the shim)
            if act_ptr:
                handler, flags, restorer, mask = struct.unpack(
                    "<QQQQ", self.mem.read(act_ptr, 32))
                self.p.sigactions[signum] = (handler, flags, restorer,
                                             mask)
            return 0
        if signum in (SIGKILL, SIGSTOP) and act_ptr:
            return -EINVAL
        if signum < 1 or signum > 64:
            return -EINVAL
        acts = self.p.sigactions
        old = acts.get(signum)
        if old_ptr:
            # kernel struct sigaction: handler, flags, restorer, mask
            if old is None:
                self.mem.write(old_ptr, b"\x00" * 32)
            else:
                self.mem.write(old_ptr, struct.pack(
                    "<QQQQ", old[0], old[1], old[2], old[3]))
        if act_ptr and signum != SIGSYS:
            handler, flags, restorer, mask = struct.unpack(
                "<QQQQ", self.mem.read(act_ptr, 32))
            acts[signum] = (handler, flags, restorer, mask)
        return 0

    def sys_pause(self, ctx, a):
        """Blocks until a signal handler runs (always -EINTR after)."""
        raise Blocked()

    def sys_tgkill(self, ctx, a):
        """Signal a thread by virtual tid. Delivery is process-level
        (one signal queue per process, like our one-thread-at-a-time
        execution model)."""
        return self._thread_kill(ctx, _s32(a[1]), _s32(a[2]))

    def sys_tkill(self, ctx, a):
        """Obsolete tgkill without the tgid check (signal.c tkill)."""
        return self._thread_kill(ctx, _s32(a[0]), _s32(a[1]))

    def _thread_kill(self, ctx, tid: int, sig: int):
        threads = getattr(self.p, "threads", {})
        if tid not in threads or not threads[tid].alive:
            return -3           # ESRCH
        if sig == 0:
            return 0
        if not getattr(self.p, "supports_signals", False):
            return -ENOSYS
        if sig < 1 or sig > 64:
            return -EINVAL
        self.p.deliver_signal(ctx, sig, target=threads[tid])
        return 0

    # -- signal masks & synchronous waits (signal.c analogues) ---------
    _UNBLOCKABLE = (1 << 8) | (1 << 18)     # SIGKILL, SIGSTOP

    def sys_rt_sigprocmask(self, ctx, a):
        """Virtual-mask mirror: the shim already performed the native
        mask change (shim.c shim_sigprocmask — SIGSYS stripped, trap
        frame mirrored) and reports here so IPC_SIGNAL delivery can
        honor blocking. Ptrace backend: kernel semantics, untouched.
        Ref: src/main/host/syscall/signal.c rt_sigprocmask."""
        if not getattr(self.p, "supports_signals", False):
            return NATIVE
        how, set_ptr, size = _s32(a[0]), a[1], a[3]
        th = self.p.current
        # validate + read the new set BEFORE touching oldset: the
        # kernel writes oldset only on success (ADVICE r4 #2)
        s = None
        if set_ptr and size >= 8:
            if how not in (0, 1, 2):
                return -EINVAL
            s = struct.unpack("<Q", self.mem.read(set_ptr, 8))[0]
            s &= ~self._UNBLOCKABLE
        if getattr(self.p, "signal_style", "ipc") == "inject" \
                and a[2] and size >= 8:
            # no shim wrote the old set natively (the ptrace kernel
            # mask is untouched) — report the VIRTUAL mask
            self.mem.write(a[2], struct.pack("<Q", th.sigmask))
        if s is not None:
            if how == 0:                    # SIG_BLOCK
                th.sigmask |= s
            elif how == 1:                  # SIG_UNBLOCK
                th.sigmask &= ~s
            else:                           # SIG_SETMASK
                th.sigmask = s
        # the post-dispatch boundary flush delivers newly unblocked
        # pending signals before this result lands
        return 0

    def sys_rt_sigpending(self, ctx, a):
        if not getattr(self.p, "supports_signals", False):
            return NATIVE
        ptr, size = a[0], a[1]
        pend = 0
        for s in list(getattr(self.p, "pending_signals", ())) + \
                list(self.p.current.pending):
            pend |= 1 << (s - 1)
        if ptr and size >= 8:
            self.mem.write(ptr, struct.pack("<Q", pend))
        return 0

    def sys_rt_sigsuspend(self, ctx, a):
        """Swap the mask and park until a virtual signal's handler has
        run; always fails with EINTR, mask restored by the delivery
        path (ManagedProcess._interrupt_parked)."""
        if not getattr(self.p, "supports_signals", False):
            return NATIVE
        if not a[0]:
            return -EFAULT
        th = self.p.current
        st = self.state
        if "ss_armed" not in st:
            st["ss_armed"] = True
            mask = struct.unpack("<Q", self.mem.read(a[0], 8))[0]
            th.restore_mask = th.sigmask
            th.sigmask = mask & ~self._UNBLOCKABLE
        raise Blocked()

    def _swap_pmask(self, ptr: int) -> None:
        """The p-variant waits' atomic temporary mask (ppoll/pselect6/
        epoll_pwait): installed on first entry, restored by the reply
        path (ManagedProcess._reply_to) when the result lands — so
        virtual delivery can interrupt a park the temp mask admits."""
        if not ptr or not getattr(self.p, "supports_signals", False):
            return
        st = self.state
        if st.get("pmask_set"):
            return
        st["pmask_set"] = True
        th = self.p.current
        mask = struct.unpack("<Q", self.mem.read(ptr, 8))[0]
        th.restore_mask = th.sigmask
        th.sigmask = mask & ~self._UNBLOCKABLE

    def sys_rt_sigtimedwait(self, ctx, a):
        """Synchronously consume a queued signal from `set` without
        running its handler (signal.c rt_sigtimedwait). Signals in the
        wait set are normally blocked by the caller; delivery to a
        parked waiter happens in ManagedProcess.deliver_signal."""
        if not getattr(self.p, "supports_signals", False):
            return NATIVE
        th = self.p.current
        set_ptr, info_ptr, timeout_ptr = a[0], a[1], a[2]
        if not set_ptr:
            return -EFAULT
        wset = struct.unpack("<Q", self.mem.read(set_ptr, 8))[0]
        for pend in (th.pending, getattr(self.p, "pending_signals",
                                         [])):
            for i, s in enumerate(pend):
                if (wset >> (s - 1)) & 1:
                    pend.pop(i)
                    th.sigwait = None
                    self.write_siginfo(info_ptr, s)
                    return s
        st = self.state
        if "deadline" not in st:
            if timeout_ptr:
                sec, nsec = struct.unpack(
                    "<qq", self.mem.read(timeout_ptr, 16))
                if sec < 0 or nsec < 0 or nsec >= 10**9:
                    return -EINVAL
                st["deadline"] = ctx.now + sec * 10**9 + nsec
            else:
                st["deadline"] = None
        if st["deadline"] is not None and ctx.now >= st["deadline"]:
            th.sigwait = None
            return -EAGAIN
        th.sigwait = (wset, info_ptr)
        raise Blocked(deadline=st["deadline"])

    def sys_execve(self, ctx, a):
        """Replace the process image (process.c exec handling): the
        shim runs the real execve through the fixed-address trampoline
        (stacked seccomp filters all allow it), the new image's shim
        reconnects over the same IPC channel, and its constructor
        announces IPC_EXEC_DONE so bookkeeping (sibling threads,
        close-on-exec descriptors, signal dispositions) completes
        before any app code runs. The caller must pass an environment
        containing the SHADOWTPU_* variables (i.e. its own environ) —
        a clean envp would produce an unmanaged image, so it is
        refused."""
        if not getattr(self.p, "supports_exec", False):
            return -ENOSYS
        if self.p.current is not self.p.threads.get(self.p.vpid):
            # exec from a secondary thread: the kernel kills siblings
            # and the exec'ing thread TAKES OVER the leader's tid —
            # tid bookkeeping neither backend models; refuse loudly
            # (preload: wrong announce channel; ptrace: stale
            # native_tid would ESRCH the tracer)
            log.warning("execve from a non-main thread is not "
                        "supported")
            return -ENOSYS
        if getattr(self.p, "interpose_style", "") == "ptrace":
            # no shim to re-announce: let the kernel exec run native;
            # the tracer sees PTRACE_EVENT_EXEC, re-patches the new
            # image's vDSO, and flags the step reply so the process
            # layer applies exec bookkeeping (_complete_exec_ptrace)
            path_ptr = a[0]
            if not path_ptr:
                return -EFAULT
            try:
                xpath = self.mem.read_cstr(path_ptr).decode(
                    errors="replace")
            except OSError:
                return -EFAULT
            self.p.exec_pending = xpath
            return NATIVE
        if not getattr(self.p, "supports_fork", False):
            return -ENOSYS
        path_ptr, envp_ptr = a[0], a[2]
        if not path_ptr:
            return -EFAULT
        try:
            path = self.mem.read_cstr(path_ptr).decode(
                errors="replace")
        except OSError:
            return -EFAULT
        if not os.path.isabs(path):
            try:
                cwd = os.readlink(f"/proc/{self.p.native_pid}/cwd")
                path = os.path.join(cwd, path)
            except OSError:
                return -ENOENT
        if not os.path.exists(path):
            return -ENOENT
        if not os.access(path, os.X_OK):
            return -13              # EACCES
        exec_str = None
        has_shm = False
        if envp_ptr:
            for i in range(4096):           # bound, not a real cap
                p = struct.unpack(
                    "<Q", self.mem.read(envp_ptr + 8 * i, 8))[0]
                if p == 0:
                    break
                try:
                    s = self.mem.read_cstr(p).decode(errors="replace")
                except OSError:
                    break
                if s.startswith("SHADOWTPU_SHM="):
                    has_shm = True
                elif s.startswith("SHADOWTPU_EXEC="):
                    exec_str = (p, s)
        if not has_shm or exec_str is None:
            log.warning(
                "execve(%s): envp lacks the SHADOWTPU_* variables "
                "(pass your environ) — refusing", path)
            return -EPERM
        # flip SHADOWTPU_EXEC to 1 IN THE ENV THE APP IS PASSING so
        # the new image's constructor knows to announce itself (works
        # for deep-copied env arrays too; the shim flips its own
        # environ back if the exec fails)
        p, s = exec_str
        self.mem.write(p + len(s) - 1, b"1")
        self.p.exec_pending = path
        return NATIVE

    # -- address-space bookkeeping (MemoryManager map side) ------------
    # Under ptrace every syscall stops here, so the plugin's mapping
    # table (host/memmap.py) is maintained LIVE — the reference's
    # memory_manager servicing of mmap/brk/munmap (mod.rs:1-17). The
    # preload filter lets these run native (the dynamic loader issues
    # them before a post-execve shim exists), and the tracker
    # refreshes lazily from /proc instead.
    def _maps(self):
        return getattr(self.p, "maps", None)

    def sys_mmap(self, ctx, a):
        # the kernel chooses the address for non-FIXED maps, the
        # tracer does not surface native return values, and even a
        # MAP_FIXED request can fail — so never record at entry; mark
        # the snapshot stale and refresh from /proc on demand
        MAP_ANONYMOUS = 0x20
        if not _s32(a[3]) & MAP_ANONYMOUS:
            fd = _s32(a[4])
            if fd >= VFD_BASE:
                # file-backed mapping of an EMULATED fd: the real fd
                # lives in the SIMULATOR. Under ptrace the mapping is
                # realized in the plugin through /proc/<sim>/fd/<osfd>
                # (ref mman.c:72-126's procfs technique) with three
                # injected syscalls: openat -> the real mmap with the
                # fd swapped -> close. Under preload there is no
                # arg-rewriting channel: ENODEV makes apps fall back
                # to read().
                d = self._desc(fd)
                if d is None:
                    return -EBADF
                if isinstance(d, HostFileDesc) and not d.is_dir and \
                        getattr(self.p, "interpose_style", "") == \
                        "ptrace":
                    return self._mmap_emulated_fd(a, d)
                return -ENODEV
        m = self._maps()
        if m is not None:
            m.dirty = True
        return NATIVE

    def _mmap_emulated_fd(self, a, d):
        from shadow_tpu.host.ptrace import PATH_ARG

        acc = d.flags & self.O_ACCMODE
        path = f"/proc/{os.getpid()}/fd/{d.osfd}".encode()
        inj = self.p.inject_syscall
        fd2 = inj(NR["openat"],
                  [self.AT_FDCWD, PATH_ARG, acc | os.O_CLOEXEC, 0],
                  path=path)
        if fd2 is None or fd2 < 0:
            return -ENODEV
        res = inj(NR["mmap"], [a[0], a[1], a[2], a[3], fd2, a[5]])
        if res is None:
            # tracee died mid-sequence: no further commands (the next
            # _continue finalizes the death); fd2 died with it
            return -ENODEV
        inj(NR["close"], [fd2])
        if res < 0:
            return res
        m = self._maps()
        if m is not None:
            m.dirty = True
        return res

    def sys_munmap(self, ctx, a):
        m = self._maps()
        if m is not None:
            m.on_munmap(int(a[0]), int(a[1]))
        return NATIVE

    def sys_mprotect(self, ctx, a):
        m = self._maps()
        if m is not None:
            m.on_mprotect(int(a[0]), int(a[1]), int(a[2]))
        return NATIVE

    def sys_brk(self, ctx, a):
        m = self._maps()
        if m is not None and a[0]:
            m.on_brk(int(a[0]))
        return NATIVE

    def sys_mremap(self, ctx, a):
        m = self._maps()
        if m is not None:
            # the old range may move to a kernel-chosen address
            m.on_munmap(int(a[0]), int(a[1]))
            m.dirty = True
        return NATIVE

    def write_siginfo(self, ptr: int, sig: int) -> None:
        """Minimal siginfo_t: si_signo / si_errno / si_code(SI_USER),
        rest zero (kernel_types.h layout; 128 bytes)."""
        if not ptr:
            return
        self.mem.write(ptr, struct.pack("<iii", sig, 0, 0)
                       + b"\x00" * 116)

    # ==================================================================
    # sockets (host/syscall/socket.c)
    # ==================================================================
    def sys_socket(self, ctx, a):
        domain, stype = _s32(a[0]), _s32(a[1])
        base = stype & 0xFF
        if domain != AF_INET:
            return -EAFNOSUPPORT
        if base == SOCK_STREAM:
            desc = TcpDesc(self.table)
        elif base == SOCK_DGRAM:
            desc = UdpDesc(self.table)
        else:
            return -EPROTONOSUPPORT
        desc.nonblock = bool(stype & SOCK_NONBLOCK)
        fd = self.table.alloc(desc)
        if stype & SOCK_CLOEXEC:
            self.table.cloexec.add(fd)
        return fd

    def sys_bind(self, ctx, a):
        fd, addr_ptr, addrlen = _s32(a[0]), a[1], int(a[2])
        desc = self._desc(fd)
        if desc is None:
            return self._no_desc(fd)
        raw = self.mem.read(addr_ptr, min(addrlen, 16))
        family, port, _ip = kmem.unpack_sockaddr_in(raw)
        if family != AF_INET:
            return -EAFNOSUPPORT
        if isinstance(desc, UdpDesc):
            if desc.sock is not None:
                return -EINVAL
            desc.ensure_bound(self.p.host.net,
                              port if port else None)
            return 0
        if isinstance(desc, TcpDesc):
            if desc.sock is not None:
                return -EINVAL
            desc.bound_port = port
            return 0
        return -ENOTSOCK

    def sys_listen(self, ctx, a):
        fd, backlog = _s32(a[0]), _s32(a[1])
        desc = self._desc(fd)
        if desc is None:
            return self._no_desc(fd)
        if isinstance(desc, TcpListenDesc):
            return 0
        if not isinstance(desc, TcpDesc):
            return -ENOTSOCK
        net = self.p.host.net
        port = desc.bound_port if desc.bound_port else net.alloc_port()
        from shadow_tpu.host.tcp import TcpSocket
        sock = TcpSocket(net, port)
        ldesc = TcpListenDesc(self.table, sock,
                              backlog if backlog > 0 else 128)
        ldesc.nonblock = desc.nonblock
        sock.listen()
        self.table.replace(fd, ldesc)
        return 0

    def sys_accept(self, ctx, a):
        return self._accept(ctx, a, flags=0)

    def sys_accept4(self, ctx, a):
        return self._accept(ctx, a, flags=_s32(a[3]))

    def _accept(self, ctx, a, flags: int):
        fd = _s32(a[0])
        desc = self._desc(fd)
        if desc is None:
            return self._no_desc(fd)
        if not isinstance(desc, TcpListenDesc):
            return -EINVAL
        if not desc.accept_queue:
            if self._nonblock(desc):
                return -EAGAIN
            raise Blocked([desc])
        if not self.table.has_room():
            return -EMFILE      # BEFORE the dequeue: the connection
                                # must stay queued, as the kernel does
        child = desc.accept_queue.popleft()
        child.nonblock = bool(flags & SOCK_NONBLOCK)
        cfd = self.table.alloc(child)
        if flags & SOCK_CLOEXEC:
            self.table.cloexec.add(cfd)
        peer_host, peer_port = child.sock.peer
        self._write_sockaddr(a[1], a[2], self._host_ip_be(peer_host),
                             peer_port)
        return cfd

    def sys_connect(self, ctx, a):
        fd, addr_ptr, addrlen = _s32(a[0]), a[1], int(a[2])
        desc = self._desc(fd)
        if desc is None:
            return self._no_desc(fd)
        raw = self.mem.read(addr_ptr, min(addrlen, 16))
        family, port, ip_be = kmem.unpack_sockaddr_in(raw)
        if family != AF_INET:
            return -EAFNOSUPPORT
        if isinstance(desc, UdpDesc):
            dst = self._resolve_dst(ip_be)
            if dst is None:
                return -ENETUNREACH
            desc.ensure_bound(self.p.host.net)
            desc.default_peer = (dst, port)
            return 0
        if not isinstance(desc, TcpDesc):
            return -ENOTSOCK
        if desc.connected:
            return 0 if self.state.get("started") else -EISCONN
        if desc.connect_err:
            err = desc.connect_err
            desc.connect_err = None
            return -err
        if not desc.connecting:
            dst = self._resolve_dst(ip_be)
            if dst is None:
                return -ENETUNREACH
            net = self.p.host.net
            from shadow_tpu.host.tcp import TcpSocket
            lport = desc.bound_port if desc.bound_port else \
                net.alloc_port()
            sock = TcpSocket(net, lport)
            desc._hook(sock)
            desc.connecting = True
            self.state["started"] = True
            sock.connect(ctx.now, dst, port)
            if desc.nonblock:
                return -EINPROGRESS
        if desc.nonblock:
            return -EALREADY
        raise Blocked([desc])

    def _dst_for_send(self, desc, addr_ptr, addrlen):
        if addr_ptr:
            raw = self.mem.read(addr_ptr, min(int(addrlen), 16))
            family, port, ip_be = kmem.unpack_sockaddr_in(raw)
            if family != AF_INET:
                return None, -EAFNOSUPPORT
            dst = self._resolve_dst(ip_be)
            if dst is None:
                return None, -ENETUNREACH
            return (dst, port), 0
        if desc.default_peer is None:
            return None, -EDESTADDRREQ
        return desc.default_peer, 0

    def sys_sendto(self, ctx, a):
        fd, buf, n, flags = _s32(a[0]), a[1], int(a[2]), _s32(a[3])
        desc = self._desc(fd)
        if desc is None:
            return self._no_desc(fd)
        if isinstance(desc, UnixPairDesc):
            if a[4]:
                return -EISCONN     # the pair is permanently connected
            return self._upair_write(ctx, desc, buf, n, flags)
        if isinstance(desc, UdpDesc):
            if n > UDP_MAX_PAYLOAD:
                return -EMSGSIZE
            dst, err = self._dst_for_send(desc, a[4], a[5])
            if err:
                return err
            desc.ensure_bound(self.p.host.net)
            payload = self.mem.read(buf, n)
            desc.sock.sendto(ctx.now, dst[0], dst[1], n, payload=payload)
            return n
        if isinstance(desc, TcpDesc):
            return self._tcp_write(ctx, desc, buf, n, flags)
        return -ENOTSOCK

    def _tcp_write(self, ctx, desc: TcpDesc, buf: int, n: int,
                   flags: int):
        if desc.connect_err:
            err = desc.connect_err
            desc.connect_err = None
            return -err
        if not desc.connected:
            return -ENOTCONN if not desc.connecting else -EAGAIN
        from shadow_tpu.host.tcp import TcpState
        if desc.sock.state not in (TcpState.ESTABLISHED,
                                   TcpState.CLOSE_WAIT):
            return -EPIPE
        space = desc.send_space()
        if space <= 0:
            if self._nonblock(desc, flags):
                return -EAGAIN
            raise Blocked([desc])
        take = min(n, space)
        data = self.mem.read(buf, take)
        self.table.send_channel(desc.sock).push(data)
        desc.sock.send(ctx.now, take)
        return take

    def sys_recvfrom(self, ctx, a):
        fd, buf, n, flags = _s32(a[0]), a[1], int(a[2]), _s32(a[3])
        desc = self._desc(fd)
        if desc is None:
            return self._no_desc(fd)
        if isinstance(desc, UnixPairDesc):
            if a[4] and not a[5]:
                return -EFAULT      # src_addr without addrlen
            r = self._upair_read(ctx, desc, buf, n, flags)
            if r >= 0 and a[4]:
                # success only (kernel leaves addrlen untouched on
                # error): the peer is unnamed -> length 0
                self.mem.write(a[5], struct.pack("<I", 0))
            return r
        if isinstance(desc, UdpDesc):
            desc.ensure_bound(self.p.host.net)
            if not desc.queue:
                if self._nonblock(desc, flags):
                    return -EAGAIN
                raise Blocked([desc])
            if flags & MSG_PEEK:
                payload, sh, sp = desc.queue[0]
            else:
                payload, sh, sp = desc.queue.popleft()
            take = min(n, len(payload))
            self.mem.write(buf, payload[:take])
            self._write_sockaddr(a[4], a[5], self._host_ip_be(sh), sp)
            return take
        if isinstance(desc, TcpDesc):
            return self._tcp_read(ctx, desc, buf, n, flags,
                                  a[4], a[5])
        return -ENOTSOCK

    def _tcp_read(self, ctx, desc: TcpDesc, buf: int, n: int, flags: int,
                  addr_ptr: int = 0, len_ptr: int = 0):
        if not desc.recv_stream:
            if desc.eof:
                return 0
            if not desc.connected:
                return -ENOTCONN
            if self._nonblock(desc, flags):
                return -EAGAIN
            raise Blocked([desc])
        if flags & MSG_PEEK:
            data = bytes(desc.recv_stream[:n])
        else:
            data = bytes(desc.recv_stream[:n])
            del desc.recv_stream[:n]
        self.mem.write(buf, data)
        if addr_ptr and desc.sock and desc.sock.peer:
            ph, pp = desc.sock.peer
            self._write_sockaddr(addr_ptr, len_ptr,
                                 self._host_ip_be(ph), pp)
        return len(data)

    def sys_shutdown(self, ctx, a):
        fd, how = _s32(a[0]), _s32(a[1])
        desc = self._desc(fd)
        if desc is None:
            return self._no_desc(fd)
        if isinstance(desc, TcpDesc) and desc.sock is not None:
            if how in (SHUT_WR, SHUT_RDWR):
                desc.sock.close(ctx.now)
            if how in (SHUT_RD, SHUT_RDWR):
                desc.eof = True
                desc.notify(ctx)
            return 0
        if isinstance(desc, UnixPairDesc):
            if how in (SHUT_RD, SHUT_RDWR):
                desc.rd_shut = True
            if how in (SHUT_WR, SHUT_RDWR):
                desc.wr_shut = True
            if desc.peer is not None:
                # both directions matter: SHUT_WR gives a blocked
                # reader EOF; SHUT_RD gives a blocked writer EPIPE
                desc.peer.notify(ctx)
            desc.notify(ctx)
            return 0
        if isinstance(desc, (UdpDesc, TcpListenDesc)):
            return 0
        return -ENOTSOCK

    def sys_getsockname(self, ctx, a):
        fd = _s32(a[0])
        desc = self._desc(fd)
        if desc is None:
            return self._no_desc(fd)
        port = 0
        if isinstance(desc, UdpDesc):
            port = desc.bound_port or 0
        elif isinstance(desc, TcpDesc):
            port = (desc.sock.local_port if desc.sock
                    else desc.bound_port or 0)
        elif isinstance(desc, TcpListenDesc):
            port = desc.sock.local_port
        elif isinstance(desc, UnixPairDesc):
            return self._write_unnamed_unix(a[1], a[2])
        else:
            return -ENOTSOCK
        self._write_sockaddr(a[1], a[2], self._self_ip_be(), port)
        return 0

    def _write_unnamed_unix(self, addr_ptr: int, len_ptr: int):
        """socketpair ends are unnamed: sockaddr_un with only
        sun_family, length 2 (Linux unix_getname)."""
        if not len_ptr:
            return -EFAULT
        if addr_ptr:
            alen = struct.unpack("<I",
                                 self.mem.read(len_ptr, 4))[0]
            self.mem.write(addr_ptr,
                           struct.pack("<H", 1)[:max(0,
                                                     min(2, alen))])
        self.mem.write(len_ptr, struct.pack("<I", 2))
        return 0

    def sys_getpeername(self, ctx, a):
        fd = _s32(a[0])
        desc = self._desc(fd)
        if desc is None:
            return self._no_desc(fd)
        peer = None
        if isinstance(desc, TcpDesc) and desc.sock is not None:
            peer = desc.sock.peer
        elif isinstance(desc, UdpDesc):
            peer = desc.default_peer
        elif isinstance(desc, UnixPairDesc):
            return self._write_unnamed_unix(a[1], a[2])
        if peer is None:
            return -ENOTCONN
        self._write_sockaddr(a[1], a[2], self._host_ip_be(peer[0]),
                             peer[1])
        return 0

    def sys_getsockopt(self, ctx, a):
        fd, level, opt = _s32(a[0]), _s32(a[1]), _s32(a[2])
        val_ptr, len_ptr = a[3], a[4]
        desc = self._desc(fd)
        if desc is None:
            return self._no_desc(fd)
        val = 0
        if level == SOL_SOCKET:
            if opt == SO_ERROR:
                if isinstance(desc, TcpDesc) and desc.connect_err:
                    val = desc.connect_err
                    desc.connect_err = None
            elif opt == SO_TYPE:
                dgramish = isinstance(desc, UdpDesc) or (
                    isinstance(desc, UnixPairDesc) and desc.dgram)
                val = SOCK_DGRAM if dgramish else SOCK_STREAM
            elif opt == SO_SNDBUF:
                sock = getattr(desc, "sock", None)
                net = self.p.host.net
                if isinstance(desc, TcpDesc) and sock is not None:
                    val = sock.send_buffer_limit()
                elif net is not None:
                    val = net.tcp_send_buffer
                else:
                    val = TcpDesc.SNDBUF
            elif opt == SO_RCVBUF:
                sock = getattr(desc, "sock", None)
                net = self.p.host.net
                if isinstance(desc, TcpDesc) and sock is not None:
                    val = sock.recv_window
                elif net is not None:
                    val = net.tcp_recv_buffer
                else:
                    from shadow_tpu.host.tcp import DEFAULT_RECV_WINDOW
                    val = DEFAULT_RECV_WINDOW
            elif opt == SO_ACCEPTCONN:
                val = 1 if isinstance(desc, TcpListenDesc) else 0
        if val_ptr and len_ptr:
            self.mem.write(val_ptr, struct.pack("<i", val))
            self.mem.write(len_ptr, struct.pack("<I", 4))
        return 0

    def sys_setsockopt(self, ctx, a):
        fd = _s32(a[0])
        desc = self._desc(fd)
        if desc is None:
            return self._no_desc(fd)
        return 0            # accept and ignore (SO_REUSEADDR, NODELAY…)

    def sys_socketpair(self, ctx, a):
        """socketpair(AF_UNIX, SOCK_STREAM|SOCK_DGRAM) as an
        in-memory bidirectional channel pair (ref dispatch
        `socketpair`; unix-socket layer). Network families answer
        EOPNOTSUPP — simulated inter-host traffic uses real
        sockets."""
        dom, typ, proto, sv_ptr = (_s32(a[0]), _s32(a[1]),
                                   _s32(a[2]), a[3])
        if dom != 1:                        # AF_UNIX only
            return -EAFNOSUPPORT
        base = typ & 0xFF
        if base not in (SOCK_STREAM, SOCK_DGRAM):
            return -EOPNOTSUPP
        if proto not in (0,):
            return -EPROTONOSUPPORT
        if not sv_ptr:
            return -EFAULT
        if not self.table.has_room(2):
            return -EMFILE                  # both ends or neither
        d1, d2 = UnixPairDesc.make_pair(dgram=base == SOCK_DGRAM)
        d1.nonblock = d2.nonblock = bool(typ & SOCK_NONBLOCK)
        fd1, fd2 = self.table.alloc(d1), self.table.alloc(d2)
        if typ & SOCK_CLOEXEC:
            self.table.cloexec.update((fd1, fd2))
        self.mem.write(sv_ptr, struct.pack("<ii", fd1, fd2))
        return 0

    def _upair_read(self, ctx, d, buf: int, n: int,
                    flags: int = 0):
        if d.rd_shut and not d._readable():
            return 0
        if not d._readable():
            if d.peer is None or d.peer.closed or d.peer.wr_shut:
                return 0                    # EOF
            if self._nonblock(d, flags):
                return -EAGAIN
            raise Blocked([d])
        peek = bool(flags & MSG_PEEK)
        if d.dgram:
            msg = d.rmsgs[0]
            data = msg[:n]                  # excess truncates (dgram)
            if not peek:
                d.rmsgs.popleft()
                d.rbytes -= len(msg)
        else:
            data = bytes(d.rbuf[:n])
            if not peek:
                del d.rbuf[:n]
        self.mem.write(buf, data)
        if not peek and d.peer is not None:
            d.peer.notify(ctx)              # writer may proceed
        return len(data)

    def _upair_send_dgram(self, ctx, d, data: bytes, flags: int):
        """One atomic datagram (bytes already gathered)."""
        if d.wr_shut or d.peer is None or d.peer.closed \
                or d.peer.rd_shut:
            return -EPIPE
        peer = d.peer
        n = len(data)
        if n > UnixPairDesc.CAPACITY:
            return -EMSGSIZE
        if peer.rbytes + n > UnixPairDesc.CAPACITY:
            if self._nonblock(d, flags):
                return -EAGAIN
            raise Blocked([d])
        peer.rmsgs.append(data)
        peer.rbytes += n
        peer.notify(ctx)
        return n

    def _upair_write(self, ctx, d, buf: int, n: int,
                     flags: int = 0):
        if d.wr_shut or d.peer is None or d.peer.closed \
                or d.peer.rd_shut:
            return -EPIPE           # plain errno, like _pipe_write
        peer = d.peer
        if d.dgram:
            return self._upair_send_dgram(
                ctx, d, bytes(self.mem.read(buf, n)), flags)
        # STREAM: Linux unix_stream_sendmsg BLOCKS until the whole
        # buffer is queued (short returns only for nonblocking);
        # progress across Blocked restarts rides the parked-syscall
        # state so replays never duplicate bytes
        st = self.state
        done = st.get("upair_done", 0)
        while done < n:
            if d.wr_shut or peer.closed or peer.rd_shut:
                st.pop("upair_done", None)
                return done if done else -EPIPE
            space = UnixPairDesc.CAPACITY - len(peer.rbuf)
            if space <= 0:
                if self._nonblock(d, flags):
                    st.pop("upair_done", None)
                    return done if done else -EAGAIN
                st["upair_done"] = done
                raise Blocked([d])
            take = min(n - done, space)
            peer.rbuf += self.mem.read(buf + done, take)
            peer.notify(ctx)
            done += take
        st.pop("upair_done", None)
        return done

    # ==================================================================
    # generic fd I/O (unistd.c / uio.c)
    # ==================================================================
    def sys_read(self, ctx, a):
        fd, buf, n = _s32(a[0]), a[1], int(a[2])
        desc = self._desc(fd)
        if desc is None:
            return self._no_desc(fd)
        if isinstance(desc, TcpDesc):
            return self._tcp_read(ctx, desc, buf, n, 0)
        if isinstance(desc, UdpDesc):
            return self.sys_recvfrom(ctx, (a[0], a[1], a[2], 0, 0, 0))
        if isinstance(desc, UnixPairDesc):
            return self._upair_read(ctx, desc, buf, n)
        if isinstance(desc, PipeDesc):
            return self._pipe_read(ctx, desc, buf, n)
        if isinstance(desc, EventfdDesc):
            return self._eventfd_read(ctx, desc, buf, n)
        if isinstance(desc, TimerfdDesc):
            return self._timerfd_read(ctx, desc, buf, n)
        if isinstance(desc, VirtualFileDesc):
            # short reads are allowed: bound what the simulator
            # materializes (the kernel caps reads at 0x7ffff000 too)
            data = desc.read_at(min(n, 1 << 20))
            if data:
                self.mem.write(buf, data)
            return len(data)
        if isinstance(desc, HostFileDesc):
            if desc.is_dir:
                return -EISDIR
            try:
                data = os.read(desc.osfd, min(n, 1 << 20))
            except OSError as e:
                # FIFOs open host-side with O_NONBLOCK (the blocking
                # open emulation, _open_fifo); a blocking app fd must
                # park on the readiness poll, not see EAGAIN
                if e.errno == EAGAIN and \
                        getattr(desc, "is_fifo", False) and \
                        not desc.nonblock:
                    raise Blocked(deadline=ctx.now
                                  + self._FIFO_POLL_NS) from None
                return -e.errno
            if data:
                self.mem.write(buf, data)
            return len(data)
        return -EINVAL

    def sys_write(self, ctx, a):
        fd, buf, n = _s32(a[0]), a[1], int(a[2])
        desc = self._desc(fd)
        if desc is None:
            return self._no_desc(fd)
        if isinstance(desc, TcpDesc):
            return self._tcp_write(ctx, desc, buf, n, 0)
        if isinstance(desc, UdpDesc):
            return self.sys_sendto(ctx, (a[0], a[1], a[2], 0, 0, 0))
        if isinstance(desc, UnixPairDesc):
            return self._upair_write(ctx, desc, buf, n)
        if isinstance(desc, PipeDesc):
            return self._pipe_write(ctx, desc, buf, n)
        if isinstance(desc, EventfdDesc):
            return self._eventfd_write(ctx, desc, buf, n)
        if isinstance(desc, VirtualFileDesc):
            if desc.generator is not None:
                return n        # writes to /dev/urandom: accepted+ignored
            return -EBADF       # the emulated files are read-only
        if isinstance(desc, HostFileDesc):
            try:
                data = self.mem.read(buf, min(n, 1 << 20))
            except OSError:
                return -EFAULT
            try:
                return os.write(desc.osfd, data)
            except OSError as e:
                # full FIFO + blocking app fd: park (see sys_read)
                if e.errno == EAGAIN and \
                        getattr(desc, "is_fifo", False) and \
                        not desc.nonblock:
                    raise Blocked(deadline=ctx.now
                                  + self._FIFO_POLL_NS) from None
                return -e.errno
        return -EINVAL

    def _gather_iov(self, a):
        return kmem.read_iovec(self.mem, a[1], _s32(a[2]))

    def _iov_loop(self, ctx, a, op):
        """Shared readv/writev walk: only the FIRST iov may block (a
        later Blocked must not discard bytes already transferred —
        restart semantics would replay them)."""
        cnt = _s32(a[2])
        if cnt < 0 or cnt > 1024:       # IOV_MAX
            return -EINVAL
        if cnt == 0:                    # kernel: zero segs reads 0
            return 0
        iov = self._gather_iov(a)
        total = 0
        for base, ln in iov:
            if ln == 0:
                continue
            try:
                r = op(ctx, (a[0], base, ln))
            except Blocked:
                if total == 0:
                    raise
                # a unix-pair write parks its committed byte count
                # (upair_done) before blocking; those bytes already
                # reached the peer and must ride the short return —
                # only _upair_write sets the key, and a completed
                # call pops it, so 0 for every other fd type
                total += self.state.pop("upair_done", 0)
                break
            if r is NATIVE or (isinstance(r, int) and r < 0):
                return r if total == 0 else total
            total += r
            if r < ln:
                break
        return total

    def sys_readv(self, ctx, a):
        if self._desc(_s32(a[0])) is None:
            return self._no_desc(_s32(a[0]))
        return self._iov_loop(ctx, a, self.sys_read)

    def sys_writev(self, ctx, a):
        if self._desc(_s32(a[0])) is None:
            return self._no_desc(_s32(a[0]))
        return self._iov_loop(ctx, a, self.sys_write)

    def _p_iov(self, ctx, a, op):
        """preadv/pwritev: positioned vector I/O — each iov chunk
        advances the explicit offset, never the fd position. Per-chunk
        dispatch through the pread64/pwrite64 handlers keeps the
        per-type semantics (os-backed files, VirtualFileDesc, ESPIPE
        for pipes/sockets) in ONE place (ref file.c handlers)."""
        off = _s64(a[3])
        if off < 0:                     # do_preadv validates pos
            return -EINVAL              # before fdget: a bad fd with
        if self._desc(_s32(a[0])) is None:   # pos -1 is EINVAL, not
            return self._no_desc(_s32(a[0]))  # EBADF
        cnt = _s32(a[2])
        if cnt < 0 or cnt > 1024:       # IOV_MAX
            return -EINVAL
        if cnt == 0:                    # kernel: zero segs transfers 0
            return 0
        total = 0
        for base, ln in kmem.read_iovec(self.mem, a[1], cnt):
            if ln == 0:
                continue
            r = op(ctx, (a[0], base, ln, off + total))
            if r is NATIVE or (isinstance(r, int) and r < 0):
                return r if total == 0 else total
            total += r
            if r < ln:
                break
        return total

    def sys_preadv(self, ctx, a):
        return self._p_iov(ctx, a, self.sys_pread64)

    def sys_pwritev(self, ctx, a):
        return self._p_iov(ctx, a, self.sys_pwrite64)

    # RWF_* flags (uapi): HIPRI/DSYNC/SYNC are accepted as hints on
    # the os-backed files; NOWAIT is honored only where it cannot
    # block anyway; APPEND is refused (we do not move the offset)
    RWF_HIPRI, RWF_DSYNC, RWF_SYNC = 1, 2, 4
    RWF_NOWAIT, RWF_APPEND = 8, 16

    def _rwf2(self, ctx, a, read: bool):
        # pos validation precedes fd resolution (do_preadv), but the
        # fd still resolves before the flag checks: pos < -1 on a bad
        # fd is EINVAL, unsupported RWF_* bits on a bad fd are EBADF
        if _s64(a[3]) < -1:
            return -EINVAL
        d = self._desc(_s32(a[0]))
        if d is None:
            return self._no_desc(_s32(a[0]))
        flags = _s32(a[5])
        known = (self.RWF_HIPRI | self.RWF_DSYNC | self.RWF_SYNC
                 | self.RWF_NOWAIT | self.RWF_APPEND)
        if flags & ~known:
            return -EOPNOTSUPP
        if flags & self.RWF_APPEND:
            return -EOPNOTSUPP
        if flags & self.RWF_NOWAIT:
            # only regular os-backed files (which never block here);
            # a pipe/socket would need the kernel's EAGAIN semantics
            if not isinstance(d, HostFileDesc):
                return -EOPNOTSUPP
        if _s64(a[3]) == -1:
            # pos == -1: "use and update the current file offset"
            return (self.sys_readv if read else self.sys_writev)(
                ctx, a)
        return self._p_iov(
            ctx, a, self.sys_pread64 if read else self.sys_pwrite64)

    def sys_preadv2(self, ctx, a):
        return self._rwf2(ctx, a, read=True)

    def sys_pwritev2(self, ctx, a):
        return self._rwf2(ctx, a, read=False)

    def sys_pread64(self, ctx, a):
        desc = self._desc(_s32(a[0]))
        if desc is None:
            return self._no_desc(_s32(a[0]))
        if isinstance(desc, VirtualFileDesc):
            off = _s64(a[3])
            if off < 0:
                return -EINVAL
            data = desc.read_at(min(int(a[2]), 1 << 20), pos=off)
            if data:
                self.mem.write(a[1], data)
            return len(data)
        if isinstance(desc, HostFileDesc):
            off = _s64(a[3])
            if off < 0:
                return -EINVAL
            try:
                data = os.pread(desc.osfd, min(int(a[2]), 1 << 20),
                                off)
            except OSError as e:
                return -e.errno
            if data:
                self.mem.write(a[1], data)
            return len(data)
        return -ESPIPE

    def sys_pwrite64(self, ctx, a):
        desc = self._desc(_s32(a[0]))
        if desc is None:
            return self._no_desc(_s32(a[0]))
        if isinstance(desc, HostFileDesc):
            off = _s64(a[3])
            if off < 0:
                return -EINVAL
            try:
                data = self.mem.read(a[1], min(int(a[2]), 1 << 20))
            except OSError:
                return -EFAULT
            try:
                return os.pwrite(desc.osfd, data, off)
            except OSError as e:
                return -e.errno
        return -ESPIPE

    def sys_lseek(self, ctx, a):
        desc = self._desc(_s32(a[0]))
        if desc is None:
            return self._no_desc(_s32(a[0]))
        if isinstance(desc, VirtualFileDesc):
            off, whence = _s64(a[1]), _s32(a[2])
            if whence not in (0, 1, 2):
                return -EINVAL
            base = (0 if whence == 0 else
                    desc.pos if whence == 1 else desc.size())
            pos = base + off
            if pos < 0:
                return -EINVAL
            desc.pos = pos
            return pos
        if isinstance(desc, HostFileDesc):
            off, whence = _s64(a[1]), _s32(a[2])
            if desc.is_dir:
                # seekdir semantics on the snapshot cursor
                if whence != 0 or off < 0:
                    return -EINVAL
                if off == 0:
                    desc.rewind_dir()
                else:
                    desc._dirpos = off
                return off
            try:
                return os.lseek(desc.osfd, off, whence)
            except OSError as e:
                return -e.errno
        return -ESPIPE

    def sys_close(self, ctx, a):
        fd = _s32(a[0])
        if self.table.get(fd) is None:
            return self._no_desc(fd)
        # (record-lock release on close happens at the close_fd
        # chokepoint — dup2-over and cloexec closes land there too)
        return 0 if self.table.close_fd(ctx, fd) else -EBADF

    # -- file opens + the fd-mediated family (ref file.c/fileat.c) -----
    AT_FDCWD = -100
    AT_SYMLINK_NOFOLLOW = 0x100
    AT_REMOVEDIR = 0x200
    AT_SYMLINK_FOLLOW = 0x400
    AT_EMPTY_PATH = 0x1000
    O_CLOEXEC_FLAG = 0x80000
    O_ACCMODE = 3

    def _host_dir(self) -> str:
        """The per-host data dir — the plugin's initial real cwd AND
        the confinement root for every emulated path operation."""
        hd = getattr(self.p, "_hostdir_cache", None)
        if hd is None:
            hd = os.path.realpath(os.path.join(
                self.p.runtime.data_dir, "hosts", self.p.host.name))
            self.p._hostdir_cache = hd
        return hd

    def _vcwd(self) -> Optional[str]:
        """Tracked virtual cwd: None = the plugin left the data dir
        (resolution falls back to NATIVE)."""
        v = getattr(self.p, "vcwd", None)
        if v is None:
            return self._host_dir()
        return None if v == "outside" else v

    def _resolve_at(self, dirfd: int, path: str):
        """dirfd-relative resolution confined to the host data dir
        (ref fileat.c _syscallhandler_validateDirHelper + descriptor/
        file.c _file_getAbsolutePath): returns a confined absolute
        path to emulate, NATIVE to let the plugin run the call in its
        own (data-dir) cwd, or -errno. The parent DIRECTORY is
        realpath'd so symlink escapes are caught; the final component
        stays lexical so symlink-ops act on the link itself."""
        if len(path) > 4096:
            return -ENAMETOOLONG
        root = self._host_dir()
        if path.startswith("/"):
            ap = os.path.normpath(path)
            if ap != root and not ap.startswith(root + "/"):
                return NATIVE       # system path: plugin runs it raw
        elif dirfd == self.AT_FDCWD:
            base = self._vcwd()
            if base is None:
                return NATIVE       # cwd moved outside the data dir
            ap = os.path.normpath(os.path.join(base, path)) \
                if path else base
        else:
            d = self._desc(dirfd)
            if d is None:
                return self._no_desc(dirfd)
            if not isinstance(d, HostFileDesc):
                return -ENOTDIR
            if path and not d.is_dir:
                return -ENOTDIR
            ap = os.path.normpath(os.path.join(d.abspath, path)) \
                if path else d.abspath
        if ap == root:
            return ap               # the root itself (open("."), …)
        head, tail = os.path.split(ap)
        try:
            rh = os.path.realpath(head)
        except OSError:
            return -ENOENT
        if rh != root and not rh.startswith(root + "/"):
            return -EACCES
        return os.path.join(rh, tail) if tail else rh

    def _confined(self, abspath: str) -> bool:
        root = self._host_dir()
        return abspath == root or abspath.startswith(root + "/")

    def sys_openat(self, ctx, a):
        return self._open_path(ctx, _s32(a[0]), a[1], _s32(a[2]),
                               int(a[3]) & 0o7777)

    def sys_open(self, ctx, a):
        return self._open_path(ctx, self.AT_FDCWD, a[0], _s32(a[1]),
                               int(a[2]) & 0o7777)

    def sys_creat(self, ctx, a):
        # open(path, O_CREAT|O_WRONLY|O_TRUNC, mode)
        return self._open_path(ctx, self.AT_FDCWD, a[0],
                               0x40 | 0x1 | 0x200, int(a[1]) & 0o7777)

    def _open_path(self, ctx, dirfd, path_ptr, flags, mode=0o644):
        """Two emulated classes (ref file.c/fileat.c mediate ALL opens
        through their descriptor table; we split by path):

        * content the simulator must OWN — /dev/urandom (seeded
          deterministic stream), the simulated /etc/hosts,
          resolv.conf/nsswitch.conf — served as VirtualFileDesc;
        * everything inside the host DATA DIR (relative paths, paths
          under it, dirfd-relative paths) — os-backed HostFileDesc:
          the simulator opens the real file (O_CLOEXEC) and mediates
          every fd op, giving dirfd resolution, deterministic sorted
          getdents, and per-host isolation with loud confinement.

        Absolute system paths (/usr, /lib, ...) stay NATIVE so the
        dynamic loader's open+mmap path keeps working."""
        if not path_ptr:
            return -EFAULT
        try:
            path = self.mem.read_cstr(path_ptr).decode(
                errors="surrogateescape")
        except OSError:
            return -EFAULT
        if path in ("/dev/urandom", "/dev/random"):
            return self.table.alloc(VirtualFileDesc(
                generator=self.p.deterministic_bytes, mode=0o20666))
        if path in ("/etc/hosts", "/etc/resolv.conf",
                    "/etc/nsswitch.conf") and (flags & 3) != 0:
            return -EACCES      # read-only emulated files
        if path == "/etc/hosts":
            hosts = os.path.join(
                getattr(self.p.runtime, "data_dir", ""), "etc_hosts")
            if os.path.exists(hosts):
                with open(hosts, "rb") as f:
                    return self.table.alloc(VirtualFileDesc(f.read()))
            return NATIVE
        if path == "/etc/resolv.conf":
            return self.table.alloc(VirtualFileDesc(b""))
        if path == "/etc/nsswitch.conf":
            return self.table.alloc(VirtualFileDesc(
                b"hosts: files\n"))
        r = self._resolve_at(dirfd, path)
        if r is NATIVE or isinstance(r, int):
            return r
        return self._open_host_file(ctx, r, flags, mode)

    # -- FIFO open emulation -------------------------------------------
    # A blocking open() of a FIFO waits for the PEER end (reader for
    # O_WRONLY, writer for O_RDONLY). The old passthrough os.open
    # wedged the whole simulator thread in a host-side blocking open
    # (ADVICE r5 medium): the writer process could never be scheduled
    # to unblock it — a whole-simulation deadlock. FIFOs now open
    # host-side with O_NONBLOCK always, and blocking-open semantics
    # are emulated with the Blocked/readiness machinery like the
    # socket paths: a per-host registry tracks open ends and parked
    # openers, and blocked opens poll on a short sim-time deadline
    # (the flock pattern) until the peer end exists.
    _FIFO_POLL_NS = 1_000_000       # 1 ms sim-time re-check

    def _fifo_registry(self) -> dict:
        t = getattr(self.p.host, "_fifo_registry", None)
        if t is None:
            t = self.p.host._fifo_registry = {}
        return t

    def _open_fifo(self, ctx, abspath: str, rp: str, flags: int,
                   mode: int):
        reg = self._fifo_registry().setdefault(
            rp, {"open": {}, "pending": {}})
        # prune closed descriptors and dead parked openers lazily.
        # Pending entries carry the sim time of their LAST poll and
        # expire after two poll periods: an abandoned open (process
        # interrupted mid-park, path unlinked so the retry never
        # reaches this function again) must not leave a phantom peer
        # that admits later openers into wrong semantics — a live
        # parked opener refreshes its entry every poll.
        for d in [d for d in reg["open"] if d.closed]:
            del reg["open"][d]
        stale = ctx.now - 2 * self._FIFO_POLL_NS
        for tok in [t for t, (proc, _, treg) in reg["pending"].items()
                    if not getattr(proc, "alive", True)
                    or treg < stale]:
            del reg["pending"][tok]
        readers = any(m in ("r", "rw") for m in reg["open"].values())
        pend_w = any(m == "w"
                     for _, m, _t in reg["pending"].values())
        nonblock = bool(flags & O_NONBLOCK)
        acc = flags & 3                       # O_ACCMODE
        st = self.state

        def _park(want):
            tok = st.get("fifo_tok")
            if tok is None:
                tok = st["fifo_tok"] = object()
            reg["pending"][tok] = (self.p, want, ctx.now)
            raise Blocked(deadline=ctx.now + self._FIFO_POLL_NS)

        def _unpark():
            tok = st.pop("fifo_tok", None)
            if tok is not None:
                reg["pending"].pop(tok, None)

        if acc == 0:                          # O_RDONLY
            want = "r"
            # the kernel blocks a read-only open until a WRITER end
            # exists — other readers are irrelevant (fifo(7)). A
            # pending blocked writer counts: admitting the reader
            # first gives the real FIFO a reader fd, so the writer's
            # next poll can host-open successfully (both ends of the
            # classic simultaneous blocking open complete)
            ok = nonblock or pend_w or \
                any(m in ("w", "rw") for m in reg["open"].values())
        elif acc == 1:                        # O_WRONLY
            want = "w"
            if nonblock and not readers:
                _unpark()
                return -ENXIO                 # kernel semantics
            ok = readers
        else:                                 # O_RDWR never blocks
            want = "rw"
            ok = True
        if not ok:
            _park(want)
        try:
            osfd = os.open(abspath,
                           (flags & ~self.O_CLOEXEC_FLAG)
                           | os.O_CLOEXEC | O_NONBLOCK, mode)
        except OSError as e:
            if e.errno == ENXIO and not nonblock:  # raced a closing
                _park(want)                        # reader — wait on
            _unpark()
            return -e.errno
        _unpark()
        d = HostFileDesc(osfd, abspath, flags, mode)
        d.realpath = rp
        d.is_fifo = True
        # the APP's view of the flags: nonblock only if it asked
        d.nonblock = nonblock
        reg["open"][d] = want
        fd = self.table.alloc(d)
        if flags & self.O_CLOEXEC_FLAG:
            self.table.cloexec.add(fd)
        return fd

    def _open_host_file(self, ctx, abspath: str, flags: int,
                        mode: int):
        # a symlink chain may point OUTSIDE the data dir: realpath the
        # full target (if it exists) before opening through it
        rp = os.path.realpath(abspath)
        if os.path.exists(rp) and not self._confined(rp):
            return -EACCES
        if not self.table.has_room():
            return -EMFILE      # BEFORE os.open: a TableFull after
                                # it would leak the simulator-side fd
        try:
            if os.path.exists(rp):
                import stat as _stat
                if _stat.S_ISFIFO(os.stat(rp).st_mode):
                    return self._open_fifo(ctx, abspath, rp, flags,
                                           mode)
        except OSError:
            pass                # races fall through to the real open
        try:
            osfd = os.open(abspath,
                           (flags & ~self.O_CLOEXEC_FLAG)
                           | os.O_CLOEXEC, mode)
        except OSError as e:
            return -e.errno
        d = HostFileDesc(osfd, abspath, flags, mode)
        d.realpath = rp             # lock-table key (cached once)
        d.nonblock = bool(flags & O_NONBLOCK)
        fd = self.table.alloc(d)
        if flags & self.O_CLOEXEC_FLAG:
            self.table.cloexec.add(fd)
        return fd

    def sys_fstat(self, ctx, a):
        fd = _s32(a[0])
        desc = self._desc(fd)
        if desc is None:
            return self._no_desc(fd)
        if isinstance(desc, HostFileDesc):
            try:
                st = os.fstat(desc.osfd)
            except OSError as e:
                return -e.errno
            self.mem.write(a[1], self._pack_os_stat(st))
            return 0
        st = bytearray(144)
        if isinstance(desc, VirtualFileDesc):
            mode = desc.mode
            struct.pack_into("<q", st, 48, desc.size())   # st_size
        else:
            mode = 0o140777 if not isinstance(desc, PipeDesc) \
                else 0o10600
        struct.pack_into("<I", st, 24, mode)
        struct.pack_into("<Q", st, 16, 1)      # nlink
        self.mem.write(a[1], bytes(st))
        return 0

    # virtual special-file stat shapes: (mode, size_fn) — size -1
    # means "the served content's length" (resolved at stat time)
    _SPECIAL_MODES = {
        "/dev/urandom": 0o20666, "/dev/random": 0o20666,
        "/etc/hosts": 0o100644, "/etc/resolv.conf": 0o100644,
        "/etc/nsswitch.conf": 0o100644,
    }

    def _special_stat(self, path: str):
        """(mode, size) for a virtualized special path, or None. The
        stat must agree with what open() of the same path serves —
        the REAL file's size/mtime would leak machine state."""
        mode = self._SPECIAL_MODES.get(path)
        if mode is None:
            return None
        if path == "/etc/hosts":
            hosts = os.path.join(
                getattr(self.p.runtime, "data_dir", ""), "etc_hosts")
            if not os.path.exists(hosts):
                return None         # open() would pass NATIVE too
            size = os.path.getsize(hosts)
        elif path == "/etc/nsswitch.conf":
            size = len(b"hosts: files\n")
        else:
            size = 0
        return mode, size

    def _write_stat(self, ptr: int, mode: int, size: int) -> int:
        st = bytearray(144)
        struct.pack_into("<I", st, 24, mode)
        struct.pack_into("<Q", st, 16, 1)          # nlink
        struct.pack_into("<q", st, 48, size)       # st_size
        self.mem.write(ptr, bytes(st))
        return 0

    def _pack_os_stat(self, st: os.stat_result) -> bytes:
        """Full x86_64 struct stat from a real os.stat_result —
        passthrough (what the same call would return natively), so
        fstat on an emulated fd and native path-stat of the same file
        agree on identity (st_dev/st_ino comparisons)."""
        b = bytearray(144)
        struct.pack_into("<Q", b, 0, st.st_dev & (1 << 64) - 1)
        struct.pack_into("<Q", b, 8, st.st_ino)
        struct.pack_into("<Q", b, 16, st.st_nlink)
        struct.pack_into("<I", b, 24, st.st_mode)
        struct.pack_into("<I", b, 28, st.st_uid)
        struct.pack_into("<I", b, 32, st.st_gid)
        struct.pack_into("<Q", b, 40, st.st_rdev & (1 << 64) - 1)
        struct.pack_into("<q", b, 48, st.st_size)
        struct.pack_into("<q", b, 56, getattr(st, "st_blksize", 4096))
        struct.pack_into("<q", b, 64, getattr(st, "st_blocks", 0))
        struct.pack_into("<q", b, 72, int(st.st_atime))
        struct.pack_into("<q", b, 80, st.st_atime_ns % 1_000_000_000)
        struct.pack_into("<q", b, 88, int(st.st_mtime))
        struct.pack_into("<q", b, 96, st.st_mtime_ns % 1_000_000_000)
        struct.pack_into("<q", b, 104, int(st.st_ctime))
        struct.pack_into("<q", b, 112, st.st_ctime_ns % 1_000_000_000)
        return bytes(b)

    def _stat_resolved(self, r, stat_ptr: int, follow: bool):
        """Shared tail of newfstatat/stat/lstat once a confined path
        is in hand."""
        try:
            st = os.stat(r) if follow else os.lstat(r)
        except OSError as e:
            return -e.errno
        self.mem.write(stat_ptr, self._pack_os_stat(st))
        return 0

    def sys_newfstatat(self, ctx, a):
        dirfd = _s32(a[0])
        if not a[1]:
            return -EFAULT
        try:
            path = self.mem.read_cstr(a[1]).decode(
                errors="surrogateescape")
        except OSError:
            return -EFAULT
        flags = _s32(a[3])
        if dirfd < VFD_BASE:
            # the special paths are absolute — the kernel ignores
            # dirfd for those, and so must the virtualization
            sp = self._special_stat(path)
            if sp is not None:
                return self._write_stat(a[2], sp[0], sp[1])
            return NATIVE           # path-relative stat on native dirs
        desc = self._desc(dirfd)
        if desc is None:
            return -EBADF
        if not path:
            if flags & self.AT_EMPTY_PATH:
                return self.sys_fstat(ctx, (a[0], a[2]))
            return -ENOENT
        if isinstance(desc, HostFileDesc):
            r = self._resolve_at(dirfd, path)
            if r is NATIVE:
                return NATIVE
            if isinstance(r, int):
                return r
            return self._stat_resolved(
                r, a[2], not flags & self.AT_SYMLINK_NOFOLLOW)
        return -ENOTDIR             # paths under a socket/pipe fd

    def sys_statx(self, ctx, a):
        dirfd = _s32(a[0])
        if dirfd < VFD_BASE:
            if a[1]:
                try:
                    path = self.mem.read_cstr(a[1]).decode(
                        errors="surrogateescape")
                except OSError:
                    return -EFAULT
                sp = self._special_stat(path)
                if sp is not None:
                    stx = bytearray(256)
                    struct.pack_into("<I", stx, 0, 0x7FF)  # stx_mask
                    struct.pack_into("<H", stx, 28, sp[0])
                    struct.pack_into("<Q", stx, 40, sp[1])  # stx_size
                    self.mem.write(a[4], bytes(stx))
                    return 0
            return NATIVE
        desc = self._desc(dirfd)
        if desc is None:
            return -EBADF
        path = b""
        if a[1]:
            try:
                path = self.mem.read_cstr(a[1])
            except OSError:
                return -EFAULT
        st = None
        if isinstance(desc, HostFileDesc):
            if path:
                r = self._resolve_at(
                    dirfd, path.decode(errors="surrogateescape"))
                if r is NATIVE:
                    return NATIVE
                if isinstance(r, int):
                    return r
                follow = not _s32(a[2]) & self.AT_SYMLINK_NOFOLLOW
                try:
                    st = os.stat(r) if follow else os.lstat(r)
                except OSError as e:
                    return -e.errno
            else:
                try:
                    st = os.fstat(desc.osfd)
                except OSError as e:
                    return -e.errno
        elif path:
            return -ENOTDIR
        stx = bytearray(256)
        struct.pack_into("<I", stx, 0, 0x7FF)          # stx_mask: basic
        if st is not None:
            struct.pack_into("<I", stx, 4, 4096)       # blksize
            struct.pack_into("<I", stx, 16, st.st_nlink)
            struct.pack_into("<I", stx, 20, st.st_uid)
            struct.pack_into("<I", stx, 24, st.st_gid)
            struct.pack_into("<H", stx, 28, st.st_mode)
            struct.pack_into("<Q", stx, 32, st.st_ino)
            struct.pack_into("<Q", stx, 40, st.st_size)
            struct.pack_into("<Q", stx, 48, st.st_blocks)
            # atime/btime/ctime/mtime: four (s64 sec, u32 nsec, pad)
            for off, (sec, ns) in (
                    (64, (int(st.st_atime),
                          st.st_atime_ns % 1_000_000_000)),
                    (96, (int(st.st_ctime),
                          st.st_ctime_ns % 1_000_000_000)),
                    (112, (int(st.st_mtime),
                           st.st_mtime_ns % 1_000_000_000))):
                struct.pack_into("<qI", stx, off, sec, ns)
        else:
            struct.pack_into("<H", stx, 28,
                             0o140777 if not isinstance(desc, PipeDesc)
                             else 0o10600)             # stx_mode
        self.mem.write(a[4], bytes(stx))
        return 0

    # -- the fd-mediated file family (ref file.c:1-499, fileat.c:1-539:
    # every handler routes through the descriptor table, with dirfd-
    # relative resolution confined to the host data dir) --------------
    def _host_file(self, fd: int):
        """desc lookup that must be an os-backed file: HostFileDesc,
        NATIVE (native fd — the plugin runs the call raw), or errno."""
        desc = self._desc(fd)
        if desc is None:
            return self._no_desc(fd)
        if not isinstance(desc, HostFileDesc):
            return -EINVAL
        return desc

    def _path_op(self, dirfd, path_ptr, fn):
        """Shared resolve-then-act tail for single-path operations:
        fn(confined_abspath) raising OSError maps to -errno."""
        if not path_ptr:
            return -EFAULT
        try:
            path = self.mem.read_cstr(path_ptr).decode(
                errors="surrogateescape")
        except OSError:
            return -EFAULT
        r = self._resolve_at(dirfd, path)
        if r is NATIVE or isinstance(r, int):
            return r
        try:
            ret = fn(r)
            return 0 if ret is None else ret
        except OSError as e:
            return -e.errno

    # getdents: served from a SORTED listing snapshot — real readdir
    # order is filesystem-nondeterministic, so emulation here is a
    # determinism win over native passthrough
    def sys_getdents64(self, ctx, a):
        return self._getdents(a, old_layout=False)

    def sys_getdents(self, ctx, a):
        return self._getdents(a, old_layout=True)

    def _getdents(self, a, old_layout: bool):
        fd, buf, count = _s32(a[0]), a[1], int(a[2])
        desc = self._desc(fd)
        if desc is None:
            return self._no_desc(fd)
        if not isinstance(desc, HostFileDesc) or not desc.is_dir:
            return -ENOTDIR
        ents = desc.dirents()
        out = bytearray()
        pos = desc._dirpos
        while pos < len(ents):
            name, ino, dtype = ents[pos]
            nb = name.encode("utf-8", "surrogateescape")
            if old_layout:
                # struct linux_dirent: ino, off, reclen, name...,
                # pad, d_type in the LAST byte
                reclen = (18 + len(nb) + 2 + 7) & ~7
                rec = struct.pack("<QqH", ino, pos + 1, reclen) + nb
                rec += b"\x00" * (reclen - 1 - len(rec))
                rec += bytes([dtype])
            else:
                # struct linux_dirent64: ino, off, reclen, d_type,
                # name...
                reclen = (19 + len(nb) + 1 + 7) & ~7
                rec = struct.pack("<QqHB", ino, pos + 1, reclen,
                                  dtype) + nb
                rec += b"\x00" * (reclen - len(rec))
            if len(out) + reclen > count:
                break
            out += rec
            pos += 1
        if not out and desc._dirpos < len(ents):
            return -EINVAL          # buffer too small for one entry
        desc._dirpos = pos
        if out:
            self.mem.write(buf, bytes(out))
        return len(out)

    # fd ops on the os-backed file -------------------------------------
    def sys_ftruncate(self, ctx, a):
        d = self._host_file(_s32(a[0]))
        if not isinstance(d, HostFileDesc):
            return d
        ln = _s64(a[1])
        if ln < 0:
            return -EINVAL
        try:
            os.ftruncate(d.osfd, ln)
            return 0
        except OSError as e:
            return -e.errno

    def sys_fsync(self, ctx, a):
        d = self._host_file(_s32(a[0]))
        if not isinstance(d, HostFileDesc):
            return d
        try:
            os.fsync(d.osfd)
            return 0
        except OSError as e:
            return -e.errno

    def sys_fdatasync(self, ctx, a):
        d = self._host_file(_s32(a[0]))
        if not isinstance(d, HostFileDesc):
            return d
        try:
            os.fdatasync(d.osfd)
            return 0
        except OSError as e:
            return -e.errno

    def sys_fallocate(self, ctx, a):
        d = self._host_file(_s32(a[0]))
        if not isinstance(d, HostFileDesc):
            return d
        mode, off, ln = _s32(a[1]), _s64(a[2]), _s64(a[3])
        if off < 0 or ln <= 0:
            return -EINVAL
        if mode != 0:
            # punch-hole/zero-range/collapse via the real fallocate(2)
            # on the confined fd — the kernel validates the mode
            # combination and answers EOPNOTSUPP for filesystems that
            # lack it, which is exactly the faithful behavior
            import ctypes
            libc = _libc()
            libc.fallocate.argtypes = (ctypes.c_int, ctypes.c_int,
                                       ctypes.c_long, ctypes.c_long)
            if libc.fallocate(d.osfd, mode, off, ln) != 0:
                return -ctypes.get_errno()
            return 0
        try:
            os.posix_fallocate(d.osfd, off, ln)
            return 0
        except OSError as e:
            return -e.errno

    # advisory I/O (ref file.c: advice steers caching, never contents
    # — the kernel contract is "may be ignored", so after fd/argument
    # validation these are deterministic successes; sync_file_range
    # additionally flushes like fdatasync so durability still holds)
    def sys_fadvise64(self, ctx, a):
        d = self._host_file(_s32(a[0]))
        if not isinstance(d, HostFileDesc):
            return d
        if _s32(a[3]) not in (0, 1, 2, 3, 4, 5):   # POSIX_FADV_*
            return -EINVAL
        return 0

    def sys_readahead(self, ctx, a):
        d = self._host_file(_s32(a[0]))
        if not isinstance(d, HostFileDesc):
            return d
        if _s64(a[1]) < 0:
            return -EINVAL
        return 0

    def sys_sync_file_range(self, ctx, a):
        d = self._host_file(_s32(a[0]))
        if not isinstance(d, HostFileDesc):
            return d
        if _s64(a[1]) < 0 or _s64(a[2]) < 0 or int(a[3]) & ~0x7:
            return -EINVAL
        try:
            os.fdatasync(d.osfd)
            return 0
        except OSError as e:
            return -e.errno

    def sys_syncfs(self, ctx, a):
        # syncfs flushes the whole filesystem holding the fd; the
        # emulated "filesystem" is the host data dir, so every open
        # os-backed descriptor of this process flushes (a superset of
        # the single fd; a single fsync would silently weaken the
        # durability contract)
        d = self._host_file(_s32(a[0]))
        if not isinstance(d, HostFileDesc):
            return d
        try:
            os.fsync(d.osfd)        # the argument fd's failure reports
        except OSError as e:
            return -e.errno
        for desc in list(self.table._slots.values()):
            if desc is d or not isinstance(desc, HostFileDesc) \
                    or desc.closed:
                continue
            try:
                os.fsync(desc.osfd)
            except OSError:
                # best-effort for the rest: an unsyncable sibling
                # (O_PATH passthrough and the like) must not fail the
                # whole-filesystem flush the way it would not natively
                continue
        return 0

    # mknod(at): regular files, FIFOs, and unix-socket nodes
    # materialize in the confined data dir (the kernel allows all
    # three unprivileged); char/block device nodes answer EPERM as
    # the kernel does for unprivileged callers — emulated regardless
    # of the simulator's own privilege, so a root-run simulation
    # cannot create real device nodes a user-run one would refuse
    def _mknod(self, dirfd, ptr, mode: int, dev: int):
        fmt = mode & 0o170000
        perm = mode & 0o7777
        if fmt in (0, 0o100000):               # S_IFREG (0 = default)
            def op(p):
                fd = os.open(p, os.O_CREAT | os.O_EXCL | os.O_WRONLY,
                             perm)
                os.close(fd)
            return self._path_op(dirfd, ptr, op)
        if fmt == 0o010000:                    # S_IFIFO
            return self._path_op(dirfd, ptr,
                                 lambda p: os.mkfifo(p, perm))
        if fmt == 0o140000:                    # S_IFSOCK
            # os.mknod of a socket node needs no privilege and keeps
            # kernel errnos (EEXIST on collision; no AF_UNIX 108-byte
            # sun_path cap that a bind()-based emulation would hit on
            # deeply nested data dirs)
            return self._path_op(dirfd, ptr,
                                 lambda p: os.mknod(p, fmt | perm))
        if fmt in (0o020000, 0o060000):        # S_IFCHR / S_IFBLK
            return -EPERM
        return -EINVAL                         # S_IFDIR / garbage

    def sys_mknodat(self, ctx, a):
        return self._mknod(_s32(a[0]), a[1], int(a[2]), int(a[3]))

    def sys_mknod(self, ctx, a):
        return self._mknod(self.AT_FDCWD, a[0], int(a[1]), int(a[2]))

    def sys_fchmod(self, ctx, a):
        d = self._host_file(_s32(a[0]))
        if not isinstance(d, HostFileDesc):
            return d
        try:
            os.fchmod(d.osfd, int(a[1]) & 0o7777)
            return 0
        except OSError as e:
            return -e.errno

    def sys_fchown(self, ctx, a):
        d = self._host_file(_s32(a[0]))
        if not isinstance(d, HostFileDesc):
            return d
        try:
            os.fchown(d.osfd, _s32(a[1]), _s32(a[2]))
            return 0
        except OSError as e:
            return -e.errno

    # flock: a VIRTUAL per-host lock table keyed by the confined path
    # (real blocking flock would stall the whole simulator thread);
    # blocking waiters poll on a short sim-time deadline. Holders that
    # closed their fd are pruned lazily.
    LOCK_SH, LOCK_EX, LOCK_NB, LOCK_UN = 1, 2, 4, 8

    def _flock_table(self) -> dict:
        t = getattr(self.p.host, "_flock_table", None)
        if t is None:
            t = self.p.host._flock_table = {}
        return t

    def sys_flock(self, ctx, a):
        d = self._host_file(_s32(a[0]))
        if not isinstance(d, HostFileDesc):
            return d
        op = _s32(a[1])
        kind = op & (self.LOCK_SH | self.LOCK_EX | self.LOCK_UN)
        if kind not in (self.LOCK_SH, self.LOCK_EX, self.LOCK_UN):
            return -EINVAL
        table = self._flock_table()
        key = d.realpath
        holders = table.setdefault(key, {})     # desc -> 'sh'|'ex'
        for h in [h for h in holders if h.closed]:
            del holders[h]
        if kind == self.LOCK_UN:
            holders.pop(d, None)
            return 0
        want = "sh" if kind == self.LOCK_SH else "ex"
        others = {h: m for h, m in holders.items() if h is not d}
        conflict = any(m == "ex" or want == "ex"
                       for m in others.values())
        if conflict:
            if op & self.LOCK_NB:
                return -EAGAIN      # EWOULDBLOCK
            raise Blocked(deadline=ctx.now + 1_000_000)
        holders[d] = want           # grant (also converts)
        return 0

    # path ops with dirfd-relative confined resolution ----------------
    def sys_unlinkat(self, ctx, a):
        flags = _s32(a[2])
        op = os.rmdir if flags & self.AT_REMOVEDIR else os.unlink
        return self._path_op(_s32(a[0]), a[1], op)

    def sys_unlink(self, ctx, a):
        return self._path_op(self.AT_FDCWD, a[0], os.unlink)

    def sys_rmdir(self, ctx, a):
        return self._path_op(self.AT_FDCWD, a[0], os.rmdir)

    def sys_mkdirat(self, ctx, a):
        mode = int(a[2]) & 0o7777
        return self._path_op(_s32(a[0]), a[1],
                             lambda p: os.mkdir(p, mode))

    def sys_mkdir(self, ctx, a):
        mode = int(a[1]) & 0o7777
        return self._path_op(self.AT_FDCWD, a[0],
                             lambda p: os.mkdir(p, mode))

    def _rename(self, olddirfd, old_ptr, newdirfd, new_ptr,
                flags: int):
        RENAME_NOREPLACE, RENAME_EXCHANGE = 1, 2
        if flags & ~(RENAME_NOREPLACE | RENAME_EXCHANGE):
            return -EINVAL
        if (flags & RENAME_EXCHANGE) and (flags & RENAME_NOREPLACE):
            return -EINVAL          # kernel: mutually exclusive
        for ptr in (old_ptr, new_ptr):
            if not ptr:
                return -EFAULT
        try:
            old = self.mem.read_cstr(old_ptr).decode(
                errors="surrogateescape")
            new = self.mem.read_cstr(new_ptr).decode(
                errors="surrogateescape")
        except OSError:
            return -EFAULT
        ro = self._resolve_at(olddirfd, old)
        rn = self._resolve_at(newdirfd, new)
        if ro is NATIVE and rn is NATIVE:
            return NATIVE
        if isinstance(ro, int):
            return ro
        if isinstance(rn, int):
            return rn
        if ro is NATIVE or rn is NATIVE:
            return -EXDEV       # confined <-> unconfined: refuse
        if flags & RENAME_NOREPLACE and os.path.lexists(rn):
            return -EEXIST
        try:
            if flags & RENAME_EXCHANGE:
                # true atomic exchange through glibc's renameat2
                # wrapper on the two CONFINED paths (os.rename cannot
                # express it; the wrapper is arch-portable where a
                # raw syscall number is not). Both targets must
                # exist, as the kernel demands.
                import ctypes
                libc = _libc()
                try:
                    fn = libc.renameat2
                except AttributeError:
                    return -EINVAL      # pre-2.28 glibc
                fn.argtypes = (ctypes.c_int, ctypes.c_char_p,
                               ctypes.c_int, ctypes.c_char_p,
                               ctypes.c_uint)
                if fn(-100, ro.encode(), -100, rn.encode(),
                      RENAME_EXCHANGE) != 0:     # AT_FDCWD anchors
                    return -ctypes.get_errno()
                return 0
            os.rename(ro, rn)
            return 0
        except OSError as e:
            return -e.errno

    def sys_renameat(self, ctx, a):
        return self._rename(_s32(a[0]), a[1], _s32(a[2]), a[3], 0)

    def sys_renameat2(self, ctx, a):
        return self._rename(_s32(a[0]), a[1], _s32(a[2]), a[3],
                            _s32(a[4]))

    def sys_rename(self, ctx, a):
        return self._rename(self.AT_FDCWD, a[0], self.AT_FDCWD,
                            a[1], 0)

    def _link(self, olddirfd, old_ptr, newdirfd, new_ptr, flags):
        for ptr in (old_ptr, new_ptr):
            if not ptr:
                return -EFAULT
        try:
            old = self.mem.read_cstr(old_ptr).decode(
                errors="surrogateescape")
            new = self.mem.read_cstr(new_ptr).decode(
                errors="surrogateescape")
        except OSError:
            return -EFAULT
        ro = self._resolve_at(olddirfd, old)
        rn = self._resolve_at(newdirfd, new)
        if ro is NATIVE and rn is NATIVE:
            return NATIVE
        if isinstance(ro, int):
            return ro
        if isinstance(rn, int):
            return rn
        if ro is NATIVE or rn is NATIVE:
            return -EXDEV
        try:
            os.link(ro, rn, follow_symlinks=bool(
                flags & self.AT_SYMLINK_FOLLOW))
            return 0
        except OSError as e:
            return -e.errno

    def sys_linkat(self, ctx, a):
        return self._link(_s32(a[0]), a[1], _s32(a[2]), a[3],
                          _s32(a[4]))

    def sys_link(self, ctx, a):
        return self._link(self.AT_FDCWD, a[0], self.AT_FDCWD, a[1],
                          self.AT_SYMLINK_FOLLOW)

    def sys_symlinkat(self, ctx, a):
        # the TARGET string is stored verbatim (never resolved here;
        # later opens through it hit the realpath confinement check)
        if not a[0]:
            return -EFAULT
        try:
            target = self.mem.read_cstr(a[0]).decode(
                errors="surrogateescape")
        except OSError:
            return -EFAULT
        return self._path_op(_s32(a[1]), a[2],
                             lambda p: os.symlink(target, p))

    def sys_symlink(self, ctx, a):
        return self.sys_symlinkat(ctx, (a[0], self.AT_FDCWD, a[1]))

    def sys_readlinkat(self, ctx, a):
        bufp, bufsz = a[2], int(a[3])
        if bufsz <= 0:
            return -EINVAL

        def do(p):
            tgt = os.readlink(p).encode("utf-8", "surrogateescape")
            out = tgt[:bufsz]
            self.mem.write(bufp, out)
            return len(out)         # no NUL terminator (kernel ABI)
        return self._path_op(_s32(a[0]), a[1], do)

    def sys_readlink(self, ctx, a):
        return self.sys_readlinkat(ctx, (self.AT_FDCWD, a[0], a[1],
                                         a[2]))

    def sys_faccessat(self, ctx, a):
        mode = _s32(a[2])

        def do(p):
            if not os.path.lexists(p):
                return -ENOENT
            ok = os.access(p, mode) if mode else os.path.exists(p)
            return 0 if ok else -EACCES
        return self._path_op(_s32(a[0]), a[1], do)

    def sys_faccessat2(self, ctx, a):
        AT_EACCESS = 0x200
        mode, flags = _s32(a[2]), _s32(a[3])
        if flags & ~(AT_EACCESS | self.AT_SYMLINK_NOFOLLOW):
            return -EINVAL
        if not flags & self.AT_SYMLINK_NOFOLLOW:
            # AT_EACCESS is a no-op here: real and effective ids match
            return self.sys_faccessat(ctx, a)

        def do(p):
            if not os.path.lexists(p):
                return -ENOENT
            if not mode:            # F_OK on the link itself
                return 0
            ok = os.access(p, mode, follow_symlinks=False)
            return 0 if ok else -EACCES
        return self._path_op(_s32(a[0]), a[1], do)

    def sys_fchmodat(self, ctx, a):
        mode = int(a[2]) & 0o7777
        return self._path_op(_s32(a[0]), a[1],
                             lambda p: os.chmod(p, mode))

    def sys_chmod(self, ctx, a):
        mode = int(a[1]) & 0o7777
        return self._path_op(self.AT_FDCWD, a[0],
                             lambda p: os.chmod(p, mode))

    def sys_fchownat(self, ctx, a):
        uid, gid, flags = _s32(a[2]), _s32(a[3]), _s32(a[4])
        follow = not flags & self.AT_SYMLINK_NOFOLLOW
        return self._path_op(
            _s32(a[0]), a[1],
            lambda p: os.chown(p, uid, gid, follow_symlinks=follow))

    def sys_chown(self, ctx, a):
        return self._path_op(
            self.AT_FDCWD, a[0],
            lambda p: os.chown(p, _s32(a[1]), _s32(a[2])))

    def sys_lchown(self, ctx, a):
        return self._path_op(
            self.AT_FDCWD, a[0],
            lambda p: os.lchown(p, _s32(a[1]), _s32(a[2])))

    def sys_truncate(self, ctx, a):
        ln = _s64(a[1])
        if ln < 0:
            return -EINVAL
        return self._path_op(self.AT_FDCWD, a[0],
                             lambda p: os.truncate(p, ln))

    # file times: UTIME_NOW resolves to SIM time, so emulated
    # timestamps stay deterministic
    UTIME_NOW, UTIME_OMIT = (1 << 30) - 1, (1 << 30) - 2

    def _read_timespec_pair(self, ctx, ptr):
        """-> (atime_ns, mtime_ns) with None = omit."""
        now = self._now_wall(ctx)
        if not ptr:
            return now, now
        raw = self.mem.read(ptr, 32)
        out = []
        for i in (0, 16):
            sec, ns = struct.unpack_from("<qq", raw, i)
            if ns == self.UTIME_NOW:
                out.append(now)
            elif ns == self.UTIME_OMIT:
                out.append(None)
            elif not 0 <= ns < 1_000_000_000:
                raise ValueError
            else:
                out.append(sec * 1_000_000_000 + ns)
        return out[0], out[1]

    def _apply_times(self, p, at, mt, follow=True):
        if at is None or mt is None:
            st = os.stat(p) if follow else os.lstat(p)
            at = st.st_atime_ns if at is None else at
            mt = st.st_mtime_ns if mt is None else mt
        os.utime(p, ns=(at, mt), follow_symlinks=follow)

    def sys_utimensat(self, ctx, a):
        try:
            at, mt = self._read_timespec_pair(ctx, a[2])
        except ValueError:
            return -EINVAL
        except OSError:
            return -EFAULT
        flags = _s32(a[3])
        follow = not flags & self.AT_SYMLINK_NOFOLLOW
        if not a[1]:
            # NULL path: futimens(fd) on the os-backed file
            d = self._host_file(_s32(a[0]))
            if not isinstance(d, HostFileDesc):
                return d
            try:
                if at is None or mt is None:
                    st = os.fstat(d.osfd)
                    at = st.st_atime_ns if at is None else at
                    mt = st.st_mtime_ns if mt is None else mt
                os.utime(d.osfd, ns=(at, mt))
                return 0
            except OSError as e:
                return -e.errno
        return self._path_op(
            _s32(a[0]), a[1],
            lambda p: self._apply_times(p, at, mt, follow))

    def _read_timeval_pair(self, ctx, ptr):
        now = self._now_wall(ctx)
        if not ptr:
            return now, now
        raw = self.mem.read(ptr, 32)
        s0, u0, s1, u1 = struct.unpack_from("<qqqq", raw)
        if not (0 <= u0 < 1_000_000 and 0 <= u1 < 1_000_000):
            raise ValueError
        return (s0 * 1_000_000_000 + u0 * 1000,
                s1 * 1_000_000_000 + u1 * 1000)

    def sys_futimesat(self, ctx, a):
        try:
            at, mt = self._read_timeval_pair(ctx, a[2])
        except ValueError:
            return -EINVAL
        except OSError:
            return -EFAULT
        return self._path_op(_s32(a[0]), a[1],
                             lambda p: self._apply_times(p, at, mt))

    def sys_utimes(self, ctx, a):
        return self.sys_futimesat(ctx, (self.AT_FDCWD, a[0], a[1]))

    def sys_utime(self, ctx, a):
        if a[1]:
            try:
                raw = self.mem.read(a[1], 16)
            except OSError:
                return -EFAULT
            at_s, mt_s = struct.unpack("<qq", raw)
            at, mt = at_s * 1_000_000_000, mt_s * 1_000_000_000
        else:
            at = mt = self._now_wall(ctx)
        return self._path_op(self.AT_FDCWD, a[0],
                             lambda p: self._apply_times(p, at, mt))

    # cwd tracking: chdir inside the data dir keeps emulated AT_FDCWD
    # resolution accurate; a chdir OUT of it flips resolution to
    # NATIVE (the plugin's own kernel cwd stays authoritative)
    def sys_chdir(self, ctx, a):
        if not a[0]:
            return -EFAULT
        try:
            path = self.mem.read_cstr(a[0]).decode(
                errors="surrogateescape")
        except OSError:
            return -EFAULT
        r = self._resolve_at(self.AT_FDCWD, path)
        if r is NATIVE:
            self.p.vcwd = "outside"
            return NATIVE
        if isinstance(r, int):
            return r
        if os.path.isdir(r):
            self.p.vcwd = r
        return NATIVE               # keep the REAL cwd in sync

    def sys_fchdir(self, ctx, a):
        fd = _s32(a[0])
        if fd < VFD_BASE:
            self.p.vcwd = "outside"     # can't see where it points
            return NATIVE
        d = self._desc(fd)
        if d is None:
            return -EBADF
        if not isinstance(d, HostFileDesc) or not d.is_dir:
            return -ENOTDIR
        if getattr(self.p, "interpose_style", "") != "ptrace":
            # the preload plugin's REAL cwd cannot follow a virtual
            # dir fd; refuse loudly rather than diverge silently
            return -EACCES
        self.p.vcwd = d.abspath
        return 0

    # xattr family (confined paths / os-backed fds) --------------------
    def _xattr_name(self, ptr):
        return self.mem.read_cstr(ptr).decode(errors="surrogateescape")

    def _xattr_get(self, target, name_ptr, val_ptr, size):
        try:
            val = os.getxattr(target, self._xattr_name(name_ptr))
        except OSError as e:
            return -e.errno
        if size == 0:
            return len(val)
        if len(val) > size:
            return -ERANGE
        self.mem.write(val_ptr, val)
        return len(val)

    def _xattr_set(self, target, name_ptr, val_ptr, size, flags):
        try:
            val = self.mem.read(val_ptr, size) if size else b""
            os.setxattr(target, self._xattr_name(name_ptr), val,
                        flags)
            return 0
        except OSError as e:
            return -e.errno

    def _xattr_list(self, target, buf_ptr, size):
        try:
            names = os.listxattr(target)
        except OSError as e:
            return -e.errno
        blob = b"".join(n.encode() + b"\x00" for n in names)
        if size == 0:
            return len(blob)
        if len(blob) > size:
            return -ERANGE
        if blob:
            self.mem.write(buf_ptr, blob)
        return len(blob)

    def _xattr_remove(self, target, name_ptr):
        try:
            os.removexattr(target, self._xattr_name(name_ptr))
            return 0
        except OSError as e:
            return -e.errno

    def sys_fgetxattr(self, ctx, a):
        d = self._host_file(_s32(a[0]))
        if not isinstance(d, HostFileDesc):
            return d
        return self._xattr_get(d.osfd, a[1], a[2], int(a[3]))

    def sys_fsetxattr(self, ctx, a):
        d = self._host_file(_s32(a[0]))
        if not isinstance(d, HostFileDesc):
            return d
        return self._xattr_set(d.osfd, a[1], a[2], int(a[3]),
                               _s32(a[4]))

    def sys_flistxattr(self, ctx, a):
        d = self._host_file(_s32(a[0]))
        if not isinstance(d, HostFileDesc):
            return d
        return self._xattr_list(d.osfd, a[1], int(a[2]))

    def sys_fremovexattr(self, ctx, a):
        d = self._host_file(_s32(a[0]))
        if not isinstance(d, HostFileDesc):
            return d
        return self._xattr_remove(d.osfd, a[1])

    def sys_getxattr(self, ctx, a):
        return self._path_op(
            self.AT_FDCWD, a[0],
            lambda p: self._xattr_get(p, a[1], a[2], int(a[3])))

    def sys_lgetxattr(self, ctx, a):
        return self.sys_getxattr(ctx, a)    # links: best effort

    def sys_setxattr(self, ctx, a):
        return self._path_op(
            self.AT_FDCWD, a[0],
            lambda p: self._xattr_set(p, a[1], a[2], int(a[3]),
                                      _s32(a[4])))

    def sys_lsetxattr(self, ctx, a):
        return self.sys_setxattr(ctx, a)

    def sys_listxattr(self, ctx, a):
        return self._path_op(
            self.AT_FDCWD, a[0],
            lambda p: self._xattr_list(p, a[1], int(a[2])))

    def sys_llistxattr(self, ctx, a):
        return self.sys_listxattr(ctx, a)

    def sys_removexattr(self, ctx, a):
        return self._path_op(
            self.AT_FDCWD, a[0],
            lambda p: self._xattr_remove(p, a[1]))

    def sys_lremovexattr(self, ctx, a):
        return self.sys_removexattr(ctx, a)

    def sys_fstatfs(self, ctx, a):
        """struct statfs for an os-backed fd: DETERMINISTIC values (a
        plausible fixed ext4 — the real filesystem's occupancy is
        machine state that must never steer a plugin). Ref file.c:135
        passes the real fstatfs through; the deviation follows the
        same policy as the rusage/limits views."""
        d = self._host_file(_s32(a[0]))
        if not isinstance(d, HostFileDesc):
            return d
        if not a[1]:
            return -EFAULT
        buf = bytearray(120)
        struct.pack_into(
            "<7q", buf, 0,
            0xEF53,                     # f_type: ext4
            4096,                       # f_bsize
            1 << 28, 1 << 27, 1 << 27,  # blocks / bfree / bavail
            1 << 24, 1 << 23)           # files / ffree
        struct.pack_into("<qq", buf, 64, 255, 4096)  # namelen, frsize
        self.mem.write(a[1], bytes(buf))
        return 0

    # POSIX record locks (fcntl F_GETLK/F_SETLK/F_SETLKW, ref
    # fcntl.c:60-90): a VIRTUAL per-host table keyed by the confined
    # path — the simulator owns every real fd, so kernel POSIX locks
    # would all share one owner and never conflict; the virtual table
    # restores per-PROCESS semantics with virtual pids in F_GETLK.
    F_GETLK, F_SETLK, F_SETLKW = 5, 6, 7
    F_OFD_GETLK, F_OFD_SETLK, F_OFD_SETLKW = 36, 37, 38
    F_RDLCK, F_WRLCK, F_UNLCK = 0, 1, 2

    def _posix_lock_table(self) -> dict:
        t = getattr(self.p.host, "_posix_locks", None)
        if t is None:
            t = self.p.host._posix_locks = {}
        return t

    def _read_flock(self, ptr):
        """-> (raw_bytes, l_type, l_whence, l_start, l_len, l_pid)."""
        raw = self.mem.read(ptr, 32)
        l_type, l_whence = struct.unpack_from("<hh", raw, 0)
        l_start, l_len = struct.unpack_from("<qq", raw, 8)
        l_pid, = struct.unpack_from("<i", raw, 24)
        return raw, l_type, l_whence, l_start, l_len, l_pid

    def _lock_range(self, desc, whence, start, ln):
        """absolute [lo, hi) — hi = 2^63-1 for 'to EOF' (l_len 0)."""
        if whence == 1:                 # SEEK_CUR
            base = os.lseek(desc.osfd, 0, os.SEEK_CUR)
        elif whence == 2:               # SEEK_END
            base = os.fstat(desc.osfd).st_size
        else:
            base = 0
        lo = base + start
        if ln > 0:
            return lo, lo + ln
        if ln < 0:
            return lo + ln, lo
        return lo, (1 << 63) - 1

    @staticmethod
    def _split_out(locks, owner, lo, hi):
        """Remove owner's coverage of [lo, hi), splitting partial
        overlaps (shared by unlock and the replace-then-add path)."""
        new = []
        for e in locks:
            own, t, a_, b_ = e
            if own is not owner or b_ <= lo or hi <= a_:
                new.append(e)
                continue
            if a_ < lo:
                new.append((own, t, a_, lo))
            if hi < b_:
                new.append((own, t, hi, b_))
        return new

    def _lock_deadlock(self, ctx, key, lo, hi, me):
        """EDEADLK detection for F_SETLKW: walk the waits-for graph
        (holder of my range -> the range IT waits on -> holders...)
        through the per-host waiting map. Entries are trusted only
        while FRESH (a parked waiter re-polls every sim-millisecond,
        so anything older than a few polls is a stale leftover from an
        interrupted wait, never a false cycle)."""
        waiting = getattr(self.p.host, "_posix_waiting", None)
        if waiting is None:
            waiting = self.p.host._posix_waiting = {}
        table = self._posix_lock_table()
        seen = set()
        frontier = [(key, lo, hi)]
        while frontier:
            k, a0, b0 = frontier.pop()
            for own, _t, x, y in table.get(k, ()):
                if own is me or x >= b0 or y <= a0 \
                        or id(own) in seen:
                    continue
                seen.add(id(own))
                w = waiting.get(own)
                if w is None:
                    continue
                wk, wlo, whi, stamp = w
                if ctx.now - stamp > 8_000_000:     # stale (> 8 polls)
                    continue
                # does MY holding set block this holder's wait?
                if any(own2 is me and x2 < whi and wlo < y2
                       for own2, _t2, x2, y2 in table.get(wk, ())):
                    return True
                frontier.append((wk, wlo, whi))
        return False

    def _fcntl_lock(self, ctx, desc, cmd, arg):
        """Record locks over the virtual table. Ownership follows the
        kernel: F_SETLK/F_GETLK/F_SETLKW locks are owned by the
        PROCESS (virtual pid in F_GETLK); F_OFD_* locks are owned by
        the open file DESCRIPTION (the shared desc object; l_pid
        reports -1). Purged eagerly at sys_close (POSIX close-any-fd
        release) and lazily when the owner dies."""
        ofd_cmd = cmd in (self.F_OFD_GETLK, self.F_OFD_SETLK,
                          self.F_OFD_SETLKW)
        if not arg:
            return -EFAULT
        try:
            raw, l_type, whence, start, ln, l_pid = \
                self._read_flock(arg)
        except OSError:
            return -EFAULT
        if ofd_cmd and cmd != self.F_OFD_GETLK and l_pid != 0:
            return -EINVAL          # kernel mandates l_pid == 0
        if whence not in (0, 1, 2):
            return -EINVAL
        try:
            lo, hi = self._lock_range(desc, whence, start, ln)
        except OSError as e:
            return -e.errno
        if lo < 0 or (hi <= lo and l_type != self.F_UNLCK):
            return -EINVAL
        table = self._posix_lock_table()
        key = desc.realpath
        locks = table.setdefault(key, [])
        me = desc if ofd_cmd else self.p

        def owner_live(entry):
            own = entry[0]
            if isinstance(own, HostFileDesc):
                return not own.closed       # OFD: dies with the desc
            if not own.alive or own.table is None:
                return False
            return any(isinstance(x, HostFileDesc) and not x.closed
                       and x.realpath == key
                       for x in own.table._slots.values())
        locks[:] = [e for e in locks if owner_live(e)]

        def conflicts(entry):
            own, t, a_, b_ = entry
            return own is not me and a_ < hi and lo < b_ and \
                (t == self.F_WRLCK or l_type == self.F_WRLCK)

        if cmd in (self.F_GETLK, self.F_OFD_GETLK):
            for e in locks:
                if conflicts(e):
                    own, t, a_, b_ = e
                    out = bytearray(32)
                    struct.pack_into("<hh", out, 0, t, 0)
                    struct.pack_into("<qq", out, 8, a_,
                                     0 if b_ >= (1 << 62) else b_ - a_)
                    pid = -1 if isinstance(own, HostFileDesc) \
                        else own.vpid
                    struct.pack_into("<i", out, 24, pid)
                    self.mem.write(arg, bytes(out))
                    return 0
            out = bytearray(raw)
            struct.pack_into("<h", out, 0, self.F_UNLCK)
            self.mem.write(arg, bytes(out))
            return 0

        waiting = getattr(self.p.host, "_posix_waiting", None)
        if waiting is not None:
            waiting.pop(me, None)           # any lock op ends a wait
        if l_type == self.F_UNLCK:
            locks[:] = self._split_out(locks, me, lo, hi)
            return 0
        if l_type not in (self.F_RDLCK, self.F_WRLCK):
            return -EINVAL
        if any(conflicts(e) for e in locks):
            if cmd in (self.F_SETLKW, self.F_OFD_SETLKW):
                if self._lock_deadlock(ctx, key, lo, hi, me):
                    return -35              # EDEADLK
                if waiting is None:
                    waiting = self.p.host._posix_waiting = {}
                waiting[me] = (key, lo, hi, ctx.now)
                raise Blocked(deadline=ctx.now + 1_000_000)
            return -EAGAIN
        # previous locks of this owner in the range are replaced
        # (POSIX merge semantics approximated by split-then-add)
        locks[:] = self._split_out(locks, me, lo, hi)
        locks.append((me, l_type, lo, hi))
        return 0

    def sys_fcntl(self, ctx, a):
        fd, cmd, arg = _s32(a[0]), _s32(a[1]), int(a[2])
        desc = self._desc(fd)
        if desc is None:
            return self._no_desc(fd)
        if cmd in (self.F_GETLK, self.F_SETLK, self.F_SETLKW,
                   self.F_OFD_GETLK, self.F_OFD_SETLK,
                   self.F_OFD_SETLKW):
            if not isinstance(desc, HostFileDesc):
                return -EBADF
            return self._fcntl_lock(ctx, desc, cmd, arg)
        if cmd in (F_DUPFD, F_DUPFD_CLOEXEC):
            min_fd = arg - VFD_BASE if arg >= VFD_BASE else 0
            nfd = self.table.dup(fd, min_fd)
            if cmd == F_DUPFD_CLOEXEC and nfd >= 0:
                self.table.cloexec.add(nfd)
            return nfd
        if cmd == F_GETFD:
            return 1 if fd in self.table.cloexec else 0
        if cmd == F_SETFD:
            if arg & 1:                     # FD_CLOEXEC
                self.table.cloexec.add(fd)
            else:
                self.table.cloexec.discard(fd)
            return 0
        if cmd == F_GETFL:
            if isinstance(desc, HostFileDesc):
                return (desc.flags & ~O_NONBLOCK) \
                    | (O_NONBLOCK if desc.nonblock else 0)
            return O_RDWR | (O_NONBLOCK if desc.nonblock else 0)
        if cmd == F_SETFL:
            desc.nonblock = bool(arg & O_NONBLOCK)
            if isinstance(desc, HostFileDesc):
                # O_APPEND is the only SETFL bit with real effect on
                # the os-backed fd
                import fcntl as _fcntl
                O_APPEND = 0x400
                try:
                    cur = _fcntl.fcntl(desc.osfd, _fcntl.F_GETFL)
                    _fcntl.fcntl(desc.osfd, _fcntl.F_SETFL,
                                 (cur & ~O_APPEND)
                                 | (arg & O_APPEND))
                except OSError as e:
                    return -e.errno
                desc.flags = (desc.flags & ~(O_APPEND | O_NONBLOCK)) \
                    | (arg & (O_APPEND | O_NONBLOCK))
            return 0
        return -EINVAL

    def sys_ioctl(self, ctx, a):
        fd, req, argp = _s32(a[0]), int(a[1]) & 0xFFFFFFFF, a[2]
        desc = self._desc(fd)
        if desc is None:
            return self._no_desc(fd)
        if req == FIONBIO:
            val = struct.unpack("<i", self.mem.read(argp, 4))[0]
            desc.nonblock = bool(val)
            return 0
        if req == FIONREAD:
            n = 0
            if isinstance(desc, TcpDesc):
                n = len(desc.recv_stream)
            elif isinstance(desc, UdpDesc) and desc.queue:
                n = len(desc.queue[0][0])
            elif isinstance(desc, PipeDesc):
                n = len(desc.buf)
            elif isinstance(desc, UnixPairDesc):
                # SIOCINQ on unix dgram = size of the next datagram
                n = (len(desc.rmsgs[0]) if desc.dgram and desc.rmsgs
                     else 0) if desc.dgram else len(desc.rbuf)
            self.mem.write(argp, struct.pack("<i", n))
            return 0
        return -ENOTTY

    def sys_dup(self, ctx, a):
        fd = _s32(a[0])
        if self._desc(fd) is None:
            return self._no_desc(fd)
        return self.table.dup(fd)

    def sys_dup2(self, ctx, a):
        return self._dup_to(ctx, _s32(a[0]), _s32(a[1]))

    def sys_dup3(self, ctx, a):
        oldfd, newfd, flags = _s32(a[0]), _s32(a[1]), _s32(a[2])
        if oldfd == newfd or flags & ~0x80000:
            return -EINVAL              # dup3(2): unlike dup2
        r = self._dup_to(ctx, oldfd, newfd)
        if isinstance(r, int) and r >= 0 and flags & 0x80000:
            self.table.cloexec.add(r)       # O_CLOEXEC
        return r

    def _dup_to(self, ctx, oldfd: int, newfd: int):
        if self._desc(oldfd) is None:
            return self._no_desc(oldfd)
        if newfd < VFD_BASE:
            return -EINVAL          # cannot shadow native kernel fds
        if newfd >= VFD_END:
            # outside the shim's fd-range gate: later I/O on it would
            # go raw to the kernel under preload (EBADF) while ptrace
            # would emulate it — refuse like the kernel does past the
            # fd limit
            return -EBADF
        if newfd == oldfd:
            return newfd
        if self.table.get(newfd) is not None:
            self.table.close_fd(ctx, newfd)
        self.table.place_at(oldfd, newfd)
        return newfd

    # ==================================================================
    # pipes / eventfd / timerfd (pipe.rs, eventd.c, timer.c)
    # ==================================================================
    def sys_pipe(self, ctx, a):
        return self._pipe(ctx, a[0], 0)

    def sys_pipe2(self, ctx, a):
        return self._pipe(ctx, a[0], _s32(a[1]))

    def _pipe(self, ctx, fds_ptr: int, flags: int):
        if not self.table.has_room(2):
            return -EMFILE          # both slots or neither
        r, w = PipeDesc.make_pair()
        r.nonblock = w.nonblock = bool(flags & O_NONBLOCK)
        rfd = self.table.alloc(r)
        wfd = self.table.alloc(w)
        if flags & 0x80000:             # O_CLOEXEC
            self.table.cloexec.update((rfd, wfd))
        self.mem.write(fds_ptr, struct.pack("<ii", rfd, wfd))
        return 0

    def _pipe_read(self, ctx, desc: PipeDesc, buf: int, n: int):
        if not desc.readable_end:
            return -EBADF
        if not desc.buf:
            if desc.peer is None or desc.peer.closed:
                return 0
            if desc.nonblock:
                return -EAGAIN
            raise Blocked([desc])
        data = bytes(desc.buf[:n])
        del desc.buf[:n]
        self.mem.write(buf, data)
        if desc.peer is not None:
            desc.peer.notify(ctx)      # writer may proceed
        return len(data)

    def _pipe_write(self, ctx, desc: PipeDesc, buf: int, n: int):
        if desc.readable_end:
            return -EBADF
        if desc.peer is None or desc.peer.closed:
            return -EPIPE
        space = PipeDesc.CAPACITY - len(desc.buf)
        if space <= 0:
            if desc.nonblock:
                return -EAGAIN
            raise Blocked([desc])
        take = min(n, space)
        desc.buf += self.mem.read(buf, take)
        desc.peer.notify(ctx)
        return take

    def sys_eventfd(self, ctx, a):
        return self._eventfd(int(a[0]), 0)

    def sys_eventfd2(self, ctx, a):
        return self._eventfd(int(a[0]), _s32(a[1]))

    def _eventfd(self, initval: int, flags: int):
        d = EventfdDesc(initval, bool(flags & EFD_SEMAPHORE))
        d.nonblock = bool(flags & EFD_NONBLOCK)
        fd = self.table.alloc(d)
        if flags & 0x80000:             # EFD_CLOEXEC
            self.table.cloexec.add(fd)
        return fd

    def _eventfd_read(self, ctx, d: EventfdDesc, buf: int, n: int):
        if n < 8:
            return -EINVAL
        if d.counter == 0:
            if d.nonblock:
                return -EAGAIN
            raise Blocked([d])
        val = 1 if d.semaphore else d.counter
        d.counter -= val
        self.mem.write(buf, struct.pack("<Q", val))
        d.notify(ctx)
        return 8

    def _eventfd_write(self, ctx, d: EventfdDesc, buf: int, n: int):
        if n < 8:
            return -EINVAL
        val = struct.unpack("<Q", self.mem.read(buf, 8))[0]
        d.counter += val
        d.notify(ctx)
        return 8

    def sys_timerfd_create(self, ctx, a):
        d = TimerfdDesc()
        flags = _s32(a[1])
        d.nonblock = bool(flags & 0x800)
        fd = self.table.alloc(d)
        if flags & 0x80000:             # TFD_CLOEXEC
            self.table.cloexec.add(fd)
        return fd

    def sys_timerfd_settime(self, ctx, a):
        fd, flags = _s32(a[0]), _s32(a[1])
        d = self._desc(fd)
        if not isinstance(d, TimerfdDesc):
            return -EBADF
        raw = self.mem.read(a[2], 32)
        interval = kmem.unpack_timespec(raw[:16])
        value = kmem.unpack_timespec(raw[16:])
        if a[3]:
            self._write_itimerspec(a[3], d, ctx)
        d.generation += 1
        d.expirations = 0
        if value == 0:
            d.next_expiry = None
            return 0
        when = value if flags & TFD_TIMER_ABSTIME else ctx.now + value
        d.interval_ns = interval
        d.next_expiry = when
        self.p.arm_timerfd(ctx, d, when, d.generation)
        return 0

    def sys_timerfd_gettime(self, ctx, a):
        d = self._desc(_s32(a[0]))
        if not isinstance(d, TimerfdDesc):
            return -EBADF
        self._write_itimerspec(a[1], d, ctx)
        return 0

    def _write_itimerspec(self, ptr: int, d: TimerfdDesc, ctx) -> None:
        remaining = max(0, (d.next_expiry or 0) - ctx.now) \
            if d.next_expiry is not None else 0
        self.mem.write(ptr, kmem.pack_timespec(d.interval_ns)
                       + kmem.pack_timespec(remaining))

    def _timerfd_read(self, ctx, d: TimerfdDesc, buf: int, n: int):
        if n < 8:
            return -EINVAL
        if d.expirations == 0:
            if d.nonblock:
                return -EAGAIN
            raise Blocked([d])
        val = d.expirations
        d.expirations = 0
        self.mem.write(buf, struct.pack("<Q", val))
        return 8

    # ==================================================================
    # readiness: epoll / poll / select (epoll.c, poll.c)
    # ==================================================================
    def sys_epoll_create(self, ctx, a):
        return self.table.alloc(EpollDesc(self.table))

    def sys_epoll_create1(self, ctx, a):
        fd = self.table.alloc(EpollDesc(self.table))
        if _s32(a[0]) & 0x80000:        # EPOLL_CLOEXEC
            self.table.cloexec.add(fd)
        return fd

    def sys_epoll_ctl(self, ctx, a):
        epfd, op, fd = _s32(a[0]), _s32(a[1]), _s32(a[2])
        ep = self._desc(epfd)
        if not isinstance(ep, EpollDesc):
            return -EBADF
        if fd < VFD_BASE:
            return -EPERM           # native fds not epollable here
        target = self._desc(fd)
        if target is None:
            return -EBADF
        if op == EPOLL_CTL_ADD:
            if fd in ep.interest:
                return -17          # EEXIST
            ev, data = kmem.EPOLL_EVENT.unpack(
                self.mem.read(a[3], kmem.EPOLL_EVENT_SIZE))
            ep.add(fd, ev, data)
            return 0
        if op == EPOLL_CTL_MOD:
            if fd not in ep.interest:
                return -ENOENT
            ev, data = kmem.EPOLL_EVENT.unpack(
                self.mem.read(a[3], kmem.EPOLL_EVENT_SIZE))
            ep.modify(fd, ev, data)
            return 0
        if op == EPOLL_CTL_DEL:
            if fd not in ep.interest:
                return -ENOENT
            ep.remove(fd)
            return 0
        return -EINVAL

    def sys_epoll_wait(self, ctx, a):
        return self._epoll_wait(ctx, a, _s32(a[3]))

    def sys_epoll_pwait(self, ctx, a):
        self._swap_pmask(a[4])
        return self._epoll_wait(ctx, a, _s32(a[3]))

    def _epoll_wait(self, ctx, a, timeout_ms: int):
        ep = self._desc(_s32(a[0]))
        if not isinstance(ep, EpollDesc):
            return -EBADF
        maxevents = _s32(a[2])
        if maxevents <= 0:
            return -EINVAL
        ready = ep.ready()
        if ready:
            out = b"".join(kmem.EPOLL_EVENT.pack(ev, data)
                           for ev, data in ready[:maxevents])
            self.mem.write(a[1], out)
            return min(len(ready), maxevents)
        st = self.state
        if timeout_ms == 0:
            return 0
        if "deadline" not in st:
            st["deadline"] = (ctx.now + timeout_ms * 1_000_000
                              if timeout_ms > 0 else None)
        if st["deadline"] is not None and ctx.now >= st["deadline"]:
            return 0
        raise Blocked([ep], deadline=st["deadline"])

    def sys_poll(self, ctx, a):
        return self._poll(ctx, a[0], int(a[1]), _s32(a[2]))

    def sys_ppoll(self, ctx, a):
        self._swap_pmask(a[3])
        timeout_ms = -1
        if a[2]:
            ns = kmem.unpack_timespec(self.mem.read(a[2], 16))
            # round up: a sub-ms timeout must still advance sim time
            # (0 would spin the plugin at one simulated instant)
            timeout_ms = -(-ns // 1_000_000)
        return self._poll(ctx, a[0], int(a[1]), timeout_ms)

    def _poll(self, ctx, fds_ptr: int, nfds: int, timeout_ms: int):
        if nfds > 4096:
            return -EINVAL
        raw = bytearray(self.mem.read(fds_ptr, kmem.POLLFD.size * nfds))
        n_ready = 0
        virt_descs = []
        for i in range(nfds):
            fd, events, _rev = kmem.POLLFD.unpack_from(
                raw, i * kmem.POLLFD.size)
            revents = 0
            if fd < 0:
                pass
            elif fd < VFD_BASE:
                # native fd (regular file / tty): always ready —
                # blocking on real external input has no simulated
                # time meaning
                revents = events & (EPOLLIN | EPOLLOUT)
            else:
                d = self._desc(fd)
                if d is None:
                    revents = 0x20      # POLLNVAL
                else:
                    virt_descs.append(d)
                    stt = d.status()
                    if (events & EPOLLIN) and (stt & R):
                        revents |= EPOLLIN
                    if (events & EPOLLOUT) and (stt & W):
                        revents |= EPOLLOUT
                    if stt & ERR:
                        revents |= EPOLLERR
            if revents:
                n_ready += 1
            kmem.POLLFD.pack_into(raw, i * kmem.POLLFD.size, fd, events,
                                  revents)
        if n_ready:
            self.mem.write(fds_ptr, bytes(raw))
            return n_ready
        st = self.state
        if timeout_ms == 0:
            self.mem.write(fds_ptr, bytes(raw))
            return 0
        if "deadline" not in st:
            st["deadline"] = (ctx.now + timeout_ms * 1_000_000
                              if timeout_ms >= 0 else None)
        if st["deadline"] is not None and ctx.now >= st["deadline"]:
            self.mem.write(fds_ptr, bytes(raw))
            return 0
        raise Blocked(virt_descs, deadline=st["deadline"])

    def sys_select(self, ctx, a):
        return self._select(ctx, a, timeval=True)

    def sys_pselect6(self, ctx, a):
        if a[5]:
            # arg 6 is a {const sigset_t *ss; size_t ss_len} pair
            ss_ptr = struct.unpack("<Q", self.mem.read(a[5], 8))[0]
            self._swap_pmask(ss_ptr)
        return self._select(ctx, a, timeval=False)

    def _select(self, ctx, a, timeval: bool):
        """Real select over the virtual fd window: since the
        [600, 1024) redesign every virtual fd fits in an fd_set, so
        select works on simulated sockets/pipes/timerfds exactly like
        poll (same descriptor status bits; native fds — regular
        files/ttys — are always ready; exceptfds map to ERR). The
        kernel contract: the return value counts BITS across all
        three sets, the sets are rewritten in place, and (for the
        timeval flavor) the remaining time is written back."""
        nfds = _s32(a[0])
        if nfds < 0 or nfds > 1024:
            return -EINVAL
        nbytes = (nfds + 7) // 8
        sets = [bytearray(self.mem.read(p, nbytes))
                if p and nbytes else bytearray(nbytes)
                for p in (a[1], a[2], a[3])]
        rset, wset, eset = sets
        out = [bytearray(nbytes) for _ in range(3)]
        n_bits = 0
        virt_descs = []
        for fd in range(nfds):
            byte, bit = fd >> 3, 1 << (fd & 7)
            want_r = rset[byte] & bit
            want_w = wset[byte] & bit
            want_e = eset[byte] & bit
            if not (want_r or want_w or want_e):
                continue
            if fd < VFD_BASE:
                # native fd (regular file / tty): always ready — the
                # same policy as _poll; never exceptional
                if want_r:
                    out[0][byte] |= bit
                    n_bits += 1
                if want_w:
                    out[1][byte] |= bit
                    n_bits += 1
                continue
            d = self._desc(fd)
            if d is None:
                return -EBADF       # kernel checks fds up front
            virt_descs.append(d)
            stt = d.status()
            if want_r and (stt & R):
                out[0][byte] |= bit
                n_bits += 1
            if want_w and (stt & W):
                out[1][byte] |= bit
                n_bits += 1
            if want_e and (stt & ERR):
                out[2][byte] |= bit
                n_bits += 1

        def write_back(which):
            for ptr, ob in zip((a[1], a[2], a[3]), which):
                if ptr and nbytes:
                    self.mem.write(ptr, bytes(ob))

        st = self.state
        if n_bits:
            write_back(out)
            if timeval and a[4] and st.get("deadline") is not None:
                # ready after blocking partway through the timeout:
                # Linux select() rewrites the timeval to the
                # remainder (the documented loop-on-same-timeval
                # idiom depends on it)
                rem = max(0, st["deadline"] - ctx.now)
                self.mem.write(a[4], struct.pack(
                    "<qq", rem // 1_000_000_000,
                    (rem % 1_000_000_000) // 1000))
            return n_bits
        if "deadline" not in st:
            if not a[4]:
                st["deadline"] = None       # block on the fds alone
            else:
                if timeval:
                    sec, usec = struct.unpack(
                        "<qq", self.mem.read(a[4], 16))
                    if sec < 0 or usec < 0:
                        return -EINVAL
                    ns = sec * 1_000_000_000 + usec * 1000
                else:
                    ns = kmem.unpack_timespec(self.mem.read(a[4], 16))
                    if ns < 0:
                        return -EINVAL
                st["deadline"] = ctx.now + ns
        if st["deadline"] is not None and ctx.now >= st["deadline"]:
            write_back(out)                 # all-zero sets
            if timeval and a[4]:
                # Linux select() updates the timeval to the remainder
                self.mem.write(a[4], struct.pack("<qq", 0, 0))
            return 0
        if not virt_descs and st["deadline"] is None:
            return -EINVAL                  # would block forever
        raise Blocked(virt_descs, deadline=st["deadline"])

    # ==================================================================
    # msghdr-based I/O (uio.c / socket.c)
    # ==================================================================
    def _read_msghdr(self, ptr: int):
        raw = self.mem.read(ptr, 56)
        name, namelen = struct.unpack_from("<QI", raw, 0)
        iov, iovlen = struct.unpack_from("<QQ", raw, 16)
        return name, namelen, kmem.read_iovec(self.mem, iov, int(iovlen))

    def sys_sendmsg(self, ctx, a):
        fd, msg_ptr, flags = _s32(a[0]), a[1], _s32(a[2])
        desc = self._desc(fd)
        if desc is None:
            return self._no_desc(fd)
        name, namelen, iov = self._read_msghdr(msg_ptr)
        if isinstance(desc, UdpDesc):
            data = b"".join(self.mem.read(b, ln) for b, ln in iov)
            if len(data) > UDP_MAX_PAYLOAD:
                return -EMSGSIZE
            dst, err = self._dst_for_send(desc, name, namelen)
            if err:
                return err
            desc.ensure_bound(self.p.host.net)
            desc.sock.sendto(ctx.now, dst[0], dst[1], len(data),
                             payload=data)
            return len(data)
        if isinstance(desc, UnixPairDesc):
            if name:
                return -EISCONN
            if desc.dgram:
                # one datagram from the gathered iovecs (atomic)
                data = b"".join(bytes(self.mem.read(b, ln))
                                for b, ln in iov if ln)
                return self._upair_send_dgram(ctx, desc, data, flags)
            total = 0
            for base, ln in iov:
                if ln == 0:
                    continue
                try:
                    r = self._upair_write(ctx, desc, base, ln, flags)
                except Blocked:
                    if total == 0:
                        raise
                    # the interrupted segment parked its committed
                    # byte count (upair_done); those bytes are already
                    # in the peer's buffer, so they MUST ride the
                    # short return — dropping them makes the app
                    # resend bytes the peer received (duplicates)
                    total += self.state.pop("upair_done", 0)
                    break
                if isinstance(r, int) and r < 0:
                    return r if total == 0 else total
                total += r
                if r < ln:
                    break
            return total
        if isinstance(desc, TcpDesc):
            # like _iov_loop: only the first iov may block — a Blocked
            # after partial progress would replay sent bytes on restart
            total = 0
            for base, ln in iov:
                if ln == 0:
                    continue
                try:
                    r = self._tcp_write(ctx, desc, base, ln, flags)
                except Blocked:
                    if total == 0:
                        raise
                    break
                if isinstance(r, int) and r < 0:
                    return r if total == 0 else total
                total += r
                if r < ln:
                    break
            return total
        return -ENOTSOCK

    def sys_recvmsg(self, ctx, a):
        fd, msg_ptr, flags = _s32(a[0]), a[1], _s32(a[2])
        desc = self._desc(fd)
        if desc is None:
            return self._no_desc(fd)
        name, namelen, iov = self._read_msghdr(msg_ptr)
        if not iov:
            return -EINVAL
        base, ln = iov[0]
        if isinstance(desc, UdpDesc):
            return self.sys_recvfrom(
                ctx, (a[0], base, ln, flags, name,
                      msg_ptr + 8 if name else 0))
        if isinstance(desc, TcpDesc):
            return self._tcp_read(ctx, desc, base, ln, flags)
        if isinstance(desc, UnixPairDesc):
            r = self._upair_read(ctx, desc, base, ln, flags)
            if isinstance(r, int) and r >= 0 and name:
                # unnamed peer: msg_namelen (msghdr + 8) becomes 0
                self.mem.write(msg_ptr + 8, struct.pack("<I", 0))
            return r
        return -ENOTSOCK

    def sys_sendmmsg(self, ctx, a):
        """Vector of sendmsg calls (socket.c's sendmmsg shape): stop at
        the first message that would block — if nothing was sent yet,
        block; otherwise report the partial count."""
        fd, vec_ptr, vlen, flags = _s32(a[0]), a[1], int(a[2]), _s32(a[3])
        if self._desc(fd) is None:
            return self._no_desc(fd)
        sent = 0
        for i in range(min(vlen, 1024)):
            mm = vec_ptr + i * 64          # struct mmsghdr = msghdr + len
            try:
                r = self.sys_sendmsg(ctx, (a[0], mm, flags))
            except Blocked:
                if sent == 0:
                    raise
                break
            if isinstance(r, int) and r < 0:
                return r if sent == 0 else sent
            self.mem.write(mm + 56, struct.pack("<I", r))
            sent += 1
        return sent

    MSG_WAITFORONE = 0x10000

    def sys_recvmmsg(self, ctx, a):
        """Kernel-faithful recvmmsg (net/socket.c do_recvmmsg shape):
        a blocking socket waits per message until vlen is filled or the
        timeout expires — and the timeout is only consulted AFTER each
        received datagram (the documented man-page quirk), so an empty
        blocking socket waits for its first datagram regardless of
        timeout. MSG_WAITFORONE drains nonblocking after the first.
        Nonblocking sockets surface -EAGAIN from recvmsg itself."""
        fd, vec_ptr, vlen, flags = _s32(a[0]), a[1], int(a[2]), _s32(a[3])
        if self._desc(fd) is None:
            return self._no_desc(fd)
        st = self.state
        if "deadline" not in st:
            st["deadline"] = None
            st["mm_got"] = 0
            if a[4]:        # struct timespec *timeout (relative)
                ns = kmem.unpack_timespec(self.mem.read(a[4], 16))
                st["deadline"] = ctx.now + max(0, ns)
        got = st["mm_got"]
        expired = (st["deadline"] is not None and
                   ctx.now >= st["deadline"])
        for i in range(got, min(vlen, 1024)):
            mm = vec_ptr + i * 64
            try:
                r = self.sys_recvmsg(
                    ctx, (a[0], mm, flags & ~self.MSG_WAITFORONE))
            except Blocked as b:
                if got > 0 and (flags & self.MSG_WAITFORONE or expired):
                    break
                if got > 0 and st["deadline"] is None:
                    # no timeout: keep blocking for the next message
                    st["mm_got"] = got
                    raise Blocked(b.descs) from None
                if got > 0:
                    st["mm_got"] = got
                    raise Blocked(
                        b.descs, deadline=st["deadline"]) from None
                # first message: wait with no deadline even when the
                # timeout already expired (kernel quirk — the timeout
                # is only consulted after a datagram; a blocking empty
                # socket waits regardless, nonblocking ones surfaced
                # -EAGAIN from recvmsg above)
                st["mm_got"] = 0
                raise Blocked(b.descs) from None
            if isinstance(r, int) and r < 0:
                return r if got == 0 else got
            self.mem.write(mm + 56, struct.pack("<I", r))
            got += 1
            if st["deadline"] is not None and ctx.now >= st["deadline"]:
                break           # timeout checked after each datagram
        return got

    # ==================================================================
    # scheduling / identity odds and ends (unistd.c, sysinfo.c)
    # ==================================================================
    def sys_sched_yield(self, ctx, a):
        return 0

    # -- deterministic resource/topology views -------------------------
    # Native getrusage/times return REAL CPU time and the scheduler
    # calls expose the REAL machine topology — all nondeterministic
    # inputs a managed program could branch on. The simulated view:
    # one CPU, and "CPU time" == simulated elapsed time (the manager's
    # heartbeat uses getrusage on itself, manager.c:587-613; plugins
    # get the virtual clock).
    def sys_getrusage(self, ctx, a):
        who = _s32(a[0])
        if who not in (0, -1, 1):   # SELF, CHILDREN, THREAD
            return -EINVAL
        if not a[1]:
            return -EFAULT
        ru = bytearray(144)
        if who != -1:
            # SELF/THREAD: simulated elapsed time; CHILDREN stays
            # zero (child CPU time isn't tracked — deterministic and
            # strictly less wrong than the parent's total)
            now = ctx.now
            struct.pack_into("<qq", ru, 0, now // 10**9,
                             (now % 10**9) // 1000)     # ru_utime
        self.mem.write(a[1], bytes(ru))
        return 0

    def sys_times(self, ctx, a):
        ticks = ctx.now * 100 // 10**9              # 100 Hz clock_t
        if a[0]:
            self.mem.write(a[0], struct.pack("<qqqq", ticks, 0, 0, 0))
        return ticks

    def sys_sched_getaffinity(self, ctx, a):
        size, mask_ptr = int(a[1]), a[2]
        if size < 8 or not mask_ptr:
            return -EINVAL
        self.mem.write(mask_ptr, struct.pack("<Q", 1))  # one CPU: #0
        return 8

    def sys_sched_setaffinity(self, ctx, a):
        return 0                # accepted, inert (one simulated CPU)

    def sys_getcpu(self, ctx, a):
        if a[0]:
            self.mem.write(a[0], struct.pack("<I", 0))
        if a[1]:
            self.mem.write(a[1], struct.pack("<I", 0))
        return 0

    def sys_gettid(self, ctx, a):
        cur = getattr(self.p, "current", None)
        return cur.vtid if cur is not None else self.p.vpid

    def sys_set_tid_address(self, ctx, a):
        cur = getattr(self.p, "current", None)
        if cur is not None:
            cur.clear_ctid = a[0]
        return cur.vtid if cur is not None else self.p.vpid

    def sys_sysinfo(self, ctx, a):
        """struct sysinfo with simulated uptime; memory fields report a
        fixed plausible machine (the plugin's view must not depend on
        the real host — determinism)."""
        if not a[0]:
            return -EFAULT
        si = bytearray(112)
        struct.pack_into("<q", si, 0,
                         ctx.now // simtime.SIMTIME_ONE_SECOND)
        gb = 1 << 32
        struct.pack_into("<QQ", si, 32, gb, gb // 2)   # totalram freeram
        struct.pack_into("<H", si, 80, 1)              # procs
        struct.pack_into("<I", si, 104, 1)             # mem_unit
        self.mem.write(a[0], bytes(si))
        return 0

    # -- resource limits + prctl (ref syscall_handler.c:250-533 tail) --
    RLIM_INFINITY = (1 << 64) - 1
    # deterministic per-resource defaults (the REAL machine's limits
    # must never leak into the plugin — same policy as the
    # rusage/times/affinity views): a plausible fixed machine
    _RLIMIT_DEFAULTS = {
        3: (8 << 20, RLIM_INFINITY),        # STACK
        7: (1024, 1 << 20),                 # NOFILE
    }

    def _rlimits(self) -> dict:
        d = getattr(self.p, "rlimits", None)
        if d is None:
            d = self.p.rlimits = {}
        return d

    def sys_prlimit64(self, ctx, a):
        pid, res = _s32(a[0]), _s32(a[1])
        if pid not in (0, self.p.vpid):
            return -EPERM           # cross-process limits: not modeled
        if not 0 <= res < 16:
            return -EINVAL
        lims = self._rlimits()
        cur = lims.get(res) or self._RLIMIT_DEFAULTS.get(
            res, (self.RLIM_INFINITY, self.RLIM_INFINITY))
        new = None
        if a[2]:
            try:
                soft, hard = struct.unpack(
                    "<QQ", self.mem.read(a[2], 16))
            except OSError:
                return -EFAULT
            if soft > hard:
                return -EINVAL
            new = (soft, hard)
        if a[3]:
            try:
                self.mem.write(a[3], struct.pack("<QQ", *cur))
            except OSError:
                return -EFAULT
        if new is not None:
            lims[res] = new
        return 0

    def sys_getrlimit(self, ctx, a):
        return self.sys_prlimit64(ctx, (0, a[0], 0, a[1]))

    def sys_setrlimit(self, ctx, a):
        # struct rlimit is u64-based on x86_64: same layout
        return self.sys_prlimit64(ctx, (0, a[0], a[1], 0))

    def sys_prctl(self, ctx, a):
        """Minimal prctl: PDEATHSIG is virtualized (delivered by the
        VIRTUAL parent-death path — the native parent of every plugin
        is the simulator, so the kernel's own delivery would fire at
        the wrong moment); PR_SET_NAME is mirrored then run native.
        Everything else passes through."""
        PR_SET_PDEATHSIG, PR_GET_PDEATHSIG = 1, 2
        PR_SET_NAME, PR_GET_NAME = 15, 16
        opt = _s32(a[0])
        if opt == PR_SET_PDEATHSIG:
            sig = _s32(a[1])
            if sig and not 1 <= sig <= 64:
                return -EINVAL
            self.p.pdeathsig = sig
            return 0
        if opt == PR_GET_PDEATHSIG:
            if not a[1]:
                return -EFAULT
            self.mem.write(a[1], struct.pack(
                "<i", getattr(self.p, "pdeathsig", 0)))
            return 0
        if opt == PR_SET_NAME:
            try:
                name = self.mem.read(a[1], 16).split(b"\x00")[0][:15]
            except OSError:
                return -EFAULT
            self.p.current.comm = name
            return NATIVE           # mirror into the real thread too
        if opt == PR_GET_NAME:
            comm = getattr(self.p.current, "comm", None)
            if comm is None:
                return NATIVE
            if not a[1]:
                return -EFAULT
            self.mem.write(a[1], comm.ljust(16, b"\x00")[:16])
            return 0
        return NATIVE

    def sys_set_robust_list(self, ctx, a):
        """Deliberate kernel delegation: robust-futex list walking
        happens at REAL thread death, and threads die for real under
        both backends — the kernel's own handling is the correct one.
        The head is mirrored for get_robust_list / introspection.
        Ref: syscall_handler.c robust-list passthrough."""
        if int(a[1]) != 24:         # sizeof(struct robust_list_head)
            return -EINVAL
        self.p.current.robust_list = int(a[0])
        return NATIVE

    def sys_get_robust_list(self, ctx, a):
        pid = _s32(a[0])
        if pid not in (0, self.p.vpid) and \
                pid not in getattr(self.p, "threads", {}):
            return -EPERM
        head = getattr(self.p.current, "robust_list", 0)
        if a[1]:
            self.mem.write(a[1], struct.pack("<Q", head))
        if a[2]:
            self.mem.write(a[2], struct.pack("<Q", 24))
        return 0

    # ==================================================================
    # futex (futex.c, futex_table.c)
    # ==================================================================
    FUTEX_WAIT, FUTEX_WAKE = 0, 1
    FUTEX_WAIT_BITSET, FUTEX_WAKE_BITSET = 9, 10
    FUTEX_CLOCK_REALTIME = 256

    def sys_futex(self, ctx, a):
        uaddr, op, val = a[0], _s32(a[1]), _s32(a[2]) & 0xFFFFFFFF
        cmd = op & 0x7F
        table = self.p.futexes
        if cmd in (self.FUTEX_WAIT, self.FUTEX_WAIT_BITSET):
            st = self.state
            if "parked" in st:           # re-entered: wake or timeout
                fx = table.get(uaddr)
                if fx is not None and not fx.conditions:
                    del table[uaddr]     # timed-out entries must not leak
                if st["deadline"] is not None and \
                        ctx.now >= st["deadline"]:
                    return -ETIMEDOUT
                return 0
            cur = struct.unpack("<I", self.mem.read(uaddr, 4))[0]
            if cur != val:
                return -EAGAIN
            st["deadline"] = None
            if a[3]:
                ns = kmem.unpack_timespec(self.mem.read(a[3], 16))
                if cmd == self.FUTEX_WAIT_BITSET:
                    # bitset waits take an absolute deadline
                    if op & self.FUTEX_CLOCK_REALTIME:
                        ns -= simtime.EMULATED_TIME_OFFSET
                    st["deadline"] = max(ns, ctx.now)
                else:
                    st["deadline"] = ctx.now + max(0, ns)
            fx = table.get(uaddr)
            if fx is None:
                fx = table[uaddr] = Futex(uaddr)
            st["parked"] = True
            raise Blocked([fx], deadline=st["deadline"])
        if cmd in (self.FUTEX_WAKE, self.FUTEX_WAKE_BITSET):
            fx = table.get(uaddr)
            if fx is None:
                return 0
            n = fx.wake(ctx, max(0, val))
            if not fx.conditions:
                table.pop(uaddr, None)
            return n
        return -ENOSYS

    def sys_sendfile(self, ctx, a):
        """sendfile(out_fd=virtual socket, in_fd=native file): the
        kernel can't see our socket, so stream the file bytes through
        the host-side view of the plugin's fd (/proc/pid/fd/N)."""
        out_fd, in_fd, off_ptr = _s32(a[0]), _s32(a[1]), a[2]
        count = int(a[3])
        out = self._desc(out_fd)
        if out is None:
            return self._no_desc(out_fd)
        if not isinstance(out, TcpDesc):
            return -EINVAL
        in_desc = None
        if in_fd >= VFD_BASE:
            in_desc = self._desc(in_fd)
            if not isinstance(in_desc, HostFileDesc):
                return -EINVAL      # in_fd must be a file
        # same connection-state gate as _tcp_write
        if out.connect_err:
            err = out.connect_err
            out.connect_err = None
            return -err
        if not out.connected:
            return -ENOTCONN if not out.connecting else -EAGAIN
        from shadow_tpu.host.tcp import TcpState
        if out.sock.state not in (TcpState.ESTABLISHED,
                                  TcpState.CLOSE_WAIT):
            return -EPIPE
        st = self.state
        if "sf_sent" not in st:
            st["sf_sent"] = 0
            if off_ptr:
                st["sf_off"] = struct.unpack(
                    "<q", self.mem.read(off_ptr, 8))[0]
            else:
                # NULL offset: stream from the fd's current position.
                # Snapshot it ONCE — on a Blocked restart the plugin's
                # own fd offset is unchanged (the syscall was
                # suppressed), so progress lives in sf_sent; the
                # plugin's real fd position is advanced at finish via
                # pidfd_getfd+lseek (shared file description).
                st["sf_off"] = None
                if in_desc is not None:
                    st["sf_base"] = os.lseek(in_desc.osfd, 0,
                                             os.SEEK_CUR)
                else:
                    st["sf_base"] = \
                        self._native_file_offset(in_fd) or 0
        space = out.send_space()
        if space <= 0:
            if out.nonblock:
                return self._sendfile_finish(ctx, off_ptr, in_fd) \
                    if st["sf_sent"] else -EAGAIN
            raise Blocked([out])
        want = min(count - st["sf_sent"], space)
        base = st["sf_off"] if st["sf_off"] is not None \
            else st["sf_base"]
        try:
            if in_desc is not None:
                # read only what this pass can push: a blocked 100 MB
                # transfer must not re-read the whole tail every wake
                data = os.pread(in_desc.osfd, want,
                                base + st["sf_sent"])
            else:
                with open(f"/proc/{self.p.native_pid}/fd/{in_fd}",
                          "rb") as f:
                    f.seek(base + st["sf_sent"])
                    data = f.read(want)
        except OSError:
            return -EBADF
        if not data:
            return self._sendfile_finish(ctx, off_ptr, in_fd)
        self.table.send_channel(out.sock).push(data)
        out.sock.send(ctx.now, len(data))
        st["sf_sent"] += len(data)
        if st["sf_sent"] >= count or len(data) < want:   # done or EOF
            return self._sendfile_finish(ctx, off_ptr, in_fd)
        if out.nonblock:
            return self._sendfile_finish(ctx, off_ptr, in_fd)
        raise Blocked([out])        # blocking: push the rest next wake

    def _sendfile_finish(self, ctx, off_ptr: int, in_fd: int):
        st = self.state
        sent = st["sf_sent"]
        if off_ptr and st["sf_off"] is not None:
            self.mem.write(off_ptr,
                           struct.pack("<q", st["sf_off"] + sent))
        elif sent and st["sf_off"] is None:
            if in_fd >= VFD_BASE:
                # emulated file: the simulator owns the offset
                d = self._desc(in_fd)
                if isinstance(d, HostFileDesc):
                    try:
                        os.lseek(d.osfd, st["sf_base"] + sent,
                                 os.SEEK_SET)
                    except OSError:
                        pass
            else:
                # NULL offset: the plugin's own fd position must
                # advance by `sent`. /proc/pid/fd opens a NEW
                # description, so seek the plugin's actual one via
                # pidfd_getfd (shares the offset).
                self._advance_plugin_fd(in_fd, st["sf_base"] + sent)
        return sent

    _warned_pidfd = False

    def _advance_plugin_fd(self, in_fd: int, new_pos: int) -> None:
        libc = _libc()
        pidfd = libc.syscall(434, self.p.native_pid, 0)  # pidfd_open
        dup = -1
        if pidfd >= 0:
            dup = libc.syscall(438, pidfd, in_fd, 0)     # pidfd_getfd
        if dup < 0:
            if not SyscallHandler._warned_pidfd:
                SyscallHandler._warned_pidfd = True
                log.warning(
                    "pidfd_getfd unavailable (kernel < 5.6 or no "
                    "ptrace permission): NULL-offset sendfile cannot "
                    "advance the plugin's fd position; repeated reads "
                    "of the same fd will see a stale offset")
            if pidfd >= 0:
                os.close(pidfd)
            return
        try:
            os.lseek(dup, new_pos, os.SEEK_SET)
        except OSError:
            pass
        finally:
            os.close(dup)
            os.close(pidfd)

    def _native_file_offset(self, in_fd: int):
        try:
            with open(f"/proc/{self.p.native_pid}/fdinfo/{in_fd}") as f:
                for line in f:
                    if line.startswith("pos:"):
                        return int(line.split()[1])
        except OSError:
            pass
        return None
