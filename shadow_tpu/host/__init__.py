from shadow_tpu.host.host import Host

__all__ = ["Host"]
