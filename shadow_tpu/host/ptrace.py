"""Ptrace interposition backend (PTRACE_SYSEMU).

The rebuild of the reference's second interposition method
(src/main/host/thread_ptrace.c): instead of a preloaded shim funneling
trapped syscalls over shared-memory IPC, the simulator ptrace-attaches
to the managed process and drives it with PTRACE_SYSEMU — every
syscall stops the tracee *before* execution and the kernel suppresses
it, so the simulator can emulate it (poke the result into %rax) or
re-execute it natively (rewind %rip over the 2-byte `syscall`
instruction and step through with PTRACE_SYSCALL — the reference's
"deliver to native" path, thread_ptrace.c:1074 onward).

Linux requires every ptrace request (and the waitpid noticing tracee
stops) to come from the tracer task itself, so each PtraceProcess owns
a dedicated tracer thread holding the fork/exec, the SYSEMU loop, and
all register access; the simulation threads talk to it over a command
queue. This mirrors the reference's per-worker fork-proxy +
tracer-affinity workarounds (thread_ptrace.c:39-56,
utility/fork_proxy.c).

TSC emulation (src/lib/tsc/tsc.c): the child sets
prctl(PR_SET_TSC, PR_TSC_SIGSEGV) before exec (the flag survives
execve), so `rdtsc`/`rdtscp` raise SIGSEGV; the tracer decodes the
instruction at %rip (0F 31 / 0F 01 F9), writes a deterministic
cycle count derived from simulated time into %edx:%eax (nominal
1 GHz ⇒ cycles == nanoseconds), advances %rip, and resumes — plugin
time reads are pure functions of sim time, like the reference's
Tsc_emulateRdtsc.
"""

from __future__ import annotations

import ctypes
import os
import queue
import signal
import struct
import threading
from typing import Optional

from shadow_tpu.host.process import ManagedProcess, RECV_TIMEOUT_MS
from shadow_tpu.host.memory import ProcessMemory
from shadow_tpu.host.syscalls import NATIVE, NR_NAME, Blocked
from shadow_tpu.utils.slog import get_logger

log = get_logger("ptrace")

_libc = ctypes.CDLL(None, use_errno=True)
_libc.ptrace.restype = ctypes.c_long
_libc.ptrace.argtypes = [ctypes.c_long, ctypes.c_long,
                         ctypes.c_void_p, ctypes.c_void_p]

# ptrace requests
# (TRACEME/SETOPTIONS are gone with the old fork path: the tracee is
# spawned via the launcher stub and PTRACE_SEIZEd with options)
CONT = 7
GETREGS = 12
SETREGS = 13
SYSCALL = 24
SYSEMU = 31

OPT_SYSGOOD = 0x1           # PTRACE_O_TRACESYSGOOD
OPT_TRACEEXEC = 0x10        # PTRACE_O_TRACEEXEC
OPT_EXITKILL = 0x00100000   # PTRACE_O_EXITKILL
SEIZE = 0x4206              # PTRACE_SEIZE
EVENT_EXEC = 4              # PTRACE_EVENT_EXEC

SYSCALL_TRAP = signal.SIGTRAP | 0x80     # sysgood syscall stop

POKEDATA = 5

# vDSO fast paths bypass the syscall instruction entirely, so SYSEMU
# never sees them; like rr, overwrite each exported vDSO function with
# an 8-byte real-syscall stub (mov eax, NR; syscall; ret) so plugin
# time reads become trappable syscalls. (The preload backend doesn't
# need this: LD_PRELOAD beats the libc symbols that call the vDSO.)
_VDSO_STUBS = {
    b"__vdso_clock_gettime": 228,
    b"__vdso_gettimeofday": 96,
    b"__vdso_time": 201,
    b"__vdso_clock_getres": 229,
    b"__vdso_getcpu": 309,
    b"clock_gettime": 228,
    b"gettimeofday": 96,
    b"time": 201,
    b"clock_getres": 229,
    b"getcpu": 309,
}


NOMINAL_TSC_HZ = 1_000_000_000           # 1 GHz: cycles == sim ns


class UserRegs(ctypes.Structure):
    _fields_ = [(n, ctypes.c_ulonglong) for n in (
        "r15", "r14", "r13", "r12", "rbp", "rbx", "r11", "r10",
        "r9", "r8", "rax", "rcx", "rdx", "rsi", "rdi", "orig_rax",
        "rip", "cs", "eflags", "rsp", "ss", "fs_base", "gs_base",
        "ds", "es", "fs", "gs")]


def _ptrace(req: int, pid: int, addr=None, data=None) -> int:
    ctypes.set_errno(0)
    r = _libc.ptrace(req, pid, addr, data)
    if r == -1:
        err = ctypes.get_errno()
        if err:
            raise OSError(err, f"ptrace({req}, {pid}): "
                          f"{os.strerror(err)}")
    return r


class _TraceeExited(Exception):
    def __init__(self, code: int):
        self.code = code


class _Tracer(threading.Thread):
    """Owns all ptrace operations for one tracee.

    Commands (cmd, payload) on self.cmds; replies on self.replies:
      spawn  -> ("pid", pid) | ("error", msg)
      step   -> payload (result|None, native: bool, sim_ns) ; applies
                the pending syscall result, resumes, and replies
                ("syscall", nr, args) | ("exit", code)
      kill   -> ("exit", code)
    """

    def __init__(self, argv, env, cwd, stdout_path, stderr_path,
                 emulate_tsc: bool = True):
        super().__init__(daemon=True)
        self.argv = argv
        self.env = env
        self.cwd = cwd
        self.stdout_path = stdout_path
        self.stderr_path = stderr_path
        self.emulate_tsc = emulate_tsc
        self.cmds: queue.Queue = queue.Queue()
        self.replies: queue.Queue = queue.Queue()
        self.pid: Optional[int] = None
        self.exited = threading.Event()
        self.sim_ns = 0

    # -- spawn + seize (replaces the old fork/TRACEME path) ------------
    def _spawn_seize(self) -> int:
        """Popen the launcher stub, wait for its self-SIGSTOP, SEIZE
        it from THIS thread (all later ptrace requests must come from
        the seizing thread), resume, and run to the real program's
        PTRACE_EVENT_EXEC stop."""
        import subprocess
        import time as _time

        from shadow_tpu import native as _native

        launcher = [_native.launcher_path()]
        if not self.emulate_tsc:
            launcher.append("--no-tsc")
        out = open(self.stdout_path, "wb")
        err = open(self.stderr_path, "wb")
        try:
            proc = subprocess.Popen(
                launcher + self.argv,
                env=self.env, cwd=self.cwd, stdout=out, stderr=err,
                stdin=subprocess.DEVNULL)
        finally:
            out.close()
            err.close()
        pid = proc.pid
        self.pid = pid
        self._popen = proc          # keeps the zombie reapable

        # the launcher raise(SIGSTOP)s itself; as its parent we see
        # the stop (or an early death) in one blocking wait
        _, status = os.waitpid(pid, os.WUNTRACED)
        if os.WIFEXITED(status):
            raise _TraceeExited(os.WEXITSTATUS(status))
        if os.WIFSIGNALED(status):
            raise _TraceeExited(128 + os.WTERMSIG(status))

        _ptrace(SEIZE, pid, None,
                ctypes.c_void_p(OPT_SYSGOOD | OPT_EXITKILL |
                                OPT_TRACEEXEC))
        # consume the post-SEIZE ptrace (group-)stop notification if
        # the kernel reports one before we resume; a CONT issued in
        # the stop-to-ptrace-trap transition window returns ESRCH,
        # which the retry below also absorbs
        t0 = _time.monotonic()
        while _time.monotonic() - t0 < 2.0:
            r, st = os.waitpid(pid, os.WNOHANG)
            if r == pid:
                # a tracee killed in this window must surface its exit
                # code, not a stale-pid SIGCONT failure
                if os.WIFEXITED(st):
                    raise _TraceeExited(os.WEXITSTATUS(st))
                if os.WIFSIGNALED(st):
                    raise _TraceeExited(128 + os.WTERMSIG(st))
                break
            _time.sleep(0.001)
        os.kill(pid, signal.SIGCONT)

        def cont(sig: int) -> None:
            for _ in range(500):
                try:
                    _ptrace(CONT, pid, None,
                            ctypes.c_void_p(sig) if sig else None)
                    return
                except OSError:
                    _time.sleep(0.001)
            raise OSError(f"pid={pid}: PTRACE_CONT kept failing")

        # run the stub to the exec of the real program
        deliver = 0
        while True:
            cont(deliver)
            deliver = 0
            _, status = os.waitpid(pid, 0)
            if os.WIFEXITED(status):
                raise _TraceeExited(os.WEXITSTATUS(status))
            if os.WIFSIGNALED(status):
                raise _TraceeExited(128 + os.WTERMSIG(status))
            if (status >> 8) == (signal.SIGTRAP | (EVENT_EXEC << 8)):
                break               # the real program's first moment
            sig = os.WSTOPSIG(status)
            if sig not in (signal.SIGSTOP, signal.SIGCONT,
                           signal.SIGTRAP):
                deliver = sig
        return pid

    # -- vDSO patching (tracer thread, at the exec stop) ----------------
    def _patch_vdso(self) -> None:
        try:
            self._patch_vdso_inner()
        except Exception as e:     # malformed ELF must not kill the
            log.warning("vdso patch skipped: %s", e)   # tracer thread

    def _patch_vdso_inner(self) -> None:
        base = size = None
        try:
            with open(f"/proc/{self.pid}/maps") as f:
                for line in f:
                    if "[vdso]" in line:
                        lo, hi = line.split()[0].split("-")
                        base, size = int(lo, 16), \
                            int(hi, 16) - int(lo, 16)
                        break
        except OSError:
            return
        if base is None:
            return
        try:
            img = ProcessMemory(self.pid).read(base, size)
        except OSError:
            return
        if img[:4] != b"\x7fELF":
            return
        # locate .dynsym / .dynstr via the section headers
        e_shoff, = struct.unpack_from("<Q", img, 0x28)
        e_shentsize, e_shnum = struct.unpack_from("<HH", img, 0x3A)
        dynsym = dynstr = None
        for i in range(e_shnum):
            off = e_shoff + i * e_shentsize
            if off + 64 > len(img):
                return
            sh_type, = struct.unpack_from("<I", img, off + 4)
            sh_offset, sh_size = struct.unpack_from("<QQ", img,
                                                    off + 0x18)
            sh_entsize, = struct.unpack_from("<Q", img, off + 0x38)
            if sh_type == 11:                      # SHT_DYNSYM
                dynsym = (sh_offset, sh_size, sh_entsize)
                sh_link, = struct.unpack_from("<I", img, off + 0x28)
                loff = e_shoff + sh_link * e_shentsize
                dynstr, = struct.unpack_from("<Q", img, loff + 0x18)
        if dynsym is None or dynstr is None:
            return
        soff, ssize, sent = dynsym
        patched = 0
        for off in range(soff, soff + ssize, sent or 24):
            st_name, = struct.unpack_from("<I", img, off)
            st_value, = struct.unpack_from("<Q", img, off + 8)
            if not st_name or not st_value:
                continue
            end = img.index(b"\0", dynstr + st_name)
            name = img[dynstr + st_name:end]
            nr = _VDSO_STUBS.get(name)
            if nr is None:
                continue
            stub = bytes([0xB8]) + struct.pack("<I", nr) \
                + b"\x0f\x05\xc3"
            word, = struct.unpack("<q", stub)
            try:
                _ptrace(POKEDATA, self.pid,
                        ctypes.c_void_p(base + st_value),
                        ctypes.c_void_p(word & (2**64 - 1)))
                patched += 1
            except OSError as e:
                log.debug("vdso patch %s failed: %s", name, e)
        log.debug("patched %d vDSO entries", patched)

    # -- tracee helpers (tracer thread only) ----------------------------
    def _getregs(self) -> UserRegs:
        regs = UserRegs()
        _ptrace(GETREGS, self.pid, None, ctypes.byref(regs))
        return regs

    def _setregs(self, regs: UserRegs) -> None:
        _ptrace(SETREGS, self.pid, None, ctypes.byref(regs))

    def _wait(self) -> int:
        """waitpid; raises _TraceeExited on termination."""
        _, status = os.waitpid(self.pid, 0)
        if os.WIFEXITED(status):
            raise _TraceeExited(os.WEXITSTATUS(status))
        if os.WIFSIGNALED(status):
            raise _TraceeExited(128 + os.WTERMSIG(status))
        return os.WSTOPSIG(status)

    def _try_emulate_tsc(self) -> bool:
        """At a SIGSEGV stop: if %rip is rdtsc/rdtscp, emulate it."""
        regs = self._getregs()
        try:
            code = ProcessMemory(self.pid).read(regs.rip, 3)
        except OSError:
            return False
        cycles = self.sim_ns  # 1 GHz nominal
        if code[:2] == b"\x0f\x31":                    # rdtsc
            regs.rip += 2
        elif code[:3] == b"\x0f\x01\xf9":              # rdtscp
            regs.rip += 3
            regs.rcx = 0                               # IA32_TSC_AUX
        else:
            return False
        regs.rax = cycles & 0xFFFFFFFF
        regs.rdx = (cycles >> 32) & 0xFFFFFFFF
        self._setregs(regs)
        return True

    def _resume_to_syscall(self, first_sig: int = 0):
        """SYSEMU-resume until the next syscall-entry stop; emulate
        rdtsc SIGSEGVs and forward other signals along the way."""
        deliver = first_sig
        while True:
            _ptrace(SYSEMU, self.pid, None,
                    ctypes.c_void_p(deliver) if deliver else None)
            deliver = 0
            sig = self._wait()
            if sig == SYSCALL_TRAP:
                regs = self._getregs()
                nr = ctypes.c_long(regs.orig_rax).value
                args = (regs.rdi, regs.rsi, regs.rdx, regs.r10,
                        regs.r8, regs.r9)
                return nr, args
            if sig == signal.SIGSEGV and self.emulate_tsc \
                    and self._try_emulate_tsc():
                continue
            if sig == signal.SIGTRAP:
                continue                       # exec stop etc.
            deliver = sig                      # forward to the tracee

    def _run_native(self) -> None:
        """Re-execute the suppressed syscall natively (rewind %rip to
        the `syscall` instruction, then two PTRACE_SYSCALL hops:
        entry stop, real execution, exit stop)."""
        regs = self._getregs()
        regs.rax = regs.orig_rax
        regs.rip -= 2
        self._setregs(regs)
        for _ in range(2):
            deliver = 0
            while True:
                _ptrace(SYSCALL, self.pid, None,
                        ctypes.c_void_p(deliver) if deliver else None)
                deliver = 0
                sig = self._wait()
                if sig == SYSCALL_TRAP:
                    break
                if sig == signal.SIGSEGV and self.emulate_tsc \
                        and self._try_emulate_tsc():
                    continue
                if sig == signal.SIGTRAP:
                    continue
                deliver = sig              # forward real faults/signals

    # -- thread main ----------------------------------------------------
    def run(self) -> None:
        while True:
            cmd, payload = self.cmds.get()
            try:
                if cmd == "spawn":
                    # NO os.fork() of the (JAX-threaded) simulator: a
                    # non-exec fork with runtime threads holding locks
                    # is a deadlock risk. Instead the child is spawned
                    # via subprocess (vfork+exec) running the launcher
                    # stub, which applies the pre-exec settings
                    # (PR_SET_TSC survives execve, ASLR already off via
                    # inherited personality) and SIGSTOPs itself; this
                    # tracer thread PTRACE_SEIZEs it there and resumes
                    # to the PTRACE_EVENT_EXEC stop of the real
                    # program. Reference: utility/fork_proxy.c solves
                    # the same hazard with a pre-forked proxy.
                    pid = self._spawn_seize()
                    self._patch_vdso()
                    self.replies.put(("pid", pid))
                elif cmd == "step":
                    result, native, sim_ns = payload
                    self.sim_ns = sim_ns
                    if native:
                        self._run_native()
                    elif result is not None:
                        regs = self._getregs()
                        regs.rax = result & 0xFFFFFFFFFFFFFFFF
                        self._setregs(regs)
                    nr, args = self._resume_to_syscall()
                    self.replies.put(("syscall", nr, args))
                elif cmd == "kill":
                    if self.pid is not None and not self.exited.is_set():
                        try:
                            os.kill(self.pid, signal.SIGKILL)
                        except ProcessLookupError:
                            pass
                        try:
                            while True:
                                self._wait()
                        except _TraceeExited as e:
                            self.exited.set()
                            self.replies.put(("exit", e.code))
                            continue
                    self.replies.put(("exit", -1))
                elif cmd == "quit":
                    return
            except _TraceeExited as e:
                self.exited.set()
                self.replies.put(("exit", e.code))
            except OSError as e:
                self.exited.set()
                self.replies.put(("error", str(e)))


class PtraceProcess(ManagedProcess):
    """A real executable driven by PTRACE_SYSEMU instead of the
    preload shim (same app interface, same SyscallHandler)."""

    supports_threads = False       # SYSEMU multi-tracee: roadmap
    supports_fork = False          # fork needs the preload channel
    supports_signals = False       # IPC_SIGNAL needs the preload shim

    def __init__(self, runtime, path: str, args, environment: str = ""):
        super().__init__(runtime, path, args, environment)
        self.tracer: Optional[_Tracer] = None
        self._pending: Optional[tuple] = None   # (result, native)
        self._native_pid: Optional[int] = None

    @property
    def native_pid(self):
        return self._native_pid

    # -- boot -----------------------------------------------------------
    def boot(self, ctx) -> None:
        from shadow_tpu.host.descriptors import DescriptorTable
        from shadow_tpu.host.syscalls import SyscallHandler

        self.host = ctx.host
        self.manager = ctx._m
        self.table = DescriptorTable(self.manager)
        self.handler = SyscallHandler(self)

        host_dir, stdout_path, stderr_path = self._host_paths()
        env = self._child_env(host_dir)

        self.tracer = _Tracer(
            argv=[self.path] + self.args, env=env, cwd=host_dir,
            stdout_path=stdout_path, stderr_path=stderr_path)
        self.tracer.start()
        self.tracer.cmds.put(("spawn", None))
        kind, *rest = self.tracer.replies.get(timeout=30)
        if kind != "pid":
            raise RuntimeError(f"ptrace spawn failed: {rest}")
        pid = rest[0]
        self.mem = ProcessMemory(pid)
        from shadow_tpu.host.memmap import ProcessMaps
        self.maps = ProcessMaps(pid)
        self.maps.refresh()
        self._native_pid = pid
        self.alive = True
        # single pseudo-thread: park/resume and per-syscall state flow
        # through the same thread objects as the preload backend
        from shadow_tpu.host.process import ManagedThread
        main = ManagedThread(self, self.vpid, None)
        self.threads = {self.vpid: main}
        self.current = main
        self._pending = (None, False)
        log.debug("ptrace-spawned %s pid=%d vpid=%d on %s", self.path,
                  pid, self.vpid, self.host.name)
        self._continue(ctx)

    # -- transport ------------------------------------------------------
    def _reply(self, res, nr: int, args) -> None:
        if res is NATIVE:
            self._pending = (None, True)
        else:
            self._pending = (int(res), False)

    def _continue(self, ctx, th=None) -> None:
        while True:
            result, native = self._pending or (None, False)
            self._pending = None
            self.tracer.cmds.put(("step", (result, native, ctx.now)))
            try:
                reply = self.tracer.replies.get(
                    timeout=RECV_TIMEOUT_MS / 1000)
            except queue.Empty:
                log.warning("%s pid=%s unresponsive for %ds; killing",
                            self.path, self._native_pid,
                            RECV_TIMEOUT_MS // 1000)
                self._kill(ctx)
                return
            kind = reply[0]
            if kind == "exit":
                self.tracer.exited.set()
                if self.exit_code is None:
                    self.exit_code = reply[1]
                self._finalize_exit(ctx)
                return
            if kind == "error":
                log.warning("tracer error on %s: %s", self.path,
                            reply[1])
                self._kill(ctx)
                return
            _, nr, args = reply
            name = NR_NAME.get(nr, str(nr))
            self.syscall_counts[name] = \
                self.syscall_counts.get(name, 0) + 1
            try:
                res = self.handler.dispatch(ctx, nr, args)
            except Blocked as b:
                self._pending = (None, False)
                self._park(ctx, b, nr, args)
                return
            except Exception:
                log.exception("syscall %s(%s) handler crashed", name,
                              args)
                res = -38
            self._reply(res, nr, args)
            self.syscall_state = {}

    # (_resume_task is inherited: the parent's park/resume logic calls
    # our _reply/_continue overrides.)

    # -- teardown -------------------------------------------------------
    def _finalize_exit(self, ctx) -> None:
        if not self.alive:
            return
        self.alive = False
        log.debug("%s on %s exited code=%s (%d syscalls, ptrace)",
                  self.path, self.host.name, self.exit_code,
                  sum(self.syscall_counts.values()))
        if self.table is not None:
            self.table.close_all(ctx)
        if self.tracer is not None:
            self.tracer.cmds.put(("quit", None))

    def _kill(self, ctx) -> None:
        if not self.alive or self.tracer is None:
            return
        # kill(2) is not a ptrace request: send it directly so a tracee
        # spinning in userspace (tracer blocked in waitpid) still dies.
        try:
            os.kill(self._native_pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            pass
        self.tracer.cmds.put(("kill", None))
        try:
            reply = self.tracer.replies.get(timeout=10)
            if self.exit_code is None and reply[0] == "exit":
                self.exit_code = reply[1]
        except queue.Empty:
            pass
        self._finalize_exit(ctx)
