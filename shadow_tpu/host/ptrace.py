"""Ptrace interposition backend (PTRACE_SYSEMU, multi-tracee).

The rebuild of the reference's second interposition method
(src/main/host/thread_ptrace.c): instead of a preloaded shim funneling
trapped syscalls over shared-memory IPC, the simulator ptrace-attaches
to the managed process and drives it with PTRACE_SYSEMU — every
syscall stops the tracee *before* execution and the kernel suppresses
it, so the simulator can emulate it (poke the result into %rax) or
re-execute it natively (rewind %rip over the 2-byte `syscall`
instruction and step through with PTRACE_SYSCALL — the reference's
"deliver to native" path, thread_ptrace.c:1074 onward).

Linux requires every ptrace request (and the waitpid noticing tracee
stops) to come from the tracer task itself, so each process TREE owns
a dedicated tracer thread holding the fork/exec, the SYSEMU loop, and
all register access; the simulation threads talk to it over a command
queue. This mirrors the reference's per-worker fork-proxy +
tracer-affinity workarounds (thread_ptrace.c:39-56,
utility/fork_proxy.c).

Threads (thread_ptrace.c:36-56's multi-tracee waitpid machinery):
PTRACE_O_TRACECLONE auto-attaches cloned threads to the same tracer;
the suppressed clone is re-executed natively, the event stop yields
the new tid, the child is held at its initial stop until the simulator
schedules it, and the clone return + (SET/CLEAR)TID words are rewritten
to the child's VIRTUAL tid — getpid/gettid/tgkill stay fully virtual,
exactly like the preload backend. Each ManagedThread maps to one
native tid; one thread of a process runs at a time (strict ping-pong).

Fork: PTRACE_O_TRACEFORK catches the new PROCESS the same way (vfork
is rewritten to fork at re-execution — same COW degradation as the
preload shim); the child PtraceProcess shares the parent's tracer
thread, commands routed by native tid.

Signals: virtual queues/masks/dispositions live in ManagedProcess
(signal.c analogue); DELIVERY uses the kernel — rt_sigaction is
recorded virtually and also installed natively, and a deliverable
virtual signal is injected at a syscall boundary via
PTRACE_SYSEMU(sig): the kernel builds the real handler frame, the
handler's own syscalls trap through the normal funnel, and
rt_sigreturn runs native. A parked (blocked) syscall interrupted by a
signal gets -EINTR poked (or %rip rewound for SA_RESTART) before the
injection resumes it — the reference delivers through the shim's
process_signals instead (thread_ptrace.c handles the same cases with
its own pending-signal forwarding).

TSC emulation (src/lib/tsc/tsc.c): the child sets
prctl(PR_SET_TSC, PR_TSC_SIGSEGV) before exec (the flag survives
execve), so `rdtsc`/`rdtscp` raise SIGSEGV; the tracer decodes the
instruction at %rip, writes a deterministic cycle count derived from
simulated time into %edx:%eax (nominal 1 GHz ⇒ cycles == nanoseconds),
advances %rip, and resumes — plugin time reads are pure functions of
sim time, like the reference's Tsc_emulateRdtsc.
"""

from __future__ import annotations

import ctypes
import os
import queue
import signal
import struct
import threading
from typing import Optional

from shadow_tpu.host.process import (
    ManagedProcess,
    ManagedThread,
    RECV_TIMEOUT_MS,
    _NO_RESTART,
)
from shadow_tpu.host.memory import ProcessMemory
from shadow_tpu.host.syscalls import (APPLIED, NATIVE, NR_NAME,
                                      Blocked, FatalDivergence)
from shadow_tpu.utils.slog import get_logger

log = get_logger("ptrace")

_libc = ctypes.CDLL(None, use_errno=True)
_libc.ptrace.restype = ctypes.c_long
_libc.ptrace.argtypes = [ctypes.c_long, ctypes.c_long,
                         ctypes.c_void_p, ctypes.c_void_p]

# ptrace requests
# (TRACEME/SETOPTIONS are gone with the old fork path: the tracee is
# spawned via the launcher stub and PTRACE_SEIZEd with options)
CONT = 7
GETREGS = 12
SETREGS = 13
SYSCALL = 24
SYSEMU = 31
POKEDATA = 5
SEIZE = 0x4206              # PTRACE_SEIZE
GETEVENTMSG = 0x4201
GET_SYSCALL_INFO = 0x420E   # PTRACE_GET_SYSCALL_INFO (kernel 5.3+)

OPT_SYSGOOD = 0x1           # PTRACE_O_TRACESYSGOOD
OPT_TRACEFORK = 0x2
OPT_TRACEVFORK = 0x4
OPT_TRACECLONE = 0x8
OPT_TRACEEXEC = 0x10
OPT_TRACEEXIT = 0x40
OPT_EXITKILL = 0x00100000   # PTRACE_O_EXITKILL

EVENT_FORK = 1
EVENT_VFORK = 2
EVENT_CLONE = 3
EVENT_EXEC = 4
EVENT_EXIT = 6

WALL = 0x40000000           # __WALL: wait for clone children too

SYSCALL_TRAP = signal.SIGTRAP | 0x80     # sysgood syscall stop

NR_FORK = 57

# vDSO fast paths bypass the syscall instruction entirely, so SYSEMU
# never sees them; like rr, overwrite each exported vDSO function with
# an 8-byte real-syscall stub (mov eax, NR; syscall; ret) so plugin
# time reads become trappable syscalls. (The preload backend doesn't
# need this: LD_PRELOAD beats the libc symbols that call the vDSO.)
_VDSO_STUBS = {
    b"__vdso_clock_gettime": 228,
    b"__vdso_gettimeofday": 96,
    b"__vdso_time": 201,
    b"__vdso_clock_getres": 229,
    b"__vdso_getcpu": 309,
    b"clock_gettime": 228,
    b"gettimeofday": 96,
    b"time": 201,
    b"clock_getres": 229,
    b"getcpu": 309,
}


NOMINAL_TSC_HZ = 1_000_000_000           # 1 GHz: cycles == sim ns

# clone flag bits the tracer needs
CLONE_PARENT_SETTID = 0x00100000
CLONE_CHILD_CLEARTID = 0x00200000
CLONE_CHILD_SETTID = 0x01000000


class UserRegs(ctypes.Structure):
    _fields_ = [(n, ctypes.c_ulonglong) for n in (
        "r15", "r14", "r13", "r12", "rbp", "rbx", "r11", "r10",
        "r9", "r8", "rax", "rcx", "rdx", "rsi", "rdi", "orig_rax",
        "rip", "cs", "eflags", "rsp", "ss", "fs_base", "gs_base",
        "ds", "es", "fs", "gs")]


def _ptrace(req: int, pid: int, addr=None, data=None) -> int:
    ctypes.set_errno(0)
    r = _libc.ptrace(req, pid, addr, data)
    if r == -1:
        err = ctypes.get_errno()
        if err:
            raise OSError(err, f"ptrace({req}, {pid}): "
                          f"{os.strerror(err)}")
    return r


def _decode_wstatus(status: int) -> int:
    if os.WIFEXITED(status):
        return os.WEXITSTATUS(status)
    if os.WIFSIGNALED(status):
        return 128 + os.WTERMSIG(status)
    return -1


PATH_ARG = object()     # _inject_syscall: substitute the scratch path


class _TraceeExited(Exception):
    """A specific tracee (thread or whole process) died."""

    def __init__(self, tid: int, code: int):
        self.tid = tid
        self.code = code


class _Tracer(threading.Thread):
    """Owns all ptrace operations for one tracee TREE (a process and
    every thread/fork descendant auto-attached to it).

    Commands (cmd, payload) on self.cmds; replies on self.replies:
      spawn  -> ("pid", pid) | ("error", msg)
      step   -> payload (tid, result|None, native, rewind, inject,
                sim_ns); applies the pending result (or rewinds %rip
                for a restart), resumes — injecting `inject` as a real
                signal if nonzero — and replies
                ("syscall", tid, nr, args, execd) |
                ("dead", tid, code) | ("error", msg)
      clone  -> (tid, new_vid, kind): natively re-executes the
                suppressed clone/fork at tid's entry stop, captures the
                auto-attached child at its first stop, rewrites the
                parent return + tid words to the virtual id, and
                replies ("cloned", new_tid) | ("clone_fail", err)
      kill   -> (tids,): SIGKILL + reap every given tid;
                replies ("killed", code)
    """

    def __init__(self, argv, env, cwd, stdout_path, stderr_path,
                 emulate_tsc: bool = True):
        super().__init__(daemon=True)
        self.argv = argv
        self.env = env
        self.cwd = cwd
        self.stdout_path = stdout_path
        self.stderr_path = stderr_path
        self.emulate_tsc = emulate_tsc
        self.cmds: queue.Queue = queue.Queue()
        self.replies: queue.Queue = queue.Queue()
        self.pid: Optional[int] = None
        self.tracees: set[int] = set()
        self.group: dict[int, int] = {}     # tid -> its leader pid
        self.sim_ns = 0
        self._execd = False

    # -- spawn + seize (replaces the old fork/TRACEME path) ------------
    def _spawn_seize(self) -> int:
        """Popen the launcher stub, wait for its self-SIGSTOP, SEIZE
        it from THIS thread (all later ptrace requests must come from
        the seizing thread), resume, and run to the real program's
        PTRACE_EVENT_EXEC stop."""
        import subprocess
        import time as _time

        from shadow_tpu import native as _native

        launcher = [_native.launcher_path()]
        if not self.emulate_tsc:
            launcher.append("--no-tsc")
        out = open(self.stdout_path, "wb")
        err = open(self.stderr_path, "wb")
        try:
            proc = subprocess.Popen(
                launcher + self.argv,
                env=self.env, cwd=self.cwd, stdout=out, stderr=err,
                stdin=subprocess.DEVNULL)
        finally:
            out.close()
            err.close()
        pid = proc.pid
        self.pid = pid
        self._popen = proc          # keeps the zombie reapable

        # the launcher raise(SIGSTOP)s itself; as its parent we see
        # the stop (or an early death) in one blocking wait
        _, status = os.waitpid(pid, os.WUNTRACED)
        if os.WIFEXITED(status):
            raise _TraceeExited(pid, os.WEXITSTATUS(status))
        if os.WIFSIGNALED(status):
            raise _TraceeExited(pid, 128 + os.WTERMSIG(status))

        _ptrace(SEIZE, pid, None,
                ctypes.c_void_p(OPT_SYSGOOD | OPT_EXITKILL |
                                OPT_TRACEEXEC | OPT_TRACECLONE |
                                OPT_TRACEFORK | OPT_TRACEVFORK |
                                OPT_TRACEEXIT))
        # consume the post-SEIZE ptrace (group-)stop notification if
        # the kernel reports one before we resume; a CONT issued in
        # the stop-to-ptrace-trap transition window returns ESRCH,
        # which the retry below also absorbs
        t0 = _time.monotonic()
        while _time.monotonic() - t0 < 2.0:
            r, st = os.waitpid(pid, os.WNOHANG)
            if r == pid:
                # a tracee killed in this window must surface its exit
                # code, not a stale-pid SIGCONT failure
                if os.WIFEXITED(st):
                    raise _TraceeExited(pid, os.WEXITSTATUS(st))
                if os.WIFSIGNALED(st):
                    raise _TraceeExited(pid, 128 + os.WTERMSIG(st))
                break
            _time.sleep(0.001)
        os.kill(pid, signal.SIGCONT)

        def cont(sig: int) -> None:
            for _ in range(500):
                try:
                    _ptrace(CONT, pid, None,
                            ctypes.c_void_p(sig) if sig else None)
                    return
                except OSError:
                    _time.sleep(0.001)
            raise OSError(f"pid={pid}: PTRACE_CONT kept failing")

        # run the stub to the exec of the real program
        deliver = 0
        while True:
            cont(deliver)
            deliver = 0
            _, status = os.waitpid(pid, 0)
            if os.WIFEXITED(status):
                raise _TraceeExited(pid, os.WEXITSTATUS(status))
            if os.WIFSIGNALED(status):
                raise _TraceeExited(pid, 128 + os.WTERMSIG(status))
            if (status >> 8) == (signal.SIGTRAP | (EVENT_EXEC << 8)):
                break               # the real program's first moment
            sig = os.WSTOPSIG(status)
            if sig not in (signal.SIGSTOP, signal.SIGCONT,
                           signal.SIGTRAP):
                deliver = sig
        self.tracees.add(pid)
        self.group[pid] = pid
        return pid

    # -- vDSO patching (tracer thread, at an exec stop) ----------------
    def _patch_vdso(self, pid: Optional[int] = None) -> None:
        try:
            self._patch_vdso_inner(pid if pid is not None
                                   else self.pid)
        except Exception as e:     # malformed ELF must not kill the
            log.warning("vdso patch skipped: %s", e)   # tracer thread

    def _patch_vdso_inner(self, pid: int) -> None:
        base = size = None
        try:
            with open(f"/proc/{pid}/maps") as f:
                for line in f:
                    if "[vdso]" in line:
                        lo, hi = line.split()[0].split("-")
                        base, size = int(lo, 16), \
                            int(hi, 16) - int(lo, 16)
                        break
        except OSError:
            return
        if base is None:
            return
        try:
            img = ProcessMemory(pid).read(base, size)
        except OSError:
            return
        if img[:4] != b"\x7fELF":
            return
        # locate .dynsym / .dynstr via the section headers
        e_shoff, = struct.unpack_from("<Q", img, 0x28)
        e_shentsize, e_shnum = struct.unpack_from("<HH", img, 0x3A)
        dynsym = dynstr = None
        for i in range(e_shnum):
            off = e_shoff + i * e_shentsize
            if off + 64 > len(img):
                return
            sh_type, = struct.unpack_from("<I", img, off + 4)
            sh_offset, sh_size = struct.unpack_from("<QQ", img,
                                                    off + 0x18)
            sh_entsize, = struct.unpack_from("<Q", img, off + 0x38)
            if sh_type == 11:                      # SHT_DYNSYM
                dynsym = (sh_offset, sh_size, sh_entsize)
                sh_link, = struct.unpack_from("<I", img, off + 0x28)
                loff = e_shoff + sh_link * e_shentsize
                dynstr, = struct.unpack_from("<Q", img, loff + 0x18)
        if dynsym is None or dynstr is None:
            return
        soff, ssize, sent = dynsym
        patched = 0
        for off in range(soff, soff + ssize, sent or 24):
            st_name, = struct.unpack_from("<I", img, off)
            st_value, = struct.unpack_from("<Q", img, off + 8)
            if not st_name or not st_value:
                continue
            end = img.index(b"\0", dynstr + st_name)
            name = img[dynstr + st_name:end]
            nr = _VDSO_STUBS.get(name)
            if nr is None:
                continue
            stub = bytes([0xB8]) + struct.pack("<I", nr) \
                + b"\x0f\x05\xc3"
            word, = struct.unpack("<q", stub)
            try:
                _ptrace(POKEDATA, pid,
                        ctypes.c_void_p(base + st_value),
                        ctypes.c_void_p(word & (2**64 - 1)))
                patched += 1
            except OSError as e:
                log.debug("vdso patch %s failed: %s", name, e)
        log.debug("patched %d vDSO entries", patched)

    # -- tracee helpers (tracer thread only) ----------------------------
    def _getregs(self, tid: int) -> UserRegs:
        regs = UserRegs()
        _ptrace(GETREGS, tid, None, ctypes.byref(regs))
        return regs

    def _setregs(self, tid: int, regs: UserRegs) -> None:
        _ptrace(SETREGS, tid, None, ctypes.byref(regs))

    def _geteventmsg(self, tid: int) -> int:
        v = ctypes.c_ulong()
        _ptrace(GETEVENTMSG, tid, None, ctypes.byref(v))
        return v.value

    def _wait(self, tid: int) -> tuple[str, int]:
        """waitpid classification: ("sig", stopsig) | ("event", ev);
        raises _TraceeExited on termination."""
        _, status = os.waitpid(tid, WALL)
        if os.WIFEXITED(status) or os.WIFSIGNALED(status):
            raise _TraceeExited(tid, _decode_wstatus(status))
        sig = os.WSTOPSIG(status)
        ev = status >> 16
        if sig == signal.SIGTRAP and ev:
            return ("event", ev)
        return ("sig", sig)

    def _on_event(self, tid: int, ev: int) -> None:
        """Events that can surface during any resume: exec re-patches
        the vDSO (new image) and is flagged to the simulator; a thread
        hitting EVENT_EXIT is let die and reported via _TraceeExited."""
        if ev == EVENT_EXEC:
            # patch the EXEC'ING process's fresh vDSO (tid may be a
            # forked child, not the root tracee)
            self._patch_vdso(tid)
            self._execd = True
            return
        if ev == EVENT_EXIT:
            wstatus = self._geteventmsg(tid)
            code = _decode_wstatus(wstatus)
            try:
                _ptrace(CONT, tid)
            except OSError:
                pass
            # reap the dead thread so it doesn't zombie — EXCEPT a
            # thread-group leader with siblings still alive: waitpid
            # on a zombie leader blocks until the whole group dies
            leader = self.group.get(tid) == tid
            siblings = any(t != tid and self.group.get(t) == tid
                           for t in self.tracees)
            if not (leader and siblings):
                try:
                    os.waitpid(tid, WALL)
                except ChildProcessError:
                    pass
            raise _TraceeExited(tid, code)

    def _try_emulate_tsc(self, tid: int) -> bool:
        """At a SIGSEGV stop: if %rip is rdtsc/rdtscp, emulate it."""
        regs = self._getregs(tid)
        try:
            code = ProcessMemory(tid).read(regs.rip, 3)
        except OSError:
            return False
        cycles = self.sim_ns  # 1 GHz nominal
        if code[:2] == b"\x0f\x31":                    # rdtsc
            regs.rip += 2
        elif code[:3] == b"\x0f\x01\xf9":              # rdtscp
            regs.rip += 3
            regs.rcx = 0                               # IA32_TSC_AUX
        else:
            return False
        regs.rax = cycles & 0xFFFFFFFF
        regs.rdx = (cycles >> 32) & 0xFFFFFFFF
        self._setregs(tid, regs)
        return True

    def _resume_to_syscall(self, tid: int, first_sig: int = 0):
        """SYSEMU-resume until the next syscall-entry stop; emulate
        rdtsc SIGSEGVs and forward other signals along the way."""
        deliver = first_sig
        while True:
            _ptrace(SYSEMU, tid, None,
                    ctypes.c_void_p(deliver) if deliver else None)
            deliver = 0
            kind, v = self._wait(tid)
            if kind == "event":
                self._on_event(tid, v)
                continue
            sig = v
            if sig == SYSCALL_TRAP:
                regs = self._getregs(tid)
                nr = ctypes.c_long(regs.orig_rax).value
                args = (regs.rdi, regs.rsi, regs.rdx, regs.r10,
                        regs.r8, regs.r9)
                return nr, args
            if sig == signal.SIGSEGV and self.emulate_tsc \
                    and self._try_emulate_tsc(tid):
                continue
            if sig in (signal.SIGTRAP, signal.SIGSTOP,
                       signal.SIGCHLD):
                # exec / initial stops; real SIGCHLD from dead native
                # children is swallowed — the VIRTUAL signal layer
                # owns SIGCHLD (real arrival times are wall-clock)
                continue
            deliver = sig                  # forward to the tracee

    def _stop_op(self, tid: int) -> int:
        """PTRACE_GET_SYSCALL_INFO op at a syscall trap:
        1 = entry stop, 2 = exit stop, 0 = none."""
        buf = (ctypes.c_uint8 * 128)()
        _ptrace(GET_SYSCALL_INFO, tid, ctypes.c_void_p(128),
                ctypes.byref(buf))
        return buf[0]

    def _run_to_exit(self, tid: int, on_clone_event=None) -> None:
        """From a SYSEMU entry stop whose %rip was rewound:
        PTRACE_SYSCALL until the re-issued syscall's TRUE exit stop.
        Resuming a SYSEMU entry stop with PTRACE_SYSCALL first
        reports a GHOST exit stop for the suppressed call (no
        execution happened); GET_SYSCALL_INFO distinguishes it — the
        real exit is the first exit stop AFTER a real entry stop.
        Clone/fork events between entry and exit go to
        `on_clone_event` (the new tid capture); everything else is
        serviced as usual."""
        deliver = 0
        seen_entry = False
        while True:
            _ptrace(SYSCALL, tid, None,
                    ctypes.c_void_p(deliver) if deliver else None)
            deliver = 0
            kind, v = self._wait(tid)
            if kind == "event":
                if on_clone_event is not None and \
                        v in (EVENT_FORK, EVENT_VFORK, EVENT_CLONE):
                    on_clone_event(self._geteventmsg(tid))
                else:
                    self._on_event(tid, v)
                continue
            if v == SYSCALL_TRAP:
                op = self._stop_op(tid)
                if op == 1:
                    seen_entry = True
                elif op == 2 and seen_entry:
                    return
                # else: the suppressed call's ghost exit stop
                continue
            if v == signal.SIGSEGV and self.emulate_tsc \
                    and self._try_emulate_tsc(tid):
                continue
            if v in (signal.SIGTRAP, signal.SIGSTOP,
                     signal.SIGCHLD):
                continue               # see _resume_to_syscall
            deliver = v                # forward real faults/signals

    def _run_native(self, tid: int) -> None:
        """Re-execute the suppressed syscall natively: rewind %rip to
        the `syscall` instruction (restoring %rax = the nr) and run to
        the real exit stop."""
        regs = self._getregs(tid)
        regs.rax = regs.orig_rax
        regs.rip -= 2
        self._setregs(tid, regs)
        self._run_to_exit(tid)

    def _inject_syscall(self, tid: int, nr: int, args,
                        path: Optional[bytes] = None) -> int:
        """Execute an EXTRA syscall in the tracee at its current
        suppressed-entry (or post-native exit) stop, then restore its
        registers exactly. `path` (if given) is written into dead
        stack space beyond the red zone and substitutes any arg equal
        to the PATH_ARG sentinel. The ref reaches the same effect
        through its shim IPC native-syscall channel; under ptrace the
        registers are ours to borrow. Returns the syscall's result."""
        saved = self._getregs(tid)
        regs = self._getregs(tid)
        argv = list(args)
        if path is not None:
            scratch = (saved.rsp - 256 - len(path) - 1) & ~0xF
            ProcessMemory(tid).write(scratch, path + b"\x00")
            argv = [scratch if a is PATH_ARG else a for a in argv]
        regs.rax = nr
        regs.rip = saved.rip - 2        # the syscall insn
        for reg, val in zip(("rdi", "rsi", "rdx", "r10", "r8", "r9"),
                            argv):
            setattr(regs, reg, val & 0xFFFFFFFFFFFFFFFF)
        self._setregs(tid, regs)
        self._run_to_exit(tid)
        out = self._getregs(tid)
        res = ctypes.c_long(out.rax).value
        self._setregs(tid, saved)       # exactly as we found it
        return res

    # -- clone / fork (TRACECLONE/TRACEFORK auto-attach) ----------------
    def _do_clone(self, tid: int, new_vid: int, kind: str,
                  flags: int, ptid: int, ctid: int,
                  stack: int) -> None:
        """At tid's suppressed clone/clone3/fork entry stop:
        re-execute natively, capture the auto-attached child at its
        initial stop, hold it there, and rewrite the parent's return
        value (and the PARENT_SETTID / CHILD_SETTID words) to the
        VIRTUAL id. flags/ptid/ctid/stack are pre-parsed by the
        syscall layer (registers for clone, struct clone_args for
        clone3). vfork is rewritten to fork — the parent must not
        block on the child (the preload shim applies the same COW
        degradation)."""
        entry = self._getregs(tid)
        if kind == "fork":
            # EVERY fork-style creation is re-issued as a plain COW
            # fork: vfork and CLONE_VFORK/CLONE_VM clones (glibc
            # posix_spawn/system) would block the parent until the
            # child execs — but the child is held at its auto-attach
            # stop, deadlocking the tracer; and a shared-VM "fork"
            # would corrupt the COW child the simulator models. The
            # (SET/CLEAR)TID effects glibc expects are applied below
            # from the ORIGINAL flags. Same degradation as the
            # preload shim's fork normalization.
            entry.rax = NR_FORK
        else:
            entry.rax = entry.orig_rax
        entry.rip -= 2
        self._setregs(tid, entry)

        new_tid = [None]
        self._run_to_exit(tid, on_clone_event=lambda t:
                          new_tid.__setitem__(0, t))

        regs = self._getregs(tid)
        real = ctypes.c_long(regs.rax).value
        if real < 0:
            self.replies.put(("clone_fail", real))
            return
        if new_tid[0] is None:
            # the kernel created a child but TRACECLONE never reported
            # it: a live, UNTRACED native task now exists outside the
            # simulation. Reporting EAGAIN to the app would paper over
            # the divergence — kill and fail loudly (the caller raises
            # FatalDivergence; the run aborts). For a missed THREAD,
            # `real` is a non-leader tid that kill(2) can't address
            # (and SIGKILL is group-directed anyway) — take down the
            # whole tracee group via its leader.
            target = self.group.get(tid, tid) if kind == "thread" \
                else real
            try:
                os.kill(target, signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                pass
            self.replies.put((
                "error",
                f"clone returned id {real} but no PTRACE clone "
                "event was captured; stray native task killed"))
            return
        child = int(new_tid[0])
        # the auto-attached child is in (or headed to) its initial
        # stop; consume the notification so later waits are clean
        try:
            os.waitpid(child, WALL)
        except ChildProcessError:
            pass
        self.tracees.add(child)
        self.group[child] = self.group.get(tid, tid) \
            if kind == "thread" else child

        if kind == "fork" and stack:
            # a fork-style clone WITH a stack argument (glibc __clone:
            # posix_spawn/system): the child branch of clone.S pops
            # fn/arg off the NEW stack, but the fork rewrite left the
            # child on the parent's %rsp — redirect it to the
            # requested stack (glibc already pushed fn/arg there; the
            # COW copy has them). CLONE_VM's shared-memory error
            # reporting degrades with COW, like the vfork rewrite.
            cregs = self._getregs(child)
            cregs.rsp = stack
            self._setregs(child, cregs)

        # virtualize the visible ids: parent return, PARENT_SETTID
        # word (glibc's pd->tid for threads), CHILD_SETTID word (the
        # child's own copy — same address pre-CoW for threads, the
        # child's private page after fork)
        regs.rax = new_vid
        self._setregs(tid, regs)
        word = struct.pack("<i", new_vid) + b"\x00\x00\x00\x00"
        if flags & CLONE_PARENT_SETTID and ptid:
            try:
                ProcessMemory(tid).write(ptid, word[:4])
            except OSError:
                pass
        if flags & CLONE_CHILD_SETTID and ctid:
            try:
                ProcessMemory(child).write(ctid, word[:4])
            except OSError:
                pass
        self.replies.put(("cloned", child))

    # -- thread main ----------------------------------------------------
    def run(self) -> None:
        while True:
            cmd, payload = self.cmds.get()
            tid = None
            try:
                if cmd == "spawn":
                    # NO os.fork() of the (JAX-threaded) simulator: a
                    # non-exec fork with runtime threads holding locks
                    # is a deadlock risk. Instead the child is spawned
                    # via subprocess (vfork+exec) running the launcher
                    # stub, which applies the pre-exec settings
                    # (PR_SET_TSC survives execve, ASLR already off via
                    # inherited personality) and SIGSTOPs itself; this
                    # tracer thread PTRACE_SEIZEs it there and resumes
                    # to the PTRACE_EVENT_EXEC stop of the real
                    # program. Reference: utility/fork_proxy.c solves
                    # the same hazard with a pre-forked proxy.
                    pid = self._spawn_seize()
                    self._patch_vdso()
                    self.replies.put(("pid", pid))
                elif cmd == "step":
                    (tid, result, native, rewind, inject,
                     sim_ns) = payload
                    self.sim_ns = sim_ns
                    # clear the exec flag BEFORE any resume: a NATIVE
                    # execve fires EVENT_EXEC inside _run_native, and
                    # clearing afterwards would wipe it out of the
                    # reply (stale fd table / sigactions in the sim)
                    self._execd = False
                    if native:
                        self._run_native(tid)
                    elif rewind:
                        regs = self._getregs(tid)
                        regs.rax = regs.orig_rax
                        regs.rip -= 2
                        self._setregs(tid, regs)
                    elif result is not None:
                        regs = self._getregs(tid)
                        regs.rax = result & 0xFFFFFFFFFFFFFFFF
                        self._setregs(tid, regs)
                    nr, args = self._resume_to_syscall(tid, inject)
                    self.replies.put(("syscall", tid, nr, args,
                                      self._execd))
                elif cmd == "inject":
                    tid, nr, args, path = payload
                    self.replies.put(
                        ("injected",
                         self._inject_syscall(tid, nr, args, path)))
                elif cmd == "clone":
                    tid, new_vid, kind, flags, ptid, ctid, stack = \
                        payload
                    self._do_clone(tid, new_vid, kind, flags, ptid,
                                   ctid, stack)
                elif cmd == "kill":
                    tids = payload[0]
                    code = -1
                    for t in tids:
                        if t not in self.tracees:
                            continue
                        try:
                            os.kill(t, signal.SIGKILL)
                        except ProcessLookupError:
                            pass
                        # the tracee may be sitting in ANY ptrace stop
                        # (incl. EVENT_EXIT): service stops until the
                        # kill lands — a blocked waitpid on a stopped
                        # tracee would wedge the whole tracer
                        try:
                            while True:
                                try:
                                    k, v = self._wait(t)
                                    if k == "event":
                                        self._on_event(t, v)
                                    else:
                                        _ptrace(CONT, t, None,
                                                ctypes.c_void_p(
                                                    signal.SIGKILL))
                                except OSError:
                                    break
                        except (_TraceeExited, ChildProcessError) as e:
                            if isinstance(e, _TraceeExited) \
                                    and e.tid == t:
                                code = e.code
                        self.tracees.discard(t)
                        self.group.pop(t, None)
                    self.replies.put(("killed", code))
                elif cmd == "quit":
                    return
            except _TraceeExited as e:
                self.tracees.discard(e.tid)
                self.group.pop(e.tid, None)
                # an exit_group (or fatal signal) may have taken
                # siblings down with it: reap whatever else is dead
                self._drain_dead()
                self.replies.put(("dead", e.tid, e.code))
            except OSError as e:
                self.replies.put(("error", f"tid={tid}: {e}"))

    def _drain_dead(self) -> None:
        for t in list(self.tracees):
            try:
                r, status = os.waitpid(t, WALL | os.WNOHANG)
            except ChildProcessError:
                self.tracees.discard(t)
                self.group.pop(t, None)
                continue
            if r == t and (os.WIFEXITED(status)
                           or os.WIFSIGNALED(status)):
                self.tracees.discard(t)
                self.group.pop(t, None)


class PtraceProcess(ManagedProcess):
    """A real executable driven by PTRACE_SYSEMU instead of the
    preload shim (same app interface, same SyscallHandler)."""

    supports_threads = True        # TRACECLONE multi-tracee SYSEMU
    supports_fork = True           # TRACEFORK (shared tracer thread)
    supports_signals = True        # kernel injection at boundaries
    supports_exec = True           # native execve under TRACEEXEC
    signal_style = "inject"        # vs the preload backend's "ipc"
    interpose_style = "ptrace"

    def __init__(self, runtime, path: str, args, environment: str = ""):
        super().__init__(runtime, path, args, environment)
        self.tracer: Optional[_Tracer] = None
        self._native_pid: Optional[int] = None
        # a death (or tracer wedge) observed by inject_syscall, to be
        # finalized by the next _continue with its full machinery
        self._inject_death: Optional[tuple] = None

    @property
    def native_pid(self):
        return self._native_pid

    # -- boot -----------------------------------------------------------
    def boot(self, ctx) -> None:
        from shadow_tpu.host.descriptors import DescriptorTable
        from shadow_tpu.host.syscalls import SyscallHandler

        self.host = ctx.host
        self.manager = ctx._m
        self.table = DescriptorTable(self.manager, owner=self)
        self.handler = SyscallHandler(self)

        host_dir, stdout_path, stderr_path = self._host_paths()
        env = self._child_env(host_dir)

        self.tracer = _Tracer(
            argv=[self.path] + self.args, env=env, cwd=host_dir,
            stdout_path=stdout_path, stderr_path=stderr_path)
        self.tracer.start()
        self.tracer.cmds.put(("spawn", None))
        kind, *rest = self.tracer.replies.get(timeout=30)
        if kind != "pid":
            raise RuntimeError(f"ptrace spawn failed: {rest}")
        pid = rest[0]
        self.mem = ProcessMemory(pid)
        from shadow_tpu.host.memmap import ProcessMaps
        self.maps = ProcessMaps(pid)
        self.maps.refresh()
        self._native_pid = pid
        self.alive = True
        main = ManagedThread(self, self.vpid, None)
        main.native_tid = pid
        main._pt_pending = (None, False, False)
        main._pt_inject = 0
        self.threads = {self.vpid: main}
        self.current = main
        log.debug("ptrace-spawned %s pid=%d vpid=%d on %s", self.path,
                  pid, self.vpid, self.host.name)
        self._continue(ctx, main)

    # -- managed threads (TRACECLONE flavor of spawn_thread) ------------
    def spawn_thread(self, ctx, flags: int, args,
                     parsed: Optional[tuple] = None):
        """`parsed` = (ptid, ctid, stack) pre-extracted from a clone3
        struct; for classic clone they come from the register args."""
        ptid, ctid, stack = parsed if parsed is not None else \
            (args[2], args[3], args[1])
        vtid = self.runtime.next_vpid()
        cur = self.current
        self.tracer.cmds.put(("clone",
                              (cur.native_tid, vtid, "thread",
                               flags, ptid, ctid, stack)))
        try:
            reply = self.tracer.replies.get(
                timeout=RECV_TIMEOUT_MS / 1000)
        except queue.Empty:
            raise RuntimeError(
                "tracer unresponsive during clone") from None
        if reply[0] == "clone_fail":
            return reply[1]
        if reply[0] == "dead":
            # the tracee died mid-clone (fatal signal during the
            # native re-execution): surface the real exit
            if self.exit_code is None:
                self.exit_code = reply[2]
            self._finalize_exit(ctx)
            return APPLIED          # process gone; nothing to apply
        if reply[0] == "error":
            # kernel/simulator divergence (e.g. stray untraced child,
            # ADVICE r4 #3): not recoverable as EAGAIN
            raise FatalDivergence(f"clone under ptrace: {reply[1]}")
        if reply[0] != "cloned":
            log.warning("clone under ptrace failed: %s", reply)
            return -11              # EAGAIN
        th = ManagedThread(self, vtid, None)
        th.native_tid = reply[1]
        th._pt_pending = (None, False, False)
        th._pt_inject = 0
        th.sigmask = cur.sigmask     # clone inherits the mask
        if flags & CLONE_CHILD_CLEARTID:
            th.clear_ctid = ctid
        self.threads[vtid] = th
        self._push_task(ctx.now,
                        lambda ctx2, ev: self._start_child(ctx2, th))
        log.debug("ptrace clone: vtid=%d tid=%d on %s", vtid,
                  th.native_tid, self.host.name)
        return APPLIED              # %rax already rewritten to vtid

    def _start_child(self, ctx, th: ManagedThread) -> None:
        """First scheduling of a cloned thread: SYSEMU it out of its
        initial stop into app code (no IPC announcement to wait for)."""
        if not self.alive or not th.alive:
            return
        self._continue(ctx, th)

    # -- fork (TRACEFORK flavor of spawn_fork) --------------------------
    def spawn_fork(self, ctx, flags: int = 0,
                   parsed: Optional[tuple] = None):
        ptid, ctid, stack = parsed if parsed is not None else (0, 0, 0)
        # a REAL constructor call (vs hand-copying __init__'s fields):
        # allocates the child vpid and every base field; the clone
        # below rewrites the parent's %rax to that vpid
        child = PtraceProcess(self.runtime, self.path, self.args,
                              self.environment)
        cur = self.current
        self.tracer.cmds.put(("clone",
                              (cur.native_tid, child.vpid, "fork",
                               flags, ptid, ctid, stack)))
        try:
            reply = self.tracer.replies.get(
                timeout=RECV_TIMEOUT_MS / 1000)
        except queue.Empty:
            raise RuntimeError(
                "tracer unresponsive during fork") from None
        if reply[0] == "clone_fail":
            return reply[1]
        if reply[0] == "dead":
            if self.exit_code is None:
                self.exit_code = reply[2]
            self._finalize_exit(ctx)
            return APPLIED
        if reply[0] == "error":
            raise FatalDivergence(f"fork under ptrace: {reply[1]}")
        if reply[0] != "cloned":
            log.warning("fork under ptrace failed: %s", reply)
            return -11
        child_pid = reply[1]

        # wire the already-running native child to the fresh object:
        # fork semantics — own fd table (shared descriptions), copied
        # dispositions, inherited mask, shared tracer thread
        from shadow_tpu.host.memmap import ProcessMaps
        from shadow_tpu.host.syscalls import SyscallHandler

        child.host = self.host
        child.manager = self.manager
        child._native_pid = child_pid
        child.mem = ProcessMemory(child_pid)
        child.table = self.table.fork_clone()
        child.handler = SyscallHandler(child)
        child.alive = True
        main = ManagedThread(child, child.vpid, None)
        main.native_tid = child_pid
        main._pt_pending = (None, False, False)
        main._pt_inject = 0
        main.sigmask = cur.sigmask
        child.threads = {child.vpid: main}
        child.current = main
        child.parent_proc = self
        child.maps = ProcessMaps(child_pid)
        child.sigactions = dict(self.sigactions)
        child.tracer = self.tracer      # SHARED tracer thread
        self.children[child.vpid] = child
        child._push_task(ctx.now,
                         lambda c2, ev: child._start_forked_ptrace(c2))
        log.debug("ptrace fork: vpid=%d -> child vpid=%d pid=%d on %s",
                  self.vpid, child.vpid, child_pid, self.host.name)
        return APPLIED              # parent %rax already = child vpid

    def _start_forked_ptrace(self, ctx) -> None:
        """First scheduling of a forked child: it resumes out of its
        initial stop inside the fork return path (kernel already set
        its %rax to 0)."""
        main = self.current
        if not self.alive or not main.alive:
            return
        self._continue(ctx, main)

    # -- signal delivery (kernel injection) -----------------------------
    def _next_inject(self, ctx, th: ManagedThread) -> Optional[int]:
        """Dequeue pending virtual signals until one has a real
        handler to inject; ignored signals are discarded, fatal
        defaults kill the process (returns None then)."""
        while self.alive and th.alive:
            sig = self._dequeue_deliverable(th)
            if sig is None:
                return None
            act = self.sigactions.get(sig)
            handler = act[0] if act else self.SIG_DFL
            if handler == self.SIG_IGN:
                continue
            if handler == self.SIG_DFL:
                if sig in self._DEFAULT_IGNORE:
                    continue
                log.debug("vpid=%d: fatal signal %d (default action)",
                          self.vpid, sig)
                self.term_signal = sig
                self.exit_code = 128 + sig
                self._kill(ctx)
                return None
            return sig
        return None

    def _interrupt_parked(self, ctx, th: ManagedThread) -> None:
        """A deliverable virtual signal interrupts a parked syscall:
        poke -EINTR (or rewind for SA_RESTART) and resume with the
        signal injected — the kernel builds the handler frame, the
        handler runs (its syscalls trap normally), rt_sigreturn
        restores, and the 'syscall' returns with our poked result (or
        re-issues itself after the rewind — kernel restart order)."""
        from shadow_tpu.host.syscalls import EINTR

        nr, args = th.parked
        th.parked = None
        sig = self._next_inject(ctx, th)
        if not self.alive or not th.alive:
            return
        if sig is None:
            th.parked = (nr, args)      # nothing actually deliverable
            return
        if th.restore_mask is not None:
            # sigsuspend epilogue: handler fires, original mask returns
            th.sigmask = th.restore_mask
            th.restore_mask = None
        th.sigwait = None
        act = self.sigactions.get(sig)
        restartable = nr not in _NO_RESTART
        if restartable and act is not None \
                and act[1] & self.SA_RESTART:
            th._pt_pending = (None, False, True)     # rewind+reissue
        else:
            th._pt_pending = (-EINTR, False, False)
        th._pt_inject = sig
        self.current = th
        th.syscall_state = {}
        self._continue(ctx, th)

    def inject_syscall(self, nr: int, args, path: bytes | None = None):
        """Run an extra syscall in the CURRENT thread at its suppressed
        entry stop (registers restored afterwards). Returns the result,
        or None on failure. Every reply is consumed IN PLACE — nothing
        is re-queued and no further commands are issued for a dead tid,
        so the shared tracer queue can never desync (sibling processes
        share one tracer). A death observed here is stashed and
        finalized by the next _continue. Used by the mmap handler to
        realize file-backed mappings of EMULATED fds through
        /proc/<simulator>/fd/<osfd> (ref mman.c:72-126)."""
        if self._inject_death is not None or not self.alive:
            return None
        self.tracer.cmds.put(("inject",
                              (self.current.native_tid, nr, list(args),
                               path)))
        try:
            reply = self.tracer.replies.get(
                timeout=RECV_TIMEOUT_MS / 1000)
        except queue.Empty:
            # wedged tracer: the next _continue's own timeout kills us;
            # record the desync so no further injects are attempted
            self._inject_death = ("timeout", None)
            return None
        if reply[0] == "injected":
            return reply[1]
        log.warning("inject_syscall(%d) failed: %s", nr, reply)
        if reply[0] == "dead":
            self._inject_death = (reply[1], reply[2])
        else:
            # a tracer error mid-inject may have left the tracee's
            # registers pointing at the injected syscall — resuming
            # it would be undefined; treat as fatal
            self._inject_death = ("error", None)
        return None

    # -- transport ------------------------------------------------------
    def _reply_to(self, th: ManagedThread, res) -> None:
        """Stage the result on the thread; the next step applies it.
        (Also the target of generic machinery like _complete_sigwait.)"""
        if th.restore_mask is not None:
            # a p-variant wait's temporary mask ends with the call
            th.sigmask = th.restore_mask
            th.restore_mask = None
        if res is NATIVE:
            th._pt_pending = (None, True, False)
        elif res is APPLIED:
            th._pt_pending = (None, False, False)
        else:
            th._pt_pending = (int(res), False, False)

    def _reply(self, res, nr: int, args) -> None:
        self._reply_to(self.current, res)

    def _continue(self, ctx, th: Optional[ManagedThread] = None) -> None:
        while True:
            if th is None:
                th = self.current
            pend = th._pt_pending or (None, False, False)
            th._pt_pending = None
            inject = th._pt_inject or 0
            th._pt_inject = 0
            # boundary delivery: pending virtual signals with real
            # handlers ride the resume as a kernel injection (one per
            # boundary; the rest follow at the handler's syscalls)
            if not inject and th.alive and self.alive \
                    and self._has_deliverable(th):
                s = self._next_inject(ctx, th)
                if not self.alive:
                    return
                if s:
                    inject = s
            result, native, rewind = pend
            death = self._inject_death
            if death is not None:
                # a failure observed mid-inject_syscall: finalize it
                # here with the normal reply machinery instead of
                # issuing more commands for a dead/wedged tracee
                self._inject_death = None
                if death[0] in ("timeout", "error"):
                    log.warning("%s pid=%s tracer %s during inject; "
                                "killing", self.path,
                                self._native_pid, death[0])
                    self._kill(ctx)
                    return
                reply = ("dead", death[0], death[1])
            else:
                self.tracer.cmds.put(("step",
                                      (th.native_tid, result, native,
                                       rewind, inject, ctx.now)))
                try:
                    reply = self.tracer.replies.get(
                        timeout=RECV_TIMEOUT_MS / 1000)
                except queue.Empty:
                    log.warning("%s pid=%s unresponsive for %ds; "
                                "killing", self.path, self._native_pid,
                                RECV_TIMEOUT_MS // 1000)
                    self._kill(ctx)
                    return
            kind = reply[0]
            if kind == "dead":
                _, tid, code = reply
                # an UNEXPECTED death (th still marked alive — no
                # sys_exit preceded it) is a fatal signal: the kernel
                # killed the WHOLE thread group, not one thread
                group_died = th.alive or self.exiting or \
                    not any(t.alive for t in self.threads.values()
                            if t is not th)
                if group_died:
                    if self.exit_code is None:
                        self.exit_code = code
                    if th.alive and code > 128:
                        self.term_signal = code - 128
                    self._finalize_exit(ctx)
                    return
                # a non-last thread's voluntary exit: CLEARTID +
                # joiner wakeups (kernel confirmed death — no guard
                # wait needed)
                self._finish_ptrace_thread_exit(ctx, th)
                return
            if kind == "error":
                log.warning("tracer error on %s: %s", self.path,
                            reply[1])
                self._kill(ctx)
                return
            _, tid, nr, args, execd = reply
            if execd:
                self._complete_exec_ptrace(ctx, th)
            elif getattr(self, "exec_pending", None) is not None:
                # a normal syscall after an approved execve means the
                # native exec failed — the old image lives on
                self.exec_pending = None
            name = NR_NAME.get(nr, str(nr))
            self.syscall_counts[name] = \
                self.syscall_counts.get(name, 0) + 1
            self.current = th
            try:
                res = self.handler.dispatch(ctx, nr, args)
            except Blocked as b:
                th._pt_pending = (None, False, False)
                self._park(ctx, b, nr, args)
                return
            except FatalDivergence:
                raise
            except Exception:
                log.exception("syscall %s(%s) handler crashed", name,
                              args)
                res = -38
            if not self.alive:
                # the handler finalized us (e.g. death mid-clone)
                return
            self._reply(res, nr, args)
            th.syscall_state = {}
            # an exiting thread's NATIVE exit executes on the next
            # loop turn and comes back as ("dead", ...)

    # (_resume_thread is inherited: the base park/resume logic calls
    # our _reply/_continue overrides.)

    def _finish_ptrace_thread_exit(self, ctx,
                                   th: ManagedThread) -> None:
        """The kernel cleared the native CLEARTID word and futex-woke
        it for real at thread death; mirror both into the EMULATED
        futex table so virtual pthread_join'ers wake."""
        if th.clear_ctid:
            try:
                self.mem.write(th.clear_ctid, struct.pack("<I", 0))
            except OSError:
                pass
            fx = self.futexes.get(th.clear_ctid)
            if fx is not None:
                fx.wake(ctx, 1 << 30)

    def _complete_exec_ptrace(self, ctx, th: ManagedThread) -> None:
        """A native execve succeeded (EVENT_EXEC seen): apply the
        shared exec rules and refresh the maps snapshot. The tracer
        already re-patched the new image's vDSO."""
        new_path = getattr(self, "exec_pending", None)
        if new_path is not None:
            log.debug("vpid=%d: execve -> %s (ptrace)", self.vpid,
                      new_path)
            self.exec_path = new_path
        self.exec_pending = None
        self._apply_exec_rules(ctx, th)
        if self.maps is not None:
            self.maps.dirty = True

    # -- teardown -------------------------------------------------------
    def _finalize_exit(self, ctx) -> None:
        if not self.alive:
            return
        self.alive = False
        for t in self.threads.values():
            t.alive = False
        log.debug("%s on %s exited code=%s (%d syscalls, ptrace)",
                  self.path, self.host.name, self.exit_code,
                  sum(self.syscall_counts.values()))
        if self.table is not None:
            self.table.close_all(ctx)
        for child in list(self.children.values()):
            if child.alive:
                child._kill(ctx)
        if self.term_signal is not None:
            self.wstatus = self.term_signal & 0x7F
        else:
            self.wstatus = ((self.exit_code or 0) & 0xFF) << 8
        if self.parent_proc is not None and self.parent_proc.alive:
            self.parent_proc.child_exited(ctx, self)
        # the LAST live process of the tracer's process TREE retires
        # the tracer thread (the root may well exit before a forked
        # child — the daemonize pattern)
        if self.tracer is not None:
            root = self
            while root.parent_proc is not None:
                root = root.parent_proc
            stack, any_alive = [root], False
            while stack and not any_alive:
                p = stack.pop()
                any_alive = p.alive
                stack.extend(p.children.values())
            if not any_alive:
                self.tracer.cmds.put(("quit", None))

    def _kill(self, ctx) -> None:
        if not self.alive or self.tracer is None:
            return
        tids = [t.native_tid for t in self.threads.values()
                if getattr(t, "native_tid", None) is not None]
        # kill(2) is not a ptrace request: send it directly so a tracee
        # spinning in userspace (tracer blocked in waitpid) still dies.
        for t in tids:
            try:
                os.kill(t, signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                pass
        self.tracer.cmds.put(("kill", (tids,)))
        # drain until the kill's own ack: an aborted in-flight step
        # may have queued a stale ("dead"/"error") reply first, and
        # leaving the ("killed") behind would desync every process
        # sharing this tracer (the next step would unpack a 2-tuple)
        try:
            for _ in range(8):
                reply = self.tracer.replies.get(timeout=10)
                if reply[0] == "killed":
                    if self.exit_code is None and reply[1] >= 0:
                        self.exit_code = reply[1]
                    break
        except queue.Empty:
            pass
        self._finalize_exit(ctx)
