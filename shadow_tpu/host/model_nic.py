"""Bandwidth + CoDel for raw model-app sends ("model NIC").

The socket path (host/nic.py + routing/queues.py) models bandwidth
with token buckets and queues of Packet objects — per-object state
that cannot live on the device. This module is the *vectorizable*
transport model used by raw ctx.send() traffic when
`experimental.model_bandwidth: true`: a fluid token bucket expressed
as virtual finish times (the scalar-per-host limit of the reference's
1 ms-refill buckets, network_interface.c:99-228) plus an event-driven
CoDel (RFC 8289, router_queue_codel.c:36-79) that decides one packet
per delivery event.

Semantics, identical by construction on the CPU engines and the device
engine (device/engine.py mirrors this arithmetic in jnp — keep them in
lockstep):

* TX at send time t of a packet of S bytes on host h:
    depart = max(t, tx_free);  tx_free = depart + S*8e9//bw_up
  (bursts within one event serialize in slot order). The drop roll and
  latency are applied on top: arrival = depart + latency; the
  bootstrap/drop gate uses the send event time t.
* RX at the packet event's execution on the destination (time arr):
    dq = max(arr, rx_free);  sojourn = dq - arr
    CoDel(sojourn, dq) may drop; otherwise
    deliver = dq + S*8e9//bw_down;  rx_free = deliver
  and the payload is re-scheduled as a KIND_PACKET_READY event at
  `deliver` (same src/seq — the app sees it then).
* CoDel control law uses an integer lookup table LAW[count] =
  interval/sqrt(count) so CPU float64 and device float32 can never
  disagree.
"""

from __future__ import annotations

import math

import numpy as np

from shadow_tpu import simtime

CODEL_TARGET_NS = 10 * simtime.SIMTIME_ONE_MILLISECOND
CODEL_INTERVAL_NS = 100 * simtime.SIMTIME_ONE_MILLISECOND
LAW_SIZE = 1024

_NS_PER_SEC = 1_000_000_000
# serialization sizes clamp to 1 GiB: size*8e9 must fit int64 on the
# device twin (which cannot use Python bigints); both twins clamp
# identically so traces stay equal
MAX_SER_BYTES = 1 << 30


def codel_law_table(interval_ns: int = CODEL_INTERVAL_NS) -> np.ndarray:
    """LAW[c] = interval/sqrt(c) ns (c=0 unused)."""
    t = np.zeros(LAW_SIZE, dtype=np.int64)
    for c in range(1, LAW_SIZE):
        t[c] = int(interval_ns / math.sqrt(c))
    return t


LAW = codel_law_table()


def serialize_ns(size_bytes: int, bw_bits: int) -> int:
    return (min(max(1, size_bytes), MAX_SER_BYTES) * 8 * _NS_PER_SEC) \
        // max(1, bw_bits)


class ModelNic:
    """Per-host model-NIC state (CPU twin of the device's 7 scalars:
    tx_free, rx_free, cd_fa, cd_next, cd_cnt, cd_last, cd_drop)."""

    def __init__(self, bw_up_bits: int, bw_down_bits: int):
        self.bw_up = bw_up_bits
        self.bw_down = bw_down_bits
        self.tx_free = 0
        self.rx_free = 0
        self.cd_fa = 0          # first_above_time
        self.cd_next = 0        # drop_next
        self.cd_cnt = 0
        self.cd_last = 0        # lastcount
        self.cd_drop = 0        # in dropping state

    # -- TX ------------------------------------------------------------
    def tx_depart(self, now: int, size: int) -> int:
        depart = max(now, self.tx_free)
        self.tx_free = depart + serialize_ns(size, self.bw_up)
        return depart

    # -- RX + event-driven CoDel ----------------------------------------
    def rx_deliver(self, arr: int, size: int) -> int:
        """Returns the delivery time, or -1 if CoDel dropped the
        packet. One packet per call — the event-driven adaptation of
        RFC 8289's dequeue loop; the device implements this exact
        decision tree."""
        dq = max(arr, self.rx_free)
        sojourn = dq - arr
        drop = False
        if sojourn < CODEL_TARGET_NS:
            self.cd_fa = 0
            self.cd_drop = 0
        elif self.cd_fa == 0:
            self.cd_fa = dq + CODEL_INTERVAL_NS
        elif dq >= self.cd_fa:
            if self.cd_drop:
                if dq >= self.cd_next:
                    drop = True
                    self.cd_cnt += 1
                    self.cd_next = self.cd_next + int(
                        LAW[min(self.cd_cnt, LAW_SIZE - 1)])
            else:
                drop = True
                self.cd_drop = 1
                delta = self.cd_cnt - self.cd_last
                if dq - self.cd_next < CODEL_INTERVAL_NS and delta > 1:
                    self.cd_cnt = delta
                else:
                    self.cd_cnt = 1
                self.cd_last = self.cd_cnt
                self.cd_next = dq + int(
                    LAW[min(self.cd_cnt, LAW_SIZE - 1)])
        else:
            self.cd_drop = 0
        if drop:
            return -1
        deliver = dq + serialize_ns(size, self.bw_down)
        self.rx_free = deliver
        return deliver
