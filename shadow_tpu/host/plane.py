"""Columnar host plane: the vectorized build/boot path.

PR 15 made million-vertex path TABLES cheap; this module does the same
lift-the-layer move one level up, at PAPER.md's layer-4 host emulation.
A device-policy run never touches most of what a Python ``Host`` object
carries — the per-host RNG is never drawn, the Cpu model never ticks,
the net stack is device state — yet ``controller.build()`` used to
construct a million of them one at a time (name f-string, blake2b seed
derivation, ``Cpu()``, DNS dict inserts, closure allocation), and
``device/runner.py`` immediately re-extracted numpy columns from them.

The :class:`HostPlane` holds the whole host table AS the columns:
vertex attachment, bandwidths, IPs, and process start/stop times are
built O(groups) vectorized (strided arange, broadcast, one bulk DNS
block per group), and the app-parameter columns the device twin needs
come from ONE prototype app per group (every host in a group shares
one args string, so the parsed fields broadcast). Full ``Host``
objects materialize LAZILY — only for hosts something actually touches
(a CPU-policy backend, tooling that reads ``sim.hosts``, a tracker
heartbeat) — and :meth:`HostPlane.materialize` constructs them
EXACTLY like the object path, including the per-host seed via the
same ``SeededRandom.child`` blake2b derivation, so a materialized
host is bit-identical to an object-built one by construction.

Bit-identity contract (enforced by tests/test_host_plane.py and the
``determinism_gate.py --host-plane`` CI rung): a columnar build
produces identical run signatures, checkpoints, and OCC/PLAN
fingerprints to the object-path build at every V where both run.

Eligibility lives in :func:`object_build_reason`: the fast path covers
pure model-app groups (tgen/phold — no managed processes, no
tor/HTTP) with deterministic O(1) vertex placement; anything else
returns a human-readable reason and ``controller.build()`` falls back
loudly to the object loop. ``SHADOW_TPU_HOST_PLANE=0`` forces the
object path (the gate's comparison leg).
"""

from __future__ import annotations

import os
from bisect import bisect_right
from dataclasses import dataclass
from typing import Optional

import numpy as np

from shadow_tpu.host.host import Host
from shadow_tpu.models import COLUMNAR_MODELS, is_model_path, make_app
from shadow_tpu.routing.address import Address
from shadow_tpu.utils.rng import SeededRandom, _derive


def object_build_reason(cfg, topology) -> Optional[str]:
    """None when the columnar fast path applies; otherwise a readable
    reason for the object-path fallback (logged loudly on device
    policies — a silently slow million-host build is the failure mode
    this module exists to kill)."""
    if os.environ.get("SHADOW_TPU_HOST_PLANE", "") in ("0", "off"):
        return "disabled by SHADOW_TPU_HOST_PLANE=0"
    if not cfg.hosts:
        return "config has no host groups"
    if cfg.ensemble is None and \
            cfg.experimental.scheduler_policy != "tpu":
        return (f"scheduler_policy "
                f"{cfg.experimental.scheduler_policy!r} is a "
                "CPU-policy backend (it touches every host, so lazy "
                "materialization buys nothing)")
    for g in cfg.hosts:
        for proc in g.processes:
            if not is_model_path(proc.path):
                return (f"hosts.{g.name} runs managed process "
                        f"{proc.path!r} (real processes need full "
                        "Host objects and the native runtime)")
        n_procs = sum(p.quantity for p in g.processes)
        if n_procs != 1:
            return (f"hosts.{g.name} runs {n_procs} processes per "
                    "host (the plane carries exactly one model app)")
        model = g.processes[0].path[len("model:"):]
        if model not in COLUMNAR_MODELS:
            return (f"hosts.{g.name} model {model!r} has no columnar "
                    f"twin (have: {sorted(COLUMNAR_MODELS)})")
        if g.ip_address_hint or g.city_code_hint or \
                g.country_code_hint:
            return (f"hosts.{g.name} uses attachment/IP hints "
                    "(hint resolution is per-host object work)")
        if g.network_node_id is None and topology.n_vertices != 1:
            return (f"hosts.{g.name} has no network_node_id on a "
                    f"{topology.n_vertices}-vertex graph (attachment "
                    "would draw from the build RNG)")
    names = [g.name for g in cfg.hosts]
    for a in names:
        for b in names:
            if a != b and b.startswith(a) and b[len(a):].isdigit():
                # "web" x quantity 20 generates web1; a sibling group
                # "web1" collides — the object path's DNS raises on
                # the duplicate, so send ambiguous layouts there
                return (f"group names {a!r} and {b!r} can collide in "
                        "generated host names")
    return None


@dataclass
class PlaneGroup:
    """One config host group's columnar record: contiguous ids
    [base_id, base_id + count), names ``{name}{i}`` (bare ``name``
    when count == 1), one model process shared by every member, and
    ONE prototype app carrying the parsed per-group arg fields."""

    name: str
    base_id: int
    count: int
    pcap_directory: Optional[str]
    path: str                      # "model:<name>"
    args: str
    start_time: int
    stop_time: int                 # -1 = no stop event
    model: str                     # registry name after "model:"
    prototype: object              # ModelApp built for host base_id

    def ids(self) -> range:
        return range(self.base_id, self.base_id + self.count)


class PlaneNameMap:
    """name -> host id WITHOUT materializing anything (the host-fault
    resolver's seam: faults.resolve_host_faults only calls ``.get``).
    Generated names parse back by group prefix + decimal suffix; the
    eligibility check already refused prefix-ambiguous group sets, so
    every name has at most one parse."""

    def __init__(self, groups: list[PlaneGroup]):
        self._groups = {g.name: g for g in groups}

    def get(self, name: str, default=None):
        g = self._groups.get(name)
        if g is not None and g.count == 1:
            return g.base_id
        for prefix, g in self._groups.items():
            if g.count > 1 and name.startswith(prefix):
                suf = name[len(prefix):]
                # generated names never carry leading zeros
                if suf.isdigit() and str(int(suf)) == suf \
                        and int(suf) < g.count:
                    return g.base_id + int(suf)
        return default

    def __contains__(self, name: str) -> bool:
        return self.get(name) is not None

    def __getitem__(self, name: str) -> int:
        hid = self.get(name)
        if hid is None:
            raise KeyError(name)
        return hid


class StartColumns:
    """Per-host process (start, stop|-1) times as [H] int64 columns.
    Iterates as the ``(host_id, start, stop, proc_idx)`` tuples
    ``Manager.boot_hosts`` expects (host_id == index: the plane
    carries exactly one process per host); the device engine's
    ``init_state`` detects :meth:`as_arrays` and fills its boot/stop
    vectors with array ops instead of a million-iteration loop."""

    def __init__(self, t0, t1):
        self.t0 = np.asarray(t0, dtype=np.int64)
        self.t1 = np.asarray(t1, dtype=np.int64)

    def as_arrays(self):
        return self.t0, self.t1

    def __len__(self) -> int:
        return int(self.t0.shape[0])

    def __getitem__(self, i):
        if isinstance(i, slice):
            return [self[j] for j in range(*i.indices(len(self)))]
        if i < 0:
            i += len(self)
        if not 0 <= i < len(self):
            raise IndexError(i)
        return (i, int(self.t0[i]), int(self.t1[i]), 0)

    def __iter__(self):
        for i in range(len(self)):
            yield (i, int(self.t0[i]), int(self.t1[i]), 0)


class HostPlane:
    """The columnar host table. Columns are aligned [H] arrays indexed
    by host id; ``materialize(i)`` builds (and caches) the full
    ``Host`` object for one row, bit-identical to what the object-path
    build constructs for the same config."""

    def __init__(self, cfg, groups: list[PlaneGroup],
                 vertex: np.ndarray, bw_down_bits: np.ndarray,
                 bw_up_bits: np.ndarray, ips: np.ndarray,
                 starts: StartColumns):
        self.cfg = cfg
        self.group_records = groups
        self.n_hosts = int(vertex.shape[0])
        self.vertex = vertex                  # [H] int64
        self.bw_down_bits = bw_down_bits      # [H] int64
        self.bw_up_bits = bw_up_bits          # [H] int64
        self.ips = ips                        # [H] int64 (host order)
        self.starts = starts
        self.root_seed = int(cfg.general.seed)
        self.names = PlaneNameMap(groups)
        self._bases = [g.base_id for g in groups]
        self._cache: dict[int, Host] = {}
        # per-host final stats adopted from the device engine
        # (adopt_final); None until a run completes
        self._final: Optional[dict] = None

    # -- identity ----------------------------------------------------
    @property
    def any_pcap(self) -> bool:
        return any(g.pcap_directory for g in self.group_records)

    @property
    def materialized_count(self) -> int:
        return len(self._cache)

    def group_of(self, host_id: int) -> PlaneGroup:
        return self.group_records[
            bisect_right(self._bases, host_id) - 1]

    def name_of(self, host_id: int) -> str:
        g = self.group_of(host_id)
        return g.name if g.count == 1 \
            else f"{g.name}{host_id - g.base_id}"

    # -- lazy materialization ---------------------------------------
    def materialize(self, host_id: int) -> Host:
        host = self._cache.get(host_id)
        if host is not None:
            return host
        from shadow_tpu.host.cpu import Cpu

        g = self.group_of(host_id)
        name = self.name_of(host_id)
        # the exact object-path construction, row by row: the seed is
        # the same root.child(f"host:{name}") blake2b derivation, so
        # any consumer that DOES draw from the host RNG (CPU-policy
        # backends after a hybrid fallback) sees identical streams
        host = Host(host_id=host_id, name=name,
                    vertex=int(self.vertex[host_id]),
                    bw_down_bits=int(self.bw_down_bits[host_id]),
                    bw_up_bits=int(self.bw_up_bits[host_id]),
                    rng=SeededRandom(_derive(self.root_seed,
                                             f"host:{name}")),
                    pcap_directory=g.pcap_directory)
        host.cpu = Cpu()
        if self.cfg.experimental.model_bandwidth:
            from shadow_tpu.host.model_nic import ModelNic
            host.model_nic = ModelNic(host.bw_up_bits,
                                      host.bw_down_bits)
        host.address = Address(host_id=host_id, name=name,
                               ip=int(self.ips[host_id]))
        host.ip = host.address.ip_str
        app = make_app(g.path, g.args, host_id, self.n_hosts)
        factory = (lambda p=g.path, a=g.args, hid=host_id,
                   n=self.n_hosts: make_app(p, a, hid, n))
        host.apps.append(app)
        host.respawn = [(factory, g.start_time, g.stop_time, True)]
        host.app = app
        if self._final is not None:
            self._apply_final(host)
        self._cache[host_id] = host
        return host

    # -- final-stats reflection (the runner's post-run seam) ---------
    def adopt_final(self, final: dict, replica: Optional[int] = None
                    ) -> None:
        """Adopt the run's per-host counters as columns (arrays may be
        padded past n_hosts; ``replica`` selects a row of the
        ensemble's [R,H] stacks). Already-materialized hosts update in
        place; later materializations pick the stats up on build —
        either way ``sim.hosts`` reads the same counters the object
        path's reflection loop would have written."""
        cols = {}
        for src, dst in (("n_exec", "events_executed"),
                         ("n_sent", "packets_sent"),
                         ("n_drop", "packets_dropped"),
                         ("n_deliv", "packets_delivered"),
                         ("chk", "trace_checksum")):
            a = np.asarray(final[src])
            cols[dst] = a[replica] if replica is not None else a
        self._final = cols
        for host in self._cache.values():
            self._apply_final(host)

    def _apply_final(self, host: Host) -> None:
        i = host.host_id
        for attr, col in self._final.items():
            setattr(host, attr, int(col[i]))


class LazyHostList:
    """Sequence view over the plane: ``sim.hosts`` for columnar
    builds. Indexing/iteration materializes (cached) Host objects, so
    every existing consumer — gates reading signatures, the hybrid
    Manager, tooling — works unchanged and pays only for the hosts it
    actually touches."""

    def __init__(self, plane: HostPlane):
        self.plane = plane

    def __len__(self) -> int:
        return self.plane.n_hosts

    def __getitem__(self, i):
        if isinstance(i, slice):
            return [self[j] for j in range(*i.indices(len(self)))]
        if i < 0:
            i += len(self)
        if not 0 <= i < len(self):
            raise IndexError(i)
        return self.plane.materialize(i)

    def __iter__(self):
        for i in range(len(self)):
            yield self.plane.materialize(i)
