"""Virtual descriptor layer for managed (real) processes.

The rebuild of the reference's descriptor subsystem (src/main/host/
descriptor/: descriptor.c, descriptor_table.rs, epoll.c, pipe.rs,
compat_socket.c) plus the status-listener pattern (status_listener.c)
and blocked-syscall conditions (syscall_condition.c):

* Virtual fds live at VFD_BASE and above so they can never collide
  with the plugin's native kernel fds — the shim's seccomp filter
  routes fd-gated syscalls by this same threshold, so native file I/O
  runs at full speed with no interposition while simulated sockets,
  pipes and epolls are fully emulated here.
* Each descriptor exposes a readiness `status()` bitmask; on every
  state change `notify()` fans out to watching epolls and to parked
  `Condition`s (blocked syscalls), which schedule the owning process's
  continue event — the status-listener -> epoll -> process_continue
  chain of the reference.
* TCP payload bytes travel out-of-band through per-direction
  `StreamChannel`s keyed by the connection 4-tuple: the TCP model
  (host/tcp.py) decides timing/ordering/drops on byte *counts*, and
  the stream hands the actual bytes over in the exact in-order
  quantities the model delivers. This keeps packet payloads off the
  device path (metadata-only packets), which is the TPU-first design.
"""

from __future__ import annotations

import os
from collections import deque
from typing import Optional

from shadow_tpu.host.sockets import UdpSocket
from shadow_tpu.host.tcp import DEFAULT_SEND_BUFFER, TcpSocket, TcpState

VFD_BASE = 600                  # keep in sync with native/shim/shim.c
VFD_END = 1024                  # exclusive; < FD_SETSIZE so select()'s
                                # fd_set can express every virtual fd
                                # (native fds are capped below 600 via
                                # RLIMIT_NOFILE at spawn)

R = 1                           # readable
W = 2                           # writable
ERR = 4                         # error/hup

# epoll event bits (uapi)
EPOLLIN = 0x001
EPOLLOUT = 0x004
EPOLLERR = 0x008
EPOLLHUP = 0x010
EPOLLRDHUP = 0x2000
EPOLLET = 1 << 31
EPOLLONESHOT = 1 << 30


class Condition:
    """A blocked syscall's wakeup condition (syscall_condition.c):
    fires once, on descriptor readiness or timeout, and schedules the
    owning process's continue event."""

    def __init__(self, process):
        self.process = process
        self.fired = False
        self._descs: list[Descriptor] = []

    def attach(self, desc: "Descriptor") -> None:
        desc.conditions.add(self)
        self._descs.append(desc)

    def detach_all(self) -> None:
        for d in self._descs:
            d.conditions.discard(self)
        self._descs.clear()

    def wake(self, ctx) -> None:
        if self.fired:
            return
        self.fired = True
        self.detach_all()
        self.process.schedule_continue(ctx)


class Futex:
    """One futex word (futex.c): a wait queue keyed by the word's
    plugin address, woken explicitly by FUTEX_WAKE rather than by a
    status bit. Reuses the Condition wiring so blocked FUTEX_WAITs
    park exactly like blocked descriptor I/O. The per-process table
    (futex_table.c) lives in ManagedProcess.futexes."""

    def __init__(self, addr: int):
        self.addr = addr
        self.conditions: set[Condition] = set()
        self.watchers: set = set()       # never epolled; protocol compat
        self.closed = False
        self.nonblock = False

    def status(self) -> int:
        return 0

    def wake(self, ctx, n: int) -> int:
        woken = 0
        for cond in list(self.conditions):
            if woken >= n:
                break
            cond.wake(ctx)
            woken += 1
        return woken

    def notify(self, ctx) -> None:
        pass                             # only explicit wakes


class Descriptor:
    def __init__(self):
        self.fd = -1
        self.refs = 1                    # dup() refcount
        self.nonblock = False
        self.closed = False
        self.watchers: set[EpollDesc] = set()
        self.conditions: set[Condition] = set()

    def status(self) -> int:
        return 0

    def notify(self, ctx) -> None:
        for ep in list(self.watchers):
            ep.member_changed(ctx, self)
        for cond in list(self.conditions):
            cond.wake(ctx)

    def close(self, ctx) -> None:
        self.closed = True
        self.watchers.clear()


class StreamChannel:
    """Out-of-band reliable byte stream for one TCP direction."""

    def __init__(self):
        self.buf = bytearray()

    def push(self, data: bytes) -> None:
        self.buf += data

    def pop(self, n: int) -> bytes:
        out = bytes(self.buf[:n])
        del self.buf[:n]
        return out


class TcpDesc(Descriptor):
    """A TCP connection descriptor wrapping host/tcp.py's TcpSocket."""

    # getsockopt fallback pre-connect; live sockets use
    # send_buffer_limit()
    SNDBUF = DEFAULT_SEND_BUFFER

    def __init__(self, table: "DescriptorTable",
                 sock: Optional[TcpSocket] = None):
        super().__init__()
        self.table = table
        self.sock = sock
        self.recv_stream = bytearray()
        self.eof = False            # peer sent FIN
        self.connected = False
        self.connect_err: Optional[int] = None   # pending SO_ERROR
        self.connecting = False
        self.bound_port: Optional[int] = None
        if sock is not None:
            self._hook(sock)

    def _hook(self, sock: TcpSocket) -> None:
        self.sock = sock
        sock.on_connected = self._on_connected
        sock.on_data = self._on_data
        sock.on_closed = self._on_closed
        sock.on_writable = self._on_writable

    # -- socket callbacks ---------------------------------------------
    def _on_connected(self, ctx, sock, now) -> None:
        self.connected = True
        self.connecting = False
        self.notify(ctx)

    def _on_data(self, ctx, sock, nbytes, now) -> None:
        ch = self.table.recv_channel(sock)
        self.recv_stream += ch.pop(nbytes)
        self.notify(ctx)

    def _on_closed(self, ctx, sock, now) -> None:
        self.eof = True
        if self.connecting:
            self.connecting = False
            self.connect_err = 111      # ECONNREFUSED-ish abort
        self.notify(ctx)

    def _on_writable(self, ctx, sock, now) -> None:
        self.notify(ctx)

    # -- state ---------------------------------------------------------
    def send_space(self) -> int:
        s = self.sock
        if s is None:
            return 0
        used = (s.snd_nxt - s.snd_una) + s.send_pending
        return max(0, s.send_buffer_limit() - used)

    def status(self) -> int:
        st = 0
        if self.recv_stream or self.eof:
            st |= R
        if self.connected and self.send_space() > 0:
            st |= W
        if self.connect_err:
            st |= ERR | W
        if self.connecting:
            st &= ~W
        return st

    def close(self, ctx) -> None:
        super().close(ctx)
        if self.sock is not None and self.sock.state != TcpState.CLOSED:
            self.sock.close(ctx.now)


class TcpListenDesc(Descriptor):
    def __init__(self, table: "DescriptorTable", sock: TcpSocket,
                 backlog: int):
        super().__init__()
        self.table = table
        self.sock = sock
        self.backlog = max(1, backlog)
        self.accept_queue: deque[TcpDesc] = deque()
        sock.on_accept = self._on_establish

    def _on_establish(self, ctx, child_sock, now) -> None:
        if len(self.accept_queue) >= self.backlog:
            child_sock.close(now)       # overflow: refuse
            return
        child = TcpDesc(self.table, child_sock)
        child.connected = True
        self.accept_queue.append(child)
        self.notify(ctx)

    def status(self) -> int:
        return R if self.accept_queue else 0

    def close(self, ctx) -> None:
        super().close(ctx)
        self.sock.close(ctx.now)


class UdpDesc(Descriptor):
    RCVBUF_DATAGRAMS = 256

    def __init__(self, table: "DescriptorTable"):
        super().__init__()
        self.table = table
        self.sock: Optional[UdpSocket] = None
        self.queue: deque[tuple[bytes, int, int]] = deque()
        # (payload, src_host, src_port)
        self.default_peer: Optional[tuple[int, int]] = None  # connect()
        self.bound_port: Optional[int] = None

    def ensure_bound(self, net, port: Optional[int] = None) -> None:
        if self.sock is None:
            self.sock = net.udp_socket(port=port,
                                       on_datagram=self._on_datagram)
            self.bound_port = self.sock.local_port

    def _on_datagram(self, ctx, sock, packet, now) -> None:
        if len(self.queue) >= self.RCVBUF_DATAGRAMS:
            return                     # tail drop
        payload = packet.payload if packet.payload is not None else b""
        payload = bytes(payload)[: packet.size]
        if len(payload) < packet.size:
            payload = payload + b"\0" * (packet.size - len(payload))
        self.queue.append((payload, packet.src_host, packet.src_port))
        self.notify(ctx)

    def status(self) -> int:
        st = W
        if self.queue:
            st |= R
        return st

    def close(self, ctx) -> None:
        super().close(ctx)
        if self.sock is not None:
            self.sock.close(ctx.now)


class PipeDesc(Descriptor):
    """One end of an anonymous pipe (descriptor/pipe.rs analogue); the
    read and write ends share a byte buffer."""

    CAPACITY = 65536

    def __init__(self, readable_end: bool):
        super().__init__()
        self.readable_end = readable_end
        self.buf: bytearray = bytearray()   # shared: reassigned on pair
        self.peer: Optional[PipeDesc] = None

    @staticmethod
    def make_pair() -> tuple["PipeDesc", "PipeDesc"]:
        r, w = PipeDesc(True), PipeDesc(False)
        shared = bytearray()
        r.buf = w.buf = shared
        r.peer, w.peer = w, r
        return r, w

    def status(self) -> int:
        if self.readable_end:
            st = R if self.buf else 0
            if self.peer is None or self.peer.closed:
                st |= R                 # EOF readable
            return st
        if self.peer is None or self.peer.closed:
            return W | ERR              # EPIPE
        return W if len(self.buf) < self.CAPACITY else 0

    def close(self, ctx) -> None:
        super().close(ctx)
        if self.peer is not None:
            self.peer.notify(ctx)   # blocked reader -> EOF,
                                    # blocked writer -> EPIPE


class UnixPairDesc(Descriptor):
    """One end of socketpair(AF_UNIX) — the reference emulates these
    via its unix-socket layer (ref syscall dispatch `socketpair`);
    here each end is a bidirectional in-memory channel with pipe
    capacity semantics per direction. SOCK_STREAM ends coalesce
    bytes; SOCK_DGRAM ends preserve message boundaries."""

    CAPACITY = 65536

    def __init__(self, dgram: bool):
        super().__init__()
        self.dgram = dgram
        self.rbuf = bytearray()             # stream inbox
        self.rmsgs: deque = deque()         # dgram inbox
        self.rbytes = 0                     # dgram inbox fill
        self.peer: Optional["UnixPairDesc"] = None
        self.rd_shut = False
        self.wr_shut = False

    @staticmethod
    def make_pair(dgram: bool) -> tuple["UnixPairDesc",
                                        "UnixPairDesc"]:
        a, b = UnixPairDesc(dgram), UnixPairDesc(dgram)
        a.peer, b.peer = b, a
        return a, b

    def _inbox_full(self) -> bool:
        if self.dgram:
            return self.rbytes >= self.CAPACITY
        return len(self.rbuf) >= self.CAPACITY

    def _readable(self) -> bool:
        return bool(self.rmsgs) if self.dgram else bool(self.rbuf)

    def status(self) -> int:
        st = 0
        peer_gone = (self.peer is None or self.peer.closed
                     or self.peer.wr_shut)
        if self._readable() or peer_gone or self.rd_shut:
            st |= R                         # data or EOF readable
        if self.peer is None or self.peer.closed:
            st |= ERR | W                   # EPIPE on write
        elif self.wr_shut or not self.peer._inbox_full():
            # SEND_SHUTDOWN keeps EPOLLOUT (Linux unix_poll): writes
            # complete immediately — with EPIPE — so a poll-then-
            # write loop must not park
            st |= W
        return st

    def close(self, ctx) -> None:
        super().close(ctx)
        if self.peer is not None:
            self.peer.notify(ctx)   # blocked reader -> EOF,
                                    # blocked writer -> EPIPE


class EpollDesc(Descriptor):
    """epoll instance (descriptor/epoll.c): level-triggered readiness
    over the interest list; EPOLLET is accepted but treated as level
    (divergence: the reference implements true edge semantics)."""

    def __init__(self, table: "DescriptorTable"):
        super().__init__()
        self.table = table
        self.interest: dict[int, tuple[int, int]] = {}  # fd -> (ev, data)

    def member_changed(self, ctx, desc: Descriptor) -> None:
        self.notify(ctx)

    def add(self, fd: int, events: int, data: int) -> None:
        self.interest[fd] = (events, data)
        d = self.table.get(fd)
        if d is not None:
            d.watchers.add(self)

    def modify(self, fd: int, events: int, data: int) -> None:
        self.interest[fd] = (events, data)

    def remove(self, fd: int) -> None:
        self.interest.pop(fd, None)
        d = self.table.get(fd)
        if d is not None and not any(
                fd2 in self.interest for fd2 in self.table.fds_of(d)):
            d.watchers.discard(self)

    def ready(self) -> list[tuple[int, int]]:
        """-> [(events, data)] for every ready interest entry."""
        out = []
        for fd, (events, data) in self.interest.items():
            d = self.table.get(fd)
            if d is None:
                continue
            st = d.status()
            rev = 0
            if (events & EPOLLIN) and (st & R):
                rev |= EPOLLIN
            if (events & EPOLLOUT) and (st & W):
                rev |= EPOLLOUT
            if st & ERR:
                rev |= EPOLLERR
            if getattr(d, "eof", False):
                if events & EPOLLRDHUP:
                    rev |= EPOLLRDHUP
            if rev:
                out.append((rev, data))
        return out

    def status(self) -> int:
        return R if self.ready() else 0


class TimerfdDesc(Descriptor):
    """timerfd (descriptor/timer.c): expirations counted; read returns
    an u64 count. Armed via the owning process's timer scheduling."""

    def __init__(self):
        super().__init__()
        self.expirations = 0
        self.interval_ns = 0
        self.next_expiry: Optional[int] = None    # absolute sim ns
        self.generation = 0                       # cancels stale timers

    def fire(self, ctx, gen: int) -> None:
        if gen != self.generation or self.closed:
            return
        self.expirations += 1
        self.notify(ctx)

    def status(self) -> int:
        return R if self.expirations > 0 else 0


class VirtualFileDesc(Descriptor):
    """An emulated regular/char file served simulator-side (the
    RegularFile slice of ref file.c for paths the SIMULATOR must own):
    deterministic RNG devices (/dev/urandom — native reads would be
    real randomness, breaking run-to-run determinism) and the
    simulated /etc/hosts (under ptrace there is no shim getaddrinfo
    override, so libc reads the file raw — it must see the simulated
    name map, not the machine's). Finite `content` with a seek
    position, or an endless `generator(n) -> bytes` device."""

    def __init__(self, content: bytes = b"", generator=None,
                 mode: int = 0o100644):
        super().__init__()
        self.content = content
        self.generator = generator
        self.mode = mode
        self.pos = 0

    def read_at(self, n: int, pos: Optional[int] = None) -> bytes:
        if self.generator is not None:
            return self.generator(n)
        p = self.pos if pos is None else pos
        data = self.content[p:p + n]
        if pos is None:
            self.pos += len(data)
        return data

    def size(self) -> int:
        return len(self.content)


class HostFileDesc(Descriptor):
    """An os-backed regular file or directory: the SIMULATOR owns the
    real fd (opened inside the host's data dir) and mediates every
    plugin-visible operation through the descriptor table — the
    fd-mediated file family of ref descriptor/file.c (struct _File's
    osfile {fd, flags, mode, abspath}) and syscall/file.c. The real
    fd is always O_CLOEXEC so it can never leak into spawned plugins;
    the app-visible flags are tracked separately. The kernel offset of
    the simulator-held fd IS the shared open-file-description offset
    (dup/fork share this object, matching kernel semantics)."""

    def __init__(self, osfd: int, abspath: str, flags: int,
                 mode: int = 0o644):
        super().__init__()
        self.osfd = osfd
        self.abspath = abspath
        self.realpath = abspath     # overwritten with the resolved
        self.flags = flags          # path at open (lock-table key)
        self.mode = mode
        self.is_dir = False
        try:
            self.is_dir = os.path.isdir(abspath)
        except OSError:
            pass
        # getdents cursor: a sorted listing snapshot (real readdir
        # order is filesystem-nondeterministic; sorting makes directory
        # iteration a determinism WIN over native passthrough)
        self._dirents: Optional[list] = None
        self._dirpos = 0

    def status(self) -> int:
        return R | W                # regular files: always ready

    def dirents(self) -> list:
        """[(name, ino, dtype)] snapshot: '.', '..', then SORTED names
        (real readdir order is filesystem-dependent; sorting makes the
        iteration order deterministic). Inodes are the real ones so
        d_ino agrees with the st_ino that fstat/stat pass through —
        the same passthrough-identity policy as the stat family."""
        if self._dirents is None:
            def ino_of(p):
                try:
                    return os.stat(p).st_ino
                except OSError:
                    return 0
            entries = [(".", ino_of(self.abspath), 4),
                       ("..", ino_of(os.path.dirname(self.abspath)),
                        4)]                           # DT_DIR
            try:
                with os.scandir(self.abspath) as it:
                    found = []
                    for e in it:
                        if e.is_dir(follow_symlinks=False):
                            dt = 4                    # DT_DIR
                        elif e.is_symlink():
                            dt = 10                   # DT_LNK
                        elif e.is_file(follow_symlinks=False):
                            dt = 8                    # DT_REG
                        else:
                            dt = 0                    # DT_UNKNOWN
                        try:
                            ino = e.inode()
                        except OSError:
                            ino = 0
                        found.append((e.name, ino, dt))
                    entries += sorted(found)
            except OSError:
                pass
            self._dirents = entries
        return self._dirents

    def rewind_dir(self) -> None:
        self._dirents = None
        self._dirpos = 0

    def close(self, ctx) -> None:
        super().close(ctx)
        if self.osfd >= 0:
            try:
                os.close(self.osfd)
            except OSError:
                pass
            self.osfd = -1


class EventfdDesc(Descriptor):
    def __init__(self, initval: int, semaphore: bool):
        super().__init__()
        self.counter = initval
        self.semaphore = semaphore

    def status(self) -> int:
        st = 0
        if self.counter > 0:
            st |= R
        if self.counter < (1 << 64) - 2:
            st |= W
        return st


class TableFull(Exception):
    """The per-process virtual fd window [VFD_BASE, VFD_END) is
    exhausted — the dispatcher answers EMFILE, exactly as the kernel
    does at RLIMIT_NOFILE."""


class DescriptorTable:
    """Per-process fd table (descriptor_table.rs): virtual fds are
    handed out from VFD_BASE upward; lowest-free-slot reuse matches
    kernel fd allocation semantics within the virtual range."""

    def __init__(self, manager, owner=None):
        self.manager = manager
        self.owner = owner          # owning ManagedProcess (lock purge)
        self._slots: dict[int, Descriptor] = {}
        # close-on-exec is a PER-FD flag (kernel fd table), not a
        # property of the open file description: dup'd fds never
        # inherit it, fork'd tables copy it, execve closes these
        self.cloexec: set[int] = set()

    def has_room(self, n: int = 1) -> bool:
        """Can `n` more fds be allocated? Handlers whose failure
        path is not side-effect-free (openat's real os.open, accept's
        queue pop, pipe's twin alloc) check this FIRST so EMFILE
        never leaks state."""
        return len(self._slots) + n <= VFD_END - VFD_BASE

    def alloc(self, desc: Descriptor, min_fd: int = 0) -> int:
        # lowest free slot, exactly like kernel fd allocation — the
        # [600, 1024) window is only 424 slots, so freed slots MUST
        # be reused (a monotonic cursor would exhaust the table after
        # 424 cumulative allocations regardless of live count)
        idx = min_fd
        while VFD_BASE + idx in self._slots:
            idx += 1
        fd = VFD_BASE + idx
        if fd >= VFD_END:
            raise TableFull()
        self._slots[fd] = desc
        if desc.fd < 0:
            desc.fd = fd
        return fd

    def get(self, fd: int) -> Optional[Descriptor]:
        return self._slots.get(fd)

    def fds_of(self, desc: Descriptor) -> list[int]:
        return [fd for fd, d in self._slots.items() if d is desc]

    def dup(self, fd: int, min_fd: int = 0) -> int:
        d = self._slots[fd]
        newfd = self.alloc(d, min_fd)   # may raise TableFull: no ref
        d.refs += 1                     # leak on the failure path
        return newfd

    def replace(self, fd: int, new_desc: Descriptor) -> None:
        """Swap the object behind fd (socket() desc -> listener desc)."""
        old = self._slots[fd]
        for f, d in list(self._slots.items()):
            if d is old:
                self._slots[f] = new_desc
        new_desc.fd = fd
        new_desc.refs = old.refs

    def place_at(self, oldfd: int, newfd: int) -> None:
        """dup2: point newfd at oldfd's descriptor (newfd known free)."""
        d = self._slots[oldfd]
        d.refs += 1
        self._slots[newfd] = d

    def close_fd(self, ctx, fd: int) -> bool:
        d = self._slots.pop(fd, None)
        self.cloexec.discard(fd)
        if d is None:
            return False
        d.refs -= 1
        if d.refs <= 0:
            d.close(ctx)
        if isinstance(d, HostFileDesc) and self.owner is not None:
            # POSIX: closing ANY fd that refers to the file releases
            # every record lock the owning PROCESS holds on it (this
            # is the chokepoint — dup2-over, cloexec and explicit
            # closes all land here). OFD locks die with their
            # description instead (pruned lazily via d.closed).
            host = getattr(self.owner, "host", None)
            table = getattr(host, "_posix_locks", None) if host \
                else None
            if table:
                locks = table.get(d.realpath)
                if locks:
                    locks[:] = [e for e in locks
                                if e[0] is not self.owner]
        return True

    def close_all(self, ctx) -> None:
        for fd in list(self._slots):
            self.close_fd(ctx, fd)

    def fork_clone(self) -> "DescriptorTable":
        """fork(2) semantics: the child gets its own fd table whose
        entries reference the SAME open file descriptions (refcounted;
        a close in either process only drops that table's reference)."""
        t = DescriptorTable(self.manager)
        t._slots = dict(self._slots)
        t.cloexec = set(self.cloexec)   # fd flags copy across fork
        for d in t._slots.values():
            d.refs += 1
        return t

    # -- TCP byte-stream channels (keyed by connection 4-tuple) --------
    def recv_channel(self, sock: TcpSocket) -> StreamChannel:
        """Channel carrying bytes TOWARD this socket."""
        peer_host, peer_port = sock.peer
        key = (peer_host, peer_port, sock.net.host.host_id,
               sock.local_port)
        return self.manager.stream_channel(key)

    def send_channel(self, sock: TcpSocket) -> StreamChannel:
        """Channel carrying bytes FROM this socket."""
        peer_host, peer_port = sock.peer
        key = (sock.net.host.host_id, sock.local_port, peer_host,
               peer_port)
        return self.manager.stream_channel(key)
