"""Socket layer: base socket + UDP.

Equivalent of the reference's descriptor/socket subsystem
(src/main/host/descriptor/socket.c, udp.c): sockets associate with an
interface by (protocol, local port, peer), buffer outbound packets for
the NIC's pull loop, and surface readability to the application
(status-listener pattern -> app callbacks here).
"""

from __future__ import annotations

from collections import deque
from typing import Callable, Optional

from shadow_tpu import simtime
from shadow_tpu.routing.packet import Packet, PacketStatus, Protocol

EPHEMERAL_PORT_START = 10000


class BaseSocket:
    def __init__(self, net, proto: Protocol, local_port: int):
        self.net = net                    # HostNetStack
        self.proto = proto
        self.local_port = local_port
        self.peer: Optional[tuple[int, int]] = None   # (host, port)
        self.closed = False
        # outbound packets staged for the NIC pull loop
        self._out: deque[Packet] = deque()

    # PacketSource interface (host/nic.py)
    def has_packet_to_send(self) -> bool:
        return bool(self._out)

    def peek_packet_size(self) -> Optional[int]:
        return self._out[0].total_size if self._out else None

    def pull_packet(self, now: int) -> Optional[Packet]:
        return self._out.popleft() if self._out else None

    def _stage(self, packet: Packet, now: int) -> None:
        packet.add_status(PacketStatus.SND_SOCKET_BUFFERED)
        self._out.append(packet)
        self.net.interface_for(packet.dst_host).wants_send(self, now)

    def handle_packet(self, packet: Packet, now: int) -> None:
        raise NotImplementedError

    def close(self, now: int) -> None:
        self.closed = True
        self.net.unregister(self)


class UdpSocket(BaseSocket):
    """Datagram socket (descriptor/udp.c): no connection state, one
    packet per datagram, fixed-size receive queue with tail drop."""

    MAX_DATAGRAM = 65507
    RECV_QUEUE_DATAGRAMS = 256

    def __init__(self, net, local_port: int,
                 on_datagram: Optional[Callable] = None):
        super().__init__(net, Protocol.UDP, local_port)
        self.on_datagram = on_datagram
        self.recv_queue: deque[Packet] = deque()
        self.dropped = 0

    def sendto(self, now: int, dst_host: int, dst_port: int,
               size: int, payload: Optional[bytes] = None) -> bool:
        if size > self.MAX_DATAGRAM:
            raise ValueError(f"datagram too large: {size}")
        # fragment at the MSS boundary like the reference's UDP-over-
        # packets (each simulated packet carries <= MTU-headers bytes)
        mss = simtime.CONFIG_MTU - simtime.CONFIG_HEADER_SIZE_UDPIPETH
        remaining = size
        while True:
            chunk = min(remaining, mss)
            pkt = self.net.new_packet(
                dst_host=dst_host, protocol=Protocol.UDP, size=chunk,
                src_port=self.local_port, dst_port=dst_port,
                payload=payload)
            self._stage(pkt, now)
            remaining -= chunk
            if remaining <= 0:
                break
        return True

    def handle_packet(self, packet: Packet, now: int) -> None:
        packet.add_status(PacketStatus.RCV_SOCKET_DELIVERED)
        if self.on_datagram is not None:
            # callback mode: deliver directly, nothing to drain later
            self.on_datagram(self.net.ctx, self, packet, now)
            return
        if len(self.recv_queue) >= self.RECV_QUEUE_DATAGRAMS:
            self.dropped += 1
            packet.add_status(PacketStatus.RCV_INTERFACE_DROPPED)
            return
        self.recv_queue.append(packet)

    def recvfrom(self) -> Optional[Packet]:
        return self.recv_queue.popleft() if self.recv_queue else None
