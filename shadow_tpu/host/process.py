"""Managed (real) process execution.

The rebuild of the reference's process/thread layer for the preload
interposition path (src/main/host/process.c:457-651 `_process_start` /
`process_continue`, thread_preload.c's shim-IPC event loop,
manager.c:386-505 LD_PRELOAD environment construction): a real Linux
executable is spawned with the shim library preloaded, its stdio
redirected into the host's data directory, ASLR disabled for
determinism (main.c:287), and then driven in strict ping-pong over the
shared-memory spinning-semaphore IPC channel:

    event fires -> resume plugin -> service trapped syscalls until the
    plugin blocks (park on a Condition) or exits -> return to the
    event loop.

Every emulated syscall executes at the host's current simulated
instant; blocking syscalls park on descriptor readiness and/or timer
deadlines, whose wakeups schedule a continue event — exactly the
SysCallCondition -> process_continue chain of the reference.

Plugin exits are noticed by a per-process reaper thread (the
ChildPidWatcher analogue, childpid_watcher.rs) that trips the
channel's plugin-exited flag so a blocked recv returns immediately.
"""

from __future__ import annotations

import os
import resource
import shlex
import shutil
import struct
import threading
from typing import Optional

from shadow_tpu import native
from shadow_tpu.core.event import Event, KIND_TASK
from shadow_tpu.host.descriptors import (Condition, DescriptorTable,
                                         VFD_BASE)
from shadow_tpu.host.memory import ProcessMemory
from shadow_tpu.host.syscalls import (
    NATIVE,
    NR,
    Blocked,
    CloneGo,
    FatalDivergence,
    NR_NAME,
    SyscallHandler,
)
from shadow_tpu.utils.slog import get_logger

log = get_logger("process")

# the kernel's never-restarted set (man 7 signal): waits, sleeps, and
# the pure signal syscalls EINTR regardless of SA_RESTART
_NO_RESTART = frozenset(NR[n] for n in (
    "pause", "rt_sigsuspend", "rt_sigtimedwait", "poll", "ppoll",
    "select", "pselect6", "epoll_wait", "epoll_pwait", "nanosleep",
    "clock_nanosleep"))

# wall-clock patience for a plugin that neither syscalls nor exits
# (a real-CPU-bound plugin phase); generous because simulator and
# plugin never run concurrently
RECV_TIMEOUT_MS = 120_000


_ASLR_OFF = [False]


def _disable_aslr_inheritable() -> None:
    """personality(ADDR_NO_RANDOMIZE) on this process; children inherit
    it across fork+exec (the reference's disable_aslr.c mechanism)."""
    if _ASLR_OFF[0]:
        return
    import ctypes
    ADDR_NO_RANDOMIZE = 0x0040000
    libc = ctypes.CDLL(None, use_errno=True)
    cur = libc.personality(0xFFFFFFFF)
    if cur != -1:
        libc.personality(cur | ADDR_NO_RANDOMIZE)
    # monotonic once-latch: a racing double-set is idempotent and the
    # personality() call it guards is too
    _ASLR_OFF[0] = True  # shadowlint: unlocked-ok(idempotent latch)


def elf_is_static(path: str) -> bool:
    """True when `path` is an ELF executable with no PT_INTERP — a
    statically linked binary. LD_PRELOAD (the preload backend's whole
    mechanism) is ignored by the kernel for these; the ptrace backend
    interposes them fine (every syscall traps, vDSO patched)."""
    try:
        with open(path, "rb") as f:
            hdr = f.read(64)
            if len(hdr) < 52 or hdr[:4] != b"\x7fELF":
                return False        # not ELF (scripts, etc.)
            if hdr[4] == 2:         # ELFCLASS64
                e_phoff, = struct.unpack_from("<Q", hdr, 0x20)
                e_phentsize, = struct.unpack_from("<H", hdr, 0x36)
                e_phnum, = struct.unpack_from("<H", hdr, 0x38)
            elif hdr[4] == 1:
                # ELFCLASS32: static i386 images ignore LD_PRELOAD
                # just the same — detect them too
                e_phoff, = struct.unpack_from("<I", hdr, 0x1C)
                e_phentsize, = struct.unpack_from("<H", hdr, 0x2A)
                e_phnum, = struct.unpack_from("<H", hdr, 0x2C)
            else:
                return False
            f.seek(e_phoff)
            phdrs = f.read(e_phentsize * e_phnum)
        for i in range(e_phnum):
            p_type, = struct.unpack_from("<I", phdrs, i * e_phentsize)
            if p_type == 3:         # PT_INTERP
                return False
        return True
    except (OSError, struct.error):
        return False


class ManagedRuntime:
    """Per-simulation services shared by all managed processes: the
    shmem arena the IPC channels live in, the shim library path, and
    the DNS view. Created lazily by the Controller when a config names
    a real executable."""

    def __init__(self, dns, data_dir: str, seed: int,
                 spin_max: int = 8096):
        # virtual pids restart per simulation: they are app-visible
        # (getpid/fork), so a process-wide monotonic counter would
        # make back-to-back runs diverge (determinism gate). Instance
        # state, so concurrent Controllers in one interpreter don't
        # rewind each other.
        self._next_vpid = 1000
        self.dns = dns
        self.data_dir = data_dir
        self.spin_max = spin_max
        self.seed = seed
        self._shim_path: Optional[str] = None
        self._arena = None          # built on first preload use only:
        self._closed = False        # the ptrace backend needs neither

    @property
    def shim_path(self) -> str:
        if self._shim_path is None:
            self._shim_path = native.shim_path()
        return self._shim_path

    @property
    def arena(self):
        if self._arena is None:
            # unlink shm files orphaned by dead/killed simulator runs
            # before creating ours (shmem_cleanup.c via main.c:247)
            try:
                n = native.cleanup_orphans()
                if n:
                    log.info("cleaned up %d orphaned shm file(s)", n)
            except Exception as e:      # never block startup on this
                log.debug("orphan cleanup skipped: %s", e)
            name = f"shadowtpu_shm_{os.getpid()}_{self.seed}"
            self._arena = native.ShmArena(name, size=1 << 22,
                                          create=True)
        return self._arena

    def next_vpid(self) -> int:
        v = self._next_vpid
        self._next_vpid += 1
        return v

    def resolve_ip(self, ip_int: int) -> Optional[int]:
        addr = self.dns.resolve_ip(ip_int)
        return addr.host_id if addr is not None else None

    def close(self) -> None:
        if not self._closed:
            self._closed = True
            if self._arena is not None:
                self._arena.unlink()
                self._arena.close()


class ManagedThread:
    """One thread of a managed process (thread.c / thread.rs): its IPC
    channel, its parked-syscall state, and its virtual tid. Exactly one
    thread of a process executes at a time; the simulator drives each
    over its own channel in strict ping-pong."""

    def __init__(self, process: "ManagedProcess", vtid: int, channel):
        self.p = process
        self.vtid = vtid
        self.channel = channel
        self.alive = True
        self.parked: Optional[tuple] = None      # (nr, args)
        self.syscall_state: dict = {}
        self.clear_ctid = 0         # CLONE_CHILD_CLEARTID address
        self.sigmask = 0            # virtual blocked set (bit sig-1)
        self.restore_mask: Optional[int] = None  # sigsuspend epilogue
        self.sigwait: Optional[tuple] = None     # (set, siginfo_ptr)
        self.pending: list = []     # thread-directed (tkill) queue

    def schedule_continue(self, ctx) -> None:
        """Condition wakeup target: resume THIS thread's parked
        syscall (syscall_condition.c -> process_continue, per thread)."""
        self.p._push_task(ctx.now,
                          lambda ctx2, ev: self.p._resume_thread(
                              ctx2, self))


class ManagedProcess:
    """One real executable on one simulated host (app-interface
    compatible with the model runtime: boot / on_stop hooks)."""

    _bypass_warned = False      # one-time raw-syscall disclosure
    supports_threads = True        # preload backend handles clone
    supports_fork = True           # IPC fork handshake (spawn_fork)
    supports_signals = True        # IPC_SIGNAL handler injection
    supports_exec = True           # IPC_EXEC_DONE re-announce

    def __init__(self, runtime: ManagedRuntime, path: str, args,
                 environment: str = ""):
        self.runtime = runtime
        self.path = path
        if isinstance(args, str):
            self.args = shlex.split(args)
        elif isinstance(args, (list, tuple)):
            self.args = [str(x) for x in args]
        elif args is None:
            self.args = []
        else:
            self.args = [str(args)]        # YAML scalar (e.g. a port)
        self.environment = environment
        self.vpid = runtime.next_vpid()

        self.host = None
        self.manager = None
        self.proc = None                  # subprocess.Popen
        self.mem: Optional[ProcessMemory] = None
        self.table: Optional[DescriptorTable] = None
        self.handler: Optional[SyscallHandler] = None
        self.channel: Optional[native.IpcChannel] = None
        self.alive = False
        self.exiting = False
        self.exit_code: Optional[int] = None
        self.futexes: dict[int, object] = {}    # addr -> Futex
        self.threads: dict[int, ManagedThread] = {}
        self.current: Optional[ManagedThread] = None
        self._reaper: Optional[threading.Thread] = None
        self._rng_counter = 0
        self.syscall_counts: dict[str, int] = {}
        # process tree + virtual signals (signal.c / exit.c analogues)
        self.parent_proc: Optional["ManagedProcess"] = None
        self.children: dict[int, "ManagedProcess"] = {}
        self.sigactions: dict[int, tuple] = {}  # sig -> (h, fl, r, m)
        self.pending_signals: list[int] = []
        self.wstatus: Optional[int] = None      # set at exit (zombie)
        self.term_signal: Optional[int] = None  # fatal-signal death
        self._pending_fork: Optional[tuple] = None
        self._forked_pid: Optional[int] = None  # real pid when forked

    # the syscall handler's per-invocation restart state lives on the
    # thread being serviced (SysCallHandler->blockedSyscallNR analogue)
    @property
    def syscall_state(self) -> dict:
        return self.current.syscall_state

    @syscall_state.setter
    def syscall_state(self, v: dict) -> None:
        self.current.syscall_state = v

    @property
    def native_pid(self) -> Optional[int]:
        if self.proc is not None:
            return self.proc.pid
        return self._forked_pid

    # -- spawn plumbing shared by the preload and ptrace backends -------
    def _host_paths(self) -> tuple[str, str, str]:
        """(host_dir, stdout_path, stderr_path) under the host data dir
        (process.c:69-77 working dir, :465-478 stdio redirect)."""
        host_dir = os.path.join(self.runtime.data_dir, "hosts",
                                self.host.name)
        os.makedirs(host_dir, exist_ok=True)
        base = os.path.basename(self.path)
        return (host_dir,
                os.path.join(host_dir, f"{base}.{self.vpid}.stdout"),
                os.path.join(host_dir, f"{base}.{self.vpid}.stderr"))

    def _child_env(self, host_dir: str) -> dict:
        """Base child environment + the config's ';'-separated
        `environment` entries (manager.c:386-505 equivalent)."""
        env = {
            "PATH": os.environ.get("PATH", "/usr/bin:/bin"),
            "HOME": host_dir,
        }
        for kv in self.environment.split(";"):
            kv = kv.strip()
            if "=" in kv:
                k, v = kv.split("=", 1)
                env[k] = v
        return env

    # -- app interface -------------------------------------------------
    def boot(self, ctx) -> None:
        import subprocess

        self.host = ctx.host
        self.manager = ctx._m
        self.mem = None
        self.table = DescriptorTable(self.manager, owner=self)
        self.handler = SyscallHandler(self)
        self.channel = native.IpcChannel(self.runtime.arena,
                                         spin_max=self.runtime.spin_max)

        host_dir, stdout_path, stderr_path = self._host_paths()
        stdout_f = open(stdout_path, "wb")
        stderr_f = open(stderr_path, "wb")

        env = self._child_env(host_dir)
        # forward the shim debug knobs from the simulator's environment
        # (the quick debugging path; config `environment` entries win)
        for k in ("SHADOWTPU_SHIM_LOG", "SHADOWTPU_TRACE_TRAPS",
                  "SHADOWTPU_CTOR_TRACE"):
            if k in os.environ and k not in env:
                env[k] = os.environ[k]
        # publish sim time into the channel only when the shim will
        # read it (log/trace runs): keeps the per-dispatch hot path
        # free of a ctypes call nobody consumes. The gate tests the
        # CHILD's environment — the only one the shim sees
        self.publish_sim_time = (
            "SHADOWTPU_SHIM_LOG" in env
            or "SHADOWTPU_TRACE_TRAPS" in env)
        env["SHADOWTPU_SHM"] = self.runtime.arena.name
        # zero-padded: forked children re-point this at THEIR channel
        # by overwriting digits in place (async-signal-safe), so an
        # execve from any process reconnects to the right channel
        env["SHADOWTPU_IPC_OFFSET"] = f"{self.channel.offset:010d}"
        env["SHADOWTPU_EXEC"] = "0"     # flipped to 1 across an execve
        env["LD_PRELOAD"] = self.runtime.shim_path
        # name resolution for the shim's getaddrinfo/gethostname
        # overrides (preload_libraries.c analogue): the simulated
        # hostname/IP and the DNS hosts file
        env["SHADOWTPU_HOSTNAME"] = self.host.name
        if self.host.ip:
            env["SHADOWTPU_HOST_IP"] = self.host.ip
        hosts_file = os.path.join(self.runtime.data_dir, "etc_hosts")
        if os.path.exists(hosts_file):
            env["SHADOWTPU_HOSTS_FILE"] = os.path.abspath(hosts_file)

        if env.get("SHADOWTPU_STRICT_TRAPS") != "1" \
                and not ManagedProcess._bypass_warned:
            # one-time disclosure (ADVICE r3 #3): outside strict-traps
            # mode the startup-window syscalls stay untrapped, so RAW
            # syscall users of exactly these bypass virtualization
            ManagedProcess._bypass_warned = True
            log.info(
                "preload backend: raw-syscall users of clock_gettime/"
                "gettimeofday/time/getpid/getrandom/open/openat bypass "
                "virtualization (libc callers are interposed); set "
                "SHADOWTPU_STRICT_TRAPS=1 in the process environment "
                "for raw-syscall-heavy binaries that never execve")

        # determinism: disable ASLR in the child (main.c:287,
        # disable_aslr.c). Like the reference, set ADDR_NO_RANDOMIZE on
        # the SIMULATOR process — the personality is inherited by every
        # child, which keeps subprocess on the fork-free posix_spawn
        # path AND avoids a wrapper binary. (A setarch wrapper would be
        # LD_PRELOADed too: its shim instance installs a seccomp filter
        # whose instruction-pointer escape dies at execve, and stacked
        # filters then kill the shim's own raw syscalls.)
        _disable_aslr_inheritable()
        # native fds must stay below the virtual-fd floor
        # (descriptors.VFD_BASE) so the seccomp fd-range gate can
        # never misclassify; libc callers see VIRTUAL rlimits via the
        # emulated getrlimit/prlimit64. Preferred: the STATIC launcher
        # stub in --run mode (rlimit + exec; LD_PRELOAD is inert in a
        # static stub) — no Python ever runs in the forked child of
        # this JAX-threaded process (CPython's documented post-fork
        # hazard) and _posixsubprocess may use vfork. Fallback on
        # machines without static libc: a preexec_fn.
        stub = native.launcher_static_path()
        preexec = None
        if stub is not None:
            # spawn-error parity with the direct-Popen path: Popen
            # raises FileNotFoundError for a missing executable; the
            # stub would only perror+exit 127 in the child, so check
            # resolvability up front (the stub execvp's bare names
            # against PATH, others against cwd=host_dir)
            p = self.path
            if os.sep not in p:
                if not shutil.which(
                        p, path=env.get("PATH",
                                        os.environ.get("PATH", ""))):
                    raise FileNotFoundError(2, "No such file or "
                                            "directory", p)
            elif not os.path.exists(
                    p if os.path.isabs(p)
                    else os.path.join(host_dir, p)):
                raise FileNotFoundError(2, "No such file or "
                                        "directory", p)
            argv = [stub, "--run", self.path] + self.args
        else:
            argv = [self.path] + self.args

            def preexec():
                # a failed cap must fail the spawn LOUDLY (subprocess
                # re-raises preexec exceptions in the parent): a
                # native fd landing in the virtual window [600,1024)
                # would be misclassified as one of ours, and the
                # divergence surfaces far from this cause
                hard = resource.getrlimit(
                    resource.RLIMIT_NOFILE)[1]
                lim = VFD_BASE \
                    if hard == resource.RLIM_INFINITY \
                    else min(VFD_BASE, hard)
                resource.setrlimit(resource.RLIMIT_NOFILE,
                                   (lim, lim))

        self.proc = subprocess.Popen(
            argv, env=env, cwd=host_dir, stdout=stdout_f,
            stderr=stderr_f, stdin=subprocess.DEVNULL,
            preexec_fn=preexec)
        stdout_f.close()
        stderr_f.close()
        self.mem = ProcessMemory(self.proc.pid)
        from shadow_tpu.host.memmap import ProcessMaps
        self.maps = ProcessMaps(self.proc.pid)
        self.alive = True
        main = ManagedThread(self, self.vpid, self.channel)
        self.threads = {self.vpid: main}
        self.current = main
        log.debug("spawned %s pid=%d vpid=%d on %s", self.path,
                  self.proc.pid, self.vpid, self.host.name)

        me = self
        proc = self.proc

        def reap():
            proc.wait()
            # the whole thread group died: every channel must unblock
            for th in list(me.threads.values()):
                th.channel.mark_plugin_exited()

        self._reaper = threading.Thread(target=reap, daemon=True)
        self._reaper.start()
        self._continue(ctx, main)

    def on_stop(self, ctx) -> None:
        self._kill(ctx)

    def on_sim_end(self, ctx) -> None:
        self._kill(ctx)

    def on_timer(self, ctx, data) -> None:     # unused; timerfds use tasks
        pass

    def on_packet(self, ctx, src, size, data) -> None:
        pass

    # -- deterministic service providers -------------------------------
    def resolve_ip(self, ip_int: int) -> Optional[int]:
        return self.runtime.resolve_ip(ip_int)

    def deterministic_bytes(self, n: int) -> bytes:
        """getrandom bytes from the host's seeded stream (the
        determinism role of the openssl_preload RNG override)."""
        return self.host.rng.np_rng().bytes(n)

    def begin_exit(self, code: int) -> None:
        self.exiting = True
        self.exit_code = code

    # -- timers ---------------------------------------------------------
    def _push_task(self, when: int, task) -> None:
        h = self.host
        self.manager.push_event(Event(
            time=when, dst_host=h.host_id, src_host=h.host_id,
            seq=h.next_event_seq(), kind=KIND_TASK, task=task))

    def arm_timerfd(self, ctx, desc, when: int, gen: int) -> None:
        def task(ctx2, ev):
            if gen != desc.generation or desc.closed:
                return
            desc.expirations += 1
            if desc.interval_ns > 0:
                desc.next_expiry = ev.time + desc.interval_ns
                self.arm_timerfd(ctx2, desc, desc.next_expiry, gen)
            else:
                desc.next_expiry = None
            desc.notify(ctx2)

        self._push_task(max(when, ctx.now), task)

    # -- park / resume (syscall_condition.c semantics) ------------------
    def schedule_continue(self, ctx) -> None:
        """Back-compat wakeup target (single-thread callers): resume
        the current thread."""
        th = self.current
        self._push_task(ctx.now,
                        lambda ctx2, ev: self._resume_thread(ctx2, th))

    def _park(self, ctx, b: Blocked, nr: int, args) -> None:
        th = self.current
        th.parked = (nr, args)
        cond = Condition(th)
        for d in b.descs:
            cond.attach(d)
        if b.deadline is not None:
            def timeout_task(ctx2, ev):
                cond.wake(ctx2)

            self._push_task(max(b.deadline, ctx.now), timeout_task)
        # a signal that was already pending when this park began (e.g.
        # raised-while-blocked, then sigsuspend swapped the mask) must
        # interrupt it now — nothing else will re-deliver it
        if self._has_deliverable(th):
            self._push_task(ctx.now, lambda ctx2, ev: (
                self._interrupt_parked(ctx2, th)
                if th.parked is not None else None))

    def _resume_thread(self, ctx, th: ManagedThread) -> None:
        if not self.alive or not th.alive or th.parked is None:
            return
        nr, args = th.parked
        th.parked = None
        self.current = th
        try:
            res = self.handler.dispatch(ctx, nr, args)
        except Blocked as b:
            self._park(ctx, b, nr, args)
            return
        except FatalDivergence:
            raise
        except Exception:
            log.exception("resumed syscall %s(%s) handler crashed",
                          NR_NAME.get(nr, nr), args)
            res = -38              # ENOSYS
        self._reply(res, nr, args)      # overridable (ptrace backend)
        th.syscall_state = {}
        self._continue(ctx, th)

    def _resume_task(self, ctx, ev) -> None:    # legacy alias
        self._resume_thread(ctx, self.current)

    # -- managed threads (clone.c / thread_clone) -----------------------
    def spawn_thread(self, ctx, flags: int, args) -> "CloneGo":
        """Approve a clone: allocate the child's IPC channel + vtid and
        schedule its first run. The shim performs the native clone and
        the child announces itself on the new channel."""
        vtid = self.runtime.next_vpid()
        ch = native.IpcChannel(self.runtime.arena,
                               spin_max=self.runtime.spin_max)
        th = ManagedThread(self, vtid, ch)
        th.sigmask = self.current.sigmask     # clone inherits the mask
        CLONE_CHILD_CLEARTID = 0x00200000
        if flags & CLONE_CHILD_CLEARTID:
            th.clear_ctid = args[3]
        self.threads[vtid] = th
        self._push_task(ctx.now,
                        lambda ctx2, ev: self._start_child(ctx2, th))
        log.debug("clone: new thread vtid=%d on %s", vtid,
                  self.host.name)
        return CloneGo(vtid, ch.offset)

    def _start_child(self, ctx, th: ManagedThread) -> None:
        """First scheduling of a cloned thread: wait for its
        THREAD_START announcement, then release it into app code."""
        if not self.alive or not th.alive:
            return
        status, msg = th.channel.recv_from_plugin_timed(RECV_TIMEOUT_MS)
        if status != 1:
            log.warning("cloned thread vtid=%d never started", th.vtid)
            th.alive = False
            return
        if msg.kind == native.IPC_THREAD_FAIL:
            log.warning("native clone failed for vtid=%d: %d",
                        th.vtid, int(msg.number))
            th.alive = False
            return
        if msg.kind != native.IPC_THREAD_START:
            log.warning("unexpected first message kind=%d from "
                        "vtid=%d", msg.kind, th.vtid)
        go = native.IpcMessage()
        go.kind = native.IPC_START
        go.number = 0
        th.channel.send_to_plugin(go)
        self._continue(ctx, th)

    # -- fork (process.c:457-651's child creation, preload-funnel form)
    def spawn_fork(self, ctx) -> "CloneGo":
        """Approve a fork: allocate the child's vpid + IPC channel.
        The shim does the real COW fork and reports the native pid via
        IPC_FORK_RESULT (handled in _continue -> _complete_fork)."""
        vpid = self.runtime.next_vpid()
        ch = native.IpcChannel(self.runtime.arena,
                               spin_max=self.runtime.spin_max)
        self._pending_fork = (vpid, ch)
        return CloneGo(vpid, ch.offset)

    def _complete_fork(self, ctx, th: ManagedThread,
                       real_pid: int) -> None:
        """IPC_FORK_RESULT from the parent: build the child process
        object around the already-running native child."""
        vpid, ch = self._pending_fork
        self._pending_fork = None
        if real_pid < 0:
            self._reply_to(th, real_pid)
            return
        child = ManagedProcess.__new__(ManagedProcess)
        child.runtime = self.runtime
        child.path = self.path
        child.args = list(self.args)
        child.environment = self.environment
        child.vpid = vpid
        child.host = self.host
        child.manager = self.manager
        child.proc = None
        child._forked_pid = real_pid
        child.mem = ProcessMemory(real_pid)
        # fork semantics: own fd table, shared file descriptions
        child.table = self.table.fork_clone()
        child.handler = SyscallHandler(child)
        child.channel = ch
        child.alive = True
        child.exiting = False
        child.exit_code = None
        child.futexes = {}          # private memory from here on
        main = ManagedThread(child, vpid, ch)
        main.sigmask = self.current.sigmask   # fork inherits the mask
        child.threads = {vpid: main}
        child.current = main
        child._rng_counter = 0
        child.syscall_counts = {}
        child.parent_proc = self
        child.children = {}
        from shadow_tpu.host.memmap import ProcessMaps
        child.maps = ProcessMaps(real_pid)
        child.sigactions = dict(self.sigactions)
        child.pending_signals = []
        child.publish_sim_time = self.publish_sim_time
        child.wstatus = None
        child.term_signal = None
        child._pending_fork = None
        self.children[vpid] = child

        # death watch without being the kernel parent: poll a pidfd
        pidfd = os.pidfd_open(real_pid)

        def reap():
            import select as _select
            _select.select([pidfd], [], [])
            # (no waitid here: the KERNEL parent is the forking
            # plugin, which reaps its own zombies via the shim's
            # wait4 drain; the pidfd only signals death)
            os.close(pidfd)
            for t in list(child.threads.values()):
                t.channel.mark_plugin_exited()

        child._reaper = threading.Thread(target=reap, daemon=True)
        child._reaper.start()

        child._push_task(ctx.now,
                         lambda c2, ev: child._start_forked(c2))
        log.debug("fork: vpid=%d -> child vpid=%d pid=%d on %s",
                  self.vpid, vpid, real_pid, self.host.name)
        self._reply_to(th, vpid)

    def _start_forked(self, ctx) -> None:
        """First scheduling of a forked child: wait for its
        announcement on the new channel, then release it."""
        main = self.current
        if not self.alive or not main.alive:
            return
        status, msg = main.channel.recv_from_plugin_timed(
            RECV_TIMEOUT_MS)
        if status != 1 or msg.kind != native.IPC_THREAD_START:
            log.warning("forked child vpid=%d never announced",
                        self.vpid)
            self.alive = False
            return
        go = native.IpcMessage()
        go.kind = native.IPC_START
        go.number = 0
        main.channel.send_to_plugin(go)
        self._continue(ctx, main)

    # -- virtual signals (signal.c analogue) ----------------------------
    SIG_DFL, SIG_IGN = 0, 1
    SIGKILL, SIGCHLD = 9, 17
    SA_RESTART = 0x10000000
    _DEFAULT_IGNORE = {17, 18, 23, 28}   # CHLD, CONT, URG, WINCH

    def deliver_signal(self, ctx, sig: int,
                       target: "ManagedThread" = None) -> None:
        """Queue a virtual signal; handlers run in the plugin at its
        next syscall boundary (IPC_SIGNAL), exactly where the kernel
        delivers. Default dispositions: terminate, or ignore for the
        usual set. A parked (blocked-syscall) thread is interrupted
        now: handler first, then -EINTR or an SA_RESTART redispatch.
        `target` directs the signal at one thread (tkill/tgkill):
        only that thread's mask gates it and only its queue holds it;
        standard (non-RT, <32) signals coalesce like the kernel's."""
        if not self.alive:
            return
        if sig == self.SIGKILL:
            self.term_signal = sig
            self.exit_code = 128 + sig
            self._kill(ctx)
            return
        bit = 1 << (sig - 1)
        # sigtimedwait consumers outrank dispositions: a thread parked
        # waiting on this signal takes it synchronously, no handler
        for th in self.threads.values():
            if th.alive and th.parked is not None and \
                    th.sigwait is not None and th.sigwait[0] & bit \
                    and (target is None or th is target):
                self._complete_sigwait(ctx, th, sig)
                return
        gate = [target] if target is not None else \
            [t for t in self.threads.values() if t.alive]
        queue = target.pending if target is not None \
            else self.pending_signals
        if gate and all(t.sigmask & bit for t in gate):
            # blocked at every eligible thread: queued regardless of
            # disposition (kernel prepare_signal: sig_ignored() is
            # false when blocked — the block-then-sigtimedwait reaper
            # idiom); ignore/default discard happens at delivery in
            # _flush_signals
            if sig >= 32 or sig not in queue:
                queue.append(sig)
            return
        act = self.sigactions.get(sig)
        handler = act[0] if act else self.SIG_DFL
        if handler == self.SIG_IGN:
            return
        if handler == self.SIG_DFL:
            if sig in self._DEFAULT_IGNORE:
                return
            log.debug("vpid=%d: fatal signal %d (default action)",
                      self.vpid, sig)
            self.term_signal = sig
            self.exit_code = 128 + sig
            self._kill(ctx)
            return
        if sig < 32 and sig in queue:
            return              # standard signals don't stack
        queue.append(sig)
        for th in gate:
            if th.parked is not None and not th.sigmask & bit:
                self._interrupt_parked(ctx, th)
                break

    def _dequeue_deliverable(self, th: "ManagedThread"):
        """Pop the first pending signal `th` doesn't block: directed
        queue first, then the shared process queue (kernel order)."""
        for q in (th.pending, self.pending_signals):
            for i, s in enumerate(q):
                if not th.sigmask & (1 << (s - 1)):
                    return q.pop(i)
        return None

    def _has_deliverable(self, th: "ManagedThread") -> bool:
        return any(not th.sigmask & (1 << (s - 1))
                   for s in th.pending + self.pending_signals)

    def _apply_exec_rules(self, ctx, th: "ManagedThread") -> None:
        """The kernel's exec semantics, shared by both backends:
        sibling threads are gone, close-on-exec descriptors close,
        caught signal dispositions reset to default (ignored ones stay
        ignored, masks and pending signals survive).
        Ref: the exec handling of process.c + kernel exec.c rules."""
        for t in list(self.threads.values()):
            if t is not th:
                t.alive = False     # the kernel killed them on exec
                # their stacks/futexes lived in the REPLACED address
                # space — no CLEARTID writes; just unblock any
                # simulator-side wait on their channels
                if t.channel is not None:
                    t.channel.mark_plugin_exited()
        self.threads = {th.vtid: th}
        self.current = th
        th.parked = None
        th.syscall_state = {}
        th.sigwait = None
        th.restore_mask = None
        for fd in sorted(self.table.cloexec):
            self.table.close_fd(ctx, fd)
        self.sigactions = {
            sig: act for sig, act in self.sigactions.items()
            if act[0] == self.SIG_IGN}

    def _complete_exec(self, ctx, th: "ManagedThread") -> None:
        """The post-execve image announced itself (IPC_EXEC_DONE):
        apply the exec rules, then release the new image into app
        code."""
        new_path = getattr(self, "exec_pending", None)
        if new_path is None:
            log.warning("vpid=%d: unexpected IPC_EXEC_DONE", self.vpid)
        else:
            log.debug("vpid=%d: execve -> %s", self.vpid, new_path)
            self.exec_path = new_path
        self.exec_pending = None
        self._apply_exec_rules(ctx, th)
        self._reply_to(th, 0)

    def _complete_sigwait(self, ctx, th: "ManagedThread",
                          sig: int) -> None:
        """Finish a parked rt_sigtimedwait with `sig` (no handler)."""
        th.parked = None
        info_ptr = th.sigwait[1]
        th.sigwait = None
        self.handler.write_siginfo(info_ptr, sig)
        self.current = th
        self._reply_to(th, sig)
        th.syscall_state = {}
        self._continue(ctx, th)

    def _flush_signals(self, ctx, th: ManagedThread) -> list[tuple]:
        """Run every pending handler in the plugin (the thread must be
        awaiting a reply). Returns the delivered (sig, act) list."""
        delivered = []
        while self.alive and th.alive:
            sig = self._dequeue_deliverable(th)
            if sig is None:
                break           # everything pending is blocked here
            act = self.sigactions.get(sig)
            if act is None or act[0] == self.SIG_DFL:
                # disposition changed since queueing — or it was queued
                # while blocked and the default action applies now
                if sig in self._DEFAULT_IGNORE:
                    continue
                self.term_signal = sig
                self.exit_code = 128 + sig
                self._kill(ctx)
                break
            if act[0] == self.SIG_IGN:
                continue
            msg = native.IpcMessage()
            msg.kind = native.IPC_SIGNAL
            msg.number = sig
            msg.args[0] = act[0]
            msg.args[1] = act[1]
            th.channel.send_to_plugin(msg)
            if not self._await_signal_ack(ctx, th, sig):
                break
            delivered.append((sig, act))
        return delivered

    def _await_signal_ack(self, ctx, th: ManagedThread,
                          sig: int) -> bool:
        """Wait for IPC_SIGNAL_DONE, servicing any trapped syscalls
        the handler itself makes (handlers may legitimately call
        write/kill/time/...). A handler syscall that would BLOCK gets
        -EINTR instead — signal handlers cannot park the ping-pong."""
        while True:
            status, ack = th.channel.recv_from_plugin_timed(
                RECV_TIMEOUT_MS)
            if status != 1:
                log.warning("vpid=%d: signal %d handler did not ack",
                            self.vpid, sig)
                return False
            if ack.kind == native.IPC_SIGNAL_DONE:
                return True
            if ack.kind == native.IPC_SYSCALL:
                nr = int(ack.number)
                args = tuple(int(ack.args[i]) for i in range(6))
                self.current = th
                try:
                    res = self.handler.dispatch(ctx, nr, args)
                except Blocked:
                    from shadow_tpu.host.syscalls import EINTR
                    res = -EINTR
                except FatalDivergence:
                    raise
                except Exception:
                    log.exception("handler-context syscall crashed")
                    res = -38
                self._reply_to(th, res)
                th.syscall_state = {}
                continue
            log.warning("vpid=%d: unexpected ipc kind %d during "
                        "signal %d delivery", self.vpid, ack.kind, sig)
            return False

    def _interrupt_parked(self, ctx, th: ManagedThread) -> None:
        """Deliver pending signals to a thread blocked in an emulated
        syscall: run the handlers, then either redispatch (SA_RESTART)
        or fail the syscall with -EINTR."""
        nr, args = th.parked
        th.parked = None
        delivered = self._flush_signals(ctx, th)
        if not self.alive or not th.alive:
            return
        if not delivered:
            # nothing ran (dispositions changed): re-park untouched
            th.parked = (nr, args)
            return
        if th.restore_mask is not None:
            # sigsuspend epilogue: handler ran, original mask returns
            th.sigmask = th.restore_mask
            th.restore_mask = None
        th.sigwait = None       # an interrupted sigtimedwait is over
        from shadow_tpu.host.syscalls import EINTR
        restartable = nr not in _NO_RESTART
        if restartable and all(a[1] & self.SA_RESTART
                               for _, a in delivered):
            self.current = th
            try:
                res = self.handler.dispatch(ctx, nr, args)
            except Blocked as b:
                self._park(ctx, b, nr, args)
                return
            except FatalDivergence:
                raise
            except Exception:
                log.exception("restarted syscall failed")
                res = -38
        else:
            res = -EINTR
        self._reply_to(th, res)
        th.syscall_state = {}
        self._continue(ctx, th)

    def child_exited(self, ctx, child: "ManagedProcess") -> None:
        """A forked child became a zombie: SIGCHLD + wake any thread
        parked in wait4."""
        self.deliver_signal(ctx, self.SIGCHLD)
        if not self.alive:
            return
        from shadow_tpu.host.syscalls import NR
        for th in self.threads.values():
            if th.alive and th.parked is not None and \
                    th.parked[0] in (NR["wait4"], NR["waitid"]):
                th.schedule_continue(ctx)
                break

    def thread_exit(self, ctx, th: ManagedThread, code: int) -> bool:
        """SYS_exit from one thread. Marks the thread dead; the
        CLEARTID write + futex wake for pthread_join'ers is deferred to
        _finish_thread_exit, AFTER the kernel confirms the native
        thread died (waking early lets glibc free a stack the dying
        thread's signal epilogue still runs on). Returns True if this
        was the last thread (the process is exiting)."""
        th.alive = False
        alive = [t for t in self.threads.values() if t.alive]
        if not alive:
            self.begin_exit(code)
            return True
        return False

    def _finish_thread_exit(self, ctx, th: ManagedThread) -> None:
        """After replying to an exiting (non-last) thread: wait for the
        kernel-cleared death guard (native_thread_alive, armed by the
        shim's clone as the kernel's CLEARTID word), then publish the
        virtual CLEARTID and wake joiners.

        The wait is authoritative: joiners are NEVER woken while the
        kernel still reports the native thread alive — glibc frees the
        thread stack on join, and a not-yet-dead thread's exit epilogue
        still runs on it (the round-1 crash). A thread that outlives
        the hard deadline fails the simulation loudly instead of
        degrading to that race."""
        import time as _time
        deadline = _time.monotonic() + RECV_TIMEOUT_MS / 1000.0
        ch = th.channel
        spins = 0
        while ch.native_thread_alive():
            if _time.monotonic() > deadline:
                raise RuntimeError(
                    f"managed thread vtid={th.vtid} (pid "
                    f"{self.native_pid}) did not die within "
                    f"{RECV_TIMEOUT_MS // 1000}s of its exit syscall; "
                    "refusing to wake joiners onto a live stack")
            spins += 1
            # death normally follows within µs; back off if not
            _time.sleep(0 if spins < 10_000 else 0.0005)
        if th.clear_ctid:
            import struct as _s
            try:
                self.mem.write(th.clear_ctid, _s.pack("<I", 0))
            except OSError:
                pass
            fx = self.futexes.get(th.clear_ctid)
            if fx is not None:
                fx.wake(ctx, 1 << 30)

    # -- the IPC ping-pong loop (thread_preload.c event loop) -----------
    def _reply_to(self, th: ManagedThread, res) -> None:
        if th.restore_mask is not None:
            # a p-variant wait's temporary mask (or sigsuspend's, on
            # paths _interrupt_parked didn't cover) ends with the call
            th.sigmask = th.restore_mask
            th.restore_mask = None
        msg = native.IpcMessage()
        if res is NATIVE:
            msg.kind = native.IPC_SYSCALL_NATIVE
            msg.number = 0
        elif isinstance(res, CloneGo):
            msg.kind = native.IPC_CLONE_GO
            msg.number = res.vtid
            msg.args[0] = res.channel_offset
        else:
            msg.kind = native.IPC_SYSCALL_DONE
            msg.number = int(res)
        th.channel.send_to_plugin(msg)

    def _reply(self, res, nr: int, args) -> None:   # legacy signature
        self._reply_to(self.current, res)

    def _continue(self, ctx, th: Optional[ManagedThread] = None) -> None:
        """Service one thread's syscalls until it blocks, exits, or
        hands control back (one thread of the process runs at a time)."""
        if th is None:
            th = self.current
        while True:
            status, msg = th.channel.recv_from_plugin_timed(
                RECV_TIMEOUT_MS)
            if status == 0:            # plugin (thread group) exited
                self._finalize_exit(ctx)
                return
            if status == -1:           # wall-clock stall
                log.warning("%s pid=%s unresponsive for %ds; killing",
                            self.path, self.native_pid,
                            RECV_TIMEOUT_MS // 1000)
                self._kill(ctx)
                return
            if msg.kind == native.IPC_FORK_RESULT:
                self._complete_fork(ctx, th, int(msg.number))
                continue
            if msg.kind == native.IPC_EXEC_DONE:
                self._complete_exec(ctx, th)
                continue
            if msg.kind != native.IPC_SYSCALL:
                log.warning("unexpected ipc kind %d", msg.kind)
                continue
            if getattr(self, "exec_pending", None) is not None:
                # a normal syscall after an approved execve means the
                # native exec failed — the old image lives on
                self.exec_pending = None
            nr = int(msg.number)
            args = tuple(int(msg.args[i]) for i in range(6))
            name = NR_NAME.get(nr, str(nr))
            self.syscall_counts[name] = self.syscall_counts.get(name,
                                                                0) + 1
            self.current = th
            try:
                res = self.handler.dispatch(ctx, nr, args)
            except Blocked as b:
                self._park(ctx, b, nr, args)
                return
            except FatalDivergence:
                raise
            except Exception:
                log.exception("syscall %s(%s) handler crashed", name,
                              args)
                res = -38              # ENOSYS
            # deliver pending virtual signals (e.g. a self-kill) at
            # the syscall boundary, before the result lands
            if (self.pending_signals or th.pending) and th.alive \
                    and self.alive:
                self._flush_signals(ctx, th)
                if not self.alive:
                    return             # a fatal disposition fired
            self._reply_to(th, res)
            th.syscall_state = {}
            if not th.alive:           # replied to an exiting thread
                if any(t.alive for t in self.threads.values()):
                    # wake pthread_join'ers only after the kernel
                    # confirms the native thread died
                    self._finish_thread_exit(ctx, th)
                    return             # others keep the process alive
                # last thread: the reply lets the native process die;
                # wait for the reaper's exited flag so sockets close
                # and the exit code lands NOW, not at sim end
                status, _ = th.channel.recv_from_plugin_timed(
                    RECV_TIMEOUT_MS)
                if status == 0:
                    self._finalize_exit(ctx)
                else:
                    log.warning("%s: exit did not complete; killing",
                                self.path)
                    self._kill(ctx)
                return

    # -- teardown -------------------------------------------------------
    def _finalize_exit(self, ctx) -> None:
        if not self.alive:
            return
        self.alive = False
        for th in self.threads.values():
            th.alive = False
        if self._reaper is not None:
            self._reaper.join(timeout=10)
        rc = self.proc.returncode if self.proc is not None else None
        if self.exit_code is None and rc is not None:
            self.exit_code = rc
        log.debug("%s on %s exited code=%s (%d syscalls)", self.path,
                  self.host.name, self.exit_code,
                  sum(self.syscall_counts.values()))
        if self.table is not None:
            self.table.close_all(ctx)
        # orphaned forked children die with us (no re-parenting
        # model); a child that armed PR_SET_PDEATHSIG gets its chosen
        # signal VIRTUALLY first, and the no-orphans hard kill is
        # DEFERRED one sim-millisecond so an installed handler gets a
        # syscall boundary to actually run (default dispositions
        # terminate during the delivery itself)
        for child in list(self.children.values()):
            if not child.alive:
                continue
            sig = getattr(child, "pdeathsig", 0)
            if sig:
                try:
                    child.deliver_signal(ctx, sig)
                except Exception:
                    log.exception("pdeathsig delivery failed")
                if child.alive:
                    child._push_task(
                        ctx.now + 1_000_000,
                        lambda ctx2, ev, c=child: (
                            c._kill(ctx2) if c.alive else None))
                    continue
            if child.alive:
                child._kill(ctx)
        # become a zombie for the parent's wait4: WIFSIGNALED encodes
        # the signal in the low 7 bits, WIFEXITED the code in byte 1
        if self.term_signal is not None:
            self.wstatus = self.term_signal & 0x7F
        else:
            self.wstatus = ((self.exit_code or 0) & 0xFF) << 8
        if self.parent_proc is not None and self.parent_proc.alive:
            self.parent_proc.child_exited(ctx, self)

    def _kill(self, ctx) -> None:
        if not self.alive:
            return
        if self.proc is not None:
            try:
                self.proc.kill()
            except ProcessLookupError:
                pass
        elif self._forked_pid is not None:
            import signal as _signal
            try:
                os.kill(self._forked_pid, _signal.SIGKILL)
            except ProcessLookupError:
                pass
        self._finalize_exit(ctx)
