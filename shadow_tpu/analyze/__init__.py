"""shadowlint — static determinism & cache-soundness analysis.

Shadow's bit-identity contract (serial == thread == hybrid == tpu,
cached == fresh, replica i == standalone i) is enforced at runtime by
the determinism gate; this package is the STATIC half of that
enforcement — three passes that prove the properties the gates can
only spot-check, without executing a single simulated event:

* **Pass 1 — jaxpr audit** (:mod:`.jaxpr_audit`): trace every
  dispatchable device program (``engine.lowerable_programs()``) and
  walk the ClosedJaxprs for (a) non-scalar closure constants not
  threaded through the traced ``wrld`` tuple — a leaked world value
  is a stale-cache and broken-ensemble hazard, (b) primitives outside
  a pinned allowlist of known-deterministic ops, and (c) cross-shard
  collectives whose axis or buffer capacity is not in the engine's
  ``collective_registry()``.
* **Pass 2 — fingerprint completeness** (:mod:`.imports_audit`): an
  import-graph walk from the engine's trace roots computes the set of
  modules whose source can shape a compiled program and requires it
  to be a subset of the AOT cache's code-digest list
  (``aotcache.CODE_DIGEST_MODULES``) — the digest list stops being
  hand-maintained and becomes machine-checked.
* **Pass 3 — concurrency lint** (:mod:`.concurrency`): an AST pass
  over the host-side layers that flags writes to registered
  shared-mutable state outside ``with <lock>`` regions, seeded from a
  declared lock registry — the ``_streams`` bug class.

All passes share one findings format (:mod:`.findings`) with
severities, a checked-in baseline for grandfathered findings (new
findings fail, suppressed ones are listed with reasons), and a
``--fix-hints`` mode that names the repair. Driver:
``scripts/analyze.py``; docs: ``docs/static_analysis.md``.
"""

from shadow_tpu.analyze.findings import (          # noqa: F401
    Finding,
    SEV_ERROR,
    SEV_WARNING,
    apply_baseline,
    load_baseline,
    write_baseline,
)

PASS_NAMES = ("jaxpr", "digest", "concurrency")

# finding-code prefix per pass (findings.CODES blocks): stale-
# suppression detection must only consider codes whose pass actually
# ran — a --pass subset run cannot know whether the other passes'
# suppressed findings still exist
PASS_CODE_PREFIX = {"jaxpr": "SL1", "digest": "SL2",
                    "concurrency": "SL3"}


def run_pass(name: str) -> list:
    """Run one named pass and return its findings list."""
    if name == "jaxpr":
        from shadow_tpu.analyze import jaxpr_audit

        return jaxpr_audit.run()
    if name == "digest":
        from shadow_tpu.analyze import imports_audit

        return imports_audit.run()
    if name == "concurrency":
        from shadow_tpu.analyze import concurrency

        return concurrency.run()
    raise ValueError(
        f"unknown pass {name!r} (choose from {PASS_NAMES})")
