"""Pass 1 — jaxpr audit of the device engine's traced programs.

The engine's determinism and cache-soundness contracts are properties
of the TRACED program, so this pass inspects exactly that: every
dispatchable program (``engine.lowerable_programs()`` — the same
names the AOT cache keys on) is traced abstractly
(``jit.trace(ShapeDtypeStruct...)``, zero device work, nothing
compiled or executed) and its ClosedJaxpr is walked for three bug
classes:

* **SL101 leaked closure constant** — a non-scalar array captured by
  the trace instead of threaded through the ``wrld`` tuple. A leaked
  world value is invisible to the program fingerprint (stale AOT
  cache entries would load for the wrong world) and frozen across
  ensemble replicas (every replica silently simulates replica 0's
  world). Allowed captures are value-matched against
  ``engine.audit_consts()`` and must carry a
  ``# shadowlint: const-ok(reason)`` comment at the capture site.
* **SL102 unpinned primitive** — an op outside PRIMITIVE_ALLOWLIST.
  The allowlist is the reviewed set of known-deterministic,
  TPU-friendly primitives the engine lowers to today; a new primitive
  appearing is exactly the event a human should look at (is it
  bit-deterministic across backends? is it a scatter sneaking into
  the hot path?).
* **SL103/SL104 collective drift** — a cross-shard collective whose
  axis or buffer capacity is not in ``engine.collective_registry()``,
  or a registered exchange mover that never appears in the lowered
  program. ``determinism_gate --analyze-consistency`` cross-checks
  the same registry against ``engine.effective{}`` at runtime.
"""

from __future__ import annotations

import hashlib
import re

import numpy as np

from shadow_tpu.analyze.findings import (
    SEV_ERROR,
    SEV_WARNING,
    Finding,
)
from shadow_tpu.utils.slog import get_logger

log = get_logger("analyze")

# The pinned allowlist: every primitive the engine's programs lower
# to today, reviewed for determinism. Notes on the entries a reader
# will squint at:
#   * sort        — jax lax.sort is stable; the engine's whole
#                   determinism story rides on it;
#   * scatter / scatter-add — app-level state updates
#                   (``app_state.at[:, k].set/add``) lower to per-host
#                   ROW scatters on tiny [H, words] operands; the
#                   engine hot path (heaps/outbox/exchange) stays
#                   scatter-free per the v2 design, and a scatter
#                   appearing elsewhere still trips SL102 on any NEW
#                   primitive variant (scatter-mul, scatter-min, ...);
#   * threefry2x32 rides inside pjit calls (counter-based, stateless);
#   * optimization_barrier — the prng vmap batching rule.
PRIMITIVE_ALLOWLIST = frozenset({
    "add", "all_gather", "all_to_all", "and", "axis_index",
    "bitcast_convert_type", "broadcast_in_dim", "concatenate",
    "cond", "convert_element_type", "copy", "cumprod", "cumsum",
    "device_put", "div", "dynamic_slice", "dynamic_update_slice",
    "eq", "gather", "ge", "gt", "iota", "le", "le_to", "lt", "max",
    "min", "mul", "ne", "neg", "not", "optimization_barrier", "or",
    "pad", "pjit", "population_count", "ppermute", "psum",
    "reduce_and", "reduce_max", "reduce_min", "reduce_or",
    "reduce_sum", "rem", "reshape", "scan", "scatter", "scatter-add",
    "select_n", "shard_map", "shift_left", "shift_right_arithmetic",
    "shift_right_logical", "sign", "slice", "sort", "squeeze", "sub",
    "threefry2x32", "transpose", "while", "xor",
})

# collective primitives whose axis/shape the registry pins
COLLECTIVE_PRIMS = frozenset({
    "psum", "pmin", "pmax", "ppermute", "all_to_all", "all_gather",
    "reduce_scatter", "pbroadcast", "axis_index",
})

# which exchange variant must lower to which mover primitive — the
# presence half of the collective check (SL104)
EXCHANGE_MOVER = {
    "all_to_all": "all_to_all",
    "all_gather": "all_gather",
    "two_phase": "ppermute",
}

# audit_consts() entry -> the capture-site variable in engine.py that
# must carry the const-ok comment (the suppression is source-visible,
# the value match is machine-checked)
CAPTURE_SITES = {
    "model_nic.LAW": "law_t",
    "bw_up": "bw_up_t",
    "bw_down": "bw_down_t",
}

_ENGINE_REL = "shadow_tpu/device/engine.py"


# ---------------------------------------------------------------------
# jaxpr walking
# ---------------------------------------------------------------------
def _sub_jaxprs(val):
    """Yield (jaxpr, consts|None) for any jaxpr-valued eqn param."""
    vals = val if isinstance(val, (list, tuple)) else [val]
    for x in vals:
        if hasattr(x, "eqns"):                       # open Jaxpr
            yield x, None
        elif hasattr(x, "jaxpr") and hasattr(x.jaxpr, "eqns"):
            yield x.jaxpr, getattr(x, "consts", None)  # ClosedJaxpr


def walk_jaxpr(closed):
    """Flatten one ClosedJaxpr: returns (consts, eqns) over the whole
    nested program (while bodies, cond branches, pjit calls,
    shard_map inner jaxprs, ...)."""
    consts, eqns = list(closed.consts), []

    def _walk(jx):
        for eqn in jx.eqns:
            eqns.append(eqn)
            for v in eqn.params.values():
                for sub, sub_consts in _sub_jaxprs(v):
                    if sub_consts:
                        consts.extend(sub_consts)
                    _walk(sub)

    _walk(closed.jaxpr)
    return consts, eqns


def _collective_axis(eqn):
    ax = eqn.params.get("axis_name", None)
    if ax is None:
        ax = eqn.params.get("axes", None)
    if isinstance(ax, (tuple, list)):
        ax = ax[0] if len(ax) == 1 else tuple(ax)
    return ax


# ---------------------------------------------------------------------
# constant classification
# ---------------------------------------------------------------------
def classify_const(arr) -> str:
    """'scalar' | 'fill' | 'iota' | 'opaque' — only opaque constants
    need an explicit allowance (fills and affine iotas are shape
    artifacts of the static program, carrying no world data).

    The iota class is deliberately narrow: exact integer arithmetic
    for integer dtypes (float64 diffs would alias i64 values past
    2^53) and a constant stride over at least 3 elements — any
    2-element pair is trivially 'affine', so pairs only qualify as
    the literal unit iota [0, 1] (what a 2-wide jnp.arange
    materializes to). Residual risk — a LEAKED table whose values
    happen to be evenly spaced (e.g. a uniform epoch_times vector)
    classifies as iota; the world()-threading convention plus the
    --analyze-consistency gate's real-config audit are the backstop
    for that corner."""
    a = np.asarray(arr)
    if a.size <= 1:
        return "scalar"
    flat = a.ravel()
    if (flat == flat.flat[0]).all():
        return "fill"
    if flat.size == 2 and np.issubdtype(flat.dtype, np.number) and \
            flat[0] == 0 and flat[1] == 1:
        return "iota"
    if flat.size >= 3 and np.issubdtype(flat.dtype, np.number):
        if np.issubdtype(flat.dtype, np.integer):
            d = np.diff(flat.astype(object))   # exact, no 2^53 alias
        else:
            d = np.diff(flat.astype(np.float64))
        if (d == d[0]).all():
            return "iota"                  # affine: arange * k + b
    return "opaque"


def _const_matches(arr, allowed: dict):
    a = np.asarray(arr)
    for name, ref in allowed.items():
        r = np.asarray(ref)
        if a.shape == r.shape and a.dtype == r.dtype and \
                np.array_equal(a, r):
            return name
    return None


def const_ok_targets(path: str) -> set[str]:
    """Assignment targets covered by a ``# shadowlint: const-ok(...)``
    comment: the comment block covers the run of simple assignments
    immediately following it (so one comment can cover a pair like
    bw_up_t/bw_down_t on consecutive lines)."""
    import ast

    with open(path) as f:
        src = f.read()
    lines = src.splitlines()
    marks = [i + 1 for i, ln in enumerate(lines)
             if re.search(r"#\s*shadowlint:\s*const-ok\(", ln)]
    if not marks:
        return set()
    assigns = []                       # (lineno, [target names])
    for node in ast.walk(ast.parse(src)):
        if isinstance(node, ast.Assign):
            names = [t.id for t in node.targets
                     if isinstance(t, ast.Name)]
            if names:
                assigns.append((node.lineno, names))
    assigns.sort()
    covered: set[str] = set()
    for m in marks:
        run_prev = None
        for ln, names in assigns:
            if ln <= m:
                continue
            # the first assignment within a short window after the
            # comment starts the covered run; consecutive assignment
            # lines extend it
            if run_prev is None:
                if ln - m > 6:
                    break
            elif ln - run_prev > 1:
                break
            covered.update(names)
            run_prev = ln
    return covered


# ---------------------------------------------------------------------
# per-program audit
# ---------------------------------------------------------------------
def audit_closed_jaxpr(closed, *, program: str,
                       allowed_consts: dict | None = None,
                       registry: dict | None = None,
                       ok_targets: set | None = None,
                       capture_sites: dict | None = None,
                       ) -> list[Finding]:
    """Audit one traced program. Separated from the engine matrix so
    tests can feed deliberately-broken fixture programs."""
    allowed = dict(allowed_consts or {})
    sites = (CAPTURE_SITES if capture_sites is None
             else capture_sites)
    consts, eqns = walk_jaxpr(closed)
    out = []

    for c in consts:
        kind = classify_const(c)
        if kind != "opaque":
            continue
        a = np.asarray(c)
        name = _const_matches(a, allowed)
        if name is None:
            # the content digest joins the identity key: a baseline
            # suppression of one known const must not grandfather a
            # DIFFERENT future leak of the same shape and dtype
            digest = hashlib.sha256(
                np.ascontiguousarray(a).tobytes()).hexdigest()[:8]
            out.append(Finding(
                code="SL101", severity=SEV_ERROR, path=program,
                obj=f"const{list(a.shape)}:{a.dtype}:{digest}",
                message=(
                    f"non-scalar closure constant {a.shape} "
                    f"{a.dtype} is baked into the trace but not "
                    "threaded through the wrld tuple — invisible to "
                    "the program fingerprint (stale-cache hazard) "
                    "and frozen across ensemble replicas"),
                hint=("thread the array through the traced wrld "
                      "tuple (engine.world()), or — if the bytes "
                      "are covered by the cache key another way — "
                      "register it in engine.audit_consts() and mark "
                      "the capture site with "
                      "# shadowlint: const-ok(<reason>)")))
        elif ok_targets is not None:
            site = sites.get(name)
            if site is not None and site not in ok_targets:
                out.append(Finding(
                    code="SL105", severity=SEV_ERROR, path=program,
                    obj=name,
                    message=(
                        f"allowed constant {name!r} (capture site "
                        f"{site!r}) has no "
                        "# shadowlint: const-ok(...) comment"),
                    hint=(f"add # shadowlint: const-ok(<reason>) "
                          f"above the {site} assignment in "
                          f"{_ENGINE_REL}")))

    prims = sorted({e.primitive.name for e in eqns})
    for p in prims:
        if p not in PRIMITIVE_ALLOWLIST:
            out.append(Finding(
                code="SL102", severity=SEV_ERROR, path=program,
                obj=p,
                message=(f"primitive {p!r} is outside the pinned "
                         "deterministic allowlist"),
                hint=("review the op for cross-backend bit-"
                      "determinism (and the no-scatters hot-path "
                      "rule), then add it to PRIMITIVE_ALLOWLIST in "
                      "shadow_tpu/analyze/jaxpr_audit.py with a "
                      "note")))

    if registry is not None:
        seen_prims = set()
        for eqn in eqns:
            p = eqn.primitive.name
            if p not in COLLECTIVE_PRIMS:
                continue
            seen_prims.add(p)
            ax = _collective_axis(eqn)
            ent = registry.get(p)
            if ent is None:
                out.append(Finding(
                    code="SL103", severity=SEV_ERROR, path=program,
                    obj=p,
                    message=(f"collective {p!r} is not in the "
                             "engine's collective registry for this "
                             "build"),
                    hint=("teach engine.collective_registry() about "
                          "the new collective (and pin its buffer "
                          "capacity) — then determinism_gate "
                          "--analyze-consistency keeps it honest")))
                continue
            if ax != ent["axis"]:
                out.append(Finding(
                    code="SL103", severity=SEV_ERROR, path=program,
                    obj=f"{p}:axis={ax!r}",
                    message=(f"collective {p!r} runs over axis "
                             f"{ax!r}, registry pins "
                             f"{ent['axis']!r}"),
                    hint="collectives must stay on the mesh axis"))
            caps = ent.get("caps")
            if caps:
                for v in eqn.invars:
                    shp = tuple(getattr(v.aval, "shape", ()))
                    last = shp[-1] if shp else 1
                    if last not in caps:
                        out.append(Finding(
                            code="SL103", severity=SEV_ERROR,
                            path=program,
                            obj=f"{p}:dim={last}",
                            message=(
                                f"{p!r} buffer trailing dim {last} "
                                f"not in the pinned capacities "
                                f"{sorted(caps)} — the exchange is "
                                "moving an unplanned buffer"),
                            hint=("size the buffer from the "
                                  "planned capacity (engine."
                                  "effective CAP/CAP2) or update "
                                  "collective_registry()")))
                        break
        mover = registry.get("__expect_mover__")
        if mover and mover not in seen_prims:
            out.append(Finding(
                code="SL104", severity=SEV_ERROR, path=program,
                obj=mover,
                message=(f"exchange mover {mover!r} is registered "
                         "for this build but absent from the "
                         "lowered program"),
                hint=("the static registry and the real program "
                      "drifted — rebuild the registry from the "
                      "resolved config")))
    return out


# ---------------------------------------------------------------------
# the engine matrix
# ---------------------------------------------------------------------
def _build_engine(exchange="all_to_all", app=None, ensemble=None,
                  epochs=1, **cfg_kw):
    from shadow_tpu.device.apps import PholdDevice
    from shadow_tpu.device.engine import DeviceEngine, EngineConfig

    H = cfg_kw.pop("H", 8)
    cfg_kw.setdefault("event_capacity", 8)
    cfg_kw.setdefault("outbox_capacity", 8)
    cfg = EngineConfig(n_hosts=H, lookahead=1_000_000,
                       stop_time=10_000_000, exchange=exchange,
                       **cfg_kw)
    app = app or PholdDevice(n_hosts_total=H, msgload=2)
    lat = np.full((2, 2), 1_000_000, np.int64)
    rel = np.ones((2, 2), np.float32)
    rel[0, 1] = 0.9                 # keep the drop rolls in the trace
    ept = None
    if epochs > 1:
        lat = np.stack([lat] * epochs)
        rel = np.stack([rel] * epochs)
        ept = (np.arange(epochs) * 5_000_000).astype(np.int64)
    return DeviceEngine(cfg, app, np.zeros(H, np.int32), lat, rel,
                        epoch_times=ept, ensemble=ensemble)


def _tiny_ensemble(R=2):
    """Duck-typed EnsembleWorlds (the engine only reads arrays + R)."""
    from shadow_tpu.ensemble.spec import seed_key_np

    class _W:
        pass

    w = _W()
    w.R = R
    lat = np.full((2, 2), 1_000_000, np.int32)
    rel = np.ones((2, 2), np.float32)
    rel[0, 1] = 0.9
    w.latency = np.stack([lat] * R)
    w.reliability = np.stack([rel] * R)
    w.epoch_times = np.zeros((R, 1), np.int64)
    ks = [seed_key_np(s) for s in range(1, R + 1)]
    w.seed_k1 = np.array([k[0] for k in ks], np.uint32)
    w.seed_k2 = np.array([k[1] for k in ks], np.uint32)
    return w


def engine_matrix() -> list[tuple[str, object]]:
    """Representative engine builds spanning every traced-code branch
    family: exchange schedules, the fluid NIC (LAW/bw consts), fault
    epochs, the audit word, both merge/pop strategies, path counting,
    burst apps, and the vmapped ensemble program."""
    from shadow_tpu.device.apps import TgenDevice

    H = 8
    tgen = TgenDevice(roles=np.array([0] + [1] * (H - 1), np.int32),
                      server_gid=np.zeros(H, np.int32),
                      size=1 << 16)
    bw = np.full(H, 5 * 10 ** 8, np.int64)

    builds = [
        ("base", _build_engine()),
        ("model_bandwidth", _build_engine(model_bandwidth=True)),
        ("count_paths", _build_engine(count_paths=True)),
        ("audited", _build_engine(audit=True)),
        ("two_phase", _build_engine(exchange="two_phase")),
        ("all_gather", _build_engine(exchange="all_gather")),
        ("window_merge", _build_engine(merge_global=False,
                                       pop_onehot=False,
                                       judge_hoist=False)),
        ("tpu_strategies", _build_engine(merge_global=True,
                                         pop_onehot=True,
                                         judge_hoist=True,
                                         outbox_compact=4)),
        ("table_onehot", _build_engine(table_onehot=True,
                                       judge_hoist=True)),
        ("tgen_faults", _build_engine(app=tgen, epochs=2,
                                      event_capacity=16,
                                      outbox_capacity=16)),
        ("ensemble", _build_engine(ensemble=_tiny_ensemble())),
    ]
    # the fluid NIC with real (non-fill) bandwidth vectors, so the
    # bw_up/bw_down consts are exercised as opaque captures
    from shadow_tpu.device.apps import PholdDevice
    from shadow_tpu.device.engine import DeviceEngine, EngineConfig

    cfg = EngineConfig(n_hosts=H, event_capacity=8,
                       outbox_capacity=8, lookahead=1_000_000,
                       stop_time=10_000_000, model_bandwidth=True)
    bw_var = bw.copy()
    bw_var[1] = 10 ** 9
    eng = DeviceEngine(cfg, PholdDevice(n_hosts_total=H, msgload=2),
                       np.zeros(H, np.int32),
                       np.full((2, 2), 1_000_000, np.int64),
                       np.ones((2, 2), np.float32),
                       bw_up_bits=bw_var, bw_down_bits=bw)
    builds.append(("model_bandwidth_vec", eng))
    return builds


def audit_engine(engine, label: str,
                 ok_targets: set | None = None) -> list[Finding]:
    out = []
    registry = dict(engine.collective_registry())
    if engine.n_shards > 1:
        registry["__expect_mover__"] = \
            EXCHANGE_MOVER[engine.effective["exchange"]]
    allowed = engine.audit_consts()
    for name, (jit_fn, args) in engine.lowerable_programs().items():
        closed = jit_fn.trace(*args).jaxpr
        reg = registry
        if name in ("pop",):
            # the pop phase contains no exchange; presence is only
            # required of programs that flush
            reg = {k: v for k, v in registry.items()
                   if k != "__expect_mover__"}
        out.extend(audit_closed_jaxpr(
            closed, program=f"engine[{label}]:{name}",
            allowed_consts=allowed, registry=reg,
            ok_targets=ok_targets))
    return out


def run() -> list[Finding]:
    """Audit the whole engine matrix. Pure tracing: no compile, no
    dispatch, no device state — the determinism_gate --telemetry-
    style spot check in CI confirms analysis runs perturb nothing."""
    import shadow_tpu.device.engine as engine_mod
    from shadow_tpu._jax import jax

    ok_targets = const_ok_targets(engine_mod.__file__)
    findings = []
    if len(jax.devices()) == 1:
        findings.append(Finding(
            code="SL104", severity=SEV_WARNING, path="jaxpr",
            obj="mesh",
            message=("single-device backend: cross-shard collectives "
                     "never lower, so the collective audit is "
                     "vacuous this run"),
            hint=("run under XLA_FLAGS=--xla_force_host_platform_"
                  "device_count=4 (scripts/analyze.py does this by "
                  "default)")))
    for label, eng in engine_matrix():
        found = audit_engine(eng, label, ok_targets=ok_targets)
        log.info("jaxpr audit: engine[%s] -> %d finding(s)", label,
                 len(found))
        findings.extend(found)
    return findings
