"""Pass 3 — concurrency lint over the host-side layers.

The CPU scheduler runs hosts on worker threads; the Manager, the
Controller, and the host/ emulation layers therefore carry a handful
of genuinely shared mutable structures (the cross-host TCP stream
registry, the hybrid judge's pending-packet list, the shared trace
list, the path-packet histogram). PR 2's ``_streams`` create-vs-
teardown race was found by hand during review; this pass makes the
class mechanical:

* :data:`LOCK_REGISTRY` declares, per file, which attribute is
  shared-mutable and which lock guards it. Every WRITE to a
  registered attribute — mutation calls (``append``/``update``/
  ``pop``/...), subscript stores/deletes, and rebinds — must sit
  inside a ``with <lock>`` region naming the registered lock
  (SL301). Construction sites (``__init__``/``__post_init__``) are
  exempt: the object is not yet shared there (happens-before via the
  thread start).
* Module-level dicts/lists/sets written from inside any function
  body without an enclosing lock are flagged generically (SL302) —
  import-time population is fine, post-import mutation from
  per-host/per-worker code paths is the bug class.
* ``# shadowlint: unlocked-ok(reason)`` on the write line suppresses
  either finding in place (single-threaded-by-construction or
  idempotent-latch paths); each suppression is logged with its
  captured reason when the pass runs, and the reason lives at the
  write site where a reviewer reads it.
"""

from __future__ import annotations

import ast
import os
import re

from shadow_tpu.analyze.findings import SEV_ERROR, Finding
from shadow_tpu.utils.slog import get_logger

log = get_logger("analyze")

# the declared lock registry: file -> {shared attribute -> its lock}.
# Seeded from the structures the Manager/NetworkModel already guard;
# registering a NEW shared structure here is part of adding it.
LOCK_REGISTRY = {
    "shadow_tpu/core/manager.py": {
        "self._streams": "self._streams_lock",
        "self._pending": "self._pending_lock",
        "self.trace": "self._trace_lock",
    },
    "shadow_tpu/core/netmodel.py": {
        "self.path_packets": "self._lock",
    },
    # the segment pipeline's in-flight ring (PipelineWindow): the
    # advance loop's issue/drain halves share it today from one
    # thread (the lock is uncontended), but it is exactly the
    # structure a future async drain worker would contend on —
    # every mutation goes through the lock now so that refactor
    # inherits a linted discipline instead of retrofitting one
    "shadow_tpu/device/supervise.py": {
        "self._ring": "self._lock",
    },
    # the chaos injector's schedule counters + dead-device set: the
    # dispatch seam runs on the advance loop's thread, but the
    # checkpoint and cache seams are exactly the calls a future
    # async drain worker would issue — every mutation takes the
    # lock now (the PipelineWindow rationale)
    "shadow_tpu/device/chaos.py": {
        "self._dead": "self._lock",
        "self._issues": "self._lock",
        "self._ck_saves": "self._lock",
        "self._stores": "self._lock",
        "self.fired": "self._lock",
    },
}

# files the pass scans (the generic module-level rule applies to all
# of them; the registry rule to the files registered above)
SCAN_GLOBS = (
    "shadow_tpu/core/manager.py",
    "shadow_tpu/core/controller.py",
    "shadow_tpu/core/netmodel.py",
    "shadow_tpu/device/chaos.py",
    "shadow_tpu/device/supervise.py",
    "shadow_tpu/host/*.py",
)

# method calls that mutate dicts/lists/sets/deques in place
MUTATORS = frozenset({
    "append", "extend", "insert", "remove", "pop", "popitem",
    "clear", "update", "setdefault", "add", "discard", "appendleft",
    "popleft", "sort", "reverse",
})

UNLOCKED_OK_RE = re.compile(
    r"#\s*shadowlint:\s*unlocked-ok\(([^)]*)\)")

_INIT_FUNCS = ("__init__", "__post_init__", "__new__")


def _base_expr(node):
    """The registry-matchable base of a write target: for
    ``self._streams[key]`` / ``self._streams.append`` /
    ``self._streams`` returns "self._streams"; for module-level
    ``TABLE[k]`` returns "TABLE"."""
    t = node
    while isinstance(t, ast.Subscript):
        t = t.value
    try:
        return ast.unparse(t)
    except Exception:           # noqa: BLE001 — exotic target
        return ""


class _Lint(ast.NodeVisitor):
    def __init__(self, relpath, src, registry, module_mutables):
        self.relpath = relpath
        self.lines = src.splitlines()
        self.registry = registry            # attr -> lock (this file)
        self.module_mutables = module_mutables
        self.with_stack: list[str] = []
        self.func_stack: list[str] = []
        self.findings: list[Finding] = []
        self.suppressed: list[dict] = []

    # -- structure tracking -------------------------------------------
    def visit_With(self, node):
        ctxs = []
        for item in node.items:
            try:
                ctxs.append(ast.unparse(item.context_expr))
            except Exception:   # noqa: BLE001
                pass
        self.with_stack.extend(ctxs)
        self.generic_visit(node)
        del self.with_stack[len(self.with_stack) - len(ctxs):]

    def _func(self, node):
        self.func_stack.append(getattr(node, "name", "<lambda>"))
        self.generic_visit(node)
        self.func_stack.pop()

    visit_FunctionDef = _func
    visit_AsyncFunctionDef = _func
    visit_Lambda = _func

    # -- write detection ----------------------------------------------
    def _held(self, lock: str) -> bool:
        return any(c == lock or c.endswith("." + lock)
                   for c in self.with_stack)

    def _suppressed_at(self, lineno: int) -> bool:
        m = UNLOCKED_OK_RE.search(self.lines[lineno - 1]) \
            if 1 <= lineno <= len(self.lines) else None
        if m:
            self.suppressed.append(
                {"path": self.relpath, "line": lineno,
                 "reason": m.group(1)})
            return True
        return False

    def _check_write(self, node, base: str, what: str):
        if not self.func_stack:
            return                          # import-time population
        if self.func_stack[-1] in _INIT_FUNCS:
            # construction site: the write executes DURING __init__ /
            # __post_init__, before the object is shared. Only the
            # innermost frame counts — a nested def or lambda defined
            # inside __init__ runs LATER, on whatever thread calls
            # it, and gets no exemption.
            return
        lock = self.registry.get(base)
        if lock is not None:
            if self._held(lock) or self._suppressed_at(node.lineno):
                return
            self.findings.append(Finding(
                code="SL301", severity=SEV_ERROR, path=self.relpath,
                obj=f"{base}@{self.func_stack[-1]}",
                line=node.lineno,
                message=(f"{what} of registered shared state "
                         f"{base!r} outside `with {lock}`"),
                hint=(f"wrap the write in `with {lock}:` (see the "
                      "lock registry in shadow_tpu/analyze/"
                      "concurrency.py), or mark the line "
                      "# shadowlint: unlocked-ok(<reason>) if the "
                      "path is single-threaded by construction")))
        elif base in self.module_mutables:
            if any(c.endswith("lock") or c.endswith("Lock()")
                   for c in self.with_stack) or \
                    self._suppressed_at(node.lineno):
                return
            self.findings.append(Finding(
                code="SL302", severity=SEV_ERROR, path=self.relpath,
                obj=f"{base}@{self.func_stack[-1]}",
                line=node.lineno,
                message=(f"{what} of module-level mutable {base!r} "
                         "from a function body without any lock"),
                hint=("register the structure (with its lock) in "
                      "LOCK_REGISTRY, make it per-instance state, "
                      "or mark the line "
                      "# shadowlint: unlocked-ok(<reason>)")))

    def _targets(self, t):
        if isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                yield from self._targets(e)
        else:
            yield t

    def visit_Assign(self, node):
        for t in node.targets:
            for tgt in self._targets(t):
                base = _base_expr(tgt)
                if isinstance(tgt, ast.Subscript):
                    self._check_write(node, base, "subscript store")
                elif base:
                    self._check_write(node, base, "rebind")
        self.generic_visit(node)

    def visit_AugAssign(self, node):
        self._check_write(node, _base_expr(node.target),
                          "augmented store")
        self.generic_visit(node)

    def visit_Delete(self, node):
        for t in node.targets:
            if isinstance(t, ast.Subscript):
                self._check_write(node, _base_expr(t),
                                  "subscript delete")
        self.generic_visit(node)

    def visit_Call(self, node):
        f = node.func
        if isinstance(f, ast.Attribute) and f.attr in MUTATORS:
            self._check_write(node, _base_expr(f.value),
                              f".{f.attr}()")
        self.generic_visit(node)


def _module_mutables(tree) -> set[str]:
    """Module-level names bound to a mutable container display or
    constructor at import time."""
    out = set()
    for node in tree.body:
        if isinstance(node, ast.Assign):
            targets = [t for t in node.targets
                       if isinstance(t, ast.Name)]
            v = node.value
        elif isinstance(node, ast.AnnAssign):      # PEP 526 style
            targets = ([node.target]
                       if isinstance(node.target, ast.Name) else [])
            v = node.value
        else:
            continue
        mutable = isinstance(v, (ast.Dict, ast.List, ast.Set)) or (
            isinstance(v, ast.Call) and isinstance(v.func, ast.Name)
            and v.func.id in ("dict", "list", "set", "defaultdict",
                              "OrderedDict", "deque"))
        if not mutable:
            continue
        for t in targets:
            out.add(t.id)
    return out


def lint_source(src: str, relpath: str,
                registry: dict | None = None,
                suppressed_out: list | None = None) -> list[Finding]:
    """Lint one file's source. `registry` defaults to this file's
    LOCK_REGISTRY entry; tests inject fixture registries.
    `suppressed_out` collects {path, line, reason} for every
    in-source unlocked-ok suppression that fired."""
    reg = (LOCK_REGISTRY.get(relpath, {}) if registry is None
           else registry)
    tree = ast.parse(src, filename=relpath)
    lint = _Lint(relpath, src, reg, _module_mutables(tree))
    lint.visit(tree)
    if suppressed_out is not None:
        suppressed_out.extend(lint.suppressed)
    return lint.findings


def scan_files(repo_root: str) -> list[str]:
    import glob as _glob

    out = []
    for pat in SCAN_GLOBS:
        out.extend(sorted(
            _glob.glob(os.path.join(repo_root, pat))))
    return out


def run(repo_root: str | None = None) -> list[Finding]:
    if repo_root is None:
        import shadow_tpu

        repo_root = os.path.dirname(os.path.dirname(
            os.path.abspath(shadow_tpu.__file__)))
    findings = []
    for path in scan_files(repo_root):
        rel = os.path.relpath(path, repo_root)
        with open(path) as f:
            src = f.read()
        suppressed: list = []
        found = lint_source(src, rel, suppressed_out=suppressed)
        if found:
            log.info("concurrency lint: %s -> %d finding(s)", rel,
                     len(found))
        for s in suppressed:
            log.info("concurrency lint: %s:%d unlocked-ok(%s)",
                     s["path"], s["line"], s["reason"])
        findings.extend(found)
    # a registered lock that the file never takes is itself a smell
    # (the registry drifted from the code) — surface it loudly
    for rel, reg in LOCK_REGISTRY.items():
        path = os.path.join(repo_root, rel)
        if not os.path.exists(path):
            findings.append(Finding(
                code="SL301", severity=SEV_ERROR, path=rel,
                obj="<registry>",
                message=f"registered file {rel} does not exist",
                hint="update LOCK_REGISTRY"))
            continue
        with open(path) as f:
            src = f.read()
        for attr, lock in reg.items():
            bare = lock.split(".")[-1]
            if bare not in src:
                findings.append(Finding(
                    code="SL301", severity=SEV_ERROR, path=rel,
                    obj=lock,
                    message=(f"registered lock {lock!r} for {attr!r} "
                             "never appears in the file"),
                    hint="update LOCK_REGISTRY to the real lock"))
    return findings
