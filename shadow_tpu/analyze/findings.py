"""Findings model, JSON record, and suppression baseline.

Every pass emits :class:`Finding` rows. A finding's identity for
baselining is ``code:path:obj`` — deliberately WITHOUT the line
number, so an unrelated edit shifting lines never invalidates a
suppression, while the finding moving to a different symbol (a new
instance of the same bug class) correctly reads as NEW.

The baseline file grandfathers known findings: each suppression
carries a human reason, new findings fail the run, and suppressions
that no longer match anything are reported stale (warning) so the
baseline cannot quietly rot. The shipped baseline
(``shadow_tpu/analyze/baseline.json``) is EMPTY — the tree passes all
three passes clean; the mechanism exists for downstream forks and for
staging multi-PR cleanups.
"""

from __future__ import annotations

import json
import os
from dataclasses import asdict, dataclass, field

SEV_ERROR = "error"
SEV_WARNING = "warning"

# finding codes, one block of ten per pass:
#   SL10x  jaxpr audit       (leaked const / primitive / collective)
#   SL20x  fingerprint completeness (digest-list subset walk)
#   SL30x  concurrency lint  (unlocked shared-state writes)
CODES = {
    "SL101": "non-scalar closure constant not threaded through wrld",
    "SL102": "primitive outside the pinned deterministic allowlist",
    "SL103": "cross-shard collective outside the engine's registry",
    "SL104": "expected exchange collective missing from the program",
    "SL105": "allowed constant lacks its const-ok suppression comment",
    "SL201": "trace-shaping module missing from the code-digest list",
    "SL202": "digested module not reachable from the trace roots",
    "SL203": "module is both digested and declared a value boundary",
    "SL301": "write to registered shared state outside its lock",
    "SL302": "module-level mutable written without any lock",
}

BASELINE_VERSION = 1
DEFAULT_BASELINE = os.path.join(os.path.dirname(__file__),
                                "baseline.json")


@dataclass
class Finding:
    code: str                  # SLxxx (CODES above)
    severity: str              # SEV_ERROR | SEV_WARNING
    path: str                  # repo-relative file, or a program id
    obj: str                   # symbol / program / module concerned
    message: str
    hint: str = ""             # the named repair (--fix-hints)
    line: int = 0              # 0 = not a source-line finding
    extra: dict = field(default_factory=dict)

    @property
    def key(self) -> str:
        return f"{self.code}:{self.path}:{self.obj}"

    def format(self, fix_hints: bool = False) -> str:
        loc = f"{self.path}:{self.line}" if self.line else self.path
        s = f"{loc}: {self.code} [{self.severity}] {self.message}"
        if fix_hints and self.hint:
            s += f"\n    fix: {self.hint}"
        return s

    def to_dict(self) -> dict:
        d = asdict(self)
        d["key"] = self.key
        return d


def load_baseline(path: str = DEFAULT_BASELINE) -> dict:
    """Read the suppression baseline; a missing file is an empty
    baseline (the shipped default is empty anyway). A malformed file
    is a hard error — silently ignoring a corrupt baseline would turn
    every grandfathered finding into a fresh CI failure (or worse,
    vice versa)."""
    if not os.path.exists(path):
        return {"version": BASELINE_VERSION, "suppressions": []}
    with open(path) as f:
        data = json.load(f)
    if not isinstance(data, dict) or \
            data.get("version") != BASELINE_VERSION or \
            not isinstance(data.get("suppressions"), list):
        raise ValueError(
            f"baseline {path}: expected "
            f'{{"version": {BASELINE_VERSION}, "suppressions": '
            f'[...]}}, got {str(data)[:120]!r}')
    for s in data["suppressions"]:
        if not isinstance(s, dict) or "key" not in s or \
                not s.get("reason"):
            raise ValueError(
                f"baseline {path}: every suppression needs a key AND "
                f"a non-empty reason; bad entry {s!r}")
    return data


def write_baseline(path: str, findings: list[Finding],
                   reason: str) -> dict:
    """Grandfather `findings` into a fresh baseline at `path` (the
    --write-baseline flow). One shared reason per batch: a baseline
    refresh is a deliberate, reviewed act, and the reason should say
    which PR staged the cleanup."""
    from shadow_tpu.utils.artifacts import atomic_write_json

    data = {
        "version": BASELINE_VERSION,
        "suppressions": [
            {"key": f.key, "reason": reason,
             "message": f.message}
            for f in sorted(findings, key=lambda f: f.key)
        ],
    }
    atomic_write_json(data, path)
    return data


def apply_baseline(findings: list[Finding], baseline: dict
                   ) -> tuple[list[Finding], list[dict], list[dict]]:
    """Split `findings` against the baseline: returns
    (new_findings, suppressed, stale_suppressions) where suppressed
    pairs each matched finding with its recorded reason and stale
    lists suppressions that matched nothing (the baseline should
    shrink when the underlying finding is fixed)."""
    sup = {s["key"]: s for s in baseline.get("suppressions", [])}
    new, suppressed = [], []
    hit = set()
    for f in findings:
        if f.key in sup:
            hit.add(f.key)
            suppressed.append({"key": f.key,
                               "reason": sup[f.key]["reason"],
                               "message": f.message})
        else:
            new.append(f)
    stale = [s for k, s in sorted(sup.items()) if k not in hit]
    return new, suppressed, stale


def record(findings: list[Finding], new: list[Finding],
           suppressed: list[dict], stale: list[dict],
           passes: list[str], walls: dict) -> dict:
    """The machine-readable run record (scripts/analyze.py --json;
    uploaded as the CI workflow artifact)."""
    errors = [f for f in new if f.severity == SEV_ERROR]
    return {
        "version": 1,
        "tool": "shadowlint",
        "passes": list(passes),
        "pass_walls_s": {k: round(v, 3) for k, v in walls.items()},
        "findings": [f.to_dict() for f in findings],
        "new": [f.key for f in new],
        "suppressed": suppressed,
        "stale_suppressions": stale,
        "counts": {
            "total": len(findings),
            "new_errors": len(errors),
            "new_warnings": len(new) - len(errors),
            "suppressed": len(suppressed),
            "stale_suppressions": len(stale),
        },
        "ok": not errors,
    }
