"""Pass 2 — fingerprint completeness of the AOT cache's code digest.

The compile cache (device/aotcache.py) keys serialized executables on
a digest of the engine-side source modules that shape the traced
programs. PR 6's review rounds grew that list by hand three times as
reviewers found modules it missed; this pass replaces the hand
maintenance with a machine check:

* start from ``aotcache.CODE_DIGEST_ROOTS`` (the engine's trace
  path) and walk STATIC imports (every ``import`` / ``from``
  statement anywhere in the module, function-level included — the
  engine imports capacity helpers and model_nic constants inside
  ``_build_program``), restricted to the repo's own package;
* stop at ``aotcache.CODE_DIGEST_BOUNDARY`` modules — each declares
  WHY its source need not be digested (its trace-relevant outputs are
  fingerprinted BY VALUE elsewhere in the cache key: program_facts,
  app_fingerprint, backend_signature) — and do not follow their
  imports;
* every reached non-boundary module must be in
  ``aotcache.CODE_DIGEST_MODULES`` (SL201, error): adding a traced
  helper module without digesting it fails CI loudly, and deleting a
  digested module the walk still reaches fails the same way;
* a digested module the walk cannot reach is reported stale (SL202,
  warning), and a module both digested and boundary-declared is a
  contradiction (SL203, error).
"""

from __future__ import annotations

import ast
import os

from shadow_tpu.analyze.findings import (
    SEV_ERROR,
    SEV_WARNING,
    Finding,
)
from shadow_tpu.utils.slog import get_logger

log = get_logger("analyze")


def default_pkg_roots() -> dict:
    import shadow_tpu

    return {"shadow_tpu":
            os.path.dirname(os.path.abspath(shadow_tpu.__file__))}


def module_file(name: str, pkg_roots: dict) -> str | None:
    """Resolve a dotted module name to its source file under the
    registered package roots (no imports executed)."""
    parts = name.split(".")
    root = pkg_roots.get(parts[0])
    if root is None:
        return None
    base = os.path.join(root, *parts[1:])
    for cand in (base + ".py", os.path.join(base, "__init__.py")):
        if os.path.exists(cand):
            return cand
    return None


def static_imports(name: str, pkg_roots: dict) -> set[str]:
    """Every in-package module `name` statically imports, at any
    nesting level (module top, function bodies, method bodies)."""
    path = module_file(name, pkg_roots)
    if path is None:
        return set()
    with open(path) as f:
        tree = ast.parse(f.read(), filename=path)
    prefixes = tuple(pkg_roots)
    out: set[str] = set()

    def _add(mod: str):
        if mod.split(".")[0] in prefixes:
            out.add(mod)

    pkg_parts = name.split(".")
    is_pkg = path.endswith("__init__.py")
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                _add(a.name)
        elif isinstance(node, ast.ImportFrom):
            if node.level:
                # relative import: resolve against this module's pkg
                base = pkg_parts[:len(pkg_parts) - node.level
                                 + (1 if is_pkg else 0)]
                mod = ".".join(base + ([node.module]
                                       if node.module else []))
            else:
                mod = node.module or ""
            if mod:
                _add(mod)
            for a in node.names:
                # "from X import Y" where X.Y is itself a module
                cand = f"{mod}.{a.name}" if mod else a.name
                if module_file(cand, pkg_roots):
                    _add(cand)
    return out


def reachable(roots, boundary, pkg_roots) -> dict[str, str]:
    """Transitive import closure from `roots`, pruned at `boundary`
    modules (reached, recorded, but not followed). Returns
    module -> importer (for the finding message)."""
    via: dict[str, str] = {m: "<root>" for m in roots}
    work = [m for m in roots if m not in boundary]
    while work:
        mod = work.pop()
        for imp in sorted(static_imports(mod, pkg_roots)):
            if imp in via:
                continue
            via[imp] = mod
            if imp not in boundary:
                work.append(imp)
    return via


def run(roots=None, boundary=None, digest=None,
        pkg_roots=None, rel_prefix: str = "") -> list[Finding]:
    """The digest-completeness check. All knobs are injectable so the
    test fixtures can run the identical logic over a scratch
    package tree."""
    from shadow_tpu.device import aotcache

    roots = tuple(roots if roots is not None
                  else aotcache.CODE_DIGEST_ROOTS)
    boundary = dict(boundary if boundary is not None
                    else aotcache.CODE_DIGEST_BOUNDARY)
    digest = set(digest if digest is not None
                 else aotcache.CODE_DIGEST_MODULES)
    pkg_roots = pkg_roots or default_pkg_roots()
    path = "shadow_tpu/device/aotcache.py" if not rel_prefix \
        else rel_prefix

    via = reachable(roots, set(boundary), pkg_roots)
    required = {m for m in via if m not in boundary}
    out = []
    for m in sorted(required - digest):
        out.append(Finding(
            code="SL201", severity=SEV_ERROR, path=path, obj=m,
            message=(f"{m} is reachable from the engine trace path "
                     f"(via {via[m]}) but absent from "
                     "CODE_DIGEST_MODULES — an edit there would NOT "
                     "invalidate cached executables"),
            hint=("add it to _CODE_DIGEST_FILES "
                  "(aotcache.CODE_DIGEST_MODULES), or declare it in "
                  "CODE_DIGEST_BOUNDARY with the reason its values "
                  "are fingerprinted elsewhere")))
    for m in sorted(digest - set(via)):
        out.append(Finding(
            code="SL202", severity=SEV_WARNING, path=path, obj=m,
            message=(f"{m} is in CODE_DIGEST_MODULES but the import "
                     "walk never reaches it from the trace roots — "
                     "stale entry, or a root is missing"),
            hint=("drop the stale digest entry, or add the new "
                  "trace root to CODE_DIGEST_ROOTS")))
    for m in sorted(digest & set(boundary)):
        out.append(Finding(
            code="SL203", severity=SEV_ERROR, path=path, obj=m,
            message=(f"{m} is both digested and declared a value-"
                     "fingerprint boundary — pick one"),
            hint="remove it from one of the two lists"))
    log.info("digest walk: %d module(s) reached, %d required, "
             "%d digested, %d finding(s)", len(via), len(required),
             len(digest), len(out))
    return out
