"""shadow_tpu — a TPU-native discrete-event network simulation framework.

shadow_tpu directly executes application models (and, via the native runtime,
real Linux programs) inside a deterministic discrete-event simulation of a
network: topology-derived latency and packet loss, token-bucket bandwidth
enforcement, CoDel router queues, and an in-simulator TCP/UDP stack.

Architecture (TPU-first, not a port):

* The **inter-host network model** — per-host event queues, topology
  latency/reliability lookups, router queues, and cross-host packet delivery
  — runs on device as batched JAX arrays: each scheduling round is one jitted
  ``round_step`` mapped over the host dimension with ``shard_map`` over a
  ``jax.sharding.Mesh``, and cross-shard packet delivery is an XLA collective
  (``all_to_all`` / ``all_gather``) over ICI/DCN.
* The **host runtime** (controller/manager/scheduler, config, logging,
  process management) runs on CPU in Python/C++, mirroring the layer map of
  the reference simulator (see SURVEY.md §1).

Determinism is a first-class property: events are totally ordered by the
(time, dst, src, seq) key and all randomness is counter-based
(`threefry`, keyed by stable ids), so results are bit-identical across
reruns *and* across different device-mesh shapes — a stronger guarantee
than the reference's per-host RNG streams.

jax is imported lazily (see shadow_tpu/_jax.py): config parsing, the CLI's
--show-config path, and the pure-Python reference engine never touch it.
"""

from shadow_tpu.version import __version__

__all__ = ["__version__"]
