"""Campaign server: a resident, multi-tenant simulation service.

The production-scale framing from ROADMAP.md — a long-lived daemon
that keeps the device mesh, AOT compile cache, and strategy plans
warm in ONE process and serves a stream of campaign submissions,
mirroring the paper's layer-2 controller/manager split. The package
splits along its two durability boundaries:

* :mod:`shadow_tpu.serve.journal` — the crash-safe submission
  journal: every campaign state transition is a durably-appended
  JSONL record (utils/artifacts.append_line), and restart replay
  reconstructs the exact queue the dead server held.
* :mod:`shadow_tpu.serve.server` — the scheduler/watchdog loop:
  priority admission through the existing verdict machinery,
  preempt-to-checkpoint reclaim for higher-priority arrivals (the
  rc-75 drain contract), stale-heartbeat supervised kills, and the
  chaos ``server_crash`` drill seam.

``python -m shadow_tpu.serve`` (or scripts/serve.py) is the CLI:
``start`` runs the daemon, ``submit`` drops a campaign into the
spool, ``status`` prints the journal's view.
"""

from shadow_tpu.serve.journal import (Campaign, Journal, RUNNABLE,
                                      STATES, TERMINAL)
from shadow_tpu.serve.server import CampaignServer, submit

__all__ = ["Campaign", "CampaignServer", "Journal", "RUNNABLE",
           "STATES", "TERMINAL", "submit"]
