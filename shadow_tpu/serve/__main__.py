"""Campaign server CLI: ``python -m shadow_tpu.serve <cmd>``.

Three verbs against one spool directory:

* ``start SPOOL`` — run the resident daemon (journal replay first, so
  restarting after a crash resumes every mid-flight campaign).
* ``submit SPOOL CONFIG`` — drop a campaign into the spool (atomic;
  needs no running server — the spool IS the queue).
* ``status SPOOL`` — print the journal's replayed view of every
  campaign, newest state per id.
"""

from __future__ import annotations

import argparse
import json
import sys

from shadow_tpu.utils import slog


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="shadow-tpu-serve",
        description="resident multi-tenant campaign server")
    sub = parser.add_subparsers(dest="cmd", required=True)

    st = sub.add_parser("start", help="run the daemon")
    st.add_argument("spool", help="spool directory (journal + queue)")
    st.add_argument("--poll", type=float, default=0.2, metavar="S",
                    help="scheduler tick interval, seconds")
    st.add_argument("--checkpoint-every", default="", metavar="TIME",
                    help="rotation cadence forced onto campaigns that "
                         "did not set one (e.g. 100ms); default "
                         "stop_time/8")
    st.add_argument("--stale-after", type=int, default=4, metavar="K",
                    help="heartbeat gaps > K x the expected cadence "
                         "count as stale (campaigns with "
                         "general.heartbeat_interval set)")
    st.add_argument("--watchdog-grace", type=float, default=30.0,
                    metavar="S",
                    help="seconds a stale campaign gets to drain "
                         "before the supervised kill + requeue")
    st.add_argument("--idle-exit", action="store_true",
                    help="exit once the queue is drained (batch mode "
                         "— the gate's restart leg uses this)")
    st.add_argument("--chaos", default="", metavar="JSON",
                    help="scripted server chaos, e.g. "
                         "'[{\"kind\": \"server_crash\", \"tick\": "
                         "40}]'")
    st.add_argument("--log-level", default="info",
                    choices=["error", "warning", "info", "debug",
                             "trace"])

    sb = sub.add_parser("submit", help="queue a campaign")
    sb.add_argument("spool")
    sb.add_argument("config", help="simulation config (YAML)")
    sb.add_argument("--priority", type=int, default=0,
                    help="higher preempts lower (rc-75 drain)")
    sb.add_argument("-o", "--option", action="append", default=[],
                    metavar="KEY=VALUE",
                    help="config override, e.g. -o general.seed=7")

    ss = sub.add_parser("status", help="print the journal's view")
    ss.add_argument("spool")
    ss.add_argument("--json", action="store_true",
                    help="machine-readable output")

    args = parser.parse_args(argv)

    if args.cmd == "submit":
        from shadow_tpu.serve.server import submit
        name = submit(args.spool, args.config,
                      priority=args.priority, overrides=args.option)
        print(f"submitted {args.config} -> {args.spool}/incoming/"
              f"{name}")
        return 0

    if args.cmd == "status":
        from shadow_tpu.serve.journal import Journal
        campaigns, meta = Journal(args.spool).replay()
        if args.json:
            json.dump({"campaigns": {c.cid: vars(c) for c in
                                     campaigns.values()},
                       "meta": meta}, sys.stdout, indent=2,
                      default=str)
            print()
            return 0
        print(f"{'cid':8} {'state':10} {'prio':>4} {'att':>3} "
              f"{'pre':>3} config")
        for c in sorted(campaigns.values(), key=lambda c: c.seq):
            print(f"{c.cid:8} {c.state:10} {c.priority:>4} "
                  f"{c.attempts:>3} {c.preemptions:>3} {c.config}")
            if c.diagnostic:
                print(f"{'':8} {c.diagnostic}")
        print(f"-- {len(campaigns)} campaign(s), "
              f"{meta['server_starts']} server start(s), "
              f"{meta['torn_lines']} torn line(s)")
        return 0

    # start
    slog.init_logging(args.log_level)
    chaos = None
    if args.chaos:
        from shadow_tpu.device.chaos import (ChaosInjector,
                                             events_from_config)
        chaos = ChaosInjector(events_from_config(
            json.loads(args.chaos)))
    every = 0
    if args.checkpoint_every:
        from shadow_tpu.config.schema import parse_time_ns
        every = parse_time_ns(args.checkpoint_every)
    from shadow_tpu.serve.server import CampaignServer
    server = CampaignServer(
        args.spool, poll_s=args.poll, checkpoint_every=every,
        stale_after=args.stale_after,
        watchdog_grace_s=args.watchdog_grace, chaos=chaos)
    return server.serve(idle_exit=args.idle_exit)


if __name__ == "__main__":
    sys.exit(main())
