"""The campaign server: scheduler, watchdog, and recovery loop.

One resident process owns the spool. Submissions arrive as atomic JSON
drops in ``<spool>/incoming/`` (:func:`submit` — no daemon connection
needed), the scheduler admits the highest-priority runnable campaign
through the normal Controller path, and every state transition is
durably journaled first (:mod:`shadow_tpu.serve.journal`), so a
``kill -9`` at ANY instant is recoverable: restart replays the
journal, requeues the mid-flight campaign from its newest readable
rotation checkpoint, and the resumed run is bit-identical to an
uninterrupted one (the checkpoint/resume contract the determinism
gate's ``--server`` rung enforces).

Scheduling model: ONE campaign runs at a time, on a worker thread,
while the scheduler thread ticks — scanning the spool, polling the
worker's heartbeat monitor, and reclaiming the slot for a
higher-priority arrival by requesting the runner's preemption guard
(the rc-75 drain: finish the in-flight dispatch segment, save a
resume checkpoint, return preempted). Serial campaigns keep the warm
in-process mesh and AOT compile cache across campaigns — that
residency is the point of a server over a per-campaign subprocess.

Per-campaign artifacts are namespaced under
``<spool>/campaigns/<cid>/``: the data directory, the rotation
checkpoints (``ck.npz.t<ns>`` / ``ck.npz.b<k>.t<ns>`` for batched
ensembles), and ``artifacts/`` for OCC/PLAN/ENSEMBLE/METRICS/TRACE
records (``experimental.artifacts_dir``), so two tenants can never
clobber each other's records. ``RESULT.json`` carries the final host
signatures for external comparison.
"""

from __future__ import annotations

import itertools
import json
import os
import re
import threading
import time
import traceback

from shadow_tpu.serve.journal import (Campaign, Journal, RUNNABLE,
                                      TERMINAL)
from shadow_tpu.utils.artifacts import atomic_write_json
from shadow_tpu.utils.slog import get_logger

log = get_logger("serve")

_SUB_COUNTER = itertools.count()

# rotation entries under a campaign dir: plain ``ck.npz.t<ns>`` and
# the batched-ensemble series ``ck.npz.b<k>.t<ns>``
_ROTATION_RE = re.compile(r"^ck\.npz(?:\.b(\d+))?\.t(\d+)$")


def submit(spool: str, config: str, priority: int = 0,
           overrides=()) -> str:
    """Drop one campaign submission into the spool. Atomic (tmp +
    rename), so the server can never observe a half-written file;
    works with no server running — the spool IS the queue. Returns
    the submission file name (the server assigns the campaign id
    when it journals the QUEUED transition)."""
    inc = os.path.join(spool, "incoming")
    os.makedirs(inc, exist_ok=True)
    name = (f"sub_{time.time_ns():020d}_{os.getpid()}_"
            f"{next(_SUB_COUNTER)}.json")
    atomic_write_json(
        {"config": os.path.abspath(config), "priority": int(priority),
         "overrides": [str(o) for o in overrides],
         "submitted_wall": time.time()},
        os.path.join(inc, name))
    return name


class ServerCrash(BaseException):
    """In-process stand-in for the chaos ``server_crash`` kill: tests
    inject ``crash_fn=_raise_server_crash`` so the drill unwinds the
    serve loop instead of taking the interpreter down. BaseException
    so no recovery code accidentally swallows the drill."""


class CampaignServer:
    """The resident daemon. ``serve()`` is the blocking loop;
    ``tick()`` is one scheduler step (exposed so tests can drive the
    server deterministically without threads of their own)."""

    def __init__(self, spool: str, poll_s: float = 0.2,
                 checkpoint_every: int = 0, stale_after: int = 4,
                 watchdog_grace_s: float = 30.0, chaos=None,
                 crash_fn=None, clock=time.monotonic):
        self.spool = os.path.abspath(spool)
        self.journal = Journal(self.spool)
        self.poll_s = float(poll_s)
        # rotation cadence forced onto campaigns that did not choose
        # one (sim-ns); 0 = stop_time // 8
        self.checkpoint_every = int(checkpoint_every)
        self.stale_after = int(stale_after)
        self.watchdog_grace_s = float(watchdog_grace_s)
        # the server holds its OWN injector (scripted server_crash
        # drills), distinct from any campaign's chaos config — a
        # campaign's injector must not count scheduler ticks
        self.chaos = chaos
        self.crash_fn = crash_fn if crash_fn is not None \
            else lambda: os._exit(137)
        self.clock = clock
        self.campaigns: dict[str, Campaign] = {}
        self._seq = 0
        self._slot = None          # holder dict of the running campaign
        self._stop = False
        self.restarts = 0          # prior server_start events replayed
        self.slo = {"done": 0, "failed": 0, "refused": 0,
                    "preemptions": 0, "stale_kills": 0,
                    "requeued_on_restart": 0, "ticks": 0}
        self._t_up = self.clock()
        os.makedirs(os.path.join(self.spool, "incoming"), exist_ok=True)
        os.makedirs(os.path.join(self.spool, "campaigns"),
                    exist_ok=True)
        # the server's own flight recorder: campaign spans + scheduler
        # instants under the "serve" phase; METRICS_<label>.json lands
        # in the spool on shutdown (the server SLO summary record)
        from shadow_tpu.obs.trace import Tracer
        self.tracer = Tracer(mode="summary", directory=self.spool,
                             label="serve")

    # -- paths ---------------------------------------------------------
    def _cdir(self, cid: str) -> str:
        return os.path.join(self.spool, "campaigns", cid)

    # -- recovery ------------------------------------------------------
    def recover(self) -> None:
        """Journal replay: reconstruct the campaign table the dead
        server held, requeue every non-terminal campaign from its
        newest readable rotation checkpoint, and journal our own
        server_start. Idempotent — a crash between the replay and the
        first tick just replays again."""
        self.campaigns, meta = self.journal.replay()
        self.restarts = meta["server_starts"]
        if meta["torn_lines"]:
            log.warning("recover: tolerated %d torn journal line(s)",
                        meta["torn_lines"])
        for c in sorted(self.campaigns.values(), key=lambda c: c.seq):
            self._seq = max(self._seq, c.seq + 1)
            if c.state in ("ADMITTED", "RUNNING"):
                # the crash caught this campaign mid-flight; its
                # worker thread died with the server. Requeue from
                # the newest checkpoint the rotation managed to land
                # (bit-identical resume), or from scratch if the kill
                # outran the first rotation save.
                resume = self._newest_resume(c.cid)
                c.state = "PREEMPTED"
                c.resume_path = resume
                c.preemptions += 1
                c.diagnostic = (
                    "requeued by journal replay after a server "
                    "restart"
                    + (f"; resuming from {resume}" if resume
                       else "; no readable checkpoint yet — "
                            "restarting from scratch"))
                self.journal.transition(
                    c.cid, "PREEMPTED", resume_path=resume,
                    preemptions=c.preemptions,
                    diagnostic=c.diagnostic)
                self.slo["requeued_on_restart"] += 1
                log.warning("recover: %s was %s at the crash — %s",
                            c.cid, "mid-flight", c.diagnostic)
        self.journal.server_event(
            "server_start", restarts=self.restarts + 1,
            pid=os.getpid(), wall=time.time())
        runnable = sum(1 for c in self.campaigns.values()
                       if c.state in RUNNABLE)
        log.info("server up on %s: %d campaign(s) replayed, %d "
                 "runnable, start #%d", self.spool,
                 len(self.campaigns), runnable, self.restarts + 1)

    def _newest_resume(self, cid: str) -> str:
        """Newest READABLE rotation checkpoint of a campaign, walking
        both the plain series (``ck.npz.t<ns>``) and the batched
        series (``ck.npz.b<k>.t<ns>`` — batches restart sim time at
        0, so order is (batch, t), not raw t)."""
        from shadow_tpu.device import checkpoint

        cdir = self._cdir(cid)
        if not os.path.isdir(cdir):
            return ""
        entries = []
        for name in os.listdir(cdir):
            m = _ROTATION_RE.match(name)
            if m:
                batch = int(m.group(1)) if m.group(1) is not None \
                    else -1
                entries.append((batch, int(m.group(2)),
                                os.path.join(cdir, name)))
        for _, _, path in sorted(entries, reverse=True):
            try:
                meta = checkpoint.peek_meta(path)
                if meta.get("format") != checkpoint.FORMAT:
                    raise ValueError(f"format {meta.get('format')}")
                return path
            except Exception as e:      # noqa: BLE001 — unreadable
                # entry = the file the kill outran; fall back to the
                # previous one, exactly the rotation's purpose
                log.warning("resume: skipping unreadable rotation "
                            "entry %s (%s)", path, e)
        return ""

    # -- intake --------------------------------------------------------
    def _scan_incoming(self) -> None:
        inc = os.path.join(self.spool, "incoming")
        try:
            names = sorted(os.listdir(inc))
        except OSError:
            return
        seen = {c.sub for c in self.campaigns.values() if c.sub}
        for name in names:
            if not name.endswith(".json") or name in seen:
                continue
            path = os.path.join(inc, name)
            try:
                with open(path, "r", encoding="utf-8") as f:
                    sub = json.load(f)
                config = str(sub["config"])
            except (OSError, ValueError, KeyError) as e:
                # submit() renames atomically, so a malformed file was
                # not written by us — quarantine it so the scanner
                # does not spin on it every tick
                log.warning("incoming: %s is not a submission (%s) — "
                            "renaming to .bad", name, e)
                try:
                    os.replace(path, path + ".bad")
                except OSError:
                    pass
                continue
            cid = f"c{self._seq:04d}"
            camp = Campaign(
                cid=cid, config=config,
                priority=int(sub.get("priority", 0)), seq=self._seq,
                overrides=[str(o) for o in sub.get("overrides", [])],
                submitted_wall=float(sub.get("submitted_wall", 0.0)),
                sub=name)
            self._seq += 1
            self.campaigns[cid] = camp
            cdir = self._cdir(cid)
            os.makedirs(cdir, exist_ok=True)
            # journal FIRST, then consume the file: a crash in between
            # leaves both the QUEUED record and the submission file,
            # and the `sub` field dedupes the rescan on restart
            self.journal.transition(
                cid, "QUEUED", config=camp.config,
                priority=camp.priority, seq=camp.seq,
                overrides=camp.overrides,
                submitted_wall=camp.submitted_wall, sub=name)
            try:
                os.replace(path, os.path.join(cdir, "submission.json"))
            except OSError:
                pass
            self.tracer.instant("submit", phase="serve", cid=cid,
                                priority=camp.priority)
            log.info("queued %s: %s (priority %d)", cid, camp.config,
                     camp.priority)

    # -- scheduling ----------------------------------------------------
    def _pick(self):
        """Highest priority first; FIFO within a priority level."""
        runnable = [c for c in self.campaigns.values()
                    if c.state in RUNNABLE]
        if not runnable:
            return None
        return min(runnable, key=lambda c: (-c.priority, c.seq))

    def _build_cfg(self, camp: Campaign):
        """Load the submitted config and re-home it under the
        campaign directory: data directory, artifacts (OCC / PLAN /
        ENSEMBLE / METRICS / TRACE records), and — for preemptible
        policies — a forced rotation checkpoint so the drain and the
        crash-recovery path always have a resume artifact."""
        from shadow_tpu.config import load_config

        cfg = load_config(camp.config, overrides=list(camp.overrides))
        cdir = self._cdir(camp.cid)
        cfg.general.data_directory = os.path.join(cdir, "shadow.data")
        xp = cfg.experimental
        if not xp.artifacts_dir:
            xp.artifacts_dir = os.path.join(cdir, "artifacts")
        if xp.scheduler_policy == "tpu":
            xp.checkpoint_save = os.path.join(cdir, "ck.npz")
            if not xp.checkpoint_every:
                xp.checkpoint_every = (
                    self.checkpoint_every
                    or max(1, int(cfg.general.stop_time) // 8))
            if camp.resume_path:
                xp.checkpoint_load = camp.resume_path
            if cfg.general.heartbeat_interval \
                    and not xp.heartbeat_stale_after:
                xp.heartbeat_stale_after = self.stale_after
        elif camp.resume_path:
            raise ValueError(
                f"campaign {camp.cid} has a resume checkpoint but "
                f"policy {xp.scheduler_policy!r} cannot load one")
        # serial/thread campaigns have no checkpoint seam: they run to
        # completion and are not preemptible — documented in
        # docs/operations.md, and the scheduler simply waits them out
        return cfg

    def _launch(self, camp: Campaign) -> dict:
        self.journal.transition(camp.cid, "ADMITTED")
        camp.state = "ADMITTED"
        camp.attempts += 1
        # journal RUNNING BEFORE the Controller build: the slow part
        # (mesh build + compile) happens with the RUNNING record
        # already durable, so a crash during the build requeues — and
        # external pollers (the gate's preemption leg) can key on
        # RUNNING appearing to time their next submission
        self.journal.transition(camp.cid, "RUNNING",
                                attempts=camp.attempts,
                                resume_path=camp.resume_path)
        camp.state = "RUNNING"
        holder = {"camp": camp, "controller": None, "stats": None,
                  "error": None, "done": threading.Event(),
                  "preempt_for": "", "stale_since": None,
                  "t_launch": self.clock()}

        def work():
            try:
                cfg = self._build_cfg(camp)
                from shadow_tpu.core.controller import Controller
                c = Controller(cfg)
                holder["controller"] = c
                holder["stats"] = c.run()
            except ServerCrash:
                raise
            except BaseException as e:   # noqa: BLE001 — classified
                holder["error"] = e      # by _finish into
            finally:                     # REFUSED/FAILED
                holder["done"].set()

        t = threading.Thread(target=work, daemon=True,
                             name=f"campaign-{camp.cid}")
        holder["thread"] = t
        log.info("launching %s (attempt %d%s)", camp.cid,
                 camp.attempts,
                 f", resume {camp.resume_path}" if camp.resume_path
                 else "")
        t.start()
        return holder

    def _signature(self, holder):
        """JSON-safe bit-identity signature of a finished run — the
        same tuple the determinism gate compares for standalone runs,
        so RESULT.json is directly comparable across server and
        standalone executions."""
        stats = holder["stats"]
        if stats.ensemble is not None:
            return [[e.get("host_checksums_sha256", ""),
                     int(e["events_executed"]),
                     int(e["packets_sent"]),
                     int(e["packets_dropped"]),
                     int(e["packets_delivered"])]
                    for e in stats.ensemble["replicas"]]
        c = holder["controller"]
        return [[h.name, int(h.trace_checksum),
                 int(h.events_executed), int(h.packets_sent),
                 int(h.packets_dropped), int(h.packets_delivered)]
                for h in c.sim.hosts]

    def _finish(self, holder) -> None:
        camp = holder["camp"]
        err = holder["error"]
        stats = holder["stats"]
        wall = self.clock() - holder["t_launch"]
        self.tracer.record(f"campaign_{camp.cid}", "serve", wall,
                           cid=camp.cid, attempt=camp.attempts)
        result = {"cid": camp.cid, "config": camp.config,
                  "attempts": camp.attempts,
                  "preemptions": camp.preemptions,
                  "wall_s": round(wall, 3)}
        if err is not None:
            # the admission verdict's strict-mode refusal is a
            # ValueError whose diagnostic leads with the admission
            # story — a REFUSED campaign, not a server failure
            diag = f"{type(err).__name__}: {err}"
            refused = (isinstance(err, ValueError)
                       and "admission" in str(err)[:80])
            camp.state = "REFUSED" if refused else "FAILED"
            camp.diagnostic = diag
            if not refused:
                log.error("campaign %s failed:\n%s", camp.cid,
                          "".join(traceback.format_exception(err)))
            self.journal.transition(camp.cid, camp.state,
                                    diagnostic=diag)
            self.slo["refused" if refused else "failed"] += 1
            result.update(state=camp.state, diagnostic=diag)
        elif stats is not None and stats.preempted:
            camp.state = "PREEMPTED"
            camp.resume_path = stats.resume_path
            camp.preemptions += 1
            camp.diagnostic = holder["preempt_for"] and (
                f"drained for higher-priority "
                f"{holder['preempt_for']}") or "drained"
            self.journal.transition(
                camp.cid, "PREEMPTED", resume_path=camp.resume_path,
                preemptions=camp.preemptions,
                diagnostic=camp.diagnostic)
            self.slo["preemptions"] += 1
            result.update(state="PREEMPTED",
                          resume_path=camp.resume_path)
            log.info("campaign %s preempted -> requeued (%s)",
                     camp.cid, camp.resume_path)
        elif stats is not None and stats.ok:
            camp.state = "DONE"
            result.update(state="DONE",
                          end_time=int(stats.end_time),
                          packets_sent=int(stats.packets_sent),
                          stale_heartbeats=int(stats.stale_heartbeats),
                          signature=self._signature(holder))
            self.journal.transition(camp.cid, "DONE")
            self.slo["done"] += 1
            log.info("campaign %s DONE in %.2fs (attempt %d)",
                     camp.cid, wall, camp.attempts)
        else:
            camp.state = "FAILED"
            camp.diagnostic = "run reported not-ok"
            self.journal.transition(camp.cid, "FAILED",
                                    diagnostic=camp.diagnostic)
            self.slo["failed"] += 1
            result.update(state="FAILED", diagnostic=camp.diagnostic)
        atomic_write_json(result, os.path.join(self._cdir(camp.cid),
                                               "RESULT.json"))
        self._write_slo()

    # -- watchdog + preemption ----------------------------------------
    def _runner_of(self, holder):
        c = holder["controller"]
        return c.runner if c is not None else None

    def _watchdog(self, holder) -> bool:
        """Stale-heartbeat supervision: first staleness requests a
        graceful drain; past the grace window the slot is abandoned
        (supervised kill — the worker thread is orphaned, the
        campaign is requeued from its newest checkpoint). Returns
        True when the slot was reclaimed."""
        runner = self._runner_of(holder)
        mon = getattr(runner, "hb_monitor", None) if runner else None
        if mon is None or not mon.stale():
            holder["stale_since"] = None
            return False
        now = self.clock()
        if holder["stale_since"] is None:
            holder["stale_since"] = now
            guard = getattr(runner, "guard", None)
            if guard is not None:
                guard.request()
            camp = holder["camp"]
            log.warning("watchdog: %s heartbeat is stale (last beat "
                        "%.1fs ago) — drain requested, %.0fs grace "
                        "before a supervised kill", camp.cid,
                        mon.gap(), self.watchdog_grace_s)
            self.journal.server_event("stale_heartbeat",
                                      cid=camp.cid, gap_s=mon.gap())
            return False
        if now - holder["stale_since"] <= self.watchdog_grace_s:
            return False
        # grace exhausted: the run is wedged. Abandon the worker
        # thread (daemon — it dies with the process, and a wedged
        # engine call cannot be interrupted from Python anyway),
        # requeue from the newest rotation checkpoint.
        camp = holder["camp"]
        resume = self._newest_resume(camp.cid)
        camp.state = "PREEMPTED"
        camp.resume_path = resume
        camp.preemptions += 1
        camp.diagnostic = (
            f"supervised kill: heartbeat stale for "
            f"{now - holder['stale_since'] + 0.0:.0f}s past the drain "
            f"request" + (f"; resuming from {resume}" if resume
                          else "; no readable checkpoint — "
                               "restarting from scratch"))
        self.journal.transition(camp.cid, "PREEMPTED",
                                resume_path=resume,
                                preemptions=camp.preemptions,
                                diagnostic=camp.diagnostic)
        self.slo["stale_kills"] += 1
        self.tracer.instant("stale_kill", phase="serve", cid=camp.cid)
        log.error("watchdog: %s — %s", camp.cid, camp.diagnostic)
        self._write_slo()
        return True

    def _maybe_preempt(self, holder) -> None:
        """Reclaim the slot for a higher-priority arrival via the
        rc-75 drain: request the guard once; the runner finishes the
        in-flight segment, saves a resume checkpoint, and returns
        preempted — _finish() requeues it bit-identically."""
        if holder["preempt_for"]:
            return
        best = self._pick()
        camp = holder["camp"]
        if best is None or best.priority <= camp.priority:
            return
        runner = self._runner_of(holder)
        guard = getattr(runner, "guard", None) if runner else None
        if guard is None:
            # controller still building, or the run has no drain seam
            # (serial policy / no segment boundaries) — re-check next
            # tick; an un-preemptible campaign just runs out
            return
        guard.request()
        holder["preempt_for"] = best.cid
        self.journal.server_event("preempt_request", cid=camp.cid,
                                  for_cid=best.cid)
        self.tracer.instant("preempt_request", phase="serve",
                            cid=camp.cid, for_cid=best.cid)
        log.info("preempting %s (priority %d) for %s (priority %d)",
                 camp.cid, camp.priority, best.cid, best.priority)

    # -- the loop ------------------------------------------------------
    def tick(self) -> bool:
        """One scheduler step. Returns True while there is work
        (a slot occupied or runnable campaigns waiting)."""
        self.slo["ticks"] += 1
        if self.chaos is not None and self.chaos.on_server_tick():
            # the drill IS a kill -9: no journal flush, no cleanup —
            # the whole point is that the journal needs neither
            self.crash_fn()
        self._scan_incoming()
        if self._slot is not None:
            if self._slot["done"].is_set():
                self._slot["thread"].join()
                self._finish(self._slot)
                self._slot = None
            elif self._watchdog(self._slot):
                self._slot = None
            else:
                self._maybe_preempt(self._slot)
        if self._slot is None:
            camp = self._pick()
            if camp is not None:
                self._slot = self._launch(camp)
        return (self._slot is not None
                or any(c.state in RUNNABLE
                       for c in self.campaigns.values()))

    def serve(self, idle_exit: bool = False) -> int:
        """The blocking daemon loop. ``idle_exit`` returns once the
        queue is empty and the slot idle for a few consecutive polls
        (drain mode — the restart leg of the gate drill uses it)."""
        self.recover()
        idle = 0
        try:
            while not self._stop:
                busy = self.tick()
                if busy:
                    idle = 0
                elif idle_exit:
                    idle += 1
                    # a few grace polls absorb the submit()-vs-scan
                    # race before declaring the spool drained
                    if idle >= 3:
                        break
                time.sleep(self.poll_s)
        finally:
            self._shutdown()
        return 0

    def stop(self) -> None:
        self._stop = True

    def _write_slo(self) -> None:
        atomic_write_json(
            {"format": 1, "restarts": self.restarts + 1,
             "uptime_s": round(self.clock() - self._t_up, 3),
             "campaigns": {
                 state: sum(1 for c in self.campaigns.values()
                            if c.state == state)
                 for state in
                 ("QUEUED", "RUNNING", "PREEMPTED", *TERMINAL)},
             **self.slo},
            os.path.join(self.spool, "SLO_server.json"))

    def _shutdown(self) -> None:
        self.journal.server_event("server_stop", wall=time.time(),
                                  **self.slo)
        self._write_slo()
        try:
            self.tracer.finalize(run_info={"spool": self.spool,
                                           **self.slo})
        except Exception as e:      # noqa: BLE001 — telemetry must
            log.warning("tracer finalize failed: %s", e)   # not mask
        log.info("server stopped: %s", self.slo)           # shutdown
