"""The campaign server's durable submission journal.

Every state transition a campaign takes — QUEUED -> ADMITTED ->
RUNNING -> PREEMPTED -> DONE/FAILED/REFUSED — is one JSON line
appended durably (O_APPEND + fsync, utils/artifacts.append_line) to
``<spool>/journal.jsonl``. The journal is the server's ONLY source
of truth across restarts: a SIGKILL can tear at most the final line,
so :meth:`Journal.replay` reconstructs the exact campaign table the
dead server held — last state wins per campaign id — and the server
requeues every non-terminal campaign from its newest readable
rotation checkpoint (the kill -9 drill in determinism_gate
--server).

Why a JSONL journal and not a rewritten state file: a state file
needs read-modify-write, and the window between the read and the
replace is exactly where a crash loses a transition. An append-only
journal has no such window — the transition either reached the disk
(replay sees it) or it did not (the campaign replays from its
previous state, which is always safe: re-running an ADMITTED
campaign or re-resuming a PREEMPTED one is idempotent by the
bit-identical resume contract).
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field

from shadow_tpu.utils.artifacts import append_line
from shadow_tpu.utils.slog import get_logger

log = get_logger("serve")

JOURNAL_FORMAT = 1

# the campaign lifecycle. ADMITTED marks slot assignment (the server
# picked the campaign and is building its Controller); the in-run
# admission verdict can still refuse it (-> REFUSED with the readable
# diagnostic). PREEMPTED campaigns are schedulable again — they carry
# the resume checkpoint the drain saved.
STATES = ("QUEUED", "ADMITTED", "RUNNING", "PREEMPTED", "DONE",
          "FAILED", "REFUSED")
TERMINAL = ("DONE", "FAILED", "REFUSED")
RUNNABLE = ("QUEUED", "PREEMPTED")

# transition fields replay copies onto the campaign when present;
# everything else in a record is provenance for the operator
_REPLAY_FIELDS = ("config", "priority", "seq", "overrides",
                  "resume_path", "diagnostic", "attempts",
                  "preemptions", "submitted_wall", "sub")


@dataclass
class Campaign:
    """One submission's live state (the replayable projection of its
    journal lines)."""

    cid: str
    config: str = ""
    priority: int = 0
    seq: int = 0                 # submission order (scheduler FIFO tiebreak)
    state: str = "QUEUED"
    resume_path: str = ""        # newest resume checkpoint, "" = fresh
    diagnostic: str = ""         # readable reason for FAILED/REFUSED/requeue
    attempts: int = 0            # RUNNING launches (1 = never disturbed)
    preemptions: int = 0         # drains absorbed (priority or watchdog)
    submitted_wall: float = 0.0  # unix time of the QUEUED record
    sub: str = ""                # incoming/ file name (rescan dedupe)
    overrides: list = field(default_factory=list)


class Journal:
    """Append/replay access to one spool's ``journal.jsonl``."""

    def __init__(self, spool: str):
        self.spool = os.path.abspath(spool)
        self.path = os.path.join(self.spool, "journal.jsonl")

    # -- append --------------------------------------------------------
    def _heal_tail(self) -> None:
        """A kill mid-append can leave the file without a trailing
        newline (the torn crash frontier). The NEXT append must not
        concatenate onto that fragment — it would merge two records
        into one unparseable line and lose the new transition — so
        every append terminates a torn tail first (appends are rare
        state transitions; one seek per append is free)."""
        try:
            with open(self.path, "rb") as f:
                f.seek(0, os.SEEK_END)
                if f.tell() == 0:
                    return
                f.seek(-1, os.SEEK_END)
                torn = f.read(1) != b"\n"
        except OSError:
            return
        if torn:
            # terminate the fragment, then stamp a marker so replay
            # can tell this tear was a healed crash frontier, not a
            # hand-edit mid-file
            append_line(self.path, "")
            append_line(self.path, json.dumps(
                {"format": JOURNAL_FORMAT,
                 "event": "torn_tail_healed"}, sort_keys=True))
            log.warning("journal: %s had a torn tail — terminated it "
                        "before appending", self.path)

    def append(self, record: dict) -> None:
        self._heal_tail()
        append_line(self.path,
                    json.dumps({"format": JOURNAL_FORMAT, **record},
                               sort_keys=True))

    def transition(self, cid: str, state: str, **fields) -> None:
        """Durably journal one campaign state transition."""
        if state not in STATES:
            raise ValueError(f"unknown campaign state {state!r} "
                             f"(one of {list(STATES)})")
        self.append({"cid": cid, "state": state, **fields})

    def server_event(self, event: str, **fields) -> None:
        """Journal a server lifecycle line (server_start/server_stop/
        preempt_request/...) — provenance, not campaign state."""
        self.append({"event": event, **fields})

    # -- replay --------------------------------------------------------
    def replay(self) -> tuple[dict, dict]:
        """Reconstruct the campaign table: ``{cid: Campaign}`` with
        last-state-wins per cid, plus a meta dict (server_starts,
        torn_lines, events). Exactly ONE torn trailing line is the
        expected crash frontier; a torn line mid-journal means
        something other than our append wrote here, and is warned
        loudly but still skipped (the lines around it are intact by
        the append contract)."""
        campaigns: dict = {}
        meta = {"server_starts": 0, "torn_lines": 0, "events": []}
        if not os.path.exists(self.path):
            return campaigns, meta
        with open(self.path, "r", encoding="utf-8") as f:
            lines = f.read().split("\n")
        for i, line in enumerate(lines):
            if not line.strip():
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                meta["torn_lines"] += 1
                # a torn line is the expected crash frontier when it
                # ends the file, or when the next record is a
                # server_start (the restart healed the tail and
                # appended after it); torn lines anywhere else mean
                # something other than our append wrote here
                nxt = next((x for x in lines[i + 1:] if x.strip()),
                           "")
                frontier = (not nxt or '"torn_tail_healed"' in nxt
                            or '"server_start"' in nxt)
                log.log(
                    30 if frontier else 40,
                    "journal: %s line %d is torn (%s) — %s",
                    self.path, i + 1,
                    "the crash frontier" if frontier
                    else "NOT at a crash frontier",
                    "replaying around it" if frontier
                    else "skipping it; the journal may have been "
                         "edited by hand")
                continue
            if "event" in rec:
                meta["events"].append(rec)
                if rec["event"] == "server_start":
                    meta["server_starts"] += 1
                continue
            cid = rec.get("cid")
            state = rec.get("state")
            if not cid or state not in STATES:
                meta["torn_lines"] += 1
                log.warning("journal: %s line %d is not a campaign "
                            "transition — skipping", self.path, i + 1)
                continue
            c = campaigns.get(cid)
            if c is None:
                c = campaigns[cid] = Campaign(cid=cid)
            c.state = state
            for k in _REPLAY_FIELDS:
                if k in rec:
                    setattr(c, k, rec[k])
        return campaigns, meta
