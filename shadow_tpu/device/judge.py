"""Batched device network judgment for hybrid execution.

Hybrid mode keeps host emulation (syscall interposition, TCP/UDP
stacks, NIC token buckets) on the CPU and lifts the inter-host network
model — the hot path of the reference's worker_sendPacket
(src/main/core/worker.c:520-579: reliability lookup -> drop roll ->
latency lookup) — onto the device as one batched call per scheduling
round. The CPU drains egress packet metadata (now, src, dst, pkt_seq)
into arrays, the device gathers latency/reliability from the topology
matrices and rolls counter-RNG drops for the whole batch at once, and
the verdicts come back as (delivered, deliver_time) for the CPU to
schedule delivery events.

Determinism: the drop roll is the identical threefry chain used by the
CPU NetworkModel (utils/nprng.py) and the full device engine
(device/engine.py), keyed by stable (src_host, pkt_seq) — so a hybrid
run's event trace is bit-identical to a pure-CPU run of the same
config.

Batches are padded to power-of-two buckets so XLA compiles a handful of
shapes, not one per round.
"""

from __future__ import annotations

import numpy as np

from shadow_tpu._jax import jax, jnp
from shadow_tpu.device import prng
from shadow_tpu.device.netsem import packet_drop_mask
from shadow_tpu.topology import hierarchy

_MIN_BUCKET = 256


def _bucket(n: int) -> int:
    b = _MIN_BUCKET
    while b < n:
        b <<= 1
    return b


class DeviceJudge:
    """Holds the topology matrices on device and a jitted batch-judge."""

    def __init__(self, topology, host_vertex: np.ndarray, seed: int,
                 bootstrap_end: int = 0, min_batch: int = 192,
                 fault_table=None):
        if topology.hier is not None:
            if hierarchy.max_composed_latency(topology.hier.lat_parts()) \
                    > np.iinfo(np.int64).max // 2:
                raise ValueError("latency overflow")
        elif (topology.latency_ns > np.iinfo(np.int64).max // 2).any():
            raise ValueError("latency overflow")
        # fault epochs ride as stacked [T,V,V] matrices + the [T]
        # epoch start times; the fault-free case keeps the plain
        # [V,V] matrices and the original program — identical XLA to
        # before the fault layer. Under the hierarchical
        # representation the matrices are replaced by the factored
        # leaf tuples ([T,C,C] + [T,V] vectors when epoch-stacked)
        # and the gather goes through hierarchy.gather_parts.
        lat, rel, ep_times = hierarchy.world_tables(topology,
                                                    fault_table)
        hier = isinstance(lat, tuple)
        if ep_times is None:
            ep_times = np.zeros(1, dtype=np.int64)
        n_epochs = len(ep_times)
        ep_times_t = jnp.asarray(ep_times)
        self._hv = jnp.asarray(host_vertex.astype(np.int32))
        if hier:
            self._lat = tuple(jnp.asarray(p) for p in lat)
            self._rel = tuple(jnp.asarray(p) for p in rel)
        else:
            self._lat = jnp.asarray(lat)
            self._rel = jnp.asarray(rel)
        self._seed_pair = prng.seed_key(seed)
        boot_end = np.int64(bootstrap_end)
        seed_pair = self._seed_pair

        def _judge(now, src, dst, pseq, hv, lat, rel):
            sv = hv[src]
            dv = hv[dst]
            if n_epochs == 1:
                if hier:
                    latv = hierarchy.gather_parts(lat, sv, dv)
                    relv = hierarchy.gather_parts(rel, sv, dv)
                else:
                    latv, relv = lat[sv, dv], rel[sv, dv]
            else:
                # active epoch at SEND time: count of epoch starts <=
                # now, minus one — the vectorized twin of the CPU
                # model's binary search (faults.FaultTable.epoch_of)
                ep = (now[:, None] >= ep_times_t[None, :]) \
                    .sum(-1).astype(jnp.int32) - 1
                if hier:
                    latv = hierarchy.gather_parts(lat, sv, dv, e=ep)
                    relv = hierarchy.gather_parts(rel, sv, dv, e=ep)
                else:
                    latv, relv = lat[ep, sv, dv], rel[ep, sv, dv]
            dropped = packet_drop_mask(seed_pair, boot_end, now, src,
                                       pseq, relv)
            return ~dropped, now + latv

        self._judge = jax.jit(_judge)
        # adaptive crossover: rounds smaller than this are judged on
        # the CPU (a device dispatch costs ~1-2 ms over a tunneled
        # TPU; a CPU judgment ~10 us/pkt — the trip never pays below
        # a couple hundred packets). The manager consults this.
        self.min_batch = min_batch
        # rounds-trip counters for observability (perf-timer analogue)
        self.batches = 0
        self.packets = 0
        self.cpu_batches = 0        # adaptive small-round fallbacks
        self.cpu_packets = 0

    def judge_batch(self, now: np.ndarray, src: np.ndarray,
                    dst: np.ndarray, pkt_seq: np.ndarray
                    ) -> tuple[np.ndarray, np.ndarray]:
        """All arrays shape [N] -> (delivered bool[N], deliver_time
        i64[N]). One device dispatch per power-of-two bucket size."""
        n = len(now)
        b = _bucket(n)
        pad = b - n

        def p(a, dtype):
            a = np.asarray(a, dtype=dtype)
            return np.pad(a, (0, pad)) if pad else a

        delivered, deliver_time = self._judge(
            jnp.asarray(p(now, np.int64)), jnp.asarray(p(src, np.int32)),
            jnp.asarray(p(dst, np.int32)),
            jnp.asarray(p(pkt_seq, np.int32)),
            self._hv, self._lat, self._rel)
        delivered = np.asarray(delivered)[:n]
        deliver_time = np.asarray(deliver_time)[:n]
        self.batches += 1
        self.packets += n
        return delivered, deliver_time
