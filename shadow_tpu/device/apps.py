"""Device (vectorized) application models.

A DeviceApp is the JAX twin of a CPU ModelApp (shadow_tpu/models/):
`handle` processes one popped event for EVERY host simultaneously —
all inputs/outputs are batched over the local host dimension [H]. To
keep traces bit-identical with the CPU twin, an app must:

* make decisions only from the provided `draws` bits (counter RNG,
  consumed in order: draw i corresponds to the CPU twin's i-th
  ctx.app_bits() call within the same hook), reporting how many draws
  each host consumed in `n_draws`;
* emit sends in the same order as the CPU twin's ctx.send() calls
  (send slot k <-> k-th send), and timers after sends (the engine
  consumes event-sequence numbers sends-first).

Static per-app capacities (max_sends/max_timers/max_draws) size the
engine's arrays; they are compile-time constants.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import NamedTuple

import numpy as np

from shadow_tpu._jax import jnp
from shadow_tpu.core.event import KIND_BOOT, KIND_PACKET, KIND_TIMER


class AppOut(NamedTuple):
    # sends, each [H, K]
    send_dst: jnp.ndarray        # destination global host id (i32)
    send_size: jnp.ndarray       # bytes (i32)
    send_d0: jnp.ndarray         # payload word 0 (i32)
    send_d1: jnp.ndarray         # payload word 1 (i32)
    send_valid: jnp.ndarray      # bool
    # timers, each [H, T]
    timer_delay: jnp.ndarray     # ns (i64)
    timer_d0: jnp.ndarray        # i32
    timer_valid: jnp.ndarray     # bool
    # bookkeeping, each [H]
    n_draws: jnp.ndarray         # app RNG draws consumed (i32)
    app_state: jnp.ndarray       # updated [H, W]


class DeviceApp:
    """Interface; see PholdDevice for the canonical implementation."""

    n_state_words: int = 1
    max_sends: int = 1
    max_timers: int = 0
    max_draws: int = 1

    def init_state(self, n_hosts: int) -> jnp.ndarray:
        return jnp.zeros((n_hosts, self.n_state_words), jnp.int32)

    def handle(self, gid, now, kind, src, size, d0, d1, app_state, draws
               ) -> AppOut:
        raise NotImplementedError


@dataclass
class PholdDevice(DeviceApp):
    """Vectorized twin of models/phold.py (PholdApp) — identical
    decision stream: boot sends `msgload` messages to peers picked as
    (self + 1 + bits % (n-1)) % n, one draw per message; each received
    packet triggers one more send the same way."""

    n_hosts_total: int
    msgload: int = 1
    size: int = 64
    selfloop: int = 0

    def __post_init__(self):
        self.n_state_words = 1          # [received_count]
        self.max_sends = max(1, self.msgload)
        self.max_timers = 0
        self.max_draws = max(1, self.msgload)

    def _pick_peer(self, gid, bits):
        n = self.n_hosts_total
        if self.selfloop or n == 1:
            return (bits % jnp.uint32(n)).astype(jnp.int32)
        return ((gid.astype(jnp.uint32) + 1
                 + bits % jnp.uint32(n - 1))
                % jnp.uint32(n)).astype(jnp.int32)

    def handle(self, gid, now, kind, src, size, d0, d1, app_state, draws
               ) -> AppOut:
        H, K = draws.shape[0], self.max_sends
        boot = kind == KIND_BOOT
        pkt = kind == KIND_PACKET

        ks = jnp.arange(K, dtype=jnp.int32)[None, :]          # [1,K]
        valid = jnp.where(boot[:, None], ks < self.msgload,
                          pkt[:, None] & (ks == 0))           # [H,K]
        peers = self._pick_peer(gid[:, None], draws[:, :K])   # [H,K]
        sizes = jnp.full((H, K), self.size, jnp.int32)
        zeros = jnp.zeros((H, K), jnp.int32)

        n_draws = jnp.where(boot, self.msgload,
                            jnp.where(pkt, 1, 0)).astype(jnp.int32)
        new_state = app_state.at[:, 0].add(pkt.astype(jnp.int32))

        return AppOut(
            send_dst=peers, send_size=sizes, send_d0=zeros, send_d1=zeros,
            send_valid=valid,
            timer_delay=jnp.zeros((H, 0), jnp.int64),
            timer_d0=jnp.zeros((H, 0), jnp.int32),
            timer_valid=jnp.zeros((H, 0), bool),
            n_draws=n_draws,
            app_state=new_state,
        )


@dataclass
class TgenDevice(DeviceApp):
    """Vectorized twin of models/tgen.py: chunked pull-based bulk
    download with a stateless server. One app covers both roles
    (branching on the per-host role word), so client/server mixes run
    on the device without heterogeneous dispatch.

    State words: [role, server_gid, chunk_start, got, downloads_done,
    req_gen, seq_mask]. Protocol/tag/timer encodings match the CPU twin
    exactly (REQ d0=TAG_REQ d1=start; DATA d0=TAG_DATA d1=seq; timer
    d0=-1 pause / d0=gen retry), so event traces are bit-identical.
    seq_mask is the received-seq bitmask within the current window:
    only fresh in-window DATA advances it, so duplicates from a
    premature retry never complete a chunk (same rule as the CPU
    twin's _mask)."""

    roles: np.ndarray = field(repr=False)        # [H] 0=server 1=client
    server_gid: np.ndarray = field(repr=False)   # [H] client's server
    size: int = 1 << 20
    count: int = 1
    pause_ns: int = 1_000_000_000
    retry_ns: int = 0

    TAG_REQ = 1
    TAG_DATA = 2

    def __post_init__(self):
        from shadow_tpu import simtime
        self.MSS = simtime.CONFIG_TCP_MAX_SEGMENT_SIZE
        self.npkts = (self.size + self.MSS - 1) // self.MSS
        self.last_sz = self.size % self.MSS or self.MSS
        from shadow_tpu.models.tgen import CHUNK_PKTS
        assert CHUNK_PKTS <= 32, \
            "seq_mask is one int32 word: CHUNK_PKTS must stay <= 32"
        self.chunk = CHUNK_PKTS
        self.n_state_words = 7
        self.max_sends = self.chunk
        self.max_timers = 1
        self.max_draws = 1              # no randomness consumed

    def init_state(self, n_hosts: int) -> jnp.ndarray:
        # n_hosts may exceed len(roles): shard padding hosts are inert
        # servers that never receive a REQ
        st = np.zeros((n_hosts, self.n_state_words), np.int32)
        n = min(n_hosts, len(self.roles))
        st[:n, 0] = self.roles[:n]
        st[:n, 1] = self.server_gid[:n]
        return jnp.asarray(st)

    def handle(self, gid, now, kind, src, size, d0, d1, app_state, draws
               ) -> AppOut:
        H, K = draws.shape[0], self.max_sends
        role = app_state[:, 0]
        server = app_state[:, 1]
        chunk_start = app_state[:, 2]
        got = app_state[:, 3]
        done = app_state[:, 4]
        gen = app_state[:, 5]
        mask = app_state[:, 6]
        is_server = role == 0
        is_client = role == 1

        is_req = is_server & (kind == KIND_PACKET) & (d0 == self.TAG_REQ)
        is_data = is_client & (kind == KIND_PACKET) & (d0 == self.TAG_DATA)
        is_boot = is_client & (kind == KIND_BOOT) & (self.count > 0)
        is_timer = is_client & (kind == KIND_TIMER)
        timer_pause = is_timer & (d0 < 0)
        timer_retry = is_timer & (d0 >= 0) & (d0 == gen)

        # ---- client window progress (fresh in-window DATA only) ----
        chunk_len = jnp.minimum(self.chunk, self.npkts - chunk_start)
        off = d1 - chunk_start
        in_window = is_data & (off >= 0) & (off < chunk_len)
        bit = jnp.left_shift(jnp.int32(1),
                             jnp.clip(off, 0, self.chunk - 1))
        fresh = in_window & ((mask & bit) == 0)
        new_mask = jnp.where(fresh, mask | bit, mask)
        new_got = jnp.where(fresh, got + 1, got)
        complete = fresh & (new_got >= chunk_len)
        next_start = chunk_start + chunk_len
        dl_done = complete & (next_start >= self.npkts)
        cont = complete & ~dl_done

        send_req = is_boot | timer_pause | timer_retry | cont
        req_start = jnp.where(cont, next_start,
                              jnp.where(timer_retry, chunk_start, 0))

        new_chunk_start = jnp.where(
            cont, next_start,
            jnp.where(is_boot | timer_pause | dl_done, 0, chunk_start))
        new_got = jnp.where(send_req | dl_done, 0, new_got)
        new_mask = jnp.where(send_req | dl_done, 0, new_mask)
        new_done = done + dl_done.astype(jnp.int32)
        new_gen = gen + (send_req | dl_done).astype(jnp.int32)

        st = app_state
        st = st.at[:, 2].set(new_chunk_start)
        st = st.at[:, 3].set(new_got)
        st = st.at[:, 4].set(new_done)
        st = st.at[:, 5].set(new_gen)
        st = st.at[:, 6].set(new_mask)

        # ---- sends ----
        ks = jnp.arange(K, dtype=jnp.int32)[None, :]           # [1,K]
        seqs = d1[:, None] + ks                                # [H,K]
        srv_valid = is_req[:, None] & (seqs < self.npkts)
        srv_size = jnp.where(seqs == self.npkts - 1, self.last_sz,
                             self.MSS)
        cli_valid = (ks == 0) & send_req[:, None]

        sv = is_server[:, None]
        send_valid = jnp.where(sv, srv_valid, cli_valid)
        send_dst = jnp.where(sv, src[:, None],
                             server[:, None]).astype(jnp.int32)
        send_size = jnp.where(sv, srv_size, 64).astype(jnp.int32)
        send_d0 = jnp.where(sv, self.TAG_DATA,
                            self.TAG_REQ).astype(jnp.int32)
        send_d1 = jnp.where(sv, seqs,
                            req_start[:, None]).astype(jnp.int32)

        # ---- timers (pause and retry are mutually exclusive) ----
        pause_valid = dl_done & (new_done < self.count)
        retry_valid = send_req & (self.retry_ns > 0)
        timer_valid = (pause_valid | retry_valid)[:, None]
        timer_delay = jnp.where(pause_valid, self.pause_ns,
                                self.retry_ns)[:, None].astype(jnp.int64)
        timer_d0 = jnp.where(pause_valid, -1,
                             new_gen)[:, None].astype(jnp.int32)

        return AppOut(
            send_dst=send_dst, send_size=send_size, send_d0=send_d0,
            send_d1=send_d1, send_valid=send_valid,
            timer_delay=timer_delay, timer_d0=timer_d0,
            timer_valid=timer_valid,
            n_draws=jnp.zeros((H,), jnp.int32),
            app_state=st,
        )
