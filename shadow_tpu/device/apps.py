"""Device (vectorized) application models.

A DeviceApp is the JAX twin of a CPU ModelApp (shadow_tpu/models/):
`handle` processes one popped event for EVERY host simultaneously —
all inputs/outputs are batched over the local host dimension [H]. To
keep traces bit-identical with the CPU twin, an app must:

* make decisions only from the provided `draws` bits (counter RNG,
  consumed in order: draw i corresponds to the CPU twin's i-th
  ctx.app_bits() call within the same hook), reporting how many draws
  each host consumed in `n_draws`;
* emit sends in the same order as the CPU twin's ctx.send() calls
  (send slot k <-> k-th send), and timers after sends (the engine
  consumes event-sequence numbers sends-first).

Static per-app capacities (max_sends/max_timers/max_draws) size the
engine's arrays; they are compile-time constants.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import NamedTuple

from shadow_tpu._jax import jnp
from shadow_tpu.core.event import KIND_BOOT, KIND_PACKET


class AppOut(NamedTuple):
    # sends, each [H, K]
    send_dst: jnp.ndarray        # destination global host id (i32)
    send_size: jnp.ndarray       # bytes (i32)
    send_d0: jnp.ndarray         # payload word 0 (i32)
    send_d1: jnp.ndarray         # payload word 1 (i32)
    send_valid: jnp.ndarray      # bool
    # timers, each [H, T]
    timer_delay: jnp.ndarray     # ns (i64)
    timer_d0: jnp.ndarray        # i32
    timer_valid: jnp.ndarray     # bool
    # bookkeeping, each [H]
    n_draws: jnp.ndarray         # app RNG draws consumed (i32)
    app_state: jnp.ndarray       # updated [H, W]


class DeviceApp:
    """Interface; see PholdDevice for the canonical implementation."""

    n_state_words: int = 1
    max_sends: int = 1
    max_timers: int = 0
    max_draws: int = 1

    def init_state(self, n_hosts: int) -> jnp.ndarray:
        return jnp.zeros((n_hosts, self.n_state_words), jnp.int32)

    def handle(self, gid, now, kind, src, size, d0, d1, app_state, draws
               ) -> AppOut:
        raise NotImplementedError


@dataclass
class PholdDevice(DeviceApp):
    """Vectorized twin of models/phold.py (PholdApp) — identical
    decision stream: boot sends `msgload` messages to peers picked as
    (self + 1 + bits % (n-1)) % n, one draw per message; each received
    packet triggers one more send the same way."""

    n_hosts_total: int
    msgload: int = 1
    size: int = 64
    selfloop: int = 0

    def __post_init__(self):
        self.n_state_words = 1          # [received_count]
        self.max_sends = max(1, self.msgload)
        self.max_timers = 0
        self.max_draws = max(1, self.msgload)

    def _pick_peer(self, gid, bits):
        n = self.n_hosts_total
        if self.selfloop or n == 1:
            return (bits % jnp.uint32(n)).astype(jnp.int32)
        return ((gid.astype(jnp.uint32) + 1
                 + bits % jnp.uint32(n - 1))
                % jnp.uint32(n)).astype(jnp.int32)

    def handle(self, gid, now, kind, src, size, d0, d1, app_state, draws
               ) -> AppOut:
        H, K = draws.shape[0], self.max_sends
        boot = kind == KIND_BOOT
        pkt = kind == KIND_PACKET

        ks = jnp.arange(K, dtype=jnp.int32)[None, :]          # [1,K]
        valid = jnp.where(boot[:, None], ks < self.msgload,
                          pkt[:, None] & (ks == 0))           # [H,K]
        peers = self._pick_peer(gid[:, None], draws[:, :K])   # [H,K]
        sizes = jnp.full((H, K), self.size, jnp.int32)
        zeros = jnp.zeros((H, K), jnp.int32)

        n_draws = jnp.where(boot, self.msgload,
                            jnp.where(pkt, 1, 0)).astype(jnp.int32)
        new_state = app_state.at[:, 0].add(pkt.astype(jnp.int32))

        return AppOut(
            send_dst=peers, send_size=sizes, send_d0=zeros, send_d1=zeros,
            send_valid=valid,
            timer_delay=jnp.zeros((H, 0), jnp.int64),
            timer_d0=jnp.zeros((H, 0), jnp.int32),
            timer_valid=jnp.zeros((H, 0), bool),
            n_draws=n_draws,
            app_state=new_state,
        )
