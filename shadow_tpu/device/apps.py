"""Device (vectorized) application models.

A DeviceApp is the JAX twin of a CPU ModelApp (shadow_tpu/models/):
`handle` processes one popped event for EVERY host simultaneously —
all inputs/outputs are batched over the local host dimension [H]. To
keep traces bit-identical with the CPU twin, an app must:

* make decisions only from the provided `draws` bits (counter RNG,
  consumed in order: draw i corresponds to the CPU twin's i-th
  ctx.app_bits() call within the same hook), reporting how many draws
  each host consumed in `n_draws`;
* emit sends in the same order as the CPU twin's ctx.send() calls
  (send slot k <-> k-th send), and timers after sends (the engine
  consumes event-sequence numbers sends-first).

Static per-app capacities (max_sends/max_timers/max_draws) size the
engine's arrays; they are compile-time constants.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import NamedTuple, Optional

import numpy as np

from shadow_tpu._jax import jnp
from jax import lax
from shadow_tpu.core.event import KIND_BOOT, KIND_PACKET, KIND_TIMER
from shadow_tpu.device import prng
from shadow_tpu.utils.rng import PURPOSE_TOR_ROUTE


class AppOut(NamedTuple):
    # sends, each [H, K]
    send_dst: jnp.ndarray        # destination global host id (i32)
    send_size: jnp.ndarray       # bytes (i32)
    send_d0: jnp.ndarray         # payload word 0 (i32)
    send_d1: jnp.ndarray         # payload word 1 (i32)
    send_valid: jnp.ndarray      # bool
    # timers, each [H, T]
    timer_delay: jnp.ndarray     # ns (i64)
    timer_d0: jnp.ndarray        # i32
    timer_valid: jnp.ndarray     # bool
    # bookkeeping, each [H]
    n_draws: jnp.ndarray         # app RNG draws consumed (i32)
    app_state: jnp.ndarray       # updated [H, W]
    # packets per send row, each [H, K] (packet TRAINS: the network
    # rolls one drop per packet with the same keys per-packet sends
    # would use and delivers a survivor bitmask as d2); None = all 1
    send_count: Optional[jnp.ndarray] = None
    # train LIVE mask, each [H, K] u32/i32: bit j = lane j of the
    # train actually carries a packet (forwarding a previous hop's
    # survivors). Seq consumption and roll keys still cover all
    # `send_count` lanes (twin alignment); only live lanes are sent,
    # counted, or rolled into d2. None = all live.
    send_mask: Optional[jnp.ndarray] = None


class DeviceApp:
    """Interface; see PholdDevice for the canonical implementation.
    `max_train` > 1 declares the app sends packet trains (send_count
    up to max_train per row); delivered events then carry the
    survivor bitmask in d2."""

    n_state_words: int = 1

    def _set_client_args(self, count, pause_ns, retry_ns,
                         shape) -> None:
        """CLIENT-LOCAL args may vary per host (heterogeneous
        configs): scalars broadcast, arrays pass through."""
        self._count = np.broadcast_to(
            np.asarray(count, np.int32), shape)
        self._pause = np.broadcast_to(
            np.asarray(pause_ns, np.int64), shape)
        self._retry = np.broadcast_to(
            np.asarray(retry_ns, np.int64), shape)

    def _client_args_at(self, gid):
        """(count, pause_ns, retry_ns) gathered per host; padded
        (out-of-range) hosts clip to the last entry — they are inert.

        shadowlint: const-ok(deliberately baked, not threaded through
        wrld: every ndarray attribute of the app is hashed into the
        cache key's workload_fp by capacity.app_fingerprint, and
        ensemble vary axes never change app parameters — see
        engine.audit_consts)"""
        cg = jnp.clip(gid, 0, len(self._count) - 1)
        return (jnp.asarray(self._count)[cg],
                jnp.asarray(self._pause)[cg],
                jnp.asarray(self._retry)[cg])
    max_sends: int = 1
    max_timers: int = 0
    max_draws: int = 1
    max_train: int = 1
    # burst de-skew (engine._step): > 1 declares that hosts selected
    # by burst_mask are STATELESS responders whose consecutive
    # KIND_PACKET events may be popped and answered P at a time, one
    # send lane per popped event. Contract: handling order within the
    # run must not feed back into the run (no state writes, no timers,
    # no draws from burst columns), so the burst is bit-identical to
    # P serial pops.
    burst_pops: int = 1

    def init_state(self, n_hosts: int) -> jnp.ndarray:
        return jnp.zeros((n_hosts, self.n_state_words), jnp.int32)

    def handle(self, gid, now, kind, src, size, d0, d1, d2, app_state,
               draws
               ) -> AppOut:
        raise NotImplementedError

    def burst_mask(self, app_state) -> jnp.ndarray:
        """[H] bool: hosts whose packet handling is stateless (may be
        burst-popped). Only consulted when burst_pops > 1."""
        raise NotImplementedError

    def handle_burst(self, gid, nowP, kindP, srcP, sizeP, d0P, d1P,
                     d2P, app_state, draws) -> AppOut:
        """All event args are [H, burst_pops] columns (inactive
        columns carry kind == -1); returns send lanes [H, burst_pops]
        (lane j answers column j)."""
        raise NotImplementedError


@dataclass
class PholdDevice(DeviceApp):
    """Vectorized twin of models/phold.py (PholdApp) — identical
    decision stream: boot sends `msgload` messages to peers picked as
    (self + 1 + bits % (n-1)) % n, one draw per message; each received
    packet triggers one more send the same way."""

    n_hosts_total: int
    msgload: int = 1
    size: int = 64
    selfloop: int = 0

    def __post_init__(self):
        self.n_state_words = 1          # [received_count]
        self.max_sends = max(1, self.msgload)
        self.max_timers = 0
        self.max_draws = max(1, self.msgload)

    def _pick_peer(self, gid, bits):
        n = self.n_hosts_total
        if self.selfloop or n == 1:
            return (bits % jnp.uint32(n)).astype(jnp.int32)
        return ((gid.astype(jnp.uint32) + 1
                 + bits % jnp.uint32(n - 1))
                % jnp.uint32(n)).astype(jnp.int32)

    def handle(self, gid, now, kind, src, size, d0, d1, d2, app_state,
               draws
               ) -> AppOut:
        H, K = draws.shape[0], self.max_sends
        boot = kind == KIND_BOOT
        pkt = kind == KIND_PACKET

        ks = jnp.arange(K, dtype=jnp.int32)[None, :]          # [1,K]
        valid = jnp.where(boot[:, None], ks < self.msgload,
                          pkt[:, None] & (ks == 0))           # [H,K]
        peers = self._pick_peer(gid[:, None], draws[:, :K])   # [H,K]
        sizes = jnp.full((H, K), self.size, jnp.int32)
        zeros = jnp.zeros((H, K), jnp.int32)

        n_draws = jnp.where(boot, self.msgload,
                            jnp.where(pkt, 1, 0)).astype(jnp.int32)
        new_state = app_state.at[:, 0].add(pkt.astype(jnp.int32))

        return AppOut(
            send_dst=peers, send_size=sizes, send_d0=zeros, send_d1=zeros,
            send_valid=valid,
            timer_delay=jnp.zeros((H, 0), jnp.int64),
            timer_d0=jnp.zeros((H, 0), jnp.int32),
            timer_valid=jnp.zeros((H, 0), bool),
            n_draws=n_draws,
            app_state=new_state,
        )


@dataclass
class TgenDevice(DeviceApp):
    """Vectorized twin of models/tgen.py: chunked pull-based bulk
    download with a stateless server. One app covers both roles
    (branching on the per-host role word), so client/server mixes run
    on the device without heterogeneous dispatch.

    State words: [role, server_gid, chunk_start, got, downloads_done,
    req_gen, seq_mask]. Protocol/tag/timer encodings match the CPU twin
    exactly (REQ d0=TAG_REQ d1=start; DATA is a packet TRAIN row with
    d1=start and the network-computed survivor bitmask in d2; timer
    d0=-1 pause / d0=gen retry), so event traces are bit-identical.
    seq_mask is the received-seq bitmask within the current window:
    only fresh in-window bits advance it, so duplicates from a
    premature retry never complete a chunk (same rule as the CPU
    twin's _mask)."""

    roles: np.ndarray = field(repr=False)        # [H] 0=server 1=client
    server_gid: np.ndarray = field(repr=False)   # [H] client's server
    size: int = 1 << 20
    count: int = 1
    pause_ns: int = 1_000_000_000
    retry_ns: int = 0

    TAG_REQ = 1
    TAG_DATA = 2

    def __post_init__(self):
        from shadow_tpu import simtime
        self.MSS = simtime.CONFIG_TCP_MAX_SEGMENT_SIZE
        self.npkts = (self.size + self.MSS - 1) // self.MSS
        self.last_sz = self.size % self.MSS or self.MSS
        from shadow_tpu.models.tgen import CHUNK_PKTS
        assert CHUNK_PKTS <= 32, \
            "seq_mask is one int32 word: CHUNK_PKTS must stay <= 32"
        self.chunk = CHUNK_PKTS
        self.n_state_words = 7
        self.max_sends = 1              # a whole chunk is ONE train row
        self.max_train = self.chunk
        self.max_timers = 1
        self.max_draws = 1              # no randomness consumed
        # servers are stateless responders: a hub answering its whole
        # REQ backlog 8 per iteration instead of 1 (burst de-skew)
        self.burst_pops = 8
        # `size` shapes the SERVER's response and must stay uniform;
        # count/pause/retry are client-local and may vary per host
        self._set_client_args(self.count, self.pause_ns,
                              self.retry_ns, np.shape(self.roles))

    def init_state(self, n_hosts: int) -> jnp.ndarray:
        # n_hosts may exceed len(roles): shard padding hosts are inert
        # servers that never receive a REQ
        st = np.zeros((n_hosts, self.n_state_words), np.int32)
        n = min(n_hosts, len(self.roles))
        st[:n, 0] = self.roles[:n]
        st[:n, 1] = self.server_gid[:n]
        return jnp.asarray(st)

    def handle(self, gid, now, kind, src, size, d0, d1, d2, app_state,
               draws
               ) -> AppOut:
        H, K = draws.shape[0], self.max_sends
        role = app_state[:, 0]
        server = app_state[:, 1]
        chunk_start = app_state[:, 2]
        got = app_state[:, 3]
        done = app_state[:, 4]
        gen = app_state[:, 5]
        mask = app_state[:, 6]
        is_server = role == 0
        is_client = role == 1

        count_h, pause_h, retry_h = self._client_args_at(gid)

        is_req = is_server & (kind == KIND_PACKET) & (d0 == self.TAG_REQ)
        is_data = is_client & (kind == KIND_PACKET) & (d0 == self.TAG_DATA)
        is_boot = is_client & (kind == KIND_BOOT) & (count_h > 0)
        is_timer = is_client & (kind == KIND_TIMER)
        timer_pause = is_timer & (d0 < 0)
        timer_retry = is_timer & (d0 >= 0) & (d0 == gen)

        # ---- client window progress (fresh in-window bits only) ----
        # a DATA train: d1 = start packet index, d2 = survivor bitmask
        # (bit j <-> packet d1+j). Align to the current window, mask
        # off already-received bits, count the rest (popcount — the
        # CPU twin counts the same bits one by one).
        chunk_len = jnp.minimum(self.chunk, self.npkts - chunk_start)
        shift = d1 - chunk_start                              # [H]
        surv_u = d2.astype(jnp.uint32)
        up = jnp.left_shift(surv_u,
                            jnp.clip(shift, 0, 31).astype(jnp.uint32))
        down = jnp.right_shift(surv_u,
                               jnp.clip(-shift, 0,
                                        31).astype(jnp.uint32))
        aligned = jnp.where(shift >= 0, up, down)
        # a train a full window or more away contributes nothing (the
        # u32 shifts clip at 31; the CPU twin's python shift yields 0)
        aligned = jnp.where((shift >= 32) | (shift <= -32),
                            jnp.uint32(0), aligned)
        wmask = jnp.where(
            chunk_len >= 32, jnp.uint32(0xFFFFFFFF),
            (jnp.uint32(1) << jnp.clip(chunk_len, 0,
                                       31).astype(jnp.uint32))
            - jnp.uint32(1))
        window = aligned & wmask
        fresh_bits = window & ~mask.astype(jnp.uint32)
        fresh = is_data & (fresh_bits != 0)
        new_mask = jnp.where(
            fresh, (mask.astype(jnp.uint32) | fresh_bits)
            .astype(jnp.int32), mask)
        new_got = jnp.where(
            fresh,
            got + lax.population_count(fresh_bits).astype(jnp.int32),
            got)
        complete = fresh & (new_got >= chunk_len)
        next_start = chunk_start + chunk_len
        dl_done = complete & (next_start >= self.npkts)
        cont = complete & ~dl_done

        send_req = is_boot | timer_pause | timer_retry | cont
        req_start = jnp.where(cont, next_start,
                              jnp.where(timer_retry, chunk_start, 0))

        new_chunk_start = jnp.where(
            cont, next_start,
            jnp.where(is_boot | timer_pause | dl_done, 0, chunk_start))
        new_got = jnp.where(send_req | dl_done, 0, new_got)
        new_mask = jnp.where(send_req | dl_done, 0, new_mask)
        new_done = done + dl_done.astype(jnp.int32)
        new_gen = gen + (send_req | dl_done).astype(jnp.int32)

        st = app_state
        st = st.at[:, 2].set(new_chunk_start)
        st = st.at[:, 3].set(new_got)
        st = st.at[:, 4].set(new_done)
        st = st.at[:, 5].set(new_gen)
        st = st.at[:, 6].set(new_mask)

        # ---- sends (K == 1: one REQ row or one DATA train row) ----
        srv_cnt, srv_bytes = self._server_response(d1)
        srv_valid = is_req & (srv_cnt > 0)

        sv = is_server
        send_valid = jnp.where(sv, srv_valid, send_req)[:, None]
        send_dst = jnp.where(sv, src, server)[:, None].astype(jnp.int32)
        send_size = jnp.where(sv, srv_bytes, 64)[:, None].astype(
            jnp.int32)
        send_d0 = jnp.where(sv, self.TAG_DATA,
                            self.TAG_REQ)[:, None].astype(jnp.int32)
        send_d1 = jnp.where(sv, d1,
                            req_start)[:, None].astype(jnp.int32)
        send_count = jnp.where(sv, srv_cnt, 1)[:, None].astype(
            jnp.int32)

        # ---- timers (pause and retry are mutually exclusive) ----
        pause_valid = dl_done & (new_done < count_h)
        retry_valid = send_req & (retry_h > 0)
        timer_valid = (pause_valid | retry_valid)[:, None]
        timer_delay = jnp.where(pause_valid, pause_h,
                                retry_h)[:, None].astype(jnp.int64)
        timer_d0 = jnp.where(pause_valid, -1,
                             new_gen)[:, None].astype(jnp.int32)

        return AppOut(
            send_dst=send_dst, send_size=send_size, send_d0=send_d0,
            send_d1=send_d1, send_valid=send_valid,
            timer_delay=timer_delay, timer_d0=timer_d0,
            timer_valid=timer_valid,
            n_draws=jnp.zeros((H,), jnp.int32),
            app_state=st,
            send_count=send_count,
        )

    def _server_response(self, d1):
        """The stateless server answer to a REQ for chunk start d1:
        (train packet count, total bytes) — the whole chunk
        [d1, d1+cnt) as one train (MSS each, last-packet remainder
        when the chunk reaches the end of the file). The SINGLE
        source of truth for both the serial and the burst path — the
        burst path's bit-identity depends on them never diverging."""
        srv_cnt = jnp.clip(self.npkts - d1, 0, self.chunk)
        ends_file = d1 + srv_cnt >= self.npkts
        srv_bytes = jnp.where(
            ends_file, (srv_cnt - 1) * self.MSS + self.last_sz,
            srv_cnt * self.MSS)
        return srv_cnt, srv_bytes

    def burst_mask(self, app_state) -> jnp.ndarray:
        return app_state[:, 0] == 0         # servers: stateless

    def handle_burst(self, gid, nowP, kindP, srcP, sizeP, d0P, d1P,
                     d2P, app_state, draws) -> AppOut:
        """Column 0 runs the FULL role logic (client window progress,
        timers, state — identical to the non-burst path); columns 1+
        can only ever be burst-popped server REQ packets, answered by
        the same stateless response computation, one lane each."""
        base = self.handle(gid, nowP[:, 0], kindP[:, 0], srcP[:, 0],
                           sizeP[:, 0], d0P[:, 0], d1P[:, 0],
                           d2P[:, 0], app_state, draws)
        is_server = (app_state[:, 0] == 0)[:, None]
        is_req = is_server & (kindP == KIND_PACKET) & \
            (d0P == self.TAG_REQ)
        srv_cnt, srv_bytes = self._server_response(d1P)
        valid = is_req & (srv_cnt > 0)
        srv_bytes = srv_bytes.astype(jnp.int32)

        def lanes(l0, rest):
            return jnp.concatenate([l0, rest[:, 1:]], axis=1)

        tag = jnp.full_like(d1P, self.TAG_DATA)
        return base._replace(
            send_dst=lanes(base.send_dst, srcP.astype(jnp.int32)),
            send_size=lanes(base.send_size, srv_bytes),
            send_d0=lanes(base.send_d0, tag.astype(jnp.int32)),
            send_d1=lanes(base.send_d1, d1P.astype(jnp.int32)),
            send_valid=lanes(base.send_valid, valid),
            send_count=lanes(base.send_count,
                             srv_cnt.astype(jnp.int32)),
        )


@dataclass
class TorDevice(DeviceApp):
    """Vectorized twin of models/tor.py: onion circuits as pure
    functions of the client id (counter-RNG keyed (TOR_ROUTE, circ,
    hop)), so relays are completely stateless and every hop decision is
    one batched branch — the design reason the CPU model keeps no
    per-relay circuit tables.

    State words (clients; relays only use word 0):
    [role, chunk_start, got, done, gen, mask].
    d1 packs (circ << SEQ_BITS) | (start-or-seq)."""

    roles: np.ndarray = field(repr=False)       # [H] 0=relay 1=client
    relay_gids: np.ndarray = field(repr=False)  # [R] sorted
    seed: int = 1
    cells: int = 64
    count: int = 1
    pause_ns: int = 1_000_000_000
    retry_ns: int = 0

    TAG_REQ = 3
    TAG_DATA = 4

    def __post_init__(self):
        from shadow_tpu.models.tor import (
            CELL_BYTES, CHUNK_CELLS, SEQ_BITS, SEQ_MASK)
        assert len(self.relay_gids) >= 3, "tor model needs >= 3 relays"
        assert self.cells <= SEQ_MASK
        assert CHUNK_CELLS <= 32, "client mask is one int32 word"
        self.CELL = CELL_BYTES
        self.chunk = CHUNK_CELLS
        self.SEQ_BITS = SEQ_BITS
        self.SEQ_MASK = SEQ_MASK
        self.n_state_words = 6
        # cells travel as packet TRAINS (one row per chunk with a
        # survivor bitmask, per-cell drop rolls): every app event
        # emits at most ONE row, which also unlocks relay burst-pops
        self.max_sends = 1
        self.max_train = self.chunk
        self.max_timers = 1
        self.max_draws = 1              # no stateful randomness
        self.burst_pops = 8             # relays: stateless responders
        self.seed_pair = prng.seed_key(self.seed)
        # `cells` shapes the exit relays' DATA service and must stay
        # uniform; count/pause/retry are client-local per-host
        self._set_client_args(self.count, self.pause_ns,
                              self.retry_ns, np.shape(self.roles))

    def init_state(self, n_hosts: int) -> jnp.ndarray:
        st = np.zeros((n_hosts, self.n_state_words), np.int32)
        n = min(n_hosts, len(self.roles))
        st[:n, 0] = self.roles[:n]
        return jnp.asarray(st)

    def _route(self, circ):
        """(guard, middle, exit) gids — models/tor.py pick_route in
        vector form, bit-identical draws."""
        R = len(self.relay_gids)
        def bits(j):
            return prng.random_bits32(prng.chain_key(
                self.seed_pair, PURPOSE_TOR_ROUTE, circ,
                jnp.full_like(circ, j)))
        g = (bits(0) % jnp.uint32(R)).astype(jnp.int32)
        m = (bits(1) % jnp.uint32(R - 1)).astype(jnp.int32)
        m = jnp.where(m >= g, m + 1, m)
        lo = jnp.minimum(g, m)
        hi = jnp.maximum(g, m)
        e = (bits(2) % jnp.uint32(R - 2)).astype(jnp.int32)
        e = jnp.where(e >= lo, e + 1, e)
        e = jnp.where(e >= hi, e + 1, e)
        gids = jnp.asarray(self.relay_gids.astype(np.int32))
        return gids[g], gids[m], gids[e]

    def _relay_lane(self, me, kind, d0, d1, d2):
        """The stateless relay answer to one popped event — shared by
        the serial path (column 0) and burst columns. All inputs are
        same-shape arrays; returns per-element lane fields (valid,
        dst, size, d0, d1, count, mask). d1 is ECHOED on every relay
        hop ((circ << SEQ_BITS) | chunk start)."""
        is_pkt = kind == KIND_PACKET
        circ = jnp.right_shift(d1, self.SEQ_BITS)
        start = d1 & self.SEQ_MASK
        G, M, E = self._route(circ)
        r_req = is_pkt & (d0 == self.TAG_REQ)
        r_data = is_pkt & (d0 == self.TAG_DATA)
        fwd_req_g = r_req & (me == G)        # -> M
        fwd_req_m = r_req & (me == M)        # -> E
        serve = r_req & (me == E)            # exit: DATA train
        fwd_data_m = r_data & (me == M)      # -> G
        fwd_data_g = r_data & (me == G)      # -> client (circ)
        fwd_data = fwd_data_m | fwd_data_g

        cnt = jnp.clip(self.cells - start, 0, self.chunk)
        full = (jnp.left_shift(jnp.uint32(1), cnt.astype(jnp.uint32))
                - jnp.uint32(1)).astype(jnp.int32)
        surv_in = d2
        live = lax.population_count(
            surv_in.astype(jnp.uint32)).astype(jnp.int32)

        valid = fwd_req_g | fwd_req_m | (serve & (cnt > 0)) | \
            (fwd_data & (surv_in != 0))
        dst = jnp.where(
            fwd_req_g, M, jnp.where(
                fwd_req_m, E, jnp.where(
                    serve, M, jnp.where(fwd_data_m, G, circ))))
        size = jnp.where(serve, self.CELL * cnt,
                         jnp.where(fwd_data, self.CELL * live, 64))
        out_d0 = jnp.where(serve, self.TAG_DATA, d0)
        count = jnp.where(serve | fwd_data, self.chunk, 1)
        lmask = jnp.where(serve, full,
                          jnp.where(fwd_data, surv_in, 1))
        return (valid, dst.astype(jnp.int32),
                size.astype(jnp.int32), out_d0.astype(jnp.int32),
                d1.astype(jnp.int32), count.astype(jnp.int32),
                lmask.astype(jnp.int32))

    def burst_mask(self, app_state) -> jnp.ndarray:
        return app_state[:, 0] == 0         # relays: stateless

    def handle_burst(self, gid, nowP, kindP, srcP, sizeP, d0P, d1P,
                     d2P, app_state, draws) -> AppOut:
        """Column 0 runs the full role logic; columns 1+ can only be
        burst-popped RELAY packets — answered by the shared stateless
        lane computation, one train row each."""
        base = self.handle(gid, nowP[:, 0], kindP[:, 0], srcP[:, 0],
                           sizeP[:, 0], d0P[:, 0], d1P[:, 0],
                           d2P[:, 0], app_state, draws)
        is_relay = (app_state[:, 0] == 0)[:, None]
        me = gid[:, None]
        valid, dst, size, d0o, d1o, count, lmask = self._relay_lane(
            me, kindP, d0P, d1P, d2P)
        valid = valid & is_relay

        def lanes(l0, rest):
            return jnp.concatenate([l0, rest[:, 1:]], axis=1)

        return base._replace(
            send_dst=lanes(base.send_dst, dst),
            send_size=lanes(base.send_size, size),
            send_d0=lanes(base.send_d0, d0o),
            send_d1=lanes(base.send_d1, d1o),
            send_valid=lanes(base.send_valid, valid),
            send_count=lanes(base.send_count, count),
            send_mask=lanes(base.send_mask, lmask),
        )

    def handle(self, gid, now, kind, src, size, d0, d1, d2, app_state,
               draws
               ) -> AppOut:
        H = draws.shape[0]
        role = app_state[:, 0]
        chunk_start = app_state[:, 1]
        got = app_state[:, 2]
        done = app_state[:, 3]
        gen = app_state[:, 4]
        mask = app_state[:, 5]
        is_relay = role == 0
        is_client = role == 1

        is_pkt = kind == KIND_PACKET
        me = gid

        # ---- relay lane (stateless; trains forwarded by mask) ----
        (r_valid, r_dst, r_size, r_d0, r_d1, r_count,
         r_mask) = self._relay_lane(me, kind, d0, d1, d2)
        r_valid = r_valid & is_relay

        # ---- client window progress (tgen train-fold rules) ----
        my_route = self._route(me)
        my_guard = my_route[0]
        count_h, pause_h, retry_h = self._client_args_at(gid)

        start_f = d1 & self.SEQ_MASK
        c_data = is_client & is_pkt & (d0 == self.TAG_DATA)
        c_boot = is_client & (kind == KIND_BOOT) & (count_h > 0)
        c_timer = is_client & (kind == KIND_TIMER)
        timer_pause = c_timer & (d0 < 0)
        timer_retry = c_timer & (d0 >= 0) & (d0 == gen)

        chunk_len = jnp.minimum(self.chunk, self.cells - chunk_start)
        shift = start_f - chunk_start
        surv_u = d2.astype(jnp.uint32)
        up = jnp.left_shift(surv_u,
                            jnp.clip(shift, 0, 31).astype(jnp.uint32))
        down = jnp.right_shift(
            surv_u, jnp.clip(-shift, 0, 31).astype(jnp.uint32))
        aligned = jnp.where(shift >= 0, up, down)
        aligned = jnp.where((shift >= 32) | (shift <= -32),
                            jnp.uint32(0), aligned)
        wmask = (jnp.left_shift(
            jnp.uint32(1),
            jnp.clip(chunk_len, 0, 31).astype(jnp.uint32))
            - jnp.uint32(1))
        window = aligned & wmask
        fresh_bits = window & ~mask.astype(jnp.uint32)
        fresh = c_data & (fresh_bits != 0)
        new_mask = jnp.where(
            fresh, (mask.astype(jnp.uint32) | fresh_bits)
            .astype(jnp.int32), mask)
        new_got = jnp.where(
            fresh,
            got + lax.population_count(fresh_bits).astype(jnp.int32),
            got)
        complete = fresh & (new_got >= chunk_len)
        nxt = chunk_start + chunk_len
        dl_done = complete & (nxt >= self.cells)
        cont = complete & ~dl_done

        send_req = c_boot | timer_pause | timer_retry | cont
        req_start = jnp.where(cont, nxt,
                              jnp.where(timer_retry, chunk_start, 0))
        new_chunk_start = jnp.where(
            cont, nxt,
            jnp.where(c_boot | timer_pause | dl_done, 0, chunk_start))
        new_got = jnp.where(send_req | dl_done, 0, new_got)
        new_mask = jnp.where(send_req | dl_done, 0, new_mask)
        new_done = done + dl_done.astype(jnp.int32)
        new_gen = gen + (send_req | dl_done).astype(jnp.int32)

        st = app_state
        st = st.at[:, 1].set(new_chunk_start)
        st = st.at[:, 2].set(new_got)
        st = st.at[:, 3].set(new_done)
        st = st.at[:, 4].set(new_gen)
        st = st.at[:, 5].set(new_mask)

        # ---- the single send lane: relay row or client REQ ----
        req_d1 = jnp.left_shift(me, self.SEQ_BITS) | req_start
        rv = r_valid
        send_valid = (rv | send_req)[:, None]
        send_dst = jnp.where(rv, r_dst, my_guard)[:, None] \
            .astype(jnp.int32)
        send_size = jnp.where(rv, r_size, 64)[:, None] \
            .astype(jnp.int32)
        send_d0 = jnp.where(rv, r_d0, self.TAG_REQ)[:, None] \
            .astype(jnp.int32)
        send_d1 = jnp.where(rv, r_d1, req_d1)[:, None] \
            .astype(jnp.int32)
        send_count = jnp.where(rv, r_count, 1)[:, None] \
            .astype(jnp.int32)
        send_mask = jnp.where(rv, r_mask, 1)[:, None] \
            .astype(jnp.int32)

        # ---- timers ----
        pause_valid = dl_done & (new_done < count_h)
        retry_valid = send_req & (retry_h > 0)
        timer_valid = (pause_valid | retry_valid)[:, None]
        timer_delay = jnp.where(pause_valid, pause_h,
                                retry_h)[:, None].astype(jnp.int64)
        timer_d0 = jnp.where(pause_valid, -1,
                             new_gen)[:, None].astype(jnp.int32)

        return AppOut(
            send_dst=send_dst, send_size=send_size, send_d0=send_d0,
            send_d1=send_d1, send_valid=send_valid,
            timer_delay=timer_delay, timer_d0=timer_d0,
            timer_valid=timer_valid,
            n_draws=jnp.zeros((H,), jnp.int32),
            app_state=st,
            send_count=send_count,
            send_mask=send_mask,
        )
