"""Supervised device runs: periodic validated checkpoints, graceful
preemption, and dispatch retry/failover.

PR 2 made the *simulated* world fault-tolerant (link outages, host
crashes); this module makes the simulator process itself survivable.
Production training/inference stacks treat preemption and
checkpoint-restart as first-class, and multi-hour 10k-host or
ensemble campaigns need the same three guarantees:

1. **Periodic validated checkpointing** — every ``checkpoint_every``
   sim ns the run writes a rotating checkpoint
   (``<checkpoint_save>.t<ns>``, atomic tmp+rename, last
   ``checkpoint_keep`` retained). A checkpoint is written only from a
   VALIDATED state: the loud overflow counters are clean and, with
   ``state_audit`` on, the on-device health word (engine.py AUD_*
   bits) is zero — so a corrupted state is never the one a
   crash-restart resumes from. ``checkpoint_load`` accepts the base
   path and resolves to the newest *readable* rotation entry,
   skipping truncated files.

2. **Graceful preemption** — SIGTERM/SIGINT set a drain flag; the
   in-flight dispatch segment finishes, a resume checkpoint is saved
   at the segment boundary, and the process exits with
   ``EXIT_PREEMPTED`` (75, EX_TEMPFAIL). Because the engine clamps
   event windows on the *global* stop, the resumed run is
   bit-identical to the uninterrupted one (the checkpoint contract).
   A second signal aborts hard (handlers restored, KeyboardInterrupt).

3. **Dispatch retry + failover** — a transient device error
   (RESOURCE_EXHAUSTED, device unavailable, ...) retries the failed
   segment from the last validated state with capped exponential
   backoff (``dispatch_retries`` / ``dispatch_retry_backoff``). After
   exhausting retries, ``failover: hybrid`` saves the last validated
   state to disk and raises :class:`DeviceFailover`, which the
   Controller answers by re-running on the hybrid backend with a loud
   diagnostic instead of aborting — the device checkpoint remains on
   disk for a device-side resume.

:func:`advance` is the single segmented-advance loop both
``DeviceRunner`` and ``EnsembleRunner`` now share: it generalizes the
overflow re-plan/retry loop PR 1 built into one recovery path for all
failure classes (capacity overflow, transient dispatch errors, audit
violations, preemption).
"""

from __future__ import annotations

import glob
import os
import signal
import time
from dataclasses import dataclass, field

import numpy as np

from shadow_tpu.obs import trace as obstrace
from shadow_tpu.utils.slog import get_logger

log = get_logger("supervise")

# distinct exit code for a graceful preemption (EX_TEMPFAIL): the
# operator/scheduler can tell "resume me" apart from success (0) and
# failure (1)
EXIT_PREEMPTED = 75

# exponential backoff cap between dispatch retries (wall seconds)
BACKOFF_CAP_S = 30.0

# substrings marking a device error as transient (worth retrying from
# the last validated state). Matched against str(exc) — XLA surfaces
# these as XlaRuntimeError messages whose class identity varies by
# jaxlib version, so the message is the stable surface.
TRANSIENT_MARKERS = (
    "RESOURCE_EXHAUSTED",
    "UNAVAILABLE",
    "DEADLINE_EXCEEDED",
    "ABORTED",
    "device unavailable",
    "failed to connect",
    "Socket closed",
    "out of memory",
)

AUDIT_BIT_NAMES = {
    1: "heap-order/head-bounds",
    2: "clock-monotonicity",
    4: "counter-negativity",
    8: "packet-conservation",
}


class AuditFailure(RuntimeError):
    """The on-device invariant audit found a corrupted state. The run
    stops rather than writing (or running past) a checkpoint that a
    restart would trust."""


class DeviceFailover(RuntimeError):
    """Dispatch retries exhausted under ``failover: hybrid``: carries
    the last validated checkpoint (for a later device-side resume) and
    the sim time it pins. The Controller catches this and re-runs the
    config on the hybrid backend."""

    def __init__(self, message: str, checkpoint_path: str = "",
                 sim_time: int = 0):
        super().__init__(message)
        self.checkpoint_path = checkpoint_path
        self.sim_time = int(sim_time)


def is_transient(exc: BaseException) -> bool:
    """Whether a dispatch error is worth retrying from the last
    validated state (vs a programming error that would just recur)."""
    text = str(exc)
    return any(m in text for m in TRANSIENT_MARKERS)


def decode_audit(word: int) -> list[str]:
    """Health-word bitmask -> the named invariants it violates."""
    return [name for bit, name in sorted(AUDIT_BIT_NAMES.items())
            if word & bit]


def check_audit(state, where: str = "", last_good: str = "") -> None:
    """Validate the on-device health word of a (standalone [H] or
    ensemble [R, H]) state. No-op when the engine was built without
    the audit. Raises :class:`AuditFailure` naming the violated
    invariants — and the last validated checkpoint, if any — on a
    nonzero word."""
    if "aud" not in state:
        return
    from shadow_tpu._jax import jax

    aud = np.asarray(jax.device_get(state["aud"]))
    if not aud.any():
        return
    names = decode_audit(int(np.bitwise_or.reduce(aud, axis=None)))
    hint = (f"; last validated checkpoint: {last_good}" if last_good
            else "; no validated checkpoint exists yet")
    raise AuditFailure(
        f"state audit failed{f' at {where}' if where else ''}: "
        f"violated invariant(s) {names} on "
        f"{int((aud != 0).sum())} host slot(s) — the state is "
        f"corrupted and will not be checkpointed or run further"
        f"{hint}")


class PreemptionGuard:
    """SIGTERM/SIGINT drain handler, installed for the duration of a
    supervised run (context manager). The first signal sets
    ``requested`` — the advance loop finishes the in-flight dispatch
    segment, saves a resume checkpoint, and returns preempted. A
    second signal restores the original handlers and raises
    KeyboardInterrupt (hard abort escape hatch). Outside the main
    thread signal handlers cannot be installed; the guard then stays
    inactive and the run behaves as before."""

    SIGNALS = (signal.SIGTERM, signal.SIGINT)

    def __init__(self):
        self.requested = False
        self.signum: int = 0
        self.active = False
        self._orig: dict = {}

    def request(self) -> None:
        """Programmatic preemption (tests, embedding harnesses)."""
        self.requested = True

    def _handle(self, signum, frame):
        if self.requested:
            self._restore()
            raise KeyboardInterrupt(
                f"second {signal.Signals(signum).name} during drain — "
                "aborting hard (state NOT saved)")
        self.requested = True
        self.signum = signum
        log.warning(
            "received %s: draining — finishing the in-flight dispatch "
            "segment, then saving a resume checkpoint and exiting "
            "with rc %d (send the signal again to abort hard)",
            signal.Signals(signum).name, EXIT_PREEMPTED)

    def _restore(self) -> None:
        for s, h in self._orig.items():
            try:
                signal.signal(s, h)
            except (ValueError, OSError):
                pass
        self._orig.clear()
        self.active = False

    def __enter__(self) -> "PreemptionGuard":
        try:
            for s in self.SIGNALS:
                self._orig[s] = signal.signal(s, self._handle)
            self.active = True
        except ValueError:
            # not the main thread: leave signal disposition alone
            self._restore()
        return self

    def __exit__(self, *exc) -> None:
        self._restore()


def heartbeat_rates(mark, sent_totals):
    """The ONE pkts/s-since-last-heartbeat rule for the
    ``[supervise-heartbeat]`` and ``[ensemble-heartbeat]`` lines
    (DeviceRunner and EnsembleRunner both delegate here so the two
    surfaces cannot drift): given the previous ``(wall, totals)``
    mark (or None) and the current cumulative sent totals (one entry
    per line — the standalone runner passes one, the campaign one per
    replica), return ``(new_mark, rates)`` with each rate a formatted
    string. The first boundary rates "n/a" — there is no previous
    mark, and a resumed run's counters include the pre-resume total,
    so a since-start rate would lie."""
    wall = time.perf_counter()
    rates = ["n/a"] * len(sent_totals)
    if mark is not None:
        dw = wall - mark[0]
        if dw > 0:
            rates = [f"{(float(s) - float(p)) / dw:.0f}"
                     for s, p in zip(sent_totals, mark[1])]
    return (wall, [float(s) for s in sent_totals]), rates


def prefetch_programs(runner, ensemble: bool = False) -> None:
    """Cache-aware prefetch (the PR 6 ROADMAP leftover): when a
    capacity plan, a strategy plan, or a re-plan has just named the
    next program — a rebuilt engine whose executable the AOT cache
    may hold — start that entry's background read NOW, so the work
    that runs before the next dispatch (state transfer, checkpoint
    load, init_state) overlaps the disk read instead of the first
    ``ensure()`` paying it synchronously. Best-effort: no cache, an
    unsupported backend, or a fingerprinting failure is a silent
    no-op (the synchronous path still serves). Traced as a
    ``compile.prefetch`` instant."""
    cache = getattr(runner, "aot_cache", None)
    engine = getattr(runner, "engine", None)
    if cache is None or engine is None or cache.unsupported:
        return
    program = "run_ens" if ensemble else "run"
    if program in getattr(engine, "_aot_exec", {}):
        return              # this engine already resolved it
    from shadow_tpu.device import aotcache

    try:
        key = aotcache.program_key(engine, program)
    except Exception:       # noqa: BLE001 — ensure() will warn
        return
    cache.prefetch(key, program=program)


def drain_possible(cfg) -> bool:
    """Whether a run under this config ever reaches a segment
    boundary before its pause — the only points a preemption drain
    can fire. Without one (no checkpoint_every, no dispatch_segment,
    no heartbeat) the whole run is ONE dispatch segment: installing
    the guard would swallow SIGTERM/SIGINT while promising a drain
    that can never happen, strictly worse than the default signal
    disposition — so the runners leave the signals alone and log
    why."""
    xp = cfg.experimental
    return bool(xp.checkpoint_every or xp.dispatch_segment
                or cfg.general.heartbeat_interval)


def make_guard(cfg):
    """The runners' guard factory: a PreemptionGuard when a drain can
    actually fire, else None (with a hint, once per run)."""
    if not cfg.experimental.checkpoint_save:
        return None
    if not drain_possible(cfg):
        log.info(
            "preemption drain inactive: the run has no segment "
            "boundaries (set experimental.checkpoint_every or "
            "dispatch_segment, or general.heartbeat_interval, to "
            "make SIGTERM drain to a resume checkpoint)")
        return None
    return PreemptionGuard()


def rotation_entries(base: str) -> list[tuple[int, str]]:
    """Existing rotation files for a checkpoint base path, sorted by
    sim time ascending: ``<base>.t<15-digit-ns>``. Non-numeric
    suffixes (in-flight ``.tmp`` files) are ignored."""
    out = []
    for p in glob.glob(glob.escape(base) + ".t*"):
        suffix = p[len(base) + 2:]
        if suffix.isdigit():
            out.append((int(suffix), p))
    return sorted(out)


def resolve_checkpoint(path: str) -> str:
    """``checkpoint_load`` resolution: a concrete file wins; otherwise
    the newest READABLE rotation entry of the base path (a truncated
    npz — the file a kill outran — is skipped with a warning, so the
    resume lands on the last validated checkpoint, exactly the
    rotation's purpose)."""
    if os.path.exists(path):
        return path
    entries = rotation_entries(path)
    if not entries:
        raise ValueError(
            f"checkpoint_load: {path!r} does not exist and has no "
            f"rotation entries ({path}.t*) — nothing to resume")
    from shadow_tpu.device import checkpoint

    for t, p in reversed(entries):
        try:
            meta = checkpoint.peek_meta(p)
            if meta.get("format") != checkpoint.FORMAT:
                raise ValueError(f"format {meta.get('format')}")
        except Exception as e:      # noqa: BLE001 — any unreadable entry
            log.warning("skipping unreadable checkpoint %s (%s); "
                        "falling back to the previous rotation entry",
                        p, e)
            continue
        log.info("checkpoint_load: %s resolved to rotation entry %s "
                 "(t=%d ns)", path, p, t)
        return p
    raise ValueError(
        f"checkpoint_load: every rotation entry of {path!r} is "
        "unreadable — nothing to resume")


class Checkpointer:
    """Rotating last-K checkpoint writer for one supervised run.
    Every write goes through the atomic tmp+rename path in
    checkpoint.save_state; pruning happens only after a successful
    replace, so there is always at least one complete checkpoint on
    disk once the first boundary passes."""

    def __init__(self, base: str, every: int, keep: int,
                 final_stop: int, extra_meta: dict = None,
                 audit_enabled: bool = False):
        self.base = base
        self.every = int(every)
        self.keep = max(1, int(keep))
        self.final_stop = int(final_stop)
        self.extra_meta = extra_meta
        self.audit_enabled = bool(audit_enabled)
        self.last_path = ""
        self.last_t = -1

    def next_after(self, t: int) -> int:
        return (t // self.every + 1) * self.every

    def save(self, engine, state, t: int) -> str:
        from shadow_tpu.device import checkpoint

        path = f"{self.base}.t{t:015d}"
        checkpoint.save_state(
            engine, state, path, t, final_stop=self.final_stop,
            extra_meta=self.extra_meta,
            audit_meta={"enabled": self.audit_enabled,
                        "violations": 0})
        self.last_path, self.last_t = path, t
        self._prune()
        log.info("rotating checkpoint at t=%d ns -> %s "
                 "(keep %d; resume with checkpoint_load: %s)",
                 t, path, self.keep, self.base)
        return path

    def _prune(self) -> None:
        entries = rotation_entries(self.base)
        for _, p in entries[:-self.keep]:
            try:
                os.unlink(p)
            except OSError as e:
                log.warning("could not prune old checkpoint %s: %s",
                            p, e)


@dataclass
class AdvanceResult:
    """What supervise.advance hands back to the runner, beyond the
    final state: the (per-replica) round counts and every way the
    advance can end short of `pause`."""

    rounds: np.ndarray = field(
        default_factory=lambda: np.int64(0))
    t_end: int = 0
    budget_hit: bool = False
    overflowed: bool = False
    preempted: bool = False
    resume_path: str = ""
    retries: int = 0


def advance(runner, state, t_start: int, pause: int, stop: int,
            ensemble: bool = False):
    """The shared segmented-advance loop (DeviceRunner and
    EnsembleRunner both delegate here): advance [t_start, pause) in
    segments cut at heartbeat / dispatch-segment / checkpoint
    boundaries, validating the state at every boundary and recovering
    from each failure class:

    * capacity overflow  -> widen + re-plan, re-run from the last
      known-good state (PR 1's loop, non-static plans only);
    * transient dispatch error -> capped-backoff retry from the last
      validated state; exhausted -> DeviceFailover (failover: hybrid)
      or re-raise;
    * audit violation    -> AuditFailure (fatal: never checkpoint or
      run forward a corrupted state);
    * preemption request -> save a resume checkpoint at the boundary
      and return preempted.

    Every unit of work records a flight-recorder span (shadow_tpu/obs
    — dispatch segments with their sim windows and ICI counters,
    heartbeats, checkpoint saves, retry backoffs, re-plans, the
    preemption drain), tagged so trace_report can attribute the run's
    wall. Tracing only reads values this loop already fetched, so
    traces stay bit-identical across telemetry modes.

    Returns (state, AdvanceResult).
    """
    from shadow_tpu._jax import jax
    from shadow_tpu.device import capacity, checkpoint

    tracer = getattr(runner, "tracer", None) or obstrace.current()
    xp = runner.sim.cfg.experimental
    hb = runner.sim.cfg.general.heartbeat_interval
    seg = xp.dispatch_segment
    ck: Checkpointer = getattr(runner, "checkpointer", None)
    guard: PreemptionGuard = getattr(runner, "guard", None)
    audit_on = bool(xp.state_audit)
    retry_ok = xp.capacity_plan != "static"
    supervised = bool(ck is not None
                      or (guard is not None and guard.active)
                      or xp.dispatch_retries
                      or xp.failover != "abort")
    # last known-good snapshot: device refs are immutable, so holding
    # the pytree costs nothing to take — but it pins the previous
    # segment's buffers, so plain static runs (which can never retry)
    # still skip it; every supervised failure class needs it
    keep_good = retry_ok or supervised
    budget = runner.engine.config.max_rounds
    label = "ensemble " if ensemble else ""

    def run_segment(st, nxt):
        if ensemble:
            return runner.engine.run_ensemble(st, stop=nxt,
                                              final_stop=stop)
        return runner.engine.run(st, stop=nxt, final_stop=stop)

    def replace_state(host_state):
        # place a host-side snapshot back onto the (possibly rebuilt)
        # engine with fresh device buffers
        if ensemble:
            return capacity.transfer(
                runner.engine, runner.sim.starts, host_state,
                template=runner.engine.init_ensemble_state(
                    runner.sim.starts))
        return capacity.transfer(runner.engine, runner.sim.starts,
                                 host_state)

    def drain_save(st, t):
        """The preemption resume checkpoint: reuse the rotation entry
        just written at this boundary, else write one."""
        if ck is not None:
            if ck.last_t == t:
                return ck.last_path
            return ck.save(runner.engine, st, t)
        path = xp.checkpoint_save
        checkpoint.save_state(
            runner.engine, st, path, t, final_stop=stop,
            extra_meta=getattr(runner, "_ck_extra_meta", None),
            audit_meta={"enabled": audit_on, "violations": 0})
        return path

    res = AdvanceResult()
    good_state, good_t = (state if keep_good else None), t_start
    failures = 0
    t = t_start
    next_hb = (t // hb + 1) * hb if hb else None
    next_ck = ck.next_after(t) if ck is not None else None
    while t < pause:
        nxt = pause
        if next_hb is not None:
            nxt = min(nxt, next_hb)
        if seg:
            nxt = min(nxt, t + seg)
        if next_ck is not None:
            nxt = min(nxt, next_ck)
        try:
            # the span covers the dispatch AND the device_gets that
            # synchronize it — that pair is what "one segment costs"
            # means on the wall clock. A raised dispatch error closes
            # the span with an error tag, so retries show on the
            # timeline as failed-dispatch + backoff + recover spans.
            with tracer.span("dispatch", "dispatch", sim_t0=t,
                             sim_t1=nxt) as sp:
                state, seg_rounds = run_segment(state, nxt)
                # both device_gets below synchronize, so
                # asynchronously raised dispatch errors surface
                # inside this try
                dims = capacity.overflow_dims(state)
                seg_rounds = np.asarray(jax.device_get(seg_rounds))
                sp.add(rounds=int(np.max(seg_rounds)))
                eff = runner.engine.effective
                if eff.get("n_shards", 1) > 1:
                    # exchange-flush attribution: the flush is fused
                    # into the compiled round on-device, so its wall
                    # is inside this span; the static per-flush ICI
                    # volume (buffers ship at capacity) rides as
                    # counters (engine.profile() measures the split
                    # walls when real exchange timing is needed)
                    sp.add(exchange=eff["exchange"],
                           shards=eff["n_shards"],
                           ici_rows_per_flush=eff[
                               "ICI_rows_per_flush"],
                           ici_bytes_per_flush=eff[
                               "ICI_bytes_per_flush"])
        except AuditFailure:
            raise
        except Exception as e:      # noqa: BLE001 — classified below
            if not is_transient(e) or good_state is None:
                raise
            # `failures` counts CONSECUTIVE failures of the current
            # segment (reset on every completed segment): unrelated
            # transient incidents hours apart must not pool into one
            # exhausted budget — a genuinely dead device still
            # exhausts it, because its segment never completes
            failures += 1
            res.retries += 1
            # live cumulative count: the supervise heartbeat line
            # reports it mid-run, not just the end-of-run SimStats
            runner.retries = res.retries
            if failures > xp.dispatch_retries:
                _escalate(runner, e, good_state, good_t, stop,
                          ensemble, ck)
            delay = min(
                xp.dispatch_retry_backoff * (2 ** (failures - 1)),
                BACKOFF_CAP_S)
            log.warning(
                "transient %sdevice dispatch error in (%d, %d] ns "
                "(%s); retry %d/%d from the last validated state "
                "t=%d ns after %.1fs backoff", label, good_t, nxt,
                e, failures, xp.dispatch_retries, good_t, delay)
            if delay:
                with tracer.span("retry.backoff", "retry",
                                 sim_t0=good_t, sim_t1=nxt,
                                 attempt=failures,
                                 error=str(e)[:200]):
                    time.sleep(delay)
            with tracer.span("retry.recover", "retry", sim_t0=good_t,
                             attempt=failures):
                state = _recover_state(runner, good_state,
                                       replace_state, ck, stop,
                                       ensemble)
            good_state = state
            t = good_t
            next_hb = (t // hb + 1) * hb if hb else None
            next_ck = ck.next_after(t) if ck is not None else None
            continue
        if dims:
            if not retry_ok or runner.replans >= capacity.MAX_REPLANS:
                res.rounds = res.rounds + seg_rounds
                t = nxt
                res.overflowed = True
                tracer.instant("capacity.overflow", "plan", sim_t0=t,
                               dims=list(dims))
                break           # loud failure (stats.ok = False)
            runner.replans += 1
            runner._capacity_overrides = capacity.widen(
                runner._capacity_overrides, dims,
                runner.engine.effective)
            log.warning(
                "%scapacity overflow on %s in (%d, %d] ns; re-plan "
                "#%d with %s, re-running from t=%d ns", label, dims,
                good_t, nxt, runner.replans,
                runner._capacity_overrides, good_t)
            with tracer.span("capacity.replan", "plan", sim_t0=good_t,
                             sim_t1=nxt, dims=list(dims),
                             replan=runner.replans):
                runner.engine = runner._build_engine()
                # the re-plan just named the next program: its AOT
                # entry read overlaps the state transfer below
                prefetch_programs(runner, ensemble)
                state = replace_state(jax.device_get(good_state))
            good_state = state
            t = good_t
            next_hb = (t // hb + 1) * hb if hb else None
            next_ck = ck.next_after(t) if ck is not None else None
            continue
        res.rounds = res.rounds + seg_rounds
        t = nxt
        failures = 0        # the segment completed; see above
        if int(np.max(res.rounds)) >= budget:
            if t < pause:
                # enforced cumulatively (per-invocation caps would
                # reset each segment); don't emit a heartbeat for an
                # interval the budget cut short
                log.warning("max_rounds (%d) exhausted during "
                            "%ssegmentation; stopping", budget, label)
            res.budget_hit = True
            tracer.instant("budget.exhausted", "host", sim_t0=t,
                           budget=int(budget))
            break
        if audit_on:
            # the boundary state is validated BEFORE it becomes the
            # known-good snapshot or a checkpoint — a corrupted state
            # is never the one a retry or a restart resumes from
            check_audit(state, where=f"t={t} ns",
                        last_good=(ck.last_path if ck is not None
                                   else ""))
        if next_hb is not None and t >= next_hb and t < stop:
            with tracer.span("heartbeat", "host", sim_t0=t):
                runner._emit_heartbeats(t, state)
            next_hb += hb
        if next_ck is not None and t >= next_ck and t < stop:
            with tracer.span("checkpoint.save", "checkpoint",
                             sim_t0=t) as sp:
                sp.add(path=ck.save(runner.engine, state, t))
            next_ck = ck.next_after(t)
        if keep_good:
            good_state, good_t = state, t
        if guard is not None and guard.requested and t < pause:
            # a signal that lands during the FINAL segment needs no
            # drain — the run reached its pause/stop and completes
            # normally (the t >= pause case falls out of the loop)
            tracer.instant("preempt.request", "checkpoint", sim_t0=t,
                           signum=guard.signum)
            with tracer.span("checkpoint.drain_save", "checkpoint",
                             sim_t0=t) as sp:
                res.resume_path = drain_save(state, t)
                sp.add(path=res.resume_path)
            res.preempted = True
            log.warning(
                "%srun preempted at t=%d ns: resume checkpoint -> %s "
                "(re-run with experimental.checkpoint_load: %s to "
                "continue; the resumed run is bit-identical to an "
                "uninterrupted one)", label, t, res.resume_path,
                ck.base if ck is not None else res.resume_path)
            break
    res.t_end = t
    return state, res


def _recover_state(runner, good_state, replace_state, ck, stop,
                   ensemble):
    """Re-place the last validated state onto fresh device buffers for
    a dispatch retry. If even fetching the held snapshot fails (the
    device that owned it is gone), fall back to the last rotating
    checkpoint on disk."""
    from shadow_tpu._jax import jax
    from shadow_tpu.device import checkpoint

    try:
        return replace_state(jax.device_get(good_state))
    except Exception as fetch_err:      # noqa: BLE001
        if ck is None or not ck.last_path:
            raise
        log.warning("could not recover the in-memory state (%s); "
                    "reloading the last validated checkpoint %s",
                    fetch_err, ck.last_path)
        # the snapshot's owner died, so the engine's compiled
        # executables (bound to the dead device's buffers) are
        # suspect too — rebuild the engine for the retry. The AOT
        # compile cache (device/aotcache.py, attached by
        # _build_engine) turns this recompile into a warm start:
        # same capacities -> same program key -> cached executable.
        runner.engine = runner._build_engine()
        # overlap the rebuilt program's AOT entry read with the
        # checkpoint reload below
        prefetch_programs(runner, ensemble)
        template = (runner.engine.init_ensemble_state(runner.sim.starts)
                    if ensemble else None)
        state, _ = checkpoint.load_state(
            runner.engine, runner.sim.starts, ck.last_path,
            final_stop=stop, template=template)
        return state


def _escalate(runner, exc, good_state, good_t, stop, ensemble, ck):
    """Retries exhausted: under ``failover: hybrid`` persist the last
    validated state and raise DeviceFailover for the Controller;
    otherwise re-raise the dispatch error."""
    from shadow_tpu._jax import jax
    from shadow_tpu.device import checkpoint

    xp = runner.sim.cfg.experimental
    if xp.failover != "hybrid" or ensemble:
        raise exc
    path, t_pin = "", good_t
    if ck is not None and ck.last_path:
        path, t_pin = ck.last_path, ck.last_t
    try:
        host_good = jax.device_get(good_state)
        fo_path = ((xp.checkpoint_save + ".failover")
                   if xp.checkpoint_save else
                   os.path.join(runner.sim.cfg.general.data_directory,
                                "device_failover.npz"))
        checkpoint.save_state(
            runner.engine, host_good, fo_path, good_t,
            final_stop=stop,
            audit_meta={"enabled": bool(xp.state_audit),
                        "violations": 0})
        path, t_pin = fo_path, good_t
    except Exception as save_err:       # noqa: BLE001
        if not path:
            log.error("failover: could not persist the last "
                      "validated state (%s) and no rotating "
                      "checkpoint exists — re-raising the dispatch "
                      "error", save_err)
            raise exc from None
        log.warning("failover: could not persist the in-memory state "
                    "(%s); the last rotating checkpoint %s (t=%d ns) "
                    "pins the device-side resume", save_err, path,
                    t_pin)
    raise DeviceFailover(
        f"device dispatch failed permanently after "
        f"{xp.dispatch_retries} retries ({exc}); last validated "
        f"state at t={t_pin} ns saved to {path or '<none>'}",
        checkpoint_path=path, sim_time=t_pin) from exc
