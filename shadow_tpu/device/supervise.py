"""Supervised device runs: periodic validated checkpoints, graceful
preemption, and dispatch retry/failover.

PR 2 made the *simulated* world fault-tolerant (link outages, host
crashes); this module makes the simulator process itself survivable.
Production training/inference stacks treat preemption and
checkpoint-restart as first-class, and multi-hour 10k-host or
ensemble campaigns need the same guarantees:

1. **Periodic validated checkpointing** — every ``checkpoint_every``
   sim ns the run writes a rotating checkpoint
   (``<checkpoint_save>.t<ns>``, atomic tmp+rename, last
   ``checkpoint_keep`` retained). A checkpoint is written only from a
   VALIDATED state: the loud overflow counters are clean and, with
   ``state_audit`` on, the on-device health word (engine.py AUD_*
   bits) is zero — so a corrupted state is never the one a
   crash-restart resumes from. ``checkpoint_load`` accepts the base
   path and resolves to the newest *readable* rotation entry,
   skipping truncated files.

2. **Graceful preemption** — SIGTERM/SIGINT set a drain flag; the
   in-flight dispatch segment finishes, a resume checkpoint is saved
   at the segment boundary, and the process exits with
   ``EXIT_PREEMPTED`` (75, EX_TEMPFAIL). Because the engine clamps
   event windows on the *global* stop, the resumed run is
   bit-identical to the uninterrupted one (the checkpoint contract).
   A second signal aborts hard (handlers restored, KeyboardInterrupt).

3. **Dispatch retry + the failover ladder** — a transient device
   error (RESOURCE_EXHAUSTED, device unavailable, ...) retries the
   failed segment from the last validated state with capped
   exponential backoff (``dispatch_retries`` /
   ``dispatch_retry_backoff``). After exhausting retries the ladder
   engages (``failover:``): ``shrink`` probes the mesh, re-shards
   the last validated state onto the surviving M devices
   (:func:`_shrink_recover` + capacity.reshard_state) and continues
   ON-DEVICE — losing 1 of N chips costs 1/N of throughput, not the
   run, and the continuation is bit-identical to an uninterrupted
   M-shard run (the mesh-shape determinism contract); when no
   shrink is possible it escalates to the hybrid rung. ``hybrid``
   saves the last validated state to disk and raises
   :class:`DeviceFailover`, which the Controller answers by
   re-running on the hybrid backend with a loud diagnostic instead
   of aborting — the device checkpoint remains on disk for a
   device-side resume. The ladder is drilled in CI by the
   deterministic chaos injector (device/chaos.py,
   ``experimental.chaos``; determinism_gate --chaos).

4. **The OOM degradation ladder** — a *deterministic* memory
   exhaustion (the same RESOURCE_EXHAUSTED twice in a row at the
   same validated boundary, or one that survives the retry budget)
   is a capacity fact, not a transient: each recurrence walks one
   rung that actually shrinks the footprint — halve the pipeline
   window depth, split the ensemble into sequential replica batches
   (:class:`DegradeToReplicaBatch`, caught by the campaign), halve
   the dispatch segment — and replays bit-identically from the last
   validated state without charging ``dispatch_retries``. Out of
   rungs, the existing ``failover:`` escalation applies. The ladder
   is the runtime backstop of the preflight admission gate
   (capacity.footprint / ``experimental.admission``) and is drilled
   by the chaos injector's ``oom`` seam (determinism_gate
   --degrade).

:func:`advance` is the single segmented-advance loop both
``DeviceRunner`` and ``EnsembleRunner`` now share: it generalizes the
overflow re-plan/retry loop PR 1 built into one recovery path for all
failure classes (capacity overflow, transient dispatch errors, audit
violations, preemption).

Since PR 11 the loop is an event-driven segment *pipeline*
(``experimental.pipeline_depth``): an ISSUE half enqueues up to N
dispatch segments back-to-back (jax dispatch is asynchronous — each
``run`` call returns device futures in ~ms), and a strictly-ordered
DRAIN half performs the blocking ``overflow``/``seg_rounds`` syncs,
audit validation, known-good snapshotting, checkpoint rotation, and
heartbeat emission for the oldest in-flight segment — so host-side
work for boundary *k* overlaps device execution of segments k+1..k+N.
Depth 0/1 reproduces today's serial issue-then-drain ordering exactly
(and the compiled device program is untouched by the knob at ANY
depth — pipelining is pure host-side orchestration). Every recovery
class survives pipelining by the same rule: the speculative in-flight
window is discarded and the loop replays serially from the last
validated state, which is bit-identical by the determinism contract
(recomputing a deterministic segment yields the same trace). A
preemption drain completes the in-flight window before saving the
boundary checkpoint, so issued device work is never thrown away on a
SIGTERM.
"""

from __future__ import annotations

import glob
import os
import signal
import threading
import time
from collections import deque
from dataclasses import dataclass, field

import numpy as np

from shadow_tpu.obs import trace as obstrace
from shadow_tpu.utils.slog import get_logger

log = get_logger("supervise")

# distinct exit code for a graceful preemption (EX_TEMPFAIL): the
# operator/scheduler can tell "resume me" apart from success (0) and
# failure (1)
EXIT_PREEMPTED = 75

# exponential backoff cap between dispatch retries (wall seconds)
BACKOFF_CAP_S = 30.0

# degradation-ladder floor: how many times the dispatch-segment rung
# may halve the segment before the ladder gives up and escalates —
# shorter segments shrink the transient working set with diminishing
# returns, and an OOM that survives 4 halvings is not segment-bound
MAX_SEG_HALVINGS = 4

# substrings marking a device error as transient (worth retrying from
# the last validated state). Matched against str(exc) — XLA surfaces
# these as XlaRuntimeError messages whose class identity varies by
# jaxlib version, so the message is the stable surface.
TRANSIENT_MARKERS = (
    "RESOURCE_EXHAUSTED",
    "UNAVAILABLE",
    "DEADLINE_EXCEEDED",
    "ABORTED",
    "device unavailable",
    "failed to connect",
    "Socket closed",
    "out of memory",
)

# the subset of TRANSIENT_MARKERS that names memory exhaustion. A
# matching error that RECURS at the same validated boundary is a
# capacity fact (the footprint does not fit), not flakiness — the
# degradation ladder, not the retry budget, is the answer.
OOM_MARKERS = (
    "RESOURCE_EXHAUSTED",
    "out of memory",
)

AUDIT_BIT_NAMES = {
    1: "heap-order/head-bounds",
    2: "clock-monotonicity",
    4: "counter-negativity",
    8: "packet-conservation",
}


class AuditFailure(RuntimeError):
    """The on-device invariant audit found a corrupted state. The run
    stops rather than writing (or running past) a checkpoint that a
    restart would trust."""


class DeviceFailover(RuntimeError):
    """Dispatch retries (and, under ``failover: shrink``, the mesh
    shrink) exhausted: carries the last validated checkpoint (for a
    later device-side resume) and the sim time it pins. The
    Controller catches this and re-runs the config on the hybrid
    backend. ``checkpoint_path`` is explicitly ``None`` when no
    state could be persisted at all (the save failed AND no rotating
    checkpoint exists) — ``persist_error`` then names the save
    failure, and the Controller's single diagnostic surfaces it: the
    hybrid rerun restarts from t=0 with no device-side resume
    point."""

    def __init__(self, message: str, checkpoint_path=None,
                 sim_time: int = 0, persist_error: str = ""):
        super().__init__(message)
        self.checkpoint_path = checkpoint_path
        self.sim_time = int(sim_time)
        self.persist_error = persist_error


class DegradeToReplicaBatch(RuntimeError):
    """OOM degradation ladder, replica-batch rung (ensembles only):
    the full-R vmap does not fit on the mesh. Carries the suggested
    per-batch replica count; the campaign catches this and re-runs
    the sweep in sequential replica batches (vmap over R/k replicas
    per batch, finals merged), which is bit-identical to the full
    vmap — each replica's trace is a pure function of its own world
    row, so stacking order and batch boundaries cannot change it."""

    def __init__(self, message: str, batch: int = 1):
        super().__init__(message)
        self.batch = max(1, int(batch))


def is_transient(exc: BaseException) -> bool:
    """Whether a dispatch error is worth retrying from the last
    validated state (vs a programming error that would just recur)."""
    text = str(exc)
    return any(m in text for m in TRANSIENT_MARKERS)


def is_oom(exc: BaseException) -> bool:
    """Whether a dispatch/compile error names memory exhaustion. OOM
    stays transient-retryable ONCE (an allocator can lose a race with
    another process and win the rerun); the second consecutive hit at
    the same validated boundary is deterministic and routes to the
    degradation ladder instead of burning the retry budget."""
    text = str(exc)
    return any(m in text for m in OOM_MARKERS)


def decode_audit(word: int) -> list[str]:
    """Health-word bitmask -> the named invariants it violates."""
    return [name for bit, name in sorted(AUDIT_BIT_NAMES.items())
            if word & bit]


def check_audit(state, where: str = "", last_good: str = "") -> None:
    """Validate the on-device health word of a (standalone [H] or
    ensemble [R, H]) state. No-op when the engine was built without
    the audit. Raises :class:`AuditFailure` naming the violated
    invariants — and the last validated checkpoint, if any — on a
    nonzero word."""
    if "aud" not in state:
        return
    from shadow_tpu._jax import jax

    aud = np.asarray(jax.device_get(state["aud"]))
    if not aud.any():
        return
    names = decode_audit(int(np.bitwise_or.reduce(aud, axis=None)))
    hint = (f"; last validated checkpoint: {last_good}" if last_good
            else "; no validated checkpoint exists yet")
    raise AuditFailure(
        f"state audit failed{f' at {where}' if where else ''}: "
        f"violated invariant(s) {names} on "
        f"{int((aud != 0).sum())} host slot(s) — the state is "
        f"corrupted and will not be checkpointed or run further"
        f"{hint}")


class PreemptionGuard:
    """SIGTERM/SIGINT drain handler, installed for the duration of a
    supervised run (context manager). The first signal sets
    ``requested`` — the advance loop finishes the in-flight dispatch
    segment, saves a resume checkpoint, and returns preempted. A
    second signal restores the original handlers and raises
    KeyboardInterrupt (hard abort escape hatch). Outside the main
    thread signal handlers cannot be installed; the guard then stays
    inactive and the run behaves as before."""

    SIGNALS = (signal.SIGTERM, signal.SIGINT)

    def __init__(self):
        self.requested = False
        self.signum: int = 0
        self.active = False
        self._orig: dict = {}

    def request(self) -> None:
        """Programmatic preemption (tests, embedding harnesses)."""
        self.requested = True

    def _handle(self, signum, frame):
        if self.requested:
            self._restore()
            raise KeyboardInterrupt(
                f"second {signal.Signals(signum).name} during drain — "
                "aborting hard (state NOT saved)")
        self.requested = True
        self.signum = signum
        log.warning(
            "received %s: draining — finishing the in-flight dispatch "
            "segment, then saving a resume checkpoint and exiting "
            "with rc %d (send the signal again to abort hard)",
            signal.Signals(signum).name, EXIT_PREEMPTED)

    def _restore(self) -> None:
        for s, h in self._orig.items():
            try:
                signal.signal(s, h)
            except (ValueError, OSError):
                pass
        self._orig.clear()
        self.active = False

    def __enter__(self) -> "PreemptionGuard":
        try:
            for s in self.SIGNALS:
                self._orig[s] = signal.signal(s, self._handle)
            self.active = True
        except ValueError:
            # not the main thread: leave signal disposition alone
            self._restore()
        return self

    def __exit__(self, *exc) -> None:
        self._restore()


def heartbeat_rates(mark, sent_totals):
    """The ONE pkts/s-since-last-heartbeat rule for the
    ``[supervise-heartbeat]`` and ``[ensemble-heartbeat]`` lines
    (DeviceRunner and EnsembleRunner both delegate here so the two
    surfaces cannot drift): given the previous ``(wall, totals)``
    mark (or None) and the current cumulative sent totals (one entry
    per line — the standalone runner passes one, the campaign one per
    replica), return ``(new_mark, rates)`` with each rate a formatted
    string. The first boundary rates "n/a" — there is no previous
    mark, and a resumed run's counters include the pre-resume total,
    so a since-start rate would lie."""
    wall = time.perf_counter()
    rates = ["n/a"] * len(sent_totals)
    if mark is not None:
        dw = wall - mark[0]
        if dw > 0:
            rates = [f"{(float(s) - float(p)) / dw:.0f}"
                     for s, p in zip(sent_totals, mark[1])]
    return (wall, [float(s) for s in sent_totals]), rates


class HeartbeatMonitor:
    """Wall-clock staleness detector on the heartbeat cadence
    (``experimental.heartbeat_stale_after`` = k; both runners own one
    per run when the knob is set). The runner calls :meth:`beat` at
    every ``[supervise-heartbeat]`` / ``[ensemble-heartbeat]``
    boundary; the expected cadence is an EWMA of the healthy gaps, and
    a gap wider than k times it is counted in ``stale_events`` with a
    loud warning — SimStats.stale_heartbeats surfaces the count.

    :meth:`stale` is the live probe the campaign server's watchdog
    polls from ITS thread: a wedged device step never reaches the next
    beat(), so only an outside observer can watch the current gap grow
    past the threshold. All state is lock-protected for exactly that
    cross-thread read. The clock is injectable (frozen-clock unit
    tests drive the gap arithmetic without sleeping)."""

    def __init__(self, k: int, clock=time.monotonic):
        # k < 2 would flag ordinary cadence jitter (a segment that
        # runs 1.3x the EWMA is normal); clamp rather than refuse so
        # a config's `1` means "as sensitive as is sane"
        self.k = max(2, int(k))
        self._clock = clock
        self._lock = threading.Lock()
        self._last = None     # wall of the previous beat
        self._expect = None   # EWMA of healthy gaps, seconds
        self.stale_events = 0

    def beat(self) -> None:
        """Record one heartbeat boundary; warn + count when the gap
        since the previous one exceeded k x the expected cadence. A
        stale gap is NOT folded into the EWMA — the expectation keeps
        tracking the healthy cadence, so one stall cannot raise the
        bar for detecting the next."""
        now = self._clock()
        with self._lock:
            if self._last is not None:
                gap = max(now - self._last, 1e-9)
                if self._expect is None:
                    self._expect = gap
                elif gap > self.k * self._expect:
                    self.stale_events += 1
                    log.warning(
                        "STALE HEARTBEAT: %.2fs since the previous "
                        "heartbeat — %.1fx the expected %.2fs cadence "
                        "(threshold %dx); the run stalled between "
                        "segment boundaries (%d stale gap(s) so far)",
                        gap, gap / self._expect, self._expect,
                        self.k, self.stale_events)
                else:
                    self._expect = 0.5 * self._expect + 0.5 * gap
            self._last = now

    def gap(self) -> float:
        """Seconds since the last beat (0.0 before the first)."""
        with self._lock:
            return (0.0 if self._last is None
                    else max(0.0, self._clock() - self._last))

    def stale(self) -> bool:
        """Live cross-thread probe: is the CURRENT gap already past
        the threshold? False until two beats have established a
        cadence — a watchdog must not kill a run that is still
        compiling its first program."""
        with self._lock:
            if self._last is None or self._expect is None:
                return False
            return (self._clock() - self._last) > \
                self.k * self._expect


def prefetch_programs(runner, ensemble: bool = False) -> None:
    """Cache-aware prefetch (the PR 6 ROADMAP leftover): when a
    capacity plan, a strategy plan, or a re-plan has just named the
    next program — a rebuilt engine whose executable the AOT cache
    may hold — start that entry's background read NOW, so the work
    that runs before the next dispatch (state transfer, checkpoint
    load, init_state) overlaps the disk read instead of the first
    ``ensure()`` paying it synchronously. Best-effort: no cache, an
    unsupported backend, or a fingerprinting failure is a silent
    no-op (the synchronous path still serves). Traced as a
    ``compile.prefetch`` instant."""
    cache = getattr(runner, "aot_cache", None)
    engine = getattr(runner, "engine", None)
    if cache is None or engine is None or cache.unsupported:
        return
    program = "run_ens" if ensemble else "run"
    if program in getattr(engine, "_aot_exec", {}):
        return              # this engine already resolved it
    from shadow_tpu.device import aotcache

    try:
        key = aotcache.program_key(engine, program)
    except Exception:       # noqa: BLE001 — ensure() will warn
        return
    cache.prefetch(key, program=program)


def surviving_devices(mesh) -> list:
    """Probe every device of a mesh for liveness (a trivial placement
    + sync per device) and return the survivors, in mesh order. The
    chaos injector's dead set is consulted first, so a scripted
    device loss (device/chaos.py) fails the probe exactly the way a
    real dead chip does — the shrink failover cannot tell them
    apart, which is the point."""
    from shadow_tpu._jax import jax
    from shadow_tpu.device import chaos as chaosmod

    inj = chaosmod.current()
    alive = []
    for d in mesh.devices.flat:
        if inj is not None and inj.is_dead(d.id):
            log.warning("device %s failed the liveness probe "
                        "(scripted device loss)", d)
            continue
        try:
            jax.block_until_ready(
                jax.device_put(np.zeros(1, np.int32), d))
        except Exception as e:      # noqa: BLE001 — any probe failure = dead
            log.warning("device %s failed the liveness probe: %s",
                        d, e)
            continue
        alive.append(d)
    return alive


def _shrink_recover(runner, exc, good_state, good_t, ensemble, ck,
                    tracer):
    """``failover: shrink`` — retries exhausted on a device error:
    probe the mesh, and if dead devices are found with at least one
    survivor, re-shard the last validated state onto the M-device
    mesh and hand back a state the advance loop continues from
    ON-DEVICE (losing 1 of N chips costs 1/N of throughput, not the
    run). Returns ``(new_state, validated_t)`` or None when no
    shrink is possible (nothing dead, nothing alive, or the state is
    unrecoverable) — the caller then escalates down the failover
    ladder.

    Determinism: the engine's traces are bit-identical across mesh
    shapes, the re-shard (capacity.reshard_state) carries every
    per-host leaf verbatim, and segment boundaries are a pure
    function of sim time — so the N-shard prefix + M-shard
    continuation equals both the uninterrupted M-shard run and the
    serial oracle (determinism_gate --chaos pins all three)."""
    from shadow_tpu._jax import jax
    from shadow_tpu.device import checkpoint

    engine = runner.engine
    old_n = engine.n_shards
    with tracer.span("reshard.probe", "reshard", sim_t0=good_t,
                     shards=old_n):
        alive = surviving_devices(engine.mesh)
    n_dead = len(list(engine.mesh.devices.flat)) - len(alive)
    if n_dead == 0:
        log.error("shrink failover: every mesh device passed the "
                  "liveness probe — the dispatch failure (%s) cannot "
                  "be attributed to a dead device; escalating", exc)
        return None
    if not alive:
        log.error("shrink failover: no mesh device survived the "
                  "liveness probe; escalating")
        return None
    # recover the last validated state host-side; a dead device owns
    # shards of the in-memory snapshot, so the fetch may fail — the
    # newest rotating checkpoint on disk is the fallback, and the
    # replay rewinds to ITS sim time (older than good_t is fine:
    # deterministic segments recompute bit-identically)
    t_good = good_t
    try:
        host_state = jax.device_get(good_state)
    except Exception as fetch_err:      # noqa: BLE001 — dead-device fetch
        if ck is None or not ck.last_path:
            log.error("shrink failover: the last validated state is "
                      "unrecoverable (%s) and no rotating checkpoint "
                      "exists; escalating", fetch_err)
            return None
        log.warning("shrink failover: could not fetch the in-memory "
                    "state (%s); re-sharding the newest readable "
                    "rotating checkpoint instead", fetch_err)
        # newest-READABLE walk (the resolve_checkpoint rule): the
        # newest entry may be the torn artifact a crash leaves —
        # forfeiting the shrink over it when an older readable entry
        # exists would be exactly the failure mode the rotation is
        # for. Replaying from an older boundary is fine:
        # deterministic segments recompute bit-identically.
        host_state = None
        for _, p_e in reversed(rotation_entries(ck.base)):
            try:
                host_state, meta = checkpoint.load_host_state(p_e)
                break
            except Exception as load_err:   # noqa: BLE001 — torn entry
                log.warning("shrink failover: rotation entry %s is "
                            "unreadable (%s); trying the previous "
                            "one", p_e, load_err)
        if host_state is None:
            log.error("shrink failover: no readable rotation entry "
                      "under %s; escalating", ck.base)
            return None
        t_good = int(meta["sim_time"])
    try:
        with tracer.span("reshard.shrink", "reshard", sim_t0=t_good,
                         from_shards=old_n, to_shards=len(alive),
                         error=str(exc)[:200]) as sp:
            state = runner._shrink_to(alive, host_state,
                                      ensemble=ensemble)
            sp.add(h_pad=runner.engine.H_pad)
    except Exception as re_err:         # noqa: BLE001 — escalate, not crash
        log.error("shrink failover: re-sharding onto the %d "
                  "surviving device(s) failed (%s); escalating",
                  len(alive), re_err)
        return None
    log.warning(
        "MESH SHRINK: %d device(s) dead (%s) — re-sharded the last "
        "validated state (t=%d ns) onto the %d surviving device(s) "
        "and continuing on-device at %d/%d of mesh throughput; "
        "checkpoints from here stamp the shrunken geometry",
        n_dead, exc, t_good, len(alive), len(alive), old_n)
    return state, t_good


def drain_possible(cfg) -> bool:
    """Whether a run under this config ever reaches a segment
    boundary before its pause — the only points a preemption drain
    can fire. Without one (no checkpoint_every, no dispatch_segment,
    no heartbeat) the whole run is ONE dispatch segment: installing
    the guard would swallow SIGTERM/SIGINT while promising a drain
    that can never happen, strictly worse than the default signal
    disposition — so the runners leave the signals alone and log
    why."""
    xp = cfg.experimental
    return bool(xp.checkpoint_every or xp.dispatch_segment
                or cfg.general.heartbeat_interval)


def make_guard(cfg):
    """The runners' guard factory: a PreemptionGuard when a drain can
    actually fire, else None (with a hint, once per run)."""
    if not cfg.experimental.checkpoint_save:
        return None
    if not drain_possible(cfg):
        log.info(
            "preemption drain inactive: the run has no segment "
            "boundaries (set experimental.checkpoint_every or "
            "dispatch_segment, or general.heartbeat_interval, to "
            "make SIGTERM drain to a resume checkpoint)")
        return None
    return PreemptionGuard()


def rotation_entries(base: str) -> list[tuple[int, str]]:
    """Existing rotation files for a checkpoint base path, sorted by
    sim time ascending: ``<base>.t<15-digit-ns>``. Non-numeric
    suffixes (in-flight ``.tmp`` files) are ignored."""
    out = []
    for p in glob.glob(glob.escape(base) + ".t*"):
        suffix = p[len(base) + 2:]
        if suffix.isdigit():
            out.append((int(suffix), p))
    return sorted(out)


def resolve_checkpoint(path: str) -> str:
    """``checkpoint_load`` resolution: a concrete file wins; otherwise
    the newest READABLE rotation entry of the base path (a truncated
    npz — the file a kill outran — is skipped with a warning, so the
    resume lands on the last validated checkpoint, exactly the
    rotation's purpose)."""
    if os.path.exists(path):
        return path
    entries = rotation_entries(path)
    if not entries:
        raise ValueError(
            f"checkpoint_load: {path!r} does not exist and has no "
            f"rotation entries ({path}.t*) — nothing to resume")
    from shadow_tpu.device import checkpoint

    for t, p in reversed(entries):
        try:
            meta = checkpoint.peek_meta(p)
            if meta.get("format") != checkpoint.FORMAT:
                raise ValueError(f"format {meta.get('format')}")
        except Exception as e:      # noqa: BLE001 — any unreadable entry
            log.warning("skipping unreadable checkpoint %s (%s); "
                        "falling back to the previous rotation entry",
                        p, e)
            continue
        log.info("checkpoint_load: %s resolved to rotation entry %s "
                 "(t=%d ns)", path, p, t)
        return p
    raise ValueError(
        f"checkpoint_load: every rotation entry of {path!r} is "
        "unreadable — nothing to resume")


class Checkpointer:
    """Rotating last-K checkpoint writer for one supervised run.
    Every write goes through the atomic tmp+rename path in
    checkpoint.save_state; pruning happens only after a successful
    replace, so there is always at least one complete checkpoint on
    disk once the first boundary passes."""

    def __init__(self, base: str, every: int, keep: int,
                 final_stop: int, extra_meta: dict = None,
                 audit_enabled: bool = False):
        self.base = base
        self.every = int(every)
        self.keep = max(1, int(keep))
        self.final_stop = int(final_stop)
        self.extra_meta = extra_meta
        self.audit_enabled = bool(audit_enabled)
        self.last_path = ""
        self.last_t = -1

    def next_after(self, t: int) -> int:
        return (t // self.every + 1) * self.every

    def save(self, engine, state, t: int) -> str:
        from shadow_tpu.device import checkpoint

        path = f"{self.base}.t{t:015d}"
        checkpoint.save_state(
            engine, state, path, t, final_stop=self.final_stop,
            extra_meta=self.extra_meta,
            audit_meta={"enabled": self.audit_enabled,
                        "violations": 0})
        self.last_path, self.last_t = path, t
        from shadow_tpu.device import chaos as chaosmod
        inj = chaosmod.current()
        if inj is not None:
            # chaos seam: a scripted checkpoint_corrupt truncates the
            # entry just landed (the decoy a SIGKILL leaves) — the
            # run continues; resume must hit the newest-readable
            # rotation fallback
            inj.on_checkpoint_saved(path)
        self._prune()
        log.info("rotating checkpoint at t=%d ns -> %s "
                 "(keep %d; resume with checkpoint_load: %s)",
                 t, path, self.keep, self.base)
        return path

    def _prune(self) -> None:
        entries = rotation_entries(self.base)
        for _, p in entries[:-self.keep]:
            try:
                os.unlink(p)
            except OSError as e:
                log.warning("could not prune old checkpoint %s: %s",
                            p, e)


@dataclass
class _InFlight:
    """One issued-but-undrained dispatch segment: its sim window and
    the asynchronous device arrays the dispatch returned. The state
    pytree pins device buffers until the drain validates (or the
    recovery discards) it — at depth N up to N segment states are
    alive at once, which is the pipeline's memory cost."""

    t0: int
    t1: int
    state: object
    rounds: object


class PipelineWindow:
    """Bounded ring of in-flight dispatch segments (FIFO: the drain
    consumes strictly in issue order — validation, checkpoints, and
    heartbeats are order-dependent side effects). Mutations are
    lock-protected and the ring is registered in the concurrency
    lint's LOCK_REGISTRY (shadow_tpu/analyze/concurrency.py). Today
    the advance loop is single-threaded and the lock is never
    contended — it exists because the ring is exactly the structure
    a future async drain worker would share, and taking the lock
    from day one means that refactor inherits a mechanically-linted
    discipline instead of having to retrofit one."""

    def __init__(self, depth: int):
        self.depth = max(1, int(depth))
        self._lock = threading.Lock()
        self._ring: deque = deque()

    def __len__(self) -> int:
        return len(self._ring)

    @property
    def full(self) -> bool:
        return len(self._ring) >= self.depth

    def push(self, fl: _InFlight) -> None:
        with self._lock:
            self._ring.append(fl)

    def pop(self) -> _InFlight:
        with self._lock:
            return self._ring.popleft()

    def discard(self) -> int:
        """Drop every speculative in-flight segment (recovery path);
        returns how many were discarded so the caller can log it."""
        with self._lock:
            n = len(self._ring)
            self._ring.clear()
        return n


@dataclass
class AdvanceResult:
    """What supervise.advance hands back to the runner, beyond the
    final state: the (per-replica) round counts and every way the
    advance can end short of `pause`."""

    rounds: np.ndarray = field(
        default_factory=lambda: np.int64(0))
    t_end: int = 0
    budget_hit: bool = False
    overflowed: bool = False
    preempted: bool = False
    resume_path: str = ""
    retries: int = 0
    # mesh shrinks absorbed (failover: shrink): each one cost a
    # drain + re-shard + engine rebuild and dropped the mesh to the
    # surviving devices
    reshards: int = 0
    # OOM degradation-ladder rungs engaged (deterministic
    # RESOURCE_EXHAUSTED): each one shrank the footprint (pipeline
    # depth, replica batching, or dispatch segment) and replayed
    # bit-identically from the last validated state
    degrades: int = 0
    # pipeline telemetry (always populated): depth, issued/drained
    # segment counts, discarded speculative segments, the wall spent
    # blocked in dispatch.sync, and the host wall that ran with >= 1
    # segment in flight (the overlap the depth bought)
    pipeline: dict = field(default_factory=dict)


def advance(runner, state, t_start: int, pause: int, stop: int,
            ensemble: bool = False):
    """The shared segmented-advance loop (DeviceRunner and
    EnsembleRunner both delegate here): advance [t_start, pause) in
    segments cut at heartbeat / dispatch-segment / checkpoint
    boundaries, validating the state at every boundary and recovering
    from each failure class:

    * capacity overflow  -> widen + re-plan, re-run from the last
      known-good state (PR 1's loop, non-static plans only);
    * transient dispatch error -> capped-backoff retry from the last
      validated state; exhausted -> DeviceFailover (failover: hybrid)
      or re-raise;
    * audit violation    -> AuditFailure (fatal: never checkpoint or
      run forward a corrupted state);
    * preemption request -> complete the in-flight window, save a
      resume checkpoint at the last drained boundary, and return
      preempted.

    The loop is split into ISSUE and DRAIN halves around a bounded
    :class:`PipelineWindow` (``experimental.pipeline_depth``; 0/1 =
    serial issue-then-drain, N >= 2 = up to N segments in flight).
    The issue half enqueues segment k+1 immediately after segment
    k's dispatch returns its asynchronous device arrays; the drain
    half performs the blocking syncs and every order-dependent side
    effect (validation, known-good snapshot, checkpoint rotation,
    heartbeats) strictly in issue order. Segment boundaries are a
    pure function of sim time, so the issue half computes the exact
    boundary sequence the serial loop would — traces are
    bit-identical at every depth, and a recovery discards the
    speculative window and replays from the last validated state.

    Every unit of work records a flight-recorder span (shadow_tpu/obs
    — ``dispatch.issue`` enqueues and ``dispatch.sync`` blocking
    waits with their sim windows and ICI counters, heartbeats,
    checkpoint saves, retry backoffs, re-plans, the preemption
    drain), tagged so trace_report can attribute the run's wall and
    tell device-bound from sync-bound time. Tracing only reads
    values this loop already fetched, so traces stay bit-identical
    across telemetry modes.

    Returns (state, AdvanceResult).
    """
    from shadow_tpu._jax import jax
    from shadow_tpu.device import capacity, checkpoint

    tracer = getattr(runner, "tracer", None) or obstrace.current()
    xp = runner.sim.cfg.experimental
    hb = runner.sim.cfg.general.heartbeat_interval
    seg = xp.dispatch_segment
    ck: Checkpointer = getattr(runner, "checkpointer", None)
    guard: PreemptionGuard = getattr(runner, "guard", None)
    audit_on = bool(xp.state_audit)
    retry_ok = xp.capacity_plan != "static"
    supervised = bool(ck is not None
                      or (guard is not None and guard.active)
                      or xp.dispatch_retries
                      or xp.failover != "abort")
    # last known-good snapshot: device refs are immutable, so holding
    # the pytree costs nothing to take — but it pins the previous
    # segment's buffers, so plain static runs (which can never retry)
    # still skip it; every supervised failure class needs it
    keep_good = retry_ok or supervised
    budget = runner.engine.config.max_rounds
    label = "ensemble " if ensemble else ""

    def run_segment(st, nxt):
        if ensemble:
            return runner.engine.run_ensemble(st, stop=nxt,
                                              final_stop=stop)
        return runner.engine.run(st, stop=nxt, final_stop=stop)

    def replace_state(host_state):
        # place a host-side snapshot back onto the (possibly rebuilt)
        # engine with fresh device buffers
        if ensemble:
            return capacity.transfer(
                runner.engine, runner.sim.starts, host_state,
                template=runner.engine.init_ensemble_state(
                    runner.sim.starts))
        return capacity.transfer(runner.engine, runner.sim.starts,
                                 host_state)

    def drain_save(st, t):
        """The preemption resume checkpoint: reuse the rotation entry
        just written at this boundary, else write one."""
        if ck is not None:
            if ck.last_t == t:
                return ck.last_path
            return ck.save(runner.engine, st, t)
        path = xp.checkpoint_save
        checkpoint.save_state(
            runner.engine, st, path, t, final_stop=stop,
            extra_meta=getattr(runner, "_ck_extra_meta", None),
            audit_meta={"enabled": audit_on, "violations": 0})
        return path

    depth = max(1, int(getattr(xp, "pipeline_depth", 0) or 0))
    adm = getattr(runner, "admission", None)
    if isinstance(adm, dict):
        # the preflight admission gate (capacity.admission_verdict)
        # may have statically degraded the pipeline depth to fit the
        # per-device budget — honor it before the first issue
        ov_depth = (adm.get("overrides") or {}).get("pipeline_depth")
        if ov_depth:
            depth = max(1, min(depth, int(ov_depth)))
    chaos_inj = getattr(runner, "chaos", None)
    res = AdvanceResult()
    window = PipelineWindow(depth)
    good_state, good_t = (state if keep_good else None), t_start
    failures = 0
    oom_streak = 0              # consecutive memory-exhaustion errors
    # at the current validated boundary; 2 = deterministic, walk the
    # degradation ladder instead of the retry budget
    seg_halvings = 0
    t = t_start                 # drained/validated sim time
    t_issue = t_start           # where the next issued segment starts
    cur_state = state           # issue-side head (newest issued state)
    pending_error = None        # an issue-time dispatch error, held
    # until the segments issued before it drain — exactly when the
    # serial loop would have observed it
    next_hb = (t // hb + 1) * hb if hb else None
    next_ck = ck.next_after(t) if ck is not None else None
    pstats = {"depth": depth, "issued": 0, "drained": 0,
              "discarded": 0, "max_in_flight": 0,
              "sync_wall_s": 0.0, "overlapped_host_s": 0.0}
    res.pipeline = pstats
    adv_wall0 = time.perf_counter()
    last_sync_end = None

    def next_boundary(ti):
        """The segment boundary the serial loop would cut at sim
        time `ti` — a pure function of the heartbeat cadence, the
        dispatch segment, and the checkpoint cadence, so the issue
        half can compute the boundary sequence ahead of the drain
        and the two halves can never disagree on where segments
        end (the drain's stateful next_hb/next_ck bookkeeping below
        walks the same sequence)."""
        nxt = pause
        if hb:
            nxt = min(nxt, (ti // hb + 1) * hb)
        if seg:
            nxt = min(nxt, ti + seg)
        if ck is not None:
            nxt = min(nxt, ck.next_after(ti))
        return nxt

    def rewind_to_good(new_state, new_t=None):
        """Recovery epilogue shared by every replay path: install
        the re-placed state as both the validated snapshot and the
        issue head, and rewind both clocks to the last validated
        boundary. The replay then proceeds through the normal
        issue/drain loop — deterministic segments recompute
        bit-identically, so a replayed prefix never changes the
        trace. ``new_t`` overrides the boundary the state pins (the
        shrink failover may fall back to an on-disk checkpoint older
        than the in-memory snapshot)."""
        nonlocal cur_state, t, t_issue, next_hb, next_ck
        nonlocal good_state, good_t, pending_error, last_sync_end
        if new_t is not None:
            good_t = int(new_t)
        cur_state = new_state
        good_state = new_state
        t = t_issue = good_t
        next_hb = (t // hb + 1) * hb if hb else None
        next_ck = ck.next_after(t) if ck is not None else None
        pending_error = None
        # the wall since the last sync was recovery work (backoff,
        # state re-placement, engine rebuild) spent with a DISCARDED
        # window — the device was idle, so it must not be credited
        # as overlapped host time
        last_sync_end = None
        return new_state

    def recover_transient(e):
        """Transient dispatch error (issue- or drain-side): discard
        the speculative in-flight window, count a CONSECUTIVE
        failure, back off, and replay from the last validated
        state. `failures` resets on every drained-and-validated
        segment: unrelated transient incidents hours apart must not
        pool into one exhausted budget — a genuinely dead device
        still exhausts it, because no segment ever drains clean."""
        nonlocal failures, oom_streak
        if not is_transient(e) or good_state is None:
            raise e
        # a deterministic OOM — the SAME memory-exhaustion error
        # twice in a row at the same validated boundary — cannot be
        # retried away: it routes to the degradation ladder WITHOUT
        # charging the retry budget (pre-ladder it burned every
        # retry replaying a segment that could never fit, then
        # escalated off-device). A single OOM still retries
        # normally: allocators do lose races and win the rerun.
        oom_streak = oom_streak + 1 if is_oom(e) else 0
        if oom_streak >= 2:
            return recover_oom(e)
        discarded = window.discard()
        pstats["discarded"] += discarded
        failures += 1
        res.retries += 1
        # live cumulative count: the supervise heartbeat line
        # reports it mid-run, not just the end-of-run SimStats
        runner.retries = res.retries
        if failures > xp.dispatch_retries:
            if is_oom(e):
                # the retry budget ran out on a memory error:
                # shrink the FOOTPRINT (the ladder), not the mesh —
                # a smaller mesh has less memory, not more
                return recover_oom(e)
            if xp.failover == "shrink":
                shrunk = _shrink_recover(runner, e, good_state,
                                         good_t, ensemble, ck,
                                         tracer)
                if shrunk is not None:
                    new_state, t_shrunk = shrunk
                    failures = 0        # the new mesh earns a fresh
                    # budget: a second device death on the shrunken
                    # mesh walks the same retry -> shrink ladder
                    res.reshards += 1
                    runner.reshards = res.reshards
                    return rewind_to_good(new_state, t_shrunk)
            _escalate(runner, e, good_state, good_t, stop,
                      ensemble, ck)
        delay = min(
            xp.dispatch_retry_backoff * (2 ** (failures - 1)),
            BACKOFF_CAP_S)
        log.warning(
            "transient %sdevice dispatch error past t=%d ns (%s); "
            "discarding %d speculative in-flight segment(s), retry "
            "%d/%d from the last validated state t=%d ns after "
            "%.1fs backoff", label, good_t, e, discarded, failures,
            xp.dispatch_retries, good_t, delay)
        if delay:
            with tracer.span("retry.backoff", "retry",
                             sim_t0=good_t, attempt=failures,
                             error=str(e)[:200]):
                time.sleep(delay)
        with tracer.span("retry.recover", "retry", sim_t0=good_t,
                         attempt=failures):
            new_state = _recover_state(runner, good_state,
                                       replace_state, ck, stop,
                                       ensemble)
        return rewind_to_good(new_state)

    def recover_oom(e):
        """The graceful-degradation ladder (the runtime backstop of
        the preflight admission gate): a deterministic OOM is a
        capacity fact, so each invocation walks ONE rung that
        actually shrinks the footprint, replays bit-identically from
        the last validated state, and leaves the retry budget
        untouched. Rungs, in order:

        1. halve the pipeline window depth — each unit of depth pins
           a full extra segment state on-device;
        2. ensembles only: raise :class:`DegradeToReplicaBatch` —
           the campaign re-runs the sweep in sequential replica
           batches (bit-identical to the full vmap);
        3. halve the dispatch segment — shorter segments bound the
           transient exchange/working-set peak (segmentation never
           changes traces: the engine clamps on the global stop);
        4. out of rungs: the existing ``failover:`` escalation.

        Every rung logs a ``degrade`` span, notifies the chaos
        injector (so a scripted repeating OOM stops firing exactly
        when a real one would — the footprint shrank), and logs the
        re-admission estimate against the budget."""
        nonlocal seg, seg_halvings, oom_streak
        pstats["discarded"] += window.discard()
        # a recurrence AFTER a rung is a fresh deterministic-OOM
        # incident at streak 2 immediately — walking the next rung
        # must not charge the retry budget either
        oom_streak = 1
        res.degrades += 1
        runner.degrades = res.degrades
        rung, span_kw = "", {}
        if window.depth > 1:
            new_depth = max(1, window.depth // 2)
            rung = f"pipeline_depth {window.depth}->{new_depth}"
            span_kw = {"depth": new_depth}
            window.depth = new_depth
            pstats["depth"] = new_depth
        elif ensemble and \
                int(getattr(runner, "_replica_batchable", 0) or 0):
            batch = int(runner._replica_batchable)
            rung = f"replica_batch {batch}"
            tracer.instant("degrade.replica_batch", "degrade",
                           sim_t0=good_t, batch=batch,
                           error=str(e)[:200])
            if chaos_inj is not None and \
                    hasattr(chaos_inj, "on_degrade_rung"):
                chaos_inj.on_degrade_rung(rung)
            log.warning(
                "OOM ladder: deterministic memory exhaustion past "
                "t=%d ns (%s) — the full-replica vmap does not fit; "
                "re-running the sweep in sequential batches of %d "
                "replica(s) (bit-identical to the full vmap)",
                good_t, e, batch)
            raise DegradeToReplicaBatch(
                f"ensemble footprint exhausted device memory ({e}); "
                f"degrade to replica batches of {batch}",
                batch=batch) from e
        else:
            cur_seg = seg if seg else max(1, int(pause) - int(good_t))
            if cur_seg > 1 and seg_halvings < MAX_SEG_HALVINGS:
                seg = max(1, cur_seg // 2)
                seg_halvings += 1
                rung = f"dispatch_segment {cur_seg}->{seg}"
                span_kw = {"segment": seg}
        if not rung:
            log.error(
                "OOM ladder exhausted: deterministic memory "
                "exhaustion past t=%d ns (%s) with no rung left "
                "(depth=1, segment floor reached); escalating via "
                "failover: %s", good_t, e, xp.failover)
            _escalate(runner, e, good_state, good_t, stop, ensemble,
                      ck)
        tracer.instant("degrade." + rung.split()[0], "degrade",
                       sim_t0=good_t, error=str(e)[:200], **span_kw)
        if chaos_inj is not None and \
                hasattr(chaos_inj, "on_degrade_rung"):
            chaos_inj.on_degrade_rung(rung)
        try:
            est = capacity.footprint(runner.engine,
                                     pipeline_depth=window.depth)
            b, src = capacity.device_budget(runner.engine, xp)
            log.warning(
                "OOM ladder rung %d (%s): deterministic memory "
                "exhaustion past t=%d ns (%s); re-admission "
                "estimate ~%s per device%s — replaying from the "
                "last validated state (bit-identical: segmentation "
                "and pipelining are pure host orchestration)",
                res.degrades, rung, good_t, e,
                capacity.fmt_bytes(est["per_device"]),
                (f" vs budget {capacity.fmt_bytes(b)} ({src})"
                 if b else ""))
        except Exception:       # noqa: BLE001 — telemetry only
            log.warning("OOM ladder rung %d (%s): replaying from "
                        "the last validated state", res.degrades,
                        rung)
        with tracer.span("degrade.recover", "degrade", sim_t0=good_t,
                         rung=rung):
            new_state = _recover_state(runner, good_state,
                                       replace_state, ck, stop,
                                       ensemble)
        return rewind_to_good(new_state)

    while t < pause:
        # ---- ISSUE half: keep up to `depth` segments in flight.
        # Dispatch is asynchronous — run_segment returns device
        # futures in milliseconds — so each push hands the device
        # its next segment before the previous one synchronized. A
        # pending error or a preemption request stops new issues;
        # the drain below settles what is already in flight.
        while pending_error is None and not window.full \
                and t_issue < pause \
                and not (guard is not None and guard.requested):
            nxt = next_boundary(t_issue)
            try:
                with tracer.span("dispatch.issue", "dispatch.issue",
                                 sim_t0=t_issue, sim_t1=nxt,
                                 in_flight=len(window)):
                    if chaos_inj is not None:
                        # the deterministic chaos seam: counts this
                        # issue and raises the scripted error when a
                        # fault (or a previously killed device on
                        # this mesh) is scheduled here — routed
                        # through pending_error like any real
                        # asynchronous dispatch failure
                        chaos_inj.on_dispatch_issue(runner.engine)
                    cur_state, seg_rounds = run_segment(cur_state,
                                                        nxt)
            except AuditFailure:
                raise
            except Exception as e:  # noqa: BLE001 — classified at drain
                # segments issued before this failure may be fine —
                # hold the error until they drain
                pending_error = e
                break
            window.push(_InFlight(t_issue, nxt, cur_state,
                                  seg_rounds))
            pstats["issued"] += 1
            pstats["max_in_flight"] = max(pstats["max_in_flight"],
                                          len(window))
            t_issue = nxt

        # ---- DRAIN half: sync + validate + boundary side effects
        # for the OLDEST in-flight segment, strictly in issue order.
        if len(window):
            fl = window.pop()
            sync0 = time.perf_counter()
            if last_sync_end is not None and len(window):
                # host wall since the last sync ended, spent with
                # further segments in flight: exactly the work the
                # device execution of those segments overlapped
                pstats["overlapped_host_s"] += sync0 - last_sync_end
            try:
                # the blocking half the old conflated "dispatch"
                # span hid: both device_gets synchronize segment
                # [t0, t1), so asynchronously raised dispatch errors
                # surface inside this try. A raised error closes the
                # span with an error tag, so retries show on the
                # timeline as failed-sync + backoff + recover spans.
                with tracer.span("dispatch.sync", "dispatch.sync",
                                 sim_t0=fl.t0, sim_t1=fl.t1,
                                 in_flight=len(window)) as sp:
                    dims = capacity.overflow_dims(fl.state)
                    seg_rounds = np.asarray(
                        jax.device_get(fl.rounds))
                    sp.add(rounds=int(np.max(seg_rounds)))
                    eff = runner.engine.effective
                    if eff.get("n_shards", 1) > 1:
                        # exchange-flush attribution: the flush is
                        # fused into the compiled round on-device,
                        # so its wall is inside the issued segment;
                        # the static per-flush ICI volume (buffers
                        # ship at capacity) rides as counters
                        # (engine.profile() measures the split
                        # walls when real exchange timing is needed)
                        sp.add(exchange=eff["exchange"],
                               shards=eff["n_shards"],
                               ici_rows_per_flush=eff[
                                   "ICI_rows_per_flush"],
                               ici_bytes_per_flush=eff[
                                   "ICI_bytes_per_flush"])
            except AuditFailure:
                raise
            except Exception as e:  # noqa: BLE001 — classified below
                last_sync_end = time.perf_counter()
                pstats["sync_wall_s"] += last_sync_end - sync0
                state = recover_transient(e)
                continue
            last_sync_end = time.perf_counter()
            pstats["sync_wall_s"] += last_sync_end - sync0
            pstats["drained"] += 1
            if dims:
                discarded = window.discard()
                pstats["discarded"] += discarded
                if not retry_ok or \
                        runner.replans >= capacity.MAX_REPLANS:
                    res.rounds = res.rounds + seg_rounds
                    state = fl.state
                    t = fl.t1
                    res.overflowed = True
                    tracer.instant("capacity.overflow", "plan",
                                   sim_t0=t, dims=list(dims))
                    break       # loud failure (stats.ok = False)
                runner.replans += 1
                runner._capacity_overrides = capacity.widen(
                    runner._capacity_overrides, dims,
                    runner.engine.effective)
                log.warning(
                    "%scapacity overflow on %s in (%d, %d] ns; "
                    "re-plan #%d with %s (%d speculative segment(s) "
                    "discarded), re-running from t=%d ns", label,
                    dims, fl.t0, fl.t1, runner.replans,
                    runner._capacity_overrides, discarded, good_t)
                with tracer.span("capacity.replan", "plan",
                                 sim_t0=good_t, sim_t1=fl.t1,
                                 dims=list(dims),
                                 replan=runner.replans):
                    runner.engine = runner._build_engine()
                    # the re-plan just named the next program: its
                    # AOT entry read overlaps the state transfer
                    prefetch_programs(runner, ensemble)
                    new_state = replace_state(
                        jax.device_get(good_state))
                state = rewind_to_good(new_state)
                continue
            state = fl.state
            res.rounds = res.rounds + seg_rounds
            t = fl.t1
            failures = 0        # the segment drained clean; see above
            oom_streak = 0      # ... and so did any OOM streak
            if int(np.max(res.rounds)) >= budget:
                # enforced cumulatively (per-invocation caps would
                # reset each segment); speculative segments past the
                # budget are discarded un-synced — the serial loop
                # would never have issued them
                pstats["discarded"] += window.discard()
                if t < pause:
                    log.warning("max_rounds (%d) exhausted during "
                                "%ssegmentation; stopping", budget,
                                label)
                res.budget_hit = True
                tracer.instant("budget.exhausted", "host", sim_t0=t,
                               budget=int(budget))
                break
            if audit_on:
                # the boundary state is validated BEFORE it becomes
                # the known-good snapshot or a checkpoint — a
                # corrupted state is never the one a retry or a
                # restart resumes from (in-flight successors of a
                # corrupted state die with the raise)
                check_audit(fl.state, where=f"t={t} ns",
                            last_good=(ck.last_path if ck is not None
                                       else ""))
            if next_hb is not None and t >= next_hb and t < stop:
                with tracer.span("heartbeat", "host", sim_t0=t):
                    runner._emit_heartbeats(t, fl.state)
                next_hb += hb
            if next_ck is not None and t >= next_ck and t < stop:
                with tracer.span("checkpoint.save", "checkpoint",
                                 sim_t0=t) as sp:
                    sp.add(path=ck.save(runner.engine, fl.state, t))
                next_ck = ck.next_after(t)
            if keep_good:
                good_state, good_t = fl.state, t
        elif pending_error is not None:
            e, pending_error = pending_error, None
            state = recover_transient(e)
        elif guard is not None and guard.requested and t < pause:
            # preemption drain: the issue half stopped on the
            # request and every in-flight segment has drained
            # through its normal boundary work above — save the
            # resume checkpoint at the last validated boundary. (A
            # signal during the FINAL segment needs no drain — the
            # t >= pause case falls out of the loop and the run
            # completes normally.)
            tracer.instant("preempt.request", "checkpoint", sim_t0=t,
                           signum=guard.signum)
            with tracer.span("checkpoint.drain_save", "checkpoint",
                             sim_t0=t) as sp:
                res.resume_path = drain_save(state, t)
                sp.add(path=res.resume_path)
            res.preempted = True
            log.warning(
                "%srun preempted at t=%d ns: resume checkpoint -> %s "
                "(re-run with experimental.checkpoint_load: %s to "
                "continue; the resumed run is bit-identical to an "
                "uninterrupted one)", label, t, res.resume_path,
                ck.base if ck is not None else res.resume_path)
            break
        else:
            # unreachable by construction (t < pause with nothing in
            # flight, nothing pending, and no preemption means the
            # issue half must have issued) — fail loudly rather than
            # spin silently if a refactor ever breaks the invariant
            raise RuntimeError(
                f"segment pipeline stalled at t={t} ns < pause="
                f"{pause} ns with an empty window")
    res.t_end = t
    adv_wall = time.perf_counter() - adv_wall0
    host_s = max(0.0, adv_wall - pstats["sync_wall_s"])
    pstats["advance_wall_s"] = round(adv_wall, 3)
    pstats["sync_wall_s"] = round(pstats["sync_wall_s"], 3)
    pstats["overlapped_host_s"] = round(pstats["overlapped_host_s"],
                                        3)
    # share of the advance loop's non-blocked host wall that ran
    # with >= 1 dispatch in flight — 0 at depth 1 by construction
    # (the window is empty whenever the host works), approaching 1
    # when the device always had queued segments during host-side
    # boundary work. This is the METRICS overlap-efficiency line.
    pstats["overlap_efficiency"] = (
        round(pstats["overlapped_host_s"] / host_s, 3)
        if host_s > 1e-9 else 0.0)
    return state, res


def _recover_state(runner, good_state, replace_state, ck, stop,
                   ensemble):
    """Re-place the last validated state onto fresh device buffers for
    a dispatch retry. If even fetching the held snapshot fails (the
    device that owned it is gone), fall back to the last rotating
    checkpoint on disk."""
    from shadow_tpu._jax import jax
    from shadow_tpu.device import checkpoint

    try:
        return replace_state(jax.device_get(good_state))
    except Exception as fetch_err:      # noqa: BLE001
        if ck is None or not ck.last_path:
            raise
        log.warning("could not recover the in-memory state (%s); "
                    "reloading the last validated checkpoint %s",
                    fetch_err, ck.last_path)
        # the snapshot's owner died, so the engine's compiled
        # executables (bound to the dead device's buffers) are
        # suspect too — rebuild the engine for the retry. The AOT
        # compile cache (device/aotcache.py, attached by
        # _build_engine) turns this recompile into a warm start:
        # same capacities -> same program key -> cached executable.
        runner.engine = runner._build_engine()
        # overlap the rebuilt program's AOT entry read with the
        # checkpoint reload below
        prefetch_programs(runner, ensemble)
        template = (runner.engine.init_ensemble_state(runner.sim.starts)
                    if ensemble else None)
        state, _ = checkpoint.load_state(
            runner.engine, runner.sim.starts, ck.last_path,
            final_stop=stop, template=template)
        return state


def _escalate(runner, exc, good_state, good_t, stop, ensemble, ck):
    """Retries exhausted and no shrink absorbed the loss: the
    failover ladder's last rung. ``abort`` re-raises; ``hybrid`` —
    and ``shrink``, whose hybrid rung this is when no shrink was
    possible — persists the last validated state and raises
    DeviceFailover for the Controller's hybrid rerun. Campaigns
    never reach the hybrid rung (CPU host emulation cannot vmap
    replicas): they re-raise with the last validated checkpoint on
    disk.

    When the persist fails AND no rotating checkpoint exists, the
    failover still runs: the raised DeviceFailover carries
    ``checkpoint_path=None`` and the persist error, and the
    Controller surfaces ONE loud diagnostic naming it — previously
    this path silently degraded to a bare re-raise with no state on
    disk and no failover at all."""
    from shadow_tpu._jax import jax
    from shadow_tpu.device import checkpoint

    xp = runner.sim.cfg.experimental
    if xp.failover == "abort" or ensemble:
        raise exc
    path, t_pin = "", good_t
    if ck is not None and ck.last_path:
        path, t_pin = ck.last_path, ck.last_t
    try:
        host_good = jax.device_get(good_state)
        fo_path = ((xp.checkpoint_save + ".failover")
                   if xp.checkpoint_save else
                   os.path.join(runner.sim.cfg.general.data_directory,
                                "device_failover.npz"))
        checkpoint.save_state(
            runner.engine, host_good, fo_path, good_t,
            final_stop=stop,
            audit_meta={"enabled": bool(xp.state_audit),
                        "violations": 0})
        path, t_pin = fo_path, good_t
    except Exception as save_err:       # noqa: BLE001
        if not path:
            # no state anywhere: the Controller's diagnostic is THE
            # loud surface (one message naming the persist error) —
            # no second error log here
            raise DeviceFailover(
                f"device dispatch failed permanently after "
                f"{xp.dispatch_retries} retries ({exc}); the last "
                f"validated state at t={good_t} ns could NOT be "
                f"persisted ({save_err})",
                checkpoint_path=None, sim_time=good_t,
                persist_error=str(save_err)) from exc
        log.warning("failover: could not persist the in-memory state "
                    "(%s); the last rotating checkpoint %s (t=%d ns) "
                    "pins the device-side resume", save_err, path,
                    t_pin)
    raise DeviceFailover(
        f"device dispatch failed permanently after "
        f"{xp.dispatch_retries} retries ({exc}); last validated "
        f"state at t={t_pin} ns saved to {path or '<none>'}",
        checkpoint_path=path, sim_time=t_pin) from exc
