"""The one device-side definition of the packet-drop rule.

Semantics of the reference's worker_sendPacket drop roll
(src/main/core/worker.c:542-548): a packet from `src` with per-source
sequence `pkt_seq` is dropped iff the path is lossy (reliability < 1),
the simulation is past the bootstrap phase (the reference never drops
while bootstrapping so initial connections always form), and the
counter-RNG roll lands at or above the reliability.

Both device consumers — the full device engine (device/engine.py) and
the hybrid batch judge (device/judge.py) — call this; the CPU twin is
NetworkModel.judge (core/netmodel.py). Keep all three in lockstep: the
trace-equality contract depends on it.
"""

from __future__ import annotations

from shadow_tpu.device import prng
from shadow_tpu.utils.rng import PURPOSE_PACKET_DROP


def packet_drop_mask(seed_pair, boot_end, now, src, pkt_seq,
                     reliability, src_key=None):
    """Elementwise drop decision; all args broadcastable arrays.
    `now` is the send time (i64), `reliability` the gathered per-path
    value (f32). Returns a bool mask, True = dropped.

    `src_key` (optional): a precomputed
    prng.purpose_id_key(seed_pair, PURPOSE_PACKET_DROP, src) — pass it
    when `src` is a small array broadcast against a much larger
    pkt_seq (the per-phase outbox judge) so the two id folds run once
    at src's shape instead of the full broadcast. Bit-identical
    either way."""
    if src_key is None:
        key = prng.chain_key(seed_pair, PURPOSE_PACKET_DROP, src,
                             pkt_seq)
    else:
        key = prng.fold_seq(src_key, pkt_seq)
    u = prng.uniform01(key)
    lossy = reliability < 1.0
    not_boot = now >= boot_end
    return lossy & not_boot & (u >= reliability)
