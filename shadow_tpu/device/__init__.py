"""The device (TPU) simulation engine.

The reference advances the simulation with N worker pthreads popping
per-host priority queues under locks (SURVEY §3.2). Here the entire
round loop runs on device instead: per-host event heaps are fixed-
capacity arrays, one `round_step` pops/executes/pushes events for every
host in lockstep (vectorized over the host dimension), topology
latency/reliability lookups are gathers into dense matrices, packet
drops are counter-RNG rolls, and cross-host delivery is a per-round
collective exchange over the device mesh (`all_gather`/`all_to_all`
over ICI/DCN). The conservative window barrier of the reference's
scheduler becomes the natural per-round synchronization of the SPMD
program, and the min-next-event reduction is a `pmin`.
"""

from shadow_tpu.device.engine import DeviceEngine
from shadow_tpu.device.runner import DeviceRunner

__all__ = ["DeviceEngine", "DeviceRunner"]
