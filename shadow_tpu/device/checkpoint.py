"""Device-state checkpoint / resume.

The reference runs every simulation start-to-finish; it has no
checkpoint facility at all (SURVEY §5: "Checkpoint / resume: none" —
the closest thing is per-host data dirs, which persist files but not
simulator state). On this engine the whole network model — event
heaps, app state, NIC/CoDel state, counters — is one explicit pytree
of device arrays, so a checkpoint is a `device_get` + `np.savez`, and
resume re-places the saved leaves with the live shardings of a
freshly built template state (works on any mesh of the same padded
width, including resuming a run on a different backend/platform).

Bit-identity contract: a paused-then-resumed run matches the
uninterrupted run exactly, because `DeviceEngine.run` clamps event
windows to the *global* stop (`final_stop`), not the pause point —
the same mechanism heartbeat/dispatch segmentation already relies on
(engine.py `run` docstring). The runner passes `final_stop =
stop_time` on both sides of a checkpoint.

Format: one .npz with a JSON `__meta__` entry (format version, pause
sim-time, the run's global stop (`final_stop`), engine fingerprint,
key-path list) and one array entry per pytree leaf. The fingerprint
pins everything that determines state layout and trace determinism:
host count, padded width, capacities, seed, the app class and its
scalar parameters, and a hash of the topology arrays (attachment,
latency, reliability). `final_stop` is checked separately from the
fingerprint: the saved prefix's windows were clamped on it, so
resuming toward a different stop would not bit-match an
uninterrupted run at that stop — the load rejects the mismatch.
The capacity planner's re-plan-and-resume path relies on this stamp
to re-run a segment against the same global stop.
"""

from __future__ import annotations

import json
import os

import numpy as np

FORMAT = 1


def _capacity_knobs():
    # deferred: checkpoint.py stays importable without pulling the
    # capacity module at import time
    from shadow_tpu.device.capacity import CAPACITY_KNOBS
    return CAPACITY_KNOBS


def probe_writable(path: str) -> None:
    """Fail on an unwritable checkpoint_save path NOW, in
    milliseconds — before a capacity warm-up spends minutes compiling,
    and not after a multi-hour run when the state would be lost. The
    probe must not leave a zero-byte decoy behind if the run later
    dies before saving. Shared by DeviceRunner and EnsembleRunner."""
    existed = os.path.lexists(path)
    try:
        with open(path, "ab"):
            pass
    except OSError as e:
        raise ValueError(
            f"checkpoint_save path {path!r} is not writable: "
            f"{e}") from e
    if not existed:
        os.unlink(path)


def prevalidate_resume(path: str, stop: int, save_path: str = "",
                       save_time: int = 0) -> int:
    """Pre-validate resume parameters from the npz meta alone (no
    array payloads), for the same fail-fast reason as probe_writable.
    Returns the saved pause time. Shared by both runners."""
    t_peek = int(peek_meta(path)["sim_time"])
    if t_peek >= stop:
        raise ValueError(
            f"checkpoint_load: saved state pauses at {t_peek} ns, "
            f"at/after stop_time {stop} ns — nothing to resume")
    if save_path and save_time and min(stop, save_time) <= t_peek:
        raise ValueError(
            f"checkpoint_save_time {min(stop, save_time)} ns is not "
            f"after the run's start time {t_peek} ns")
    return t_peek


def _fingerprint(engine) -> dict:
    import hashlib

    cfg = engine.config
    # topology + app parameters both steer the remaining replay, so a
    # checkpoint loaded against an edited graph or app config must be
    # rejected, not silently resumed into a divergent trace. Topology
    # hashes the attachment/latency/reliability arrays; the app hashes
    # its scalar instance attributes (msgload, sizes, counts, ... —
    # device apps keep per-host state in the engine state dict, so
    # scalars are the configuration surface).
    # with a fault schedule, epoch_times joins the world hash: the
    # stacked latency/reliability matrices already cover the
    # schedule's *values*, but two schedules can share matrices with
    # different boundary times — resuming across an edited schedule
    # must fail. Fault-free engines hash exactly the pre-fault-layer
    # surface, so existing fault-free checkpoints keep loading.
    faulted = len(engine.epoch_times) > 1
    h = hashlib.sha256()
    # hierarchical world tables are tuples of factored leaves —
    # hash each leaf in order (dense engines hash the exact
    # pre-hierarchy byte sequence)
    arrs: list = [engine.host_vertex]
    for t in (engine.latency, engine.reliability):
        arrs.extend(t if isinstance(t, tuple) else (t,))
    arrs += [engine.bw_up, engine.bw_down]
    if faulted:
        arrs.append(engine.epoch_times)
    for arr in arrs:
        a = np.ascontiguousarray(np.asarray(arr))
        h.update(str(a.shape).encode())
        h.update(a.tobytes())
    # scalar surface shared with the occupancy-record fingerprint
    # (capacity.app_scalars): burst_pops stays out there too —
    # retuning width across a save/resume pair is exactly its use
    # case (pinned by test_resume_at_different_burst_width)
    from shadow_tpu.device.capacity import app_scalars
    h.update(json.dumps(app_scalars(engine.app),
                        sort_keys=True).encode())
    # NB: the shard geometry (n_shards, H_pad, H_loc) deliberately
    # stays OUT of the fingerprint: it lives in the readable
    # meta["geometry"] keys instead, so a mismatch names the shard
    # counts ("saved on 4 shards, loading on 3") rather than hiding
    # inside an opaque fingerprint diff — and so the mesh-shrink
    # failover's resume path can validate/adopt it directly.
    fp = {
        "n_hosts": int(cfg.n_hosts),
        "event_capacity": int(cfg.event_capacity),
        "outbox_capacity": int(cfg.outbox_capacity),
        "seed": int(cfg.seed),
        "model_bandwidth": bool(cfg.model_bandwidth),
        "app": type(engine.app).__name__,
        "world": h.hexdigest(),
    }
    if faulted:
        # readable fault-schedule stamp alongside the world hash: a
        # mismatch names the schedule, not just "world changed".
        # Only present under a schedule — fault-free fingerprints
        # stay key-compatible with pre-fault-layer checkpoints.
        fp["fault_epochs"] = int(len(engine.epoch_times))
    return fp


def _flatten(state):
    from jax.tree_util import tree_flatten_with_path, keystr
    leaves, treedef = tree_flatten_with_path(state)
    return [(keystr(kp), leaf) for kp, leaf in leaves], treedef


def save_state(engine, state, path: str, sim_time: int,
               final_stop: int = 0, extra_meta: dict = None,
               audit_meta: dict = None) -> None:
    """Write `state` (a live, possibly sharded device pytree) plus
    the pause `sim_time`, the run's global stop (`final_stop` — the
    window-clamping bound the saved prefix was computed against), and
    the engine fingerprint to `path`. `extra_meta` (the ensemble
    runner's campaign fingerprint stamp) lands under meta["ensemble"]
    — its presence marks a campaign checkpoint, which standalone runs
    refuse to resume."""
    from shadow_tpu._jax import jax

    host_state = jax.device_get(state)
    named, _ = _flatten(host_state)
    meta = {
        "format": FORMAT,
        "sim_time": int(sim_time),
        "final_stop": int(final_stop),
        "fingerprint": _fingerprint(engine),
        # the shard geometry the state is laid out for, as READABLE
        # keys (not folded into the fingerprint): H_pad depends on
        # n_shards, so a checkpoint written after a mesh-shrink
        # failover stamps the shrunken geometry here and the runners
        # adopt it on resume (rebuild the mesh to match) instead of
        # failing on an opaque fingerprint diff
        "geometry": {"n_shards": int(engine.n_shards),
                     "h_pad": int(engine.H_pad),
                     "h_loc": int(engine.H_loc)},
        # ALL capacity knobs of the saving engine, not just the
        # layout-determining two in the fingerprint: a resume under
        # capacity_plan adopts these, so a plan/widen that grew
        # exchange_in/exchange/outbox_compact is not silently
        # reverted to the config statics (which would just replay
        # the overflow + re-plan cycle past the resume point)
        "capacities": {
            k: int(getattr(engine.config, k))
            for k in _capacity_knobs()},
        # the exchange schedule the saving engine compiled: traces
        # are variant-invariant, but a resume under `exchange: auto`
        # adopts it so the adopted capacities stay meaningful
        "exchange": str(engine.config.exchange),
        "keys": [k for k, _ in named],
    }
    if extra_meta:
        meta["ensemble"] = dict(extra_meta)
    if audit_meta is not None:
        # the supervisor's validation stamp (device/supervise.py): the
        # on-device invariant audit word was checked clean before this
        # state was written, so a resume can trust it
        meta["audit"] = dict(audit_meta)
    arrays = {f"leaf_{i}": np.asarray(v)
              for i, (_, v) in enumerate(named)}
    # atomic tmp+rename: a SIGKILL (or a preemption that outruns the
    # drain) mid-save must never leave a truncated npz where a valid
    # checkpoint used to be — the previous rotation entry survives
    from shadow_tpu.utils.artifacts import atomic_write

    atomic_write(
        path,
        lambda f: np.savez_compressed(f, __meta__=json.dumps(meta),
                                      **arrays))


def peek_meta(path: str) -> dict:
    """Read ONLY the npz meta (no array payloads): the runner uses
    it to rebuild a capacity-planned engine with the SAVED capacities
    before loading, so a checkpoint written under capacity_plan: auto
    stays loadable even though the planner's sizes differ from the
    config's static knobs — and to pre-validate resume parameters in
    milliseconds, before the planner spends minutes compiling."""
    with np.load(path, allow_pickle=False) as z:
        return json.loads(str(z["__meta__"]))


def peek_fingerprint(path: str) -> dict:
    return peek_meta(path)["fingerprint"]


def peek_geometry(meta: dict) -> dict:
    """The shard-geometry stamp of a checkpoint's meta dict.
    Pre-geometry checkpoints carried only h_pad, inside the
    fingerprint — surface what exists so callers get one shape."""
    geom = meta.get("geometry")
    if geom is not None:
        return dict(geom)
    fp = meta.get("fingerprint") or {}
    return ({"h_pad": int(fp["h_pad"])} if "h_pad" in fp else {})


def validate_geometry(path: str, meta: dict, engine) -> None:
    """Reject a geometry mismatch with a READABLE message naming the
    shard counts and padded widths — the runners normally adopt the
    saved geometry before loading (DeviceRunner.
    _adopt_checkpoint_geometry), so reaching this error means the
    adoption was impossible or the caller loaded directly."""
    geom = peek_geometry(meta)
    if not geom:
        return
    saved_n = geom.get("n_shards")
    saved_pad = geom.get("h_pad")
    if (saved_n is not None and int(saved_n) != engine.n_shards) or \
            (saved_pad is not None and int(saved_pad) != engine.H_pad):
        raise ValueError(
            f"checkpoint {path}: saved on "
            f"{saved_n if saved_n is not None else '?'} shard(s) "
            f"(H_pad {saved_pad}), loading on {engine.n_shards} "
            f"(H_pad {engine.H_pad}) — resume on a mesh of the saved "
            "shard count (the tpu runner adopts it automatically "
            "from this stamp; experimental.mesh_shards pins it by "
            "hand), or re-run from scratch")


def load_host_state(path: str):
    """Raw host-side leaves + meta, with NO engine/template
    validation: the mesh-shrink failover re-shards the saved pytree
    onto a DIFFERENT geometry (capacity.reshard_state), so the usual
    shape/sharding checks cannot apply here. Keys come back plain
    (``"['ht']"`` -> ``"ht"``). Returns (state, meta)."""
    import re

    with np.load(path, allow_pickle=False) as z:
        meta = json.loads(str(z["__meta__"]))
        if meta.get("format") != FORMAT:
            raise ValueError(
                f"checkpoint {path}: format {meta.get('format')} "
                f"(this build reads format {FORMAT})")
        saved = {k: z[f"leaf_{i}"]
                 for i, k in enumerate(meta["keys"])}
    state = {}
    for k, v in saved.items():
        m = re.fullmatch(r"\['(\w+)'\]", k)
        if not m:
            raise ValueError(
                f"checkpoint {path}: unexpected state key {k!r}")
        state[m.group(1)] = v
    return state, meta


def load_state(engine, starts, path: str, final_stop: int = 0,
               template: dict = None):
    """Load a checkpoint into a fresh engine: builds a template state
    via `init_state(starts)` (for tree structure + shardings),
    validates the fingerprint, the run's global stop, and every
    leaf's shape/dtype, and device_puts each saved leaf with the
    template leaf's sharding. `template` overrides the standalone
    template (the ensemble runner passes init_ensemble_state's
    [R, ...] stack); a campaign checkpoint (meta["ensemble"] present)
    refuses to load without one.

    `final_stop` is this run's global stop; a checkpoint saved
    against a different one is rejected (the saved prefix's windows
    were clamped on the stop it was computed for, so the resumed
    trace would not bit-match an uninterrupted run). Pass 0 to skip
    the check (records saved before the stamp existed load as
    before).

    Returns (state, sim_time)."""
    from shadow_tpu._jax import jax

    with np.load(path, allow_pickle=False) as z:
        meta = json.loads(str(z["__meta__"]))
        if meta.get("format") != FORMAT:
            raise ValueError(
                f"checkpoint {path}: format {meta.get('format')} "
                f"(this build reads format {FORMAT})")
        saved = {k: z[f"leaf_{i}"]
                 for i, k in enumerate(meta["keys"])}

    saved_stop = int(meta.get("final_stop", 0))
    if final_stop and saved_stop and saved_stop != final_stop:
        raise ValueError(
            f"checkpoint {path} was saved for a run with stop_time "
            f"{saved_stop} ns; this run stops at {final_stop} ns — "
            "the saved prefix's event windows were clamped on the "
            "original stop, so resuming toward a different one would "
            "not bit-match an uninterrupted run (re-run from scratch "
            "or restore the original stop_time)")

    if meta.get("ensemble") and template is None:
        raise ValueError(
            f"checkpoint {path} was saved by an ensemble campaign "
            f"({meta['ensemble']}); a standalone run cannot resume "
            "it — load it under the same ensemble config")

    # shard geometry first, by its readable keys: "saved on 4 shards,
    # loading on 2" beats an opaque fingerprint diff, and the reshard
    # path validates exactly these
    validate_geometry(path, meta, engine)
    fp, want = dict(meta["fingerprint"]), _fingerprint(engine)
    # pre-geometry checkpoints carried the padded width inside the
    # fingerprint; validate_geometry covered it above
    fp.pop("h_pad", None)
    if fp != want:
        diffs = {k: (fp.get(k), want[k]) for k in want
                 if fp.get(k) != want[k]}
        raise ValueError(
            f"checkpoint {path} does not match this simulation "
            f"(saved vs configured): {diffs}")

    if template is None:
        template = engine.init_state(starts)
    named, treedef = _flatten(template)
    want_keys = [k for k, _ in named]
    saved_keys = meta["keys"]
    # auxiliary leaves may differ between the saving and resuming
    # engines without perturbing the trace: the occ_* telemetry
    # (postdates FORMAT 1 checkpoints — zeroed counters then cover
    # the resumed segment only) and the aud* invariant-audit leaves
    # (experimental.state_audit may be toggled across a save/resume
    # pair; the audit is reseeded below so it stays exact). Any other
    # key difference is a real layout change and fails loudly.
    def _aux(k: str) -> bool:
        return "'occ_" in k or "'aud" in k

    missing = [k for k in want_keys if k not in saved_keys]
    extra = [k for k in saved_keys if k not in want_keys]
    aux_only = all(_aux(k) for k in missing) and \
        all(_aux(k) for k in extra) and \
        [k for k in saved_keys if k not in extra] == \
        [k for k in want_keys if k not in missing]
    if want_keys != saved_keys and not aux_only:
        raise ValueError(
            f"checkpoint {path}: state layout changed "
            f"(saved keys != this engine's state keys)")
    leaves = []
    for key, tmpl in named:
        if key not in saved:
            if key == "['aud_tx']":
                # reseed the conservation ledger from the saved
                # counters so the global identity (rows produced ==
                # rows popped + rows live + rows counted lost) holds
                # at the resume point — the audit only ever balances
                # the SUM, so this per-host reseed is exact
                ht = saved["['ht']"]
                head = saved["['head']"]
                E = ht.shape[-1]
                live = ((np.arange(E) >= head[..., None]) &
                        (ht < (np.int64(1) << np.int64(62)))) \
                    .sum(-1)
                recon = (saved["['n_exec']"].astype(np.int64) + live
                         + saved["['overflow']"].astype(np.int64)
                         + saved["['x_overflow']"].astype(np.int64))
                leaves.append(jax.device_put(
                    recon.astype(np.int64), tmpl.sharding))
                continue
            leaves.append(tmpl)
            continue
        arr = saved[key]
        if arr.shape != tmpl.shape or arr.dtype != np.dtype(tmpl.dtype):
            raise ValueError(
                f"checkpoint {path}: leaf {key} is "
                f"{arr.shape}/{arr.dtype}, engine expects "
                f"{tmpl.shape}/{tmpl.dtype}")
        leaves.append(jax.device_put(arr, tmpl.sharding))
    from jax.tree_util import tree_unflatten
    return tree_unflatten(treedef, leaves), int(meta["sim_time"])
