"""Device-state checkpoint / resume.

The reference runs every simulation start-to-finish; it has no
checkpoint facility at all (SURVEY §5: "Checkpoint / resume: none" —
the closest thing is per-host data dirs, which persist files but not
simulator state). On this engine the whole network model — event
heaps, app state, NIC/CoDel state, counters — is one explicit pytree
of device arrays, so a checkpoint is a `device_get` + `np.savez`, and
resume re-places the saved leaves with the live shardings of a
freshly built template state (works on any mesh of the same padded
width, including resuming a run on a different backend/platform).

Bit-identity contract: a paused-then-resumed run matches the
uninterrupted run exactly, because `DeviceEngine.run` clamps event
windows to the *global* stop (`final_stop`), not the pause point —
the same mechanism heartbeat/dispatch segmentation already relies on
(engine.py `run` docstring). The runner passes `final_stop =
stop_time` on both sides of a checkpoint.

Format: one .npz with a JSON `__meta__` entry (format version, pause
sim-time, engine fingerprint, key-path list) and one array entry per
pytree leaf. The fingerprint pins everything that determines state
layout and trace determinism: host count, padded width, capacities,
seed, the app class and its scalar parameters, and a hash of the
topology arrays (attachment, latency, reliability).
"""

from __future__ import annotations

import json

import numpy as np

FORMAT = 1


def _fingerprint(engine) -> dict:
    import hashlib

    cfg = engine.config
    # topology + app parameters both steer the remaining replay, so a
    # checkpoint loaded against an edited graph or app config must be
    # rejected, not silently resumed into a divergent trace. Topology
    # hashes the attachment/latency/reliability arrays; the app hashes
    # its scalar instance attributes (msgload, sizes, counts, ... —
    # device apps keep per-host state in the engine state dict, so
    # scalars are the configuration surface).
    h = hashlib.sha256()
    for arr in (engine.host_vertex, engine.latency,
                engine.reliability, engine.bw_up, engine.bw_down):
        a = np.ascontiguousarray(np.asarray(arr))
        h.update(str(a.shape).encode())
        h.update(a.tobytes())
    app_params = {k: v for k, v in sorted(vars(engine.app).items())
                  if isinstance(v, (bool, int, float, str))}
    # burst_pops is a trace-invariant lane-width knob (pinned by
    # test_burst_width_identical_traces) that the runner writes onto
    # the app when experimental.burst_pops overrides it — retuning
    # width across a save/resume pair is exactly its use case, so it
    # must not poison the fingerprint
    app_params.pop("burst_pops", None)
    h.update(json.dumps(app_params, sort_keys=True).encode())
    return {
        "n_hosts": int(cfg.n_hosts),
        "h_pad": int(engine.H_pad),
        "event_capacity": int(cfg.event_capacity),
        "outbox_capacity": int(cfg.outbox_capacity),
        "seed": int(cfg.seed),
        "model_bandwidth": bool(cfg.model_bandwidth),
        "app": type(engine.app).__name__,
        "world": h.hexdigest(),
    }


def _flatten(state):
    from jax.tree_util import tree_flatten_with_path, keystr
    leaves, treedef = tree_flatten_with_path(state)
    return [(keystr(kp), leaf) for kp, leaf in leaves], treedef


def save_state(engine, state, path: str, sim_time: int) -> None:
    """Write `state` (a live, possibly sharded device pytree) plus
    the pause `sim_time` and the engine fingerprint to `path`."""
    from shadow_tpu._jax import jax

    host_state = jax.device_get(state)
    named, _ = _flatten(host_state)
    meta = {
        "format": FORMAT,
        "sim_time": int(sim_time),
        "fingerprint": _fingerprint(engine),
        "keys": [k for k, _ in named],
    }
    arrays = {f"leaf_{i}": np.asarray(v)
              for i, (_, v) in enumerate(named)}
    with open(path, "wb") as f:
        np.savez_compressed(f, __meta__=json.dumps(meta), **arrays)


def load_state(engine, starts, path: str):
    """Load a checkpoint into a fresh engine: builds a template state
    via `init_state(starts)` (for tree structure + shardings),
    validates the fingerprint and every leaf's shape/dtype, and
    device_puts each saved leaf with the template leaf's sharding.

    Returns (state, sim_time)."""
    from shadow_tpu._jax import jax

    with np.load(path, allow_pickle=False) as z:
        meta = json.loads(str(z["__meta__"]))
        if meta.get("format") != FORMAT:
            raise ValueError(
                f"checkpoint {path}: format {meta.get('format')} "
                f"(this build reads format {FORMAT})")
        saved = {k: z[f"leaf_{i}"]
                 for i, k in enumerate(meta["keys"])}

    fp, want = meta["fingerprint"], _fingerprint(engine)
    if fp != want:
        diffs = {k: (fp.get(k), want[k]) for k in want
                 if fp.get(k) != want[k]}
        raise ValueError(
            f"checkpoint {path} does not match this simulation "
            f"(saved vs configured): {diffs}")

    template = engine.init_state(starts)
    named, treedef = _flatten(template)
    if [k for k, _ in named] != meta["keys"]:
        raise ValueError(
            f"checkpoint {path}: state layout changed "
            f"(saved keys != this engine's state keys)")
    leaves = []
    for key, tmpl in named:
        arr = saved[key]
        if arr.shape != tmpl.shape or arr.dtype != np.dtype(tmpl.dtype):
            raise ValueError(
                f"checkpoint {path}: leaf {key} is "
                f"{arr.shape}/{arr.dtype}, engine expects "
                f"{tmpl.shape}/{tmpl.dtype}")
        leaves.append(jax.device_put(arr, tmpl.sharding))
    from jax.tree_util import tree_unflatten
    return tree_unflatten(treedef, leaves), int(meta["sim_time"])
