"""Device-side threefry counter RNG (batched jnp form).

Identical algorithm to utils/nprng.py (which is itself bit-identical to
jax.random's threefry path) — implemented directly on uint32 arrays so
the engine can draw batches of decisions keyed by (purpose, host, seq)
without jax.random key-array plumbing inside shard_map'd code.
tests/test_device_engine.py asserts bit-identity with the numpy form.
"""

from __future__ import annotations

from shadow_tpu._jax import jax, jnp

# optimization_barrier is identity per operand, but this jax version
# ships no vmap batching rule for it — the ensemble program (vmapped
# replicas, device/engine.py) hits the barriers inside chain_key.
# Register the trivial pass-through batcher: bind the barrier on the
# batched operands and carry the batch dims unchanged, so the XLA
# simplifier-loop workaround the barriers exist for holds in the
# vmapped program too.
try:
    from jax.interpreters import batching as _batching
    from jax._src.lax.lax import optimization_barrier_p as _ob_p

    if _ob_p not in _batching.primitive_batchers:
        def _ob_batcher(args, dims):
            return _ob_p.bind(*args), list(dims)

        _batching.primitive_batchers[_ob_p] = _ob_batcher
except ImportError:        # pragma: no cover - newer jax ships a rule
    pass

_ROT_A = (13, 15, 26, 6)
_ROT_B = (17, 29, 16, 24)
_PARITY = 0x1BD11BDA


def _rotl(x, r):
    return (x << r) | (x >> (32 - r))


def threefry2x32(k1, k2, x0, x1):
    k1 = k1.astype(jnp.uint32)
    k2 = k2.astype(jnp.uint32)
    x0 = x0.astype(jnp.uint32)
    x1 = x1.astype(jnp.uint32)
    ks2 = k1 ^ k2 ^ jnp.uint32(_PARITY)
    ks = (k1, k2, ks2)
    x0 = x0 + ks[0]
    x1 = x1 + ks[1]
    for block in range(5):
        rots = _ROT_A if block % 2 == 0 else _ROT_B
        for r in rots:
            x0 = x0 + x1
            x1 = _rotl(x1, r) ^ x0
        x0 = x0 + ks[(block + 1) % 3]
        x1 = x1 + ks[(block + 2) % 3] + jnp.uint32(block + 1)
    return x0, x1


def seed_key(seed: int):
    """Python-int seed -> (k1, k2) scalar uint32 pair (host-side)."""
    seed = int(seed) & 0xFFFF_FFFF_FFFF_FFFF
    return (jnp.uint32(seed >> 32), jnp.uint32(seed & 0xFFFF_FFFF))


def fold_in(key, data):
    """data: any int array; broadcasts with key parts."""
    k1, k2 = key
    data = data.astype(jnp.uint32)
    zero = jnp.zeros_like(data)
    return threefry2x32(jnp.broadcast_to(k1, data.shape),
                        jnp.broadcast_to(k2, data.shape), zero, data)


def random_bits32(key):
    k1, k2 = key
    zero = jnp.zeros_like(k1)
    b1, b2 = threefry2x32(k1, k2, zero, zero)
    return b1 ^ b2


def uniform01(key):
    bits = random_bits32(key)
    float_bits = (bits >> jnp.uint32(9)) | jnp.uint32(0x3F800000)
    return jax.lax.bitcast_convert_type(float_bits, jnp.float32) \
        - jnp.float32(1.0)


def purpose_id_key(seed_pair, purpose, ids):
    """The first two chain_key folds — (purpose, id) — computed at the
    ids' own (small) shape. Combine with fold_seq for the final
    per-seq fold: fold_seq(purpose_id_key(s, p, ids), seqs) is
    bit-identical to chain_key(s, p, ids, seqs) but lets the caller
    amortize the id folds when seqs is a much larger broadcast (the
    optimization_barriers below otherwise force ALL three folds to
    materialize at the broadcast shape)."""
    ids = jnp.asarray(ids).astype(jnp.uint32)
    zero = jnp.zeros_like(ids)
    k1 = jnp.broadcast_to(seed_pair[0], ids.shape)
    k2 = jnp.broadcast_to(seed_pair[1], ids.shape)
    k = threefry2x32(k1, k2, zero,
                     jnp.full(ids.shape, purpose, jnp.uint32))
    k = jax.lax.optimization_barrier(k)
    k = threefry2x32(k[0], k[1], zero, ids)
    return jax.lax.optimization_barrier(k)


def fold_seq(key, seqs):
    """The last chain_key fold: fold_in(key, seqs) broadcast over
    seqs. See purpose_id_key."""
    seqs = jnp.asarray(seqs).astype(jnp.uint32)
    shape = jnp.broadcast_shapes(key[0].shape, seqs.shape)
    seqs = jnp.broadcast_to(seqs, shape)
    zero = jnp.zeros(shape, jnp.uint32)
    return threefry2x32(jnp.broadcast_to(key[0], shape),
                        jnp.broadcast_to(key[1], shape), zero, seqs)


def chain_key(seed_pair, purpose, ids, seqs):
    """fold(fold(fold(seed, purpose), id), seq) — vectorized over
    ids/seqs arrays (matches utils.rng.packet_key / nprng.packet_uniform:
    each fold_in(k, d) is threefry(k, (0, uint32(d))).

    The optimization_barriers between folds are value-identity: three
    chained threefrys (~150 add/xor/rotate ops) send XLA's algebraic
    simplifier into a canonicalization loop ("stuck in a circular
    simplification loop", 50-run bailout on every compile); breaking
    the expression at the fold boundaries stops the churn. Two-deep
    chains don't trigger it, so one barrier pair suffices."""
    ids = jnp.asarray(ids).astype(jnp.uint32)
    seqs = jnp.asarray(seqs).astype(jnp.uint32)
    shape = jnp.broadcast_shapes(ids.shape, seqs.shape)
    return fold_seq(
        purpose_id_key(seed_pair, purpose,
                       jnp.broadcast_to(ids, shape)),
        jnp.broadcast_to(seqs, shape))
