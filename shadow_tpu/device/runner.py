"""DeviceRunner: adapts the device engine to the Controller.

Selected by `experimental.scheduler_policy: tpu` — the device-mesh
scheduler policy slotting in beside the CPU thread policies, exactly as
the north-star design places it (a new policy alongside
src/main/core/scheduler's five).

Heterogeneity: client-LOCAL args (count/pause/retry) vary per host —
the device apps carry them as per-host arrays, covering the
tornettools shape (varied client behavior over a shared relay/server
fabric). Args that shape SHARED hosts' responses (tgen `size`, tor
`cells`) must stay uniform, and hosts must all belong to one model
family; mixed-family configs run hybrid (CPU host emulation + device
network judgments) via the NoDeviceTwin fallback.
"""

from __future__ import annotations

from typing import Optional

import numpy as np


from shadow_tpu._jax import jax
from shadow_tpu.core.manager import SimStats, resolve_host_ref
from shadow_tpu.obs import trace as obstrace
from shadow_tpu.device.apps import (
    DeviceApp,
    PholdDevice,
    TgenDevice,
    TorDevice,
)
from shadow_tpu.device.engine import AXIS, DeviceEngine, EngineConfig
from shadow_tpu.models.phold import PholdApp
from shadow_tpu.models.tgen import TgenClientApp, TgenServerApp
from shadow_tpu.models.tor import TorClientApp, TorRelayApp
from shadow_tpu.topology import hierarchy
from shadow_tpu.utils.slog import get_logger

log = get_logger("device")


def _tristate(value: str, true_word: str):
    """Strategy-knob mapping shared by every auto/<on>/<off> choice:
    'auto' -> None (engine picks by platform), `true_word` -> True,
    anything else (the schema-validated off word) -> False."""
    return None if value == "auto" else value == true_word


class NoDeviceTwin(ValueError):
    """The config's apps have no fully-vectorized device twin; the tpu
    policy falls back to hybrid execution (CPU host emulation + device
    network judgment, core/manager.py flush_judgments)."""


def _plane_twin(sim, plane) -> DeviceApp:
    """Device twin straight from the columnar host plane — no host
    materialization, no per-host iteration. Each group's ONE prototype
    app carries the parsed args; the twin's per-host arrays fill from
    group slices. Raises the exact errors the object path would, so
    the fallback story reads the same either way."""
    n_hosts = plane.n_hosts
    models = {g.model for g in plane.group_records}

    if models == {"phold"}:
        first = plane.group_records[0].prototype
        for g in plane.group_records[1:]:
            a = g.prototype
            if (a.msgload, a.size, a.selfloop) != (first.msgload,
                                                   first.size,
                                                   first.selfloop):
                raise ValueError("tpu policy: phold args must match "
                                 "across hosts")
        return PholdDevice(n_hosts_total=n_hosts, msgload=first.msgload,
                           size=first.size, selfloop=first.selfloop)

    # eligibility (host/plane.py COLUMNAR_MODELS) admits only phold
    # and tgen; a mixed phold+tgen set still lands here
    if models <= {"tgen_server", "tgen_client"}:
        client_groups = [g for g in plane.group_records
                         if g.model == "tgen_client"]
        if not client_groups:
            raise ValueError("tpu policy: tgen config has no clients")
        first = client_groups[0].prototype
        for g in client_groups:
            if g.prototype.size != first.size:
                raise ValueError(
                    "tpu policy: tgen client `size` must match across "
                    "hosts (it shapes the shared servers' responses); "
                    "count/pause/retry may vary")
        roles = np.zeros(n_hosts, np.int32)
        server_gid = np.zeros(n_hosts, np.int32)
        count = np.zeros(n_hosts, np.int32)
        pause = np.zeros(n_hosts, np.int64)
        retry = np.zeros(n_hosts, np.int64)
        for g in client_groups:
            sl = slice(g.base_id, g.base_id + g.count)
            a = g.prototype
            roles[sl] = 1
            count[sl] = a.count
            pause[sl] = a.pause_ns
            retry[sl] = a.retry_ns
            # same name-or-group rule as resolve_host_ref: an exact
            # host name pins every client in the group to one server;
            # a group name fans out by asker_id % group size
            sid = plane.names.get(a.server_name)
            if sid is not None:
                server_gid[sl] = sid
                continue
            members = (sim.groups or {}).get(a.server_name)
            if not members:
                raise ValueError(
                    f"tgen client on {plane.name_of(g.base_id)}: "
                    f"unknown server {a.server_name!r}")
            ids = np.arange(g.base_id, g.base_id + g.count,
                            dtype=np.int64)
            server_gid[sl] = (members[0]
                              + ids % len(members)).astype(np.int32)
        return TgenDevice(roles=roles, server_gid=server_gid,
                          size=first.size, count=count,
                          pause_ns=pause, retry_ns=retry)

    names = sorted(models)
    raise NoDeviceTwin(f"no device twin registered for {names}; "
                       "available: phold, tgen (server+client) — "
                       "running hybrid (CPU hosts + device net model)")


def device_twin(sim) -> DeviceApp:
    """Map the config's CPU model apps to their vectorized device twin.
    Supported: homogeneous phold; tgen server/client mixes (homogeneous
    client args)."""
    plane = getattr(sim, "plane", None)
    if plane is not None:
        return _plane_twin(sim, plane)
    if any(len(h.apps) > 1 for h in sim.hosts):
        raise NoDeviceTwin("tpu policy: multi-process hosts run hybrid")
    apps = [h.app for h in sim.hosts]
    n_hosts = len(sim.hosts)
    real = [a for a in apps if a is not None]
    if not real:
        raise NoDeviceTwin("tpu policy: no model apps configured")
    classes = {type(a) for a in real}

    if classes == {PholdApp}:
        first = real[0]
        for a in real:
            if (a.msgload, a.size, a.selfloop) != (first.msgload,
                                                   first.size,
                                                   first.selfloop):
                raise ValueError("tpu policy: phold args must match "
                                 "across hosts")
        return PholdDevice(n_hosts_total=n_hosts, msgload=first.msgload,
                           size=first.size, selfloop=first.selfloop)

    if classes <= {TgenServerApp, TgenClientApp}:
        name_to_id = {h.name: h.host_id for h in sim.hosts}
        roles = np.zeros(n_hosts, np.int32)
        server_gid = np.zeros(n_hosts, np.int32)
        clients = [a for a in real if isinstance(a, TgenClientApp)]
        if not clients:
            raise ValueError("tpu policy: tgen config has no clients")
        first = clients[0]
        # client-LOCAL args (count/pause/retry) vary per host; `size`
        # shapes the server's response and must stay uniform
        for c in clients:
            if c.size != first.size:
                raise ValueError(
                    "tpu policy: tgen client `size` must match across "
                    "hosts (it shapes the shared servers' responses); "
                    "count/pause/retry may vary")
        count = np.zeros(n_hosts, np.int32)
        pause = np.zeros(n_hosts, np.int64)
        retry = np.zeros(n_hosts, np.int64)
        for h in sim.hosts:
            if isinstance(h.app, TgenClientApp):
                roles[h.host_id] = 1
                count[h.host_id] = h.app.count
                pause[h.host_id] = h.app.pause_ns
                retry[h.host_id] = h.app.retry_ns
                try:
                    # same name-or-group rule as the CPU ctx.resolve
                    server_gid[h.host_id] = resolve_host_ref(
                        name_to_id, getattr(sim, "groups", None),
                        h.app.server_name, h.host_id)
                except KeyError:
                    raise ValueError(
                        f"tgen client on {h.name}: unknown server "
                        f"{h.app.server_name!r}") from None
        return TgenDevice(roles=roles, server_gid=server_gid,
                          size=first.size, count=count,
                          pause_ns=pause, retry_ns=retry)

    if classes <= {TorRelayApp, TorClientApp}:
        clients = [a for a in real if isinstance(a, TorClientApp)]
        if not clients:
            raise ValueError("tpu policy: tor config has no clients")
        first = clients[0]
        # `cells` shapes the exit relays' DATA service: uniform;
        # count/pause/retry are client-local and may vary
        for c in clients:
            if c.cells != first.cells:
                raise ValueError(
                    "tpu policy: tor client `cells` must match across "
                    "hosts (it shapes the exit relays' responses); "
                    "count/pause/retry may vary")
        roles = np.zeros(n_hosts, np.int32)
        count = np.zeros(n_hosts, np.int32)
        pause = np.zeros(n_hosts, np.int64)
        retry = np.zeros(n_hosts, np.int64)
        relay_gids = []
        for h in sim.hosts:
            if isinstance(h.app, TorClientApp):
                roles[h.host_id] = 1
                count[h.host_id] = h.app.count
                pause[h.host_id] = h.app.pause_ns
                retry[h.host_id] = h.app.retry_ns
            elif isinstance(h.app, TorRelayApp):
                relay_gids.append(h.host_id)
        if len(relay_gids) < 3:
            raise ValueError("tor model needs >= 3 relays")
        return TorDevice(roles=roles,
                         relay_gids=np.array(relay_gids, np.int64),
                         seed=sim.cfg.general.seed,
                         cells=first.cells, count=count,
                         pause_ns=pause, retry_ns=retry)

    names = sorted(c.__name__ for c in classes)
    raise NoDeviceTwin(f"no device twin registered for {names}; "
                       "available: phold, tgen (server+client), "
                       "tor (relay+client) — "
                       "running hybrid (CPU hosts + device net model)")


class DeviceRunner:
    def __init__(self, sim, trace: Optional[list] = None, mesh=None,
                 defer_engine: bool = False):
        if getattr(sim, "host_faults", None):
            # host crash/restart are manager-side events (processes
            # are killed and respawned) — the device engine has no
            # manager loop, so these configs run hybrid: CPU host
            # emulation with the batched device network judge, which
            # carries the same fault epoch table
            raise NoDeviceTwin(
                "host_crash/host_restart faults are manager-side "
                "events; running hybrid")
        self.app = device_twin(sim)     # raises NoDeviceTwin -> hybrid
        if trace is not None:
            raise ValueError(
                "the tpu policy does not record python event traces; "
                "use per-host trace checksums (Host.trace_checksum) for "
                "equivalence testing")
        self.sim = sim
        cfg = sim.cfg
        plane = getattr(sim, "plane", None)
        if (plane.any_pcap if plane is not None
                else any(h.pcap_directory for h in sim.hosts)):
            log.warning("tpu policy: pcap capture requires a CPU "
                        "scheduler policy (packets are device-resident "
                        "metadata here)")
        if mesh is None and cfg.experimental.mesh_shards:
            # experimental.mesh_shards: pin the mesh to the first N
            # devices (the chaos gate's uninterrupted M-shard
            # comparison runs; shrunken-geometry resumes on a
            # healthy pool) without XLA_FLAGS process-global
            # forcing. Resolved before plan adoption below — the
            # plan's applicability gates must see the mesh that
            # actually runs.
            from jax.sharding import Mesh
            n = cfg.experimental.mesh_shards
            devs = jax.devices()
            if n > len(devs):
                raise ValueError(
                    f"experimental.mesh_shards={n} but only "
                    f"{len(devs)} device(s) are available")
            mesh = Mesh(np.array(devs[:n]), (AXIS,))
        # strategy-plan adoption (shadow_tpu/tune/plan.py,
        # docs/autotune.md): under experimental.strategy_plan a
        # stored PLAN record for this workload fingerprint re-tunes
        # the config's execution knobs BEFORE anything below reads
        # them. Adoption changes wall time only — every plan-space
        # knob is bit-identity-pinned — and a fingerprint mismatch
        # refuses loudly inside adopt(). The provenance rides
        # SimStats.strategy_plan so bench can stamp it.
        from shadow_tpu.tune import plan as planmod
        self.strategy_plan = planmod.adopt(
            cfg, self.app, len(sim.hosts),
            n_shards=(mesh.devices.size if mesh is not None
                      else len(jax.devices())))
        # flow control blocks a host's pops when the outbox lacks a
        # full-burst (max_sends) of headroom; at OB == K that means one
        # event per phase, paying one collective exchange per event.
        # Give bursty apps 8 bursts of room unless the config asks for
        # more.
        bp = cfg.experimental.burst_pops
        if bp:
            # width override for on-chip tuning: lowering to 1 is
            # always safe (disables bursting); raising needs an app
            # that implements the burst contract (handle_burst +
            # burst_mask). Traces are P-invariant — per-host pop
            # order is (t, src, seq) regardless of lane width —
            # pinned by test_burst_width_identical_traces.
            if bp > 1 and getattr(self.app, "burst_pops", 1) <= 1:
                raise ValueError(
                    "experimental.burst_pops > 1 requires an app "
                    "with burst support (stateless-responder "
                    "contract); this app pops one event per "
                    "iteration")
            self.app.burst_pops = bp
        self._burst = max(1, getattr(self.app, "burst_pops", 1))
        self._mesh = mesh
        # deterministic chaos injection (device/chaos.py): installed
        # process-global for the run's lifetime — None without a
        # schedule, so schedules never leak across in-process runs
        from shadow_tpu.device import chaos as chaosmod
        self.chaos = chaosmod.from_config(cfg.experimental)
        chaosmod.set_current(self.chaos)
        # capacity overrides on top of the config's static knobs:
        # filled by the occupancy planner (capacity_plan: auto|path)
        # and widened by the overflow re-plan/retry loop
        self._capacity_overrides: dict = {}
        # `exchange: auto` resolution (capacity.choose_exchange over
        # the OCC record): None until a plan/record/checkpoint picks
        # a concrete variant; the engine builder falls back to
        # all_to_all meanwhile (warm-up slices, static plans)
        self._exchange_choice: str = ""
        # persistent AOT compile cache (device/aotcache.py): ONE
        # instance per run, attached to every engine this runner
        # builds — warm-up engines, re-planned engines, and resumed
        # engines all consult the same cache, and its report is the
        # run's loud hit/miss surface (SimStats.compile_cache)
        from shadow_tpu.device import aotcache
        self.aot_cache = aotcache.resolve_cache(cfg.experimental)
        # defer_engine: the EnsembleRunner reuses this class for twin
        # mapping + knob plumbing but builds ITS engine with the
        # stacked replica worlds — constructing a standalone engine
        # here too would be pure waste
        self.engine = None if defer_engine else self._build_engine()
        self.final_state: Optional[dict] = None
        self.occ_record: Optional[dict] = None
        self.replans = 0
        # supervision plumbing (device/supervise.py): the rotating
        # checkpoint writer and the SIGTERM/SIGINT drain guard, set up
        # per run() invocation; the shared advance loop reads them
        self.checkpointer = None
        self.guard = None
        # wall-clock heartbeat staleness monitor (supervise.
        # HeartbeatMonitor), created per run() when
        # experimental.heartbeat_stale_after is set; the campaign
        # server's watchdog polls it cross-thread
        self.hb_monitor = None
        self.retries = 0
        self.reshards = 0
        # OOM degradation-ladder rungs engaged (supervise.advance
        # walks the ladder; the heartbeat and SimStats report it)
        self.degrades = 0
        # preflight admission verdict (capacity.admission_verdict),
        # set per run(); the advance loop honors its overrides and
        # SimStats/bench stamp it
        self.admission = None
        # flight recorder (shadow_tpu/obs): the Controller attaches
        # its run-wide tracer; None (direct construction in tests)
        # falls through to the module-global current() in advance
        self.tracer = None
        # supervise-heartbeat rate mark: (wall, packets) at the last
        # heartbeat, for the pkts/s-since-last-heartbeat log column
        self._hb_mark = None
        # campaign checkpoint stamp (EnsembleRunner overrides)
        self._ck_extra_meta: Optional[dict] = None
        # set once _plan_capacities has sized the engine: run() skips
        # re-planning, so a caller may plan ahead of its timed window
        # (bench.py) and a re-used runner keeps its plan
        self._planned = False

    def _build_engine(self, ensemble=None,
                      lookahead: Optional[int] = None,
                      seed: Optional[int] = None) -> DeviceEngine:
        """Construct the engine from the config's static knobs plus
        any planner/retry capacity overrides (re-invoked by the
        re-plan loop; a capacity change recompiles the program).

        `ensemble`/`lookahead`/`seed` are the EnsembleRunner's
        overrides: with ensemble worlds the DeviceEngine constructor
        swaps in replica 0's tables itself, the campaign shares one
        conservative lookahead, and the engine seed is replica 0's —
        everything else (knob plumbing, outbox floors, strategy
        tristates) is identical, so campaigns reuse this one builder
        instead of copy-pasting it."""
        sim = self.sim
        cfg = sim.cfg
        xp = cfg.experimental
        per_iter = self.app.max_sends * self._burst + \
            self.app.max_timers
        # floor the outbox at 8 iterations per phase — 4 when bursts
        # drain backlogs P events at a time
        outbox = max(xp.outbox_capacity,
                     (4 if self._burst > 1 else 8) * per_iter)
        if outbox != xp.outbox_capacity and \
                "outbox_capacity" not in self._capacity_overrides:
            log.info("outbox_capacity raised %d -> %d (8 iterations "
                     "of %d lanes)",
                     xp.outbox_capacity, outbox, per_iter)
        knobs = {
            "event_capacity": xp.event_capacity,
            "outbox_capacity": outbox,
            "exchange_capacity": xp.exchange_capacity,
            "exchange_capacity2": xp.exchange_capacity2,
            "exchange_in_capacity": xp.exchange_in_capacity,
            "outbox_compact": xp.outbox_compact,
        }
        knobs.update(self._capacity_overrides)
        # exchange: auto resolves to whatever the planner (or an
        # adopted checkpoint) chose; before any record exists — the
        # warm-up slice, static plans — the direct all_to_all stands
        # in (it measures the occ_x pair matrix auto needs)
        exchange = xp.exchange
        if exchange == "auto":
            exchange = self._exchange_choice or "all_to_all"
        # link-fault epoch table (shadow_tpu/faults.py): the engine
        # carries the stacked [T,V,V] matrices and selects the active
        # epoch inside the jitted program; without faults it gets the
        # single base epoch and compiles identically to before
        ft = getattr(sim, "fault_table", None)
        latency_ns, reliability, epoch_times = hierarchy.world_tables(
            sim.topology, ft)
        engine = DeviceEngine(
            EngineConfig(
                n_hosts=len(sim.hosts),
                lookahead=(max(1, sim.lookahead)
                           if lookahead is None else lookahead),
                stop_time=cfg.general.stop_time,
                bootstrap_end=cfg.general.bootstrap_end_time,
                seed=cfg.general.seed if seed is None else seed,
                exchange=exchange,
                model_bandwidth=xp.model_bandwidth,
                count_paths=xp.count_paths,
                judge_hoist=_tristate(xp.judge_placement, "flush"),
                merge_global=_tristate(xp.merge_strategy, "global"),
                pop_onehot=_tristate(xp.pop_strategy, "onehot"),
                table_onehot=_tristate(xp.table_strategy, "onehot"),
                audit=xp.state_audit,
                **knobs,
            ),
            self.app,
            host_vertex=sim.netmodel.host_vertex.astype(np.int32),
            latency_ns=latency_ns,
            reliability=reliability,
            epoch_times=epoch_times,
            ensemble=ensemble,
            mesh=self._mesh,
            bw_up_bits=(sim.plane.bw_up_bits
                        if getattr(sim, "plane", None) is not None
                        else np.array([h.bw_up_bits
                                       for h in sim.hosts],
                                      dtype=np.int64)),
            bw_down_bits=(sim.plane.bw_down_bits
                          if getattr(sim, "plane", None) is not None
                          else np.array([h.bw_down_bits
                                         for h in sim.hosts],
                                        dtype=np.int64)),
        )
        # every engine this runner builds (static, warm-up, planned,
        # re-planned, resumed) shares the one AOT compile cache, so a
        # rebuild at previously-seen capacities starts warm
        engine.aot_cache = self.aot_cache
        return engine

    def _plan_capacities(self, stop: int,
                         load_path: Optional[str] = None) -> None:
        """capacity_plan: auto|<path> — size the engine's capacities
        from measured occupancy instead of the hand-tuned knobs.
        `auto` runs a short warm-up slice on the statically-sized
        engine (window clamping on the global stop, so the windows
        match the real run's prefix); a path consumes a previously
        written OCC record. Either way the planned engine's traces
        bit-match the static engine's whenever nothing overflows, and
        the overflow retry loop (supervise.advance) covers the
        undershoot case loudly. `load_path` is the rotation-resolved
        checkpoint_load path (run() resolves it once)."""
        from shadow_tpu.device import capacity

        xp = self.sim.cfg.experimental
        mode = xp.capacity_plan
        if load_path is None:
            load_path = xp.checkpoint_load
        if load_path:
            # the checkpoint fingerprint pins the saved engine's
            # capacities — a checkpoint written under a plan carries
            # the PLANNER's sizes, not the config's static knobs, so
            # re-planning (or building the static engine) would only
            # produce a loud fingerprint mismatch. Adopt the saved
            # capacities instead; an overflow past the resume point
            # still re-plans through the normal retry loop.
            self._adopt_checkpoint_caps(load_path)
            self.engine = self._build_engine()
            self._planned = True
            # the adopted capacities name the resume program: its AOT
            # entry read overlaps the checkpoint load that follows
            from shadow_tpu.device import supervise
            supervise.prefetch_programs(self)
            log.warning("capacity_plan: %s skipped — checkpoint_load "
                        "resumes with the saved engine's capacities "
                        "%s", mode, self._capacity_overrides)
            return
        # the record's audit baseline: what the config's static knobs
        # build, captured BEFORE any warm-up widen-retry rebuilds the
        # engine (else an overflowed warm-up reports the doubled
        # values as "static")
        static_knobs = {k: getattr(self.engine.config, k)
                        for k in capacity.CAPACITY_KNOBS}
        if mode == "auto":
            warm = xp.capacity_warmup or max(1, stop // 8)
            warm = min(warm, stop)
            # honor dispatch_segment here too: the warm-up is a real
            # device dispatch, and the segment bound exists because
            # tunneled-TPU relays kill executions that run too long —
            # an un-segmented warm-up would break on exactly the
            # platform the planner targets. Overflow is checked at
            # each boundary, so a bad static sizing re-plans without
            # finishing the slice first.
            seg = xp.dispatch_segment
            state = self.engine.init_state(self.sim.starts)
            for attempt in range(capacity.MAX_REPLANS + 1):
                t = 0
                dims = ()
                while t < warm:
                    nxt = min(warm, t + seg) if seg else warm
                    state, _ = self.engine.run(state, stop=nxt,
                                               final_stop=stop)
                    t = nxt
                    dims = capacity.overflow_dims(state)
                    if dims:
                        break
                if not dims:
                    break
                if attempt == capacity.MAX_REPLANS:
                    raise RuntimeError(
                        f"capacity warm-up still overflows after "
                        f"{capacity.MAX_REPLANS} doublings on {dims}")
                self._capacity_overrides = capacity.widen(
                    self._capacity_overrides, dims,
                    self.engine.effective)
                log.warning("capacity warm-up overflowed on %s; "
                            "retrying with %s", dims,
                            self._capacity_overrides)
                self.engine = self._build_engine()
                state = self.engine.init_state(self.sim.starts)
            record = capacity.measure(self.engine, state,
                                      source=f"warmup:{warm}ns")
        else:
            record = capacity.load_record(mode)
            want = {"app": type(self.app).__name__,
                    "app_fp": capacity.app_fingerprint(self.app),
                    "n_hosts": len(self.sim.hosts)}
            got = {k: record["workload"].get(k) for k in want}
            if got != want:
                raise ValueError(
                    f"occupancy record {mode} was measured on {got}; "
                    f"this simulation is {want} — re-measure with "
                    "capacity_plan: auto")
        exchange = self._resolve_exchange(record)
        planned = capacity.plan(
            record,
            per_iter=self.engine.effective["M_out"],
            floor_iters=4 if self._burst > 1 else 8,
            n_shards=self.engine.n_shards,
            headroom=self._headroom(),
            exchange=exchange)
        record["planned"] = planned
        record["static"] = static_knobs
        self.occ_record = record
        self._capacity_overrides = dict(planned)
        self.engine = self._build_engine()
        self._planned = True
        # the planned program is now named: overlap its AOT cache
        # entry read with the init_state / checkpoint-load work that
        # follows (supervise.prefetch_programs)
        from shadow_tpu.device import supervise
        supervise.prefetch_programs(self)
        log.info("capacity plan (%s, exchange %s, headroom %g): %s  "
                 "[measured %s]", mode, exchange, self._headroom(),
                 planned, record["measured"])

    def _headroom(self) -> float:
        """The capacity planner's pad factor: the tunable
        experimental.capacity_headroom when set, else the planner
        default. One accessor shared by the plan and the
        exchange-choice estimates so they can never pad
        differently."""
        from shadow_tpu.device import capacity

        return (self.sim.cfg.experimental.capacity_headroom
                or capacity.HEADROOM)

    def _adopt_checkpoint_caps(self, load_path: str) -> None:
        """Checkpoint resume under a capacity plan: adopt the SAVED
        engine's capacity knobs (the fingerprint pins them — a fresh
        plan would only produce a loud mismatch) and, under
        `exchange: auto`, the saved exchange schedule the caps were
        planned for. ONE adopt path for both runners — the campaign
        delegates here so standalone and ensemble resumes can never
        drift."""
        from shadow_tpu.device import checkpoint

        meta = checkpoint.peek_meta(load_path)
        caps = meta.get("capacities")
        if caps is None:
            # pre-"capacities" checkpoints: only the two
            # layout-determining knobs ride the fingerprint
            caps = {k: meta["fingerprint"][k]
                    for k in ("event_capacity", "outbox_capacity")}
        self._capacity_overrides = {k: int(v)
                                    for k, v in caps.items()}
        if self.sim.cfg.experimental.exchange == "auto":
            self._exchange_choice = meta.get("exchange",
                                             "all_to_all")

    def _adopt_checkpoint_geometry(self, load_path: str) -> bool:
        """A checkpoint written after a mesh-shrink failover stamps
        the shrunken geometry (checkpoint meta["geometry"]); loading
        it onto the full mesh would be a hard layout mismatch. Adopt
        instead: rebuild the mesh on the first ``n_shards`` available
        devices so the resume lands on the saved geometry — traces
        are mesh-placement-invariant, so WHICH devices is free.
        Returns whether the mesh changed (the EnsembleRunner rebuilds
        its campaign engine then). ONE adopt path for both runners,
        like _adopt_checkpoint_caps."""
        from shadow_tpu.device import checkpoint

        geom = checkpoint.peek_geometry(
            checkpoint.peek_meta(load_path))
        n = geom.get("n_shards")
        if n is None:
            return False
        n = int(n)
        cur = (self._mesh.devices.size if self._mesh is not None
               else len(jax.devices()))
        if n == cur:
            return False
        devs = (list(self._mesh.devices.flat)
                if self._mesh is not None else jax.devices())
        if n > len(devs):
            raise ValueError(
                f"checkpoint {load_path} was saved on {n} shard(s) "
                f"but only {len(devs)} device(s) are available — "
                "resume on a pool of at least the saved shard count")
        from jax.sharding import Mesh
        log.warning(
            "checkpoint %s was saved on %d shard(s) (this pool has "
            "%d) — rebuilding the mesh to the saved geometry for "
            "the resume", load_path, n, len(devs))
        self._mesh = Mesh(np.array(devs[:n]), (AXIS,))
        if self.engine is not None:
            self.engine = self._build_engine()
        return True

    def _replan_for_shrink(self, n_shards: int, record: dict = None,
                           per_iter: int = 0) -> None:
        """The exchange-geometry capacities were planned/auto-sized
        for the OLD shard count — fewer shards mean more hosts (and
        rows) per shard pair, so carrying them over would guarantee
        overflow re-plans. Drop them, re-resolve the exchange
        schedule for the new width (``exchange: auto``), and re-plan
        the caps from the measured occupancy record when one exists
        (capacity.pair_matrix degrades a mismatched-shape pair
        matrix to a safe scalar bound). Per-host capacities
        (event/outbox/IN/compact) are shard-independent and stay."""
        from shadow_tpu.device import capacity
        from shadow_tpu.tune import plan as planmod

        xp = self.sim.cfg.experimental
        for k in ("exchange_capacity", "exchange_capacity2"):
            # 0, not pop: a hand-set static knob was sized for the
            # dead geometry too — the override restores the engine's
            # own auto-sizing until the record-based plan below (if
            # any) supplies measured caps for the new width
            self._capacity_overrides[k] = 0
        record = record if record is not None else self.occ_record
        floor_iters = 4 if self._burst > 1 else 8
        # the EnsembleRunner passes its campaign engine's lane width
        # (the base runner's engine is deferred there)
        per_iter = per_iter or self.engine.effective["M_out"]
        exchange = xp.exchange
        if xp.exchange == "auto":
            if record is not None:
                choice, info = capacity.choose_exchange(
                    record, n_shards, per_iter=per_iter,
                    floor_iters=floor_iters,
                    headroom=self._headroom())
                record["exchange_auto"] = info
                exchange = self._exchange_choice = choice
                log.info("shrink re-plan: exchange auto -> %s at %d "
                         "shard(s)", choice, n_shards)
            else:
                exchange = self._exchange_choice = "all_to_all"
        if record is not None:
            planned = capacity.plan(
                record, per_iter=per_iter, floor_iters=floor_iters,
                n_shards=n_shards, headroom=self._headroom(),
                exchange=exchange)
            for k in ("exchange_capacity", "exchange_capacity2"):
                if planned[k]:
                    self._capacity_overrides[k] = planned[k]
            log.info("shrink re-plan at %d shard(s): %s", n_shards,
                     {k: v for k, v in self._capacity_overrides
                      .items() if k.startswith("exchange")})
        # the adopted strategy plan was validated against the old run
        # shape: re-run its applicability gates under the new shard
        # count and surface the knobs that no longer apply
        self.strategy_plan = planmod.revalidate_after_reshard(
            self.sim.cfg, self.strategy_plan, n_shards)

    def _shrink_to(self, alive, host_state: dict,
                   ensemble: bool = False):
        """Re-shard a host-side validated snapshot onto the surviving
        devices: new mesh, re-planned exchange capacities, rebuilt
        engine (warm through the shared AOT cache), and the snapshot
        re-padded to the new geometry (capacity.reshard_state) and
        re-placed with the new template's shardings. Returns the
        on-device state the advance loop continues from. The
        EnsembleRunner overrides this to rebuild its campaign
        engine; the mesh/override mutations stay here — one owner.

        Transactional: a failure anywhere rolls the mesh, engine,
        overrides, and plan provenance back to the pre-shrink
        state before re-raising — the escalation that follows
        persists the (old-geometry) snapshot through
        ``runner.engine``, so a half-committed shrink would stamp
        the NEW geometry over old-layout leaves and poison the
        failover checkpoint."""
        from jax.sharding import Mesh

        from shadow_tpu.device import supervise

        rollback = (self._mesh, self.engine,
                    dict(self._capacity_overrides),
                    self._exchange_choice, self.strategy_plan)
        try:
            self._mesh = Mesh(np.array(list(alive)), (AXIS,))
            self._replan_for_shrink(len(alive))
            self.engine = self._build_engine()
            supervise.prefetch_programs(self, ensemble=ensemble)
            return self._place_resharded(self, host_state, ensemble)
        except Exception:
            (self._mesh, self.engine, self._capacity_overrides,
             self._exchange_choice, self.strategy_plan) = rollback
            raise

    @staticmethod
    def _place_resharded(runner, host_state: dict, ensemble: bool):
        """Shared tail of the shrink: build the new engine's template
        (shapes + shardings + padding-row values), re-pad the
        snapshot onto it, and device_put. The template round-trips
        through the host once — the padding rows' contents (app init
        rows, heap fills) must be exactly what an uninterrupted run
        on the new mesh would hold, and init_state is their one
        source of truth."""
        from shadow_tpu.device import capacity

        engine = runner.engine
        template = (engine.init_ensemble_state(runner.sim.starts)
                    if ensemble else
                    engine.init_state(runner.sim.starts))
        new_host = capacity.reshard_state(
            host_state, len(runner.sim.hosts),
            jax.device_get(template))
        return capacity.transfer(engine, runner.sim.starts, new_host,
                                 template=template)

    def _resolve_exchange(self, record: dict, engine=None) -> str:
        """The exchange variant the planned engine will compile:
        the config's explicit choice, or — under `exchange: auto` —
        capacity.choose_exchange over the measured occ_x pair matrix
        (stamped into the record so the decision is auditable).
        Shared by DeviceRunner and EnsembleRunner (which passes its
        own campaign engine; this runner's may be deferred)."""
        from shadow_tpu.device import capacity

        engine = engine if engine is not None else self.engine
        xp = self.sim.cfg.experimental
        if xp.exchange != "auto":
            return xp.exchange
        choice, info = capacity.choose_exchange(
            record, engine.n_shards,
            per_iter=engine.effective["M_out"],
            floor_iters=4 if self._burst > 1 else 8,
            headroom=self._headroom())
        record["exchange_auto"] = info
        self._exchange_choice = choice
        if engine.n_shards > 1:
            log.info("exchange: auto -> %s (per-flush ICI row "
                     "estimates %s)", choice, info["estimates"])
        return choice

    def _emit_heartbeats(self, now: int, state) -> None:
        """Per-host [shadow-heartbeat] CSV lines from device counters
        at a run-segment boundary (tracker.c:418-560 format: same
        Tracker, same headers, counters device_get'd between
        segments). Interval attribution is window-granular: the
        segment pauses when the next event passes `now`, so events in
        [now, now+lookahead) of the last window are counted in THIS
        interval — up to one lookahead of skew vs the CPU tracker's
        exact per-tick attribution. Totals always agree.

        One aggregate ``[supervise-heartbeat]`` line rides along with
        the wall-clock pkts/s since the previous heartbeat and the
        cumulative retry/replan counts, so a stalling or thrashing
        run is visible from the log stream alone."""
        from shadow_tpu import simtime
        from shadow_tpu.device.supervise import heartbeat_rates
        from shadow_tpu.host.tracker import Tracker

        if self.hb_monitor is not None:
            self.hb_monitor.beat()
        n_exec = np.asarray(state["n_exec"])
        n_sent = np.asarray(state["n_sent"])
        n_drop = np.asarray(state["n_drop"])
        for h in self.sim.hosts:
            i = h.host_id
            if h.tracker is None:
                h.tracker = Tracker(
                    h.name, self.sim.cfg.general.heartbeat_interval)
            h.tracker.set_events_total(int(n_exec[i]))
            h.packets_sent = int(n_sent[i])
            h.packets_dropped = int(n_drop[i])
            h.tracker.heartbeat(now, h)
        H = len(self.sim.hosts)
        sent_total = int(n_sent[:H].sum())
        self._hb_mark, (rate,) = heartbeat_rates(self._hb_mark,
                                                 [sent_total])
        # live device memory, when the backend exposes allocator
        # stats (TPU/GPU); "n/a" on CPU — an approaching OOM is
        # visible from the log stream alone
        from shadow_tpu.device import capacity as capmod
        mem = self.engine.device_memory_stats()
        mem_s = (f"{capmod.fmt_bytes(mem[0])}/"
                 f"{capmod.fmt_bytes(mem[1])}"
                 if mem is not None else "n/a")
        log.info("[supervise-heartbeat] t=%s events=%d sent=%d "
                 "pkts/s=%s retries=%d replans=%d reshards=%d "
                 "mem=%s",
                 simtime.format_time(now), int(n_exec[:H].sum()),
                 sent_total, rate, self.retries, self.replans,
                 self.reshards, mem_s)

    def run(self, stop: int) -> SimStats:
        import time as _time

        from shadow_tpu.device import capacity, supervise

        xp = self.sim.cfg.experimental
        tracer = self.tracer or obstrace.current()
        self.replans = 0
        self.retries = 0
        self.reshards = 0
        self.degrades = 0
        self._hb_mark = None
        if xp.capacity_plan == "static":
            # a re-used runner must not merge this run's measurements
            # into a stale record from an earlier run (the merge
            # branch below is the with-a-plan-active path, and it
            # WRITES artifacts/OCC_*.json)
            self.occ_record = None
        if xp.checkpoint_save:
            from shadow_tpu.device import checkpoint
            checkpoint.probe_writable(xp.checkpoint_save)
        load_path = ""
        if xp.checkpoint_load:
            # rotation-aware resolution (a supervised run's base path
            # resolves to its newest readable rotation entry), then
            # pre-validate the resume parameters from the npz meta
            # alone — fail in milliseconds, not after the capacity
            # warm-up spends minutes compiling
            from shadow_tpu.device import checkpoint
            load_path = supervise.resolve_checkpoint(
                xp.checkpoint_load)
            checkpoint.prevalidate_resume(
                load_path, stop,
                save_path=xp.checkpoint_save,
                save_time=xp.checkpoint_save_time)
            # a post-shrink checkpoint stamps the shrunken geometry:
            # adopt it (rebuild the mesh + engine to match) BEFORE
            # planning/loading, so the resume lands on the saved
            # padded width instead of a loud layout mismatch
            self._adopt_checkpoint_geometry(load_path)
        # preflight admission (capacity.py): the modeled footprint —
        # state copies x pipeline depth, exchange scratch, world
        # tables — against the per-device budget, BEFORE any compile
        # (the first compile happens lazily at the first dispatch,
        # which the capacity warm-up below would trigger). strict
        # refuses over-budget with a readable diagnostic; auto may
        # statically lower the pipeline depth, and the runtime
        # degradation ladder backstops what the model cannot see.
        self.admission = capacity.admission_verdict(
            self.engine, xp,
            pipeline_depth=getattr(xp, "pipeline_depth", 0))
        if xp.capacity_plan != "static" and not self._planned:
            with tracer.span("capacity.plan", "plan",
                             mode=xp.capacity_plan):
                self._plan_capacities(stop, load_path=load_path)
        if load_path:
            from shadow_tpu.device import checkpoint
            with tracer.span("checkpoint.load", "checkpoint",
                             path=load_path):
                state, t_start = checkpoint.load_state(
                    self.engine, self.sim.starts, load_path,
                    final_stop=stop)
            if t_start >= stop:
                raise ValueError(
                    f"checkpoint_load: saved state pauses at "
                    f"{t_start} ns, at/after stop_time {stop} ns — "
                    f"nothing to resume")
            log.info("resumed checkpoint %s at t=%d ns",
                     load_path, t_start)
        else:
            state = self.engine.init_state(self.sim.starts)
            t_start = 0
        # with checkpoint_save, the run PAUSES at checkpoint_save_time
        # (0 = at stop_time) and writes the state there; window
        # clamping stays on the global stop either way, so the
        # paused+resumed pair bit-matches the uninterrupted run
        pause = stop
        if xp.checkpoint_save:
            if xp.checkpoint_save_time:
                pause = min(stop, xp.checkpoint_save_time)
            if pause <= t_start:
                raise ValueError(
                    f"checkpoint_save_time {pause} ns is not after "
                    f"the run's start time {t_start} ns")
        # supervision (device/supervise.py): the rotating checkpoint
        # writer and the SIGTERM/SIGINT drain guard — installed when
        # a checkpoint_save path exists AND the run has segment
        # boundaries for the drain to fire at (supervise.make_guard)
        self.checkpointer = None
        if xp.checkpoint_every:
            self.checkpointer = supervise.Checkpointer(
                xp.checkpoint_save, xp.checkpoint_every,
                xp.checkpoint_keep, final_stop=stop,
                extra_meta=self._ck_extra_meta,
                audit_enabled=xp.state_audit)
        self.guard = supervise.make_guard(self.sim.cfg)
        self.hb_monitor = (
            supervise.HeartbeatMonitor(xp.heartbeat_stale_after)
            if getattr(xp, "heartbeat_stale_after", 0) else None)
        import contextlib
        t0 = _time.perf_counter()
        # shared segmented advance (supervise.advance): heartbeat /
        # dispatch-segment / checkpoint boundaries, the overflow
        # re-plan loop, dispatch retry, audit validation, and the
        # preemption drain. A boundary that lands exactly on `pause`
        # still emits its heartbeat (an uninterrupted run would); only
        # the global end suppresses — resume restarts past the saved
        # t, so the pair emits each boundary exactly once
        with (self.guard if self.guard is not None
              else contextlib.nullcontext()):
            state, adv = supervise.advance(self, state, t_start,
                                           pause, stop)
        rounds, t_end = int(np.max(adv.rounds)), adv.t_end
        budget_hit, overflowed = adv.budget_hit, adv.overflowed
        self.retries = adv.retries
        if xp.checkpoint_save:
            if budget_hit or overflowed:
                # budget: the simulation stopped at an unknown
                # sim-time short of `pause`, so stamping `pause`
                # would let a resume skip unexecuted work. overflow:
                # the state has already dropped events, so a resumed
                # trace would silently replay the loss. Refuse both
                # loudly instead of leaving a valid-looking decoy.
                log.error("%s before the checkpoint boundary — NOT "
                          "saving %s",
                          "max_rounds exhausted" if budget_hit
                          else "capacity overflow (events lost)",
                          xp.checkpoint_save)
            elif adv.preempted:
                # the drain already saved the resume checkpoint
                # (adv.resume_path); a second, later-stamped save here
                # would shadow it with identical content
                pass
            else:
                from shadow_tpu.device import checkpoint
                with tracer.span("checkpoint.save", "checkpoint",
                                 sim_t0=t_end,
                                 path=xp.checkpoint_save):
                    checkpoint.save_state(
                        self.engine, state, xp.checkpoint_save, t_end,
                        final_stop=stop,
                        audit_meta=({"enabled": True, "violations": 0}
                                    if xp.state_audit else None))
                log.info("checkpoint saved at t=%d ns -> %s (run %s)",
                         t_end, xp.checkpoint_save,
                         "complete" if t_end >= stop else
                         "paused early; resume with checkpoint_load")
        # fetch ONLY the stats the controller needs — the [H,E] event
        # heaps are ~20 MB at the 10k rung (250 MB at tor_large) and
        # dominate wall time over a tunneled TPU if pulled back
        stat_keys = [k for k in state
                     if k not in ("ht", "hk", "hm", "hv", "hw")]
        with tracer.span("state.fetch", "host", sim_t0=t_end):
            final = jax.device_get({k: state[k] for k in stat_keys})
        wall = _time.perf_counter() - t0
        self.final_state = final
        H = len(self.sim.hosts)
        if "path_cnt" in final:
            # surface the device path histogram through the same API
            # the CPU engines populate (NetworkModel.path_packets)
            V = self.engine.n_vertices
            cnt = np.asarray(final["path_cnt"]).sum(0).reshape(V, V)
            nz = np.nonzero(cnt)
            self.sim.netmodel.record_paths(
                {(int(i), int(j)): int(cnt[i, j])
                 for i, j in zip(*nz)})
        n_exec_total = int(final["n_exec"][:H].sum())
        # perf-timer parity (USE_PERF_TIMERS round summaries): the
        # device program is one fused loop, so the breakdown is
        # per-run — rounds, wall, and throughput
        log.info("device perf: %d rounds in %.2fs wall "
                 "(%.0f rounds/s, %.0f events/s)", rounds,
                 wall, rounds / wall if wall > 0 else 0.0,
                 n_exec_total / wall if wall > 0 else 0.0)

        # occupancy record: measured high-water marks from the FULL
        # run alongside the capacities that held them; with a plan
        # active, merged into the planner's record and written to
        # artifacts/OCC_*.json for reuse (capacity_plan: <path>,
        # scripts/tune_10k.py sweep pruning)
        occ = capacity.measure(self.engine, state, source="run")
        if self.occ_record is not None:
            self.occ_record["final_measured"] = occ["measured"]
            self.occ_record["effective"] = occ["effective"]
            self.occ_record["replans"] = self.replans
            self.occ_record["applied"] = dict(self._capacity_overrides)
            if adv.preempted:
                # a preempted run's high-water marks cover only the
                # executed prefix — don't publish them as a workload
                # record the planner would size from
                log.info("occupancy record not written (run "
                         "preempted)")
            else:
                path = capacity.record_path(
                    self.engine,
                    directory=getattr(xp, "artifacts_dir", ""))
                try:
                    capacity.save_record(self.occ_record, path)
                    log.info("occupancy record -> %s", path)
                except OSError as e:
                    log.warning("could not write occupancy record "
                                "%s: %s", path, e)
        else:
            self.occ_record = occ

        stats = SimStats()
        stats.end_time = t_end
        stats.rounds = int(rounds)
        stats.occupancy = self.occ_record
        stats.strategy_plan = self.strategy_plan
        if self.aot_cache is not None:
            # loud hit/miss surface: the whole run's compile-cache
            # attribution (warm-up + planned + re-planned engines)
            self.aot_cache.publish(stats)
        stats.replans = self.replans
        stats.retries = self.retries
        stats.reshards = adv.reshards
        stats.degrades = adv.degrades
        stats.admission = self.admission
        mem = self.engine.device_memory_stats()
        if mem is not None:
            stats.mem_bytes_in_use, stats.mem_budget = mem
        stats.preempted = adv.preempted
        stats.resume_path = adv.resume_path
        if self.hb_monitor is not None:
            stats.stale_heartbeats = self.hb_monitor.stale_events
        # segment-pipeline telemetry (supervise.advance): depth,
        # issue/drain counts, sync wall, and the overlap the depth
        # bought — bench stamps it and trace_report prints the
        # overlap-efficiency line from it
        stats.pipeline = adv.pipeline or None
        stats.events_executed = n_exec_total
        stats.packets_sent = int(final["n_sent"][:H].sum())
        stats.packets_dropped = int(final["n_drop"][:H].sum())
        stats.packets_delivered = int(final["n_deliv"][:H].sum())
        overflow = int(final["overflow"][:H].sum())
        if overflow:
            stats.ok = False
            log.error("device engine overflow: %d events lost — raise "
                      "experimental.event_capacity/outbox_capacity, "
                      "or set capacity_plan: auto to size and retry "
                      "automatically", overflow)
        x_overflow = int(final["x_overflow"][:H].sum())
        if x_overflow:
            stats.ok = False
            log.error("exchange overflow: %d rows exceeded the per-"
                      "shard-pair capacity — raise experimental."
                      "exchange_capacity (or use exchange: all_gather "
                      "for hub-concentrated traffic, or "
                      "capacity_plan: auto)", x_overflow)

        # reflect per-host results back onto the Host objects — or,
        # for a columnar build, adopt them as plane columns: hosts
        # materialized later still read the real counters, and nothing
        # is materialized just to carry five ints
        plane = getattr(self.sim, "plane", None)
        if plane is not None:
            plane.adopt_final(final)
        else:
            for h in self.sim.hosts:
                i = h.host_id
                h.events_executed = int(final["n_exec"][i])
                h.packets_sent = int(final["n_sent"][i])
                h.packets_dropped = int(final["n_drop"][i])
                h.packets_delivered = int(final["n_deliv"][i])
                h.trace_checksum = int(final["chk"][i])
        return stats
