"""DeviceRunner: adapts the device engine to the Controller.

Selected by `experimental.scheduler_policy: tpu` — the device-mesh
scheduler policy slotting in beside the CPU thread policies, exactly as
the north-star design places it (a new policy alongside
src/main/core/scheduler's five).

Heterogeneity: client-LOCAL args (count/pause/retry) vary per host —
the device apps carry them as per-host arrays, covering the
tornettools shape (varied client behavior over a shared relay/server
fabric). Args that shape SHARED hosts' responses (tgen `size`, tor
`cells`) must stay uniform, and hosts must all belong to one model
family; mixed-family configs run hybrid (CPU host emulation + device
network judgments) via the NoDeviceTwin fallback.
"""

from __future__ import annotations

from typing import Optional

import numpy as np


from shadow_tpu._jax import jax
from shadow_tpu.core.manager import SimStats, resolve_host_ref
from shadow_tpu.device.apps import (
    DeviceApp,
    PholdDevice,
    TgenDevice,
    TorDevice,
)
from shadow_tpu.device.engine import DeviceEngine, EngineConfig
from shadow_tpu.models.phold import PholdApp
from shadow_tpu.models.tgen import TgenClientApp, TgenServerApp
from shadow_tpu.models.tor import TorClientApp, TorRelayApp
from shadow_tpu.utils.slog import get_logger

log = get_logger("device")


def _tristate(value: str, true_word: str):
    """Strategy-knob mapping shared by every auto/<on>/<off> choice:
    'auto' -> None (engine picks by platform), `true_word` -> True,
    anything else (the schema-validated off word) -> False."""
    return None if value == "auto" else value == true_word


class NoDeviceTwin(ValueError):
    """The config's apps have no fully-vectorized device twin; the tpu
    policy falls back to hybrid execution (CPU host emulation + device
    network judgment, core/manager.py flush_judgments)."""


def device_twin(sim) -> DeviceApp:
    """Map the config's CPU model apps to their vectorized device twin.
    Supported: homogeneous phold; tgen server/client mixes (homogeneous
    client args)."""
    if any(len(h.apps) > 1 for h in sim.hosts):
        raise NoDeviceTwin("tpu policy: multi-process hosts run hybrid")
    apps = [h.app for h in sim.hosts]
    n_hosts = len(sim.hosts)
    real = [a for a in apps if a is not None]
    if not real:
        raise NoDeviceTwin("tpu policy: no model apps configured")
    classes = {type(a) for a in real}

    if classes == {PholdApp}:
        first = real[0]
        for a in real:
            if (a.msgload, a.size, a.selfloop) != (first.msgload,
                                                   first.size,
                                                   first.selfloop):
                raise ValueError("tpu policy: phold args must match "
                                 "across hosts")
        return PholdDevice(n_hosts_total=n_hosts, msgload=first.msgload,
                           size=first.size, selfloop=first.selfloop)

    if classes <= {TgenServerApp, TgenClientApp}:
        name_to_id = {h.name: h.host_id for h in sim.hosts}
        roles = np.zeros(n_hosts, np.int32)
        server_gid = np.zeros(n_hosts, np.int32)
        clients = [a for a in real if isinstance(a, TgenClientApp)]
        if not clients:
            raise ValueError("tpu policy: tgen config has no clients")
        first = clients[0]
        # client-LOCAL args (count/pause/retry) vary per host; `size`
        # shapes the server's response and must stay uniform
        for c in clients:
            if c.size != first.size:
                raise ValueError(
                    "tpu policy: tgen client `size` must match across "
                    "hosts (it shapes the shared servers' responses); "
                    "count/pause/retry may vary")
        count = np.zeros(n_hosts, np.int32)
        pause = np.zeros(n_hosts, np.int64)
        retry = np.zeros(n_hosts, np.int64)
        for h in sim.hosts:
            if isinstance(h.app, TgenClientApp):
                roles[h.host_id] = 1
                count[h.host_id] = h.app.count
                pause[h.host_id] = h.app.pause_ns
                retry[h.host_id] = h.app.retry_ns
                try:
                    # same name-or-group rule as the CPU ctx.resolve
                    server_gid[h.host_id] = resolve_host_ref(
                        name_to_id, getattr(sim, "groups", None),
                        h.app.server_name, h.host_id)
                except KeyError:
                    raise ValueError(
                        f"tgen client on {h.name}: unknown server "
                        f"{h.app.server_name!r}")
        return TgenDevice(roles=roles, server_gid=server_gid,
                          size=first.size, count=count,
                          pause_ns=pause, retry_ns=retry)

    if classes <= {TorRelayApp, TorClientApp}:
        clients = [a for a in real if isinstance(a, TorClientApp)]
        if not clients:
            raise ValueError("tpu policy: tor config has no clients")
        first = clients[0]
        # `cells` shapes the exit relays' DATA service: uniform;
        # count/pause/retry are client-local and may vary
        for c in clients:
            if c.cells != first.cells:
                raise ValueError(
                    "tpu policy: tor client `cells` must match across "
                    "hosts (it shapes the exit relays' responses); "
                    "count/pause/retry may vary")
        roles = np.zeros(n_hosts, np.int32)
        count = np.zeros(n_hosts, np.int32)
        pause = np.zeros(n_hosts, np.int64)
        retry = np.zeros(n_hosts, np.int64)
        relay_gids = []
        for h in sim.hosts:
            if isinstance(h.app, TorClientApp):
                roles[h.host_id] = 1
                count[h.host_id] = h.app.count
                pause[h.host_id] = h.app.pause_ns
                retry[h.host_id] = h.app.retry_ns
            elif isinstance(h.app, TorRelayApp):
                relay_gids.append(h.host_id)
        if len(relay_gids) < 3:
            raise ValueError("tor model needs >= 3 relays")
        return TorDevice(roles=roles,
                         relay_gids=np.array(relay_gids, np.int64),
                         seed=sim.cfg.general.seed,
                         cells=first.cells, count=count,
                         pause_ns=pause, retry_ns=retry)

    names = sorted(c.__name__ for c in classes)
    raise NoDeviceTwin(f"no device twin registered for {names}; "
                       "available: phold, tgen (server+client), "
                       "tor (relay+client) — "
                       "running hybrid (CPU hosts + device net model)")


class DeviceRunner:
    def __init__(self, sim, trace: Optional[list] = None, mesh=None):
        self.app = device_twin(sim)     # raises NoDeviceTwin -> hybrid
        if trace is not None:
            raise ValueError(
                "the tpu policy does not record python event traces; "
                "use per-host trace checksums (Host.trace_checksum) for "
                "equivalence testing")
        self.sim = sim
        cfg = sim.cfg
        if any(h.pcap_directory for h in sim.hosts):
            log.warning("tpu policy: pcap capture requires a CPU "
                        "scheduler policy (packets are device-resident "
                        "metadata here)")
        # flow control blocks a host's pops when the outbox lacks a
        # full-burst (max_sends) of headroom; at OB == K that means one
        # event per phase, paying one collective exchange per event.
        # Give bursty apps 8 bursts of room unless the config asks for
        # more.
        bp = cfg.experimental.burst_pops
        if bp:
            # width override for on-chip tuning: lowering to 1 is
            # always safe (disables bursting); raising needs an app
            # that implements the burst contract (handle_burst +
            # burst_mask). Traces are P-invariant — per-host pop
            # order is (t, src, seq) regardless of lane width —
            # pinned by test_burst_width_identical_traces.
            if bp > 1 and getattr(self.app, "burst_pops", 1) <= 1:
                raise ValueError(
                    "experimental.burst_pops > 1 requires an app "
                    "with burst support (stateless-responder "
                    "contract); this app pops one event per "
                    "iteration")
            self.app.burst_pops = bp
        burst = max(1, getattr(self.app, "burst_pops", 1))
        per_iter = self.app.max_sends * burst + self.app.max_timers
        # floor the outbox at 8 iterations per phase — 4 when bursts
        # drain backlogs P events at a time
        outbox = max(cfg.experimental.outbox_capacity,
                     (4 if burst > 1 else 8) * per_iter)
        if outbox != cfg.experimental.outbox_capacity:
            log.info("outbox_capacity raised %d -> %d (8 iterations "
                     "of %d lanes)",
                     cfg.experimental.outbox_capacity, outbox,
                     per_iter)
        self.engine = DeviceEngine(
            EngineConfig(
                n_hosts=len(sim.hosts),
                event_capacity=cfg.experimental.event_capacity,
                outbox_capacity=outbox,
                lookahead=max(1, sim.lookahead),
                stop_time=cfg.general.stop_time,
                bootstrap_end=cfg.general.bootstrap_end_time,
                seed=cfg.general.seed,
                exchange=cfg.experimental.exchange,
                exchange_capacity=cfg.experimental.exchange_capacity,
                exchange_in_capacity=cfg.experimental
                .exchange_in_capacity,
                outbox_compact=cfg.experimental.outbox_compact,
                model_bandwidth=cfg.experimental.model_bandwidth,
                count_paths=cfg.experimental.count_paths,
                judge_hoist=_tristate(
                    cfg.experimental.judge_placement, "flush"),
                merge_global=_tristate(
                    cfg.experimental.merge_strategy, "global"),
                pop_onehot=_tristate(
                    cfg.experimental.pop_strategy, "onehot"),
                table_onehot=_tristate(
                    cfg.experimental.table_strategy, "onehot"),
            ),
            self.app,
            host_vertex=sim.netmodel.host_vertex.astype(np.int32),
            latency_ns=sim.topology.latency_ns,
            reliability=sim.topology.reliability,
            mesh=mesh,
            bw_up_bits=np.array([h.bw_up_bits for h in sim.hosts],
                                dtype=np.int64),
            bw_down_bits=np.array([h.bw_down_bits for h in sim.hosts],
                                  dtype=np.int64),
        )
        self.final_state: Optional[dict] = None

    def _emit_heartbeats(self, now: int, state) -> None:
        """Per-host [shadow-heartbeat] CSV lines from device counters
        at a run-segment boundary (tracker.c:418-560 format: same
        Tracker, same headers, counters device_get'd between
        segments). Interval attribution is window-granular: the
        segment pauses when the next event passes `now`, so events in
        [now, now+lookahead) of the last window are counted in THIS
        interval — up to one lookahead of skew vs the CPU tracker's
        exact per-tick attribution. Totals always agree."""
        from shadow_tpu.host.tracker import Tracker

        n_exec = np.asarray(state["n_exec"])
        n_sent = np.asarray(state["n_sent"])
        n_drop = np.asarray(state["n_drop"])
        for h in self.sim.hosts:
            i = h.host_id
            if h.tracker is None:
                h.tracker = Tracker(
                    h.name, self.sim.cfg.general.heartbeat_interval)
            h.tracker.set_events_total(int(n_exec[i]))
            h.packets_sent = int(n_sent[i])
            h.packets_dropped = int(n_drop[i])
            h.tracker.heartbeat(now, h)

    def run(self, stop: int) -> SimStats:
        import time as _time

        xp = self.sim.cfg.experimental
        if xp.checkpoint_load:
            from shadow_tpu.device import checkpoint
            state, t_start = checkpoint.load_state(
                self.engine, self.sim.starts, xp.checkpoint_load)
            if t_start >= stop:
                raise ValueError(
                    f"checkpoint_load: saved state pauses at "
                    f"{t_start} ns, at/after stop_time {stop} ns — "
                    f"nothing to resume")
            log.info("resumed checkpoint %s at t=%d ns",
                     xp.checkpoint_load, t_start)
        else:
            state = self.engine.init_state(self.sim.starts)
            t_start = 0
        # with checkpoint_save, the run PAUSES at checkpoint_save_time
        # (0 = at stop_time) and writes the state there; window
        # clamping stays on the global stop either way, so the
        # paused+resumed pair bit-matches the uninterrupted run
        pause = stop
        if xp.checkpoint_save:
            if xp.checkpoint_save_time:
                pause = min(stop, xp.checkpoint_save_time)
            if pause <= t_start:
                raise ValueError(
                    f"checkpoint_save_time {pause} ns is not after "
                    f"the run's start time {t_start} ns")
            # fail on an unwritable path NOW, in milliseconds — not
            # after a multi-hour run when the state would be lost.
            # The probe must not leave a zero-byte decoy behind if
            # the run later dies before saving
            import os as _os
            existed = _os.path.lexists(xp.checkpoint_save)
            try:
                with open(xp.checkpoint_save, "ab"):
                    pass
            except OSError as e:
                raise ValueError(
                    f"checkpoint_save path {xp.checkpoint_save!r} "
                    f"is not writable: {e}") from e
            if not existed:
                _os.unlink(xp.checkpoint_save)
        t0 = _time.perf_counter()
        hb = self.sim.cfg.general.heartbeat_interval
        seg = xp.dispatch_segment
        budget_hit = False
        t_end = pause
        if hb or seg:
            # pause the (single compiled) device program at each
            # heartbeat boundary and/or dispatch-segment boundary;
            # window clamping stays on the global stop so the trace
            # equals an unsegmented run
            rounds = 0
            budget = self.engine.config.max_rounds
            t = t_start
            next_hb = None
            if hb:
                next_hb = (t // hb + 1) * hb
            while t < pause:
                nxt = pause
                if next_hb is not None:
                    nxt = min(nxt, next_hb)
                if seg:
                    nxt = min(nxt, t + seg)
                state, seg_rounds = self.engine.run(
                    state, stop=nxt, final_stop=stop)
                rounds += int(seg_rounds)
                t = nxt
                if rounds >= budget:
                    # the per-invocation cap would otherwise reset per
                    # segment; enforce it cumulatively and don't emit
                    # a heartbeat for an interval the budget cut short
                    log.warning("max_rounds (%d) exhausted during "
                                "heartbeat segmentation; stopping",
                                budget)
                    budget_hit = True
                    break
                # a boundary that lands exactly on `pause` still emits
                # (an uninterrupted run would); only the global end
                # suppresses — resume restarts past the saved t, so
                # the pair emits each boundary exactly once
                if next_hb is not None and t >= next_hb and t < stop:
                    self._emit_heartbeats(t, state)
                    next_hb += hb
            t_end = t
        else:
            # pass stop explicitly: a cached/reused engine may have
            # been built for a different stop_time (runtime scalar)
            state, rounds = self.engine.run(state, stop=pause,
                                            final_stop=stop)
            rounds = int(rounds)
            budget_hit = rounds >= self.engine.config.max_rounds
        if xp.checkpoint_save:
            if budget_hit:
                # the simulation stopped at an unknown sim-time short
                # of `pause`; stamping `pause` would let a resume skip
                # unexecuted work, so refuse loudly instead
                log.error("max_rounds exhausted before the checkpoint "
                          "boundary — NOT saving %s",
                          xp.checkpoint_save)
            else:
                from shadow_tpu.device import checkpoint
                checkpoint.save_state(self.engine, state,
                                      xp.checkpoint_save, t_end)
                log.info("checkpoint saved at t=%d ns -> %s (run %s)",
                         t_end, xp.checkpoint_save,
                         "complete" if t_end >= stop else
                         "paused early; resume with checkpoint_load")
        # fetch ONLY the stats the controller needs — the [H,E] event
        # heaps are ~20 MB at the 10k rung (250 MB at tor_large) and
        # dominate wall time over a tunneled TPU if pulled back
        stat_keys = [k for k in state
                     if k not in ("ht", "hk", "hm", "hv", "hw")]
        final = jax.device_get({k: state[k] for k in stat_keys})
        wall = _time.perf_counter() - t0
        self.final_state = final
        H = len(self.sim.hosts)
        if "path_cnt" in final:
            # surface the device path histogram through the same API
            # the CPU engines populate (NetworkModel.path_packets)
            V = self.engine.n_vertices
            cnt = np.asarray(final["path_cnt"]).sum(0).reshape(V, V)
            nz = np.nonzero(cnt)
            self.sim.netmodel.record_paths(
                {(int(i), int(j)): int(cnt[i, j])
                 for i, j in zip(*nz)})
        n_exec_total = int(final["n_exec"][:H].sum())
        # perf-timer parity (USE_PERF_TIMERS round summaries): the
        # device program is one fused loop, so the breakdown is
        # per-run — rounds, wall, and throughput
        log.info("device perf: %d rounds in %.2fs wall "
                 "(%.0f rounds/s, %.0f events/s)", rounds,
                 wall, rounds / wall if wall > 0 else 0.0,
                 n_exec_total / wall if wall > 0 else 0.0)

        stats = SimStats()
        stats.end_time = t_end
        stats.rounds = int(rounds)
        stats.events_executed = n_exec_total
        stats.packets_sent = int(final["n_sent"][:H].sum())
        stats.packets_dropped = int(final["n_drop"][:H].sum())
        stats.packets_delivered = int(final["n_deliv"][:H].sum())
        overflow = int(final["overflow"][:H].sum())
        if overflow:
            stats.ok = False
            log.error("device engine overflow: %d events lost — raise "
                      "experimental.event_capacity/outbox_capacity",
                      overflow)
        x_overflow = int(final["x_overflow"][:H].sum())
        if x_overflow:
            stats.ok = False
            log.error("exchange overflow: %d rows exceeded the per-"
                      "shard-pair capacity — raise experimental."
                      "exchange_capacity (or use exchange: all_gather "
                      "for hub-concentrated traffic)", x_overflow)

        # reflect per-host results back onto the Host objects
        for h in self.sim.hosts:
            i = h.host_id
            h.events_executed = int(final["n_exec"][i])
            h.packets_sent = int(final["n_sent"][i])
            h.packets_dropped = int(final["n_drop"][i])
            h.packets_delivered = int(final["n_deliv"][i])
            h.trace_checksum = int(final["chk"][i])
        return stats
